"""Wall-time attribution tracing — the host-side third of observability.

:mod:`apex_tpu.pyprof` answers *where device time went*;
:mod:`apex_tpu.monitor` answers *is the run healthy over time*.  What
neither could answer is where the **wall** time goes when it is not on
the device — ROADMAP item 2's 84 TF/s-device / 33 TF/s-wall gap was a
single opaque number.  This module is the instrument for that surgery,
in four pieces:

* :class:`SpanTracer` — near-zero-overhead ``span("name")`` context
  manager / decorator with thread-and-process-aware monotonic timing.
  Spans drain as ``span`` events into the existing crash-safe JSONL
  sinks and export as Chrome trace-event JSON
  (:meth:`SpanTracer.chrome_trace`), so host spans load into Perfetto
  side-by-side with ``jax.profiler`` device traces — the TPU-native
  form of the reference's nvtx→nvvp join
  (ref: apex/pyprof/nvtx/nvmarker.py + pyprof/parse/nvvp.py).
* :class:`StepWaterfall` — per-step wall attribution over the
  canonical components ``data_load`` / ``dispatch`` /
  ``device_compute`` (the async-dispatch ``block_until_ready``
  boundary) / ``telemetry_drain`` / ``ckpt_io`` plus the ``other``
  residual, emitted per step as one ``attr`` event with
  ``wall_ms = Σ parts`` and ``wall_device_ratio`` — ROADMAP item 2's
  exit criterion ("wall/device > 0.9") as a per-step number.
* :class:`DeviceMetricsBuffer` / :class:`DeferredTelemetry` —
  sync-free telemetry: per-step scalars (loss, grad-norm,
  overflow/skip state from :class:`~apex_tpu.amp.mixed_precision.
  StepInfo`) accumulate into a device-resident ring **inside the
  jitted step** and drain to the :class:`~apex_tpu.monitor.
  step_monitor.StepMonitor` every K steps through one explicit
  ``jax.device_get`` — zero per-step host transfers, provable under
  ``analysis.sanitize(transfer_guard="disallow",
  transfer_scope="device_to_host")``.  At K=1 the drained values are
  bitwise-identical to the synchronous per-step readbacks.
* :class:`CaptureTrigger` — on-demand profiling: a file-touch or
  SIGUSR1 trigger opens a :class:`apex_tpu.pyprof.ProfileWindow` for N
  steps mid-run (exactly one window per trigger), plus auto-capture
  when ``wall_device_ratio`` falls below the
  ``APEX_TPU_TRACE_RATIO_MIN`` registry flag — the waterfall's sibling
  of the Watchdog's stall-trace hook.

All clocks are injectable (fake-clock tests in
tests/test_monitor_tracing.py); every flag is registered in
:mod:`apex_tpu.analysis.flags`.  Full story with a worked waterfall
read: docs/api/observability.md.
"""
from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from ..analysis.flags import flag_float, flag_int, flag_str
from ..utils.log_util import get_logger
from .events import Event, Sink, terminal_reason

logger = get_logger(__name__)

__all__ = [
    "Span", "SpanTracer", "get_tracer", "set_tracer", "span",
    "StepWaterfall", "WATERFALL_PARTS",
    "DeviceMetricsBuffer", "MetricsBufferState", "DeferredTelemetry",
    "CaptureTrigger", "TraceSession",
    "chrome_trace_from_events", "write_chrome_trace", "check_trace",
    "SERVE_PHASES", "serve_lane_events", "serve_lanes_from_events",
    "serve_chrome_trace", "check_serve_trace",
]


# ---------------------------------------------------------------------------
# Host span tracer
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Span:
    """One completed host span.  ``t0`` is epoch seconds (wall-anchored
    monotonic time — see :class:`SpanTracer`), ``dur`` seconds."""

    name: str
    t0: float
    dur: float
    pid: int
    tid: int
    thread: str
    depth: int
    step: Optional[int] = None
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_event(self) -> Event:
        attrs = {"t0": round(self.t0, 6), "tid": self.tid,
                 "thread": self.thread, "depth": self.depth}
        attrs.update(self.attrs)
        return Event(time=self.t0 + self.dur, step=self.step,
                     kind="span", name=self.name, value=self.dur,
                     attrs=attrs)

    def chrome_event(self) -> dict:
        ev = {"name": self.name, "ph": "X", "cat": "host",
              "ts": round(self.t0 * 1e6, 3),
              "dur": round(self.dur * 1e6, 3),
              "pid": self.pid, "tid": self.tid}
        args = dict(self.attrs)
        if self.step is not None:
            args["step"] = self.step
        if args:
            ev["args"] = args
        return ev


class _SpanHandle(contextlib.ContextDecorator):
    """Context-manager *and* decorator for one span occurrence —
    ``with tracer.span("x"):`` and ``@tracer.span("x")`` both work."""

    def __init__(self, tracer: "SpanTracer", name: str,
                 step: Optional[int], attrs: Dict[str, Any]):
        self._tracer = tracer
        self._name = name
        self._step = step
        self._attrs = attrs
        self._t0 = None

    def __enter__(self):
        self._t0 = self._tracer._begin()
        return self

    def __exit__(self, *exc):
        self._tracer._end(self._name, self._t0, step=self._step,
                          attrs=self._attrs)
        return False


class _NullSpan(contextlib.ContextDecorator):
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class SpanTracer:
    """Near-zero-overhead host span recorder.

    Each completed span costs two monotonic clock reads and one
    list-append on a per-thread buffer (no lock on the hot path; the
    lock is only taken when a *new* thread first spans and at drain).
    Timing is ``time.perf_counter`` anchored once against the wall
    clock at construction, so exported spans carry epoch timestamps
    without paying a wall-clock syscall per span — the property that
    lets Perfetto line host spans up against a ``jax.profiler`` device
    trace captured in the same process.

    Nesting is tracked per thread (``depth``); the tracer is safe to
    use concurrently from any number of threads.
    """

    def __init__(self, *, clock: Callable[[], float] = time.perf_counter,
                 wall_clock: Callable[[], float] = time.time,
                 max_spans: int = 1_000_000):
        self._clock = clock
        # one wall anchor: epoch = anchor + (perf_counter - perf0)
        self._perf0 = clock()
        self._wall0 = wall_clock()
        self._pid = os.getpid()
        self._local = threading.local()
        self._lock = threading.Lock()
        self._buffers: List[List[Span]] = []
        self._max_spans = int(max_spans)
        self._dropped = 0

    # -- hot path ------------------------------------------------------------

    def _thread_buf(self) -> Tuple[List[Span], List[int]]:
        buf = getattr(self._local, "buf", None)
        if buf is None:
            buf = self._local.buf = []
            self._local.depth = [0]
            with self._lock:
                self._buffers.append(buf)
        return buf, self._local.depth

    def _begin(self) -> float:
        _, depth = self._thread_buf()
        depth[0] += 1
        return self._clock()

    def _end(self, name: str, t0: float, *, step=None, attrs=None) -> None:
        t1 = self._clock()
        buf, depth = self._thread_buf()
        depth[0] -= 1
        if len(buf) >= self._max_spans:
            # cold path (the buffer is already full): the shared drop
            # counter takes the lock — += from concurrent threads
            # loses counts (APX801)
            with self._lock:
                self._dropped += 1
            return
        th = threading.current_thread()
        buf.append(Span(
            name=name, t0=self._wall0 + (t0 - self._perf0),
            dur=t1 - t0, pid=self._pid, tid=th.ident or 0,
            thread=th.name, depth=depth[0], step=step,
            attrs=attrs or {}))

    def span(self, name: str, *, step: Optional[int] = None,
             **attrs) -> _SpanHandle:
        """``with tracer.span("data_load"): ...`` — also usable as a
        decorator (``@tracer.span("load_batch")``)."""
        return _SpanHandle(self, name, step, attrs)

    def add_complete(self, name: str, t0: float, dur: float, *,
                     tid: Optional[int] = None, thread: str = "",
                     step: Optional[int] = None, **attrs) -> None:
        """Record an externally-timed complete span (``t0`` epoch
        seconds) — how :meth:`apex_tpu.transformer.pipeline_parallel.
        utils.Timers.chrome_events` and the waterfall feed accumulated
        times into the same Chrome writer."""
        buf, _ = self._thread_buf()
        if len(buf) >= self._max_spans:
            with self._lock:
                self._dropped += 1
            return
        th = threading.current_thread()
        buf.append(Span(name=name, t0=float(t0), dur=float(dur),
                        pid=self._pid,
                        tid=th.ident if tid is None else int(tid),
                        thread=thread or th.name, depth=0, step=step,
                        attrs=attrs))

    def now(self) -> float:
        """Current time on the tracer's epoch-anchored timeline."""
        return self._wall0 + (self._clock() - self._perf0)

    # -- drain / export ------------------------------------------------------

    def drain(self) -> List[Span]:
        """Remove and return every recorded span (all threads),
        t0-ordered.  Only the snapshotted prefix of each per-thread
        buffer is deleted — an append racing in from the owning thread
        (the hot path is deliberately lock-free) lands at the tail and
        survives for the next drain instead of being silently lost."""
        out: List[Span] = []
        with self._lock:
            for buf in self._buffers:
                got = buf[:]
                out.extend(got)
                del buf[:len(got)]
        out.sort(key=lambda s: s.t0)
        return out

    def events(self, sink, step: Optional[int] = None) -> int:
        """Drain into a sink (anything with ``emit(Event)``) as
        ``span`` events; returns the number emitted.  Spans recorded
        without a step inherit ``step``."""
        spans = self.drain()
        for s in spans:
            if s.step is None and step is not None:
                s = dataclasses.replace(s, step=step)
            sink.emit(s.to_event())
        return len(spans)

    def chrome_trace(self, spans: Optional[List[Span]] = None) -> dict:
        """Chrome trace-event JSON object (load in Perfetto /
        chrome://tracing next to a ``jax.profiler`` dump).  Without
        ``spans``, drains the tracer."""
        if spans is None:
            spans = self.drain()
        with self._lock:
            dropped = self._dropped
        return _chrome_json([s.chrome_event() for s in spans],
                            pid=self._pid, dropped=dropped)

    def write_chrome_trace(self, path: str,
                           spans: Optional[List[Span]] = None) -> str:
        """Write :meth:`chrome_trace` atomically (scratch + rename —
        the bench-artifact commit protocol) and return ``path``."""
        return write_chrome_trace(path, self.chrome_trace(spans))


_GLOBAL_TRACER: Optional[SpanTracer] = None


def get_tracer() -> Optional[SpanTracer]:
    """The process-wide tracer, or None when tracing is off."""
    return _GLOBAL_TRACER


def set_tracer(tracer: Optional[SpanTracer]) -> None:
    """Publish (or clear, with None) the process-wide tracer."""
    global _GLOBAL_TRACER
    _GLOBAL_TRACER = tracer


def span(name: str, **attrs):
    """Module-level ``with span("name"):`` against the process-wide
    tracer — a no-op (shared null handle, zero allocation) when no
    tracer is installed, so library code can instrument
    unconditionally."""
    t = _GLOBAL_TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def _chrome_json(events: List[dict], *, pid: int,
                 dropped: int = 0) -> dict:
    meta = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": "apex_tpu host"}}]
    out = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    if dropped:
        out["otherData"] = {"dropped_spans": dropped}
    return out


def write_chrome_trace(path: str, trace: dict) -> str:
    """Atomic Chrome-trace write: scratch file then ``os.replace`` so a
    kill mid-write never leaves a truncated artifact."""
    scratch = path + ".partial"
    with open(scratch, "w") as f:
        json.dump(trace, f, separators=(",", ":"))
    os.replace(scratch, path)
    return path


def chrome_trace_from_events(events) -> dict:
    """Rebuild a Chrome trace from a monitor event log: ``span`` events
    become host ``X`` (complete) events; ``timer`` events (phase times
    exported by ``Timers.events`` — value in seconds, stamped at stop)
    become complete events ending at their emission time on a synthetic
    ``timers`` track; serving ``request_done`` lifecycle events become
    one per-request lane each with queued/prefill/decode phases
    (:func:`serve_lanes_from_events`).  The read-side join: any
    committed run JSONL can be turned back into a Perfetto-loadable
    timeline (``tools/monitor_summary.py --chrome OUT.json``)."""
    pid = os.getpid()
    out: List[dict] = []
    timer_tid = 1
    for e in events:
        if e.kind == "span" and isinstance(e.value, (int, float)):
            t0 = e.attrs.get("t0", e.time - float(e.value))
            ev = {"name": e.name, "ph": "X", "cat": "host",
                  "ts": round(float(t0) * 1e6, 3),
                  "dur": round(float(e.value) * 1e6, 3),
                  "pid": pid, "tid": e.attrs.get("tid", 0)}
            args = {k: v for k, v in e.attrs.items()
                    if k not in ("t0", "tid")}
            if e.step is not None:
                args["step"] = e.step
            if args:
                ev["args"] = args
            out.append(ev)
        elif e.kind == "timer" and isinstance(e.value, (int, float)):
            dur = float(e.value)
            ev = {"name": e.name, "ph": "X", "cat": "timer",
                  "ts": round((e.time - dur) * 1e6, 3),
                  "dur": round(dur * 1e6, 3),
                  "pid": pid, "tid": timer_tid}
            if e.step is not None:
                ev["args"] = {"step": e.step}
            out.append(ev)
    out.extend(serve_lanes_from_events(events, pid=pid))
    return _chrome_json(out, pid=pid)


# ---------------------------------------------------------------------------
# Serving request lanes (apex_tpu.serving.metrics is the write side)
# ---------------------------------------------------------------------------

#: Per-request lane phases, in lifecycle order.  ``queued`` is
#: submit → admission start, ``prefill`` admission → first token,
#: ``decode`` first token → terminal — contiguous sub-intervals of the
#: request wall, so the lane IS the request's waterfall.
SERVE_PHASES = ("queued", "prefill", "decode")

#: tid offset for request lanes so they sort below the host-span and
#: timer tracks in Perfetto
_SERVE_LANE_TID0 = 1000


def serve_lane_events(rows: List[dict], *,
                      pid: Optional[int] = None) -> List[dict]:
    """Chrome trace events (one lane per request) from lane rows —
    ``{rid, end (epoch s), queue_wait_ms, prefill_ms, decode_ms,
    new_tokens, preempted, tick}`` as produced by
    :meth:`apex_tpu.serving.metrics.RequestTrace.lane_row` (exact
    timestamps) or reconstructed from terminal events
    (:func:`serve_lanes_from_events`).  ``prefill_ms``/``decode_ms``
    are None for a request preempted before admission (its lane is
    queue wait only)."""
    pid = os.getpid() if pid is None else pid
    out: List[dict] = []
    for i, r in enumerate(rows):
        if r.get("end") is None:
            continue
        tid = _SERVE_LANE_TID0 + i
        out.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": f"req {r['rid']}"}})
        parts = [(p, r.get(_ATTR_FOR_PHASE[p]))
                 for p in SERVE_PHASES]
        total_ms = sum(v for _, v in parts
                       if isinstance(v, (int, float)))
        t = r["end"] * 1e6 - total_ms * 1e3   # lane start, us
        args = {"rid": r["rid"]}
        for k in ("new_tokens", "preempted", "tick"):
            if r.get(k) is not None:
                args[k] = r[k]
        for phase, ms in parts:
            if not isinstance(ms, (int, float)):
                continue
            out.append({"name": phase, "ph": "X", "cat": "serve",
                        "ts": round(t, 3),
                        "dur": round(ms * 1e3, 3),
                        "pid": pid, "tid": tid, "args": args})
            t += ms * 1e3
    return out


_ATTR_FOR_PHASE = {"queued": "queue_wait_ms", "prefill": "prefill_ms",
                   "decode": "decode_ms"}


def serve_lanes_from_events(events, *,
                            pid: Optional[int] = None) -> List[dict]:
    """Rebuild per-request Chrome lanes from a run JSONL's serving
    lifecycle events: each terminal ``request_done`` carries the whole
    queued/prefill/decode breakdown, anchored backwards from its own
    emission time.  (The write-side export —
    ``ServeMetrics.chrome_trace`` — uses the exact engine-clock
    timestamps instead; the two agree to within the emit latency.)"""
    rows = []
    for e in events:
        if e.kind != "serving" or e.name != "request_done":
            continue
        a = e.attrs
        rows.append({
            "rid": a.get("rid"),
            "end": e.time,
            "queue_wait_ms": a.get("queue_wait_ms"),
            "prefill_ms": (a.get("prefill_ms")
                           if "ttft_ms" in a else None),
            "decode_ms": (a.get("decode_ms")
                          if "ttft_ms" in a else None),
            "new_tokens": a.get("new_tokens"),
            "preempted": a.get("preempted"),
            "tick": e.step,
        })
    return serve_lane_events(rows, pid=pid)


def serve_chrome_trace(rows: List[dict]) -> dict:
    """Chrome trace-event JSON object holding only request lanes (the
    ``--serve --trace`` artifact; write with
    :func:`write_chrome_trace`)."""
    pid = os.getpid()
    return _chrome_json(serve_lane_events(rows, pid=pid), pid=pid)


def check_serve_trace(jsonl_path,
                      chrome_path: Optional[str] = None, *,
                      tolerance: float = 0.02) -> List[str]:
    """Validate a serve run's telemetry (``tools/trace_check.py
    --serve``, ci.sh step 11).  ``jsonl_path`` may be ONE path or a
    sequence of per-replica paths (``trace_check --serve
    serve-r0.jsonl serve-r1.jsonl ...`` — the ISSUE-14 fleet form):
    events merge before checking, so *N submitted ⇒ N terminal* holds
    across the whole fleet — a request routed to replica A and
    journal-replayed there still closes exactly once fleet-wide, and
    a rid appearing on two replicas' logs (a double submit the router
    must never produce) fails.  Returns failure strings (empty =
    pass):

    * lifecycle completeness — every submitted rid ends in exactly one
      terminal ``request_done`` (N submitted ⇒ N terminal events), no
      terminal without a submit.  This holds on EVERY terminal path:
      finished, drain-preempted, ``deadline``/``deadline_exceeded``
      expiry, ``shed``, and across a supervised crash-replay (a
      journal-replayed rid re-enters WITHOUT a second submit event,
      so the chain still closes exactly once);
    * TTFT present for every rid that *finished* (``request_first_
      token`` event + ``ttft_ms`` on the terminal); preempted / shed /
      deadline-expired requests may legitimately end before their
      first token;
    * per-request attribution — ``queue_wait + prefill + decode`` sums
      to the rid's ``wall_ms`` within ``tolerance``;
    * engine gauges — a run that decoded must carry ``serve_tick``
      events;
    * the live metrics plane (ISSUE-17) — every ``slo_burn`` alarm
      traces back to an ``slo_objectives`` definition event,
      ``fleet_tick`` steps are monotone non-decreasing per log, and
      ``metrics_server_started`` / ``metrics_server_stopped`` pair
      up (every started server was torn down, and vice versa);
    * the distributed control plane (ISSUE-18) — when supervisor
      ``replica_spawned`` events are present, every spawned
      ``(replica, incarnation)`` pairs with exactly one
      ``replica_reaped`` and vice versa (a kill-9'd incarnation is
      reaped before its replay incarnation spawns; a drained
      scale-down victim is reaped too — nothing leaks), and every
      ``autoscale`` event carries a valid ``action`` with its
      subject replica's lifecycle events in the log;
    * the Chrome artifact (when given) parses and carries one lane per
      terminal rid with the canonical queued/prefill/decode phases.
    """
    from .summary import load_events

    failures: List[str] = []
    paths = ([jsonl_path] if isinstance(jsonl_path, (str, os.PathLike))
             else list(jsonl_path))
    events = []
    for p in paths:
        evs, malformed = load_events(p)
        if malformed:
            failures.append(f"{malformed} malformed line(s) in {p}")
        # fleet aggregation rounds must advance in emission order
        # WITHIN each log (merged logs interleave legitimately)
        last_ft = None
        for e in evs:
            if e.kind == "fleet_tick":
                if last_ft is not None and e.step is not None \
                        and e.step < last_ft:
                    failures.append(
                        f"{p}: fleet_tick step went backwards "
                        f"({last_ft} -> {e.step})")
                if e.step is not None:
                    last_ft = e.step
        events.extend(evs)
    srv = [e for e in events if e.kind == "serving"]
    # ISSUE-17: every slo_burn alarm must trace back to an objective
    # definition event, and the exporter lifecycle must pair up
    burns = [e for e in events
             if e.kind == "alarm" and e.name == "slo_burn"]
    slo_defs = [e for e in events
                if e.kind == "slo" and e.name == "slo_objectives"]
    if burns and not slo_defs:
        failures.append(
            f"{len(burns)} slo_burn alarm(s) with no slo_objectives "
            f"definition event — burns must be attributable to a "
            f"declared objective")
    started = sum(1 for e in events if e.kind == "metrics"
                  and e.name == "metrics_server_started")
    stopped = sum(1 for e in events if e.kind == "metrics"
                  and e.name == "metrics_server_stopped")
    if started != stopped:
        failures.append(
            f"metrics_server_started ({started}) != "
            f"metrics_server_stopped ({stopped}) — every metrics "
            f"server must be torn down")
    # ISSUE-18: process-isolated fleet lifecycle — checks arm only
    # when a supervisor log is in the merge (single-process serve
    # runs have no spawn events and skip this block entirely)
    fleet = [e for e in events if e.kind == "fleet"]
    spawned_pairs: Dict[tuple, int] = {}
    reaped_pairs: Dict[tuple, int] = {}
    for e in fleet:
        key = (str(e.attrs.get("replica")),
               int(e.attrs.get("incarnation") or 0))
        if e.name == "replica_spawned":
            spawned_pairs[key] = spawned_pairs.get(key, 0) + 1
        elif e.name == "replica_reaped":
            reaped_pairs[key] = reaped_pairs.get(key, 0) + 1
    if spawned_pairs:
        for key, n in sorted(spawned_pairs.items()):
            if n != 1:
                failures.append(
                    f"replica {key[0]} incarnation {key[1]}: "
                    f"{n} replica_spawned events, want exactly 1")
            if reaped_pairs.get(key, 0) != 1:
                failures.append(
                    f"replica {key[0]} incarnation {key[1]}: "
                    f"spawned but {reaped_pairs.get(key, 0)} "
                    f"replica_reaped event(s) — every incarnation "
                    f"must be reaped exactly once")
        for key in sorted(set(reaped_pairs) - set(spawned_pairs)):
            failures.append(
                f"replica {key[0]} incarnation {key[1]}: "
                f"replica_reaped without a replica_spawned")
        known = {k[0] for k in spawned_pairs}
        for e in fleet:
            if e.name != "autoscale":
                continue
            action = e.attrs.get("action")
            if action not in ("up", "down"):
                failures.append(
                    f"autoscale event with invalid action "
                    f"{action!r} (want 'up' or 'down')")
            if str(e.attrs.get("replica")) not in known:
                failures.append(
                    f"autoscale {action} names replica "
                    f"{e.attrs.get('replica')!r} with no lifecycle "
                    f"events in the log")
    # fleet-mode sanity: one rid must live on exactly one replica —
    # its submit and terminal must carry the same replica stamp
    if len(paths) > 1:
        homes: Dict[str, set] = {}
        for e in srv:
            if e.name in ("request_submitted", "request_done") \
                    and e.attrs.get("replica") is not None:
                homes.setdefault(str(e.attrs.get("rid")),
                                 set()).add(str(e.attrs["replica"]))
        for rid, reps in sorted(homes.items()):
            if len(reps) > 1:
                failures.append(
                    f"rid {rid}: lifecycle events on "
                    f"{len(reps)} replicas ({sorted(reps)}) — a "
                    f"request must live on exactly one")
    submitted = [str(e.attrs.get("rid")) for e in srv
                 if e.name == "request_submitted"]
    terminal: Dict[str, int] = {}
    done_events = {}
    for e in srv:
        if e.name == "request_done":
            rid = str(e.attrs.get("rid"))
            terminal[rid] = terminal.get(rid, 0) + 1
            done_events[rid] = e
    first_token = {str(e.attrs.get("rid")) for e in srv
                   if e.name == "request_first_token"}
    if not submitted:
        failures.append("no request_submitted events — not a serve "
                        "run log?")
    for rid in submitted:
        n = terminal.get(rid, 0)
        if n != 1:
            failures.append(f"rid {rid}: {n} terminal request_done "
                            f"event(s), want exactly 1")
    for rid in terminal:
        if rid not in submitted:
            failures.append(f"rid {rid}: terminal event without a "
                            f"request_submitted")
    for rid, e in sorted(done_events.items()):
        a = e.attrs
        term = terminal_reason(a)
        if term == "finished":
            if "ttft_ms" not in a:
                failures.append(f"rid {rid}: finished without a "
                                f"ttft_ms — TTFT must exist for "
                                f"every finished request")
            if rid not in first_token:
                failures.append(f"rid {rid}: no request_first_token "
                                f"event in the chain")
        wall = a.get("wall_ms")
        if isinstance(wall, (int, float)) and wall > 0:
            parts = sum(float(a.get(k) or 0.0)
                        for k in ("queue_wait_ms", "prefill_ms",
                                  "decode_ms"))
            if abs(parts - wall) > tolerance * wall + 1e-3:
                failures.append(
                    f"rid {rid}: queued+prefill+decode "
                    f"{parts:.3f} ms != wall {wall:.3f} ms "
                    f"(> {tolerance:.0%})")
    decoded = any(e.name == "decode_step" for e in srv)
    gauges = [e for e in events if e.kind == "serve_tick"]
    if decoded and not gauges:
        failures.append("run decoded but emitted no serve_tick "
                        "engine gauges")
    if chrome_path is not None:
        try:
            with open(chrome_path) as f:
                trace = json.load(f)
            evs = trace.get("traceEvents", [])
            lanes: Dict[str, set] = {}
            for t in evs:
                if t.get("ph") == "X" and t.get("cat") == "serve":
                    rid = str((t.get("args") or {}).get("rid"))
                    lanes.setdefault(rid, set()).add(t.get("name"))
            for rid, e in sorted(done_events.items()):
                phases = lanes.get(rid)
                if phases is None:
                    failures.append(f"{chrome_path}: no lane for "
                                    f"rid {rid}")
                    continue
                want = {"queued"}
                if "ttft_ms" in e.attrs:
                    want = set(SERVE_PHASES)
                miss = sorted(want - phases)
                if miss:
                    failures.append(f"{chrome_path}: rid {rid} lane "
                                    f"missing phase(s) {miss}")
        except (OSError, json.JSONDecodeError) as e:
            failures.append(f"{chrome_path}: unreadable Chrome trace "
                            f"({e})")
    return failures


# ---------------------------------------------------------------------------
# Per-step wall-time waterfall
# ---------------------------------------------------------------------------

#: Canonical per-step components.  ``device_compute`` is measured from
#: the async-dispatch boundary: the time the host spends blocked in
#: ``block_until_ready`` on the step's outputs.  Everything not inside
#: a named part lands in the ``other`` residual, so the parts sum to
#: the step wall time *by construction*.
WATERFALL_PARTS = ("data_load", "dispatch", "device_compute",
                   "telemetry_drain", "ckpt_io")


class StepWaterfall:
    """Per-step wall-time attribution over :data:`WATERFALL_PARTS`.

    Usage (the shared smoke-loop shape)::

        wf.begin_step(i)
        with wf.part("dispatch"):
            out = step_fn(...)          # returns at enqueue (async)
        with wf.part("device_compute"):
            jax.block_until_ready(loss)  # the device boundary
        ...
        row = wf.end_step(sink, step=i)  # one 'attr' event

    ``end_step`` computes ``wall_ms``, per-part ms, the ``other``
    residual (``wall - Σ parts``, >= 0 by construction since parts are
    disjoint sub-intervals of the step window) and
    ``wall_device_ratio = device_compute / wall``.  With a
    :class:`SpanTracer` attached, each part is also recorded as a span
    so the waterfall appears in the Chrome trace.
    """

    def __init__(self, tracer: Optional[SpanTracer] = None, *,
                 clock: Callable[[], float] = time.perf_counter,
                 on_row: Optional[Callable[[dict], None]] = None):
        self._tracer = tracer
        self._clock = clock
        self._on_row = on_row
        self._t0: Optional[float] = None
        self._step: Optional[int] = None
        self._parts: Dict[str, float] = {}
        self.rows: List[dict] = []

    def begin_step(self, step: Optional[int] = None) -> None:
        self._t0 = self._clock()
        self._step = step
        self._parts = {}

    @contextlib.contextmanager
    def part(self, name: str):
        """Attribute the enclosed block to component ``name`` (repeat
        entries accumulate).  Unknown names are allowed — they appear
        as extra components in the row."""
        if self._t0 is None:
            # not inside a step: still time it, attributed on emit as
            # a standalone span only
            if self._tracer is not None:
                with self._tracer.span(name):
                    yield
            else:
                yield
            return
        span_ctx = (self._tracer.span(name, step=self._step)
                    if self._tracer is not None else _NULL_SPAN)
        t0 = self._clock()
        try:
            with span_ctx:
                yield
        finally:
            self._parts[name] = (self._parts.get(name, 0.0)
                                 + self._clock() - t0)

    def end_step(self, sink=None, step: Optional[int] = None,
                 **extra) -> dict:
        """Close the step: compute the attribution row, emit it as one
        ``attr`` event into ``sink`` (when given), invoke the ``on_row``
        hook (auto-capture wiring), and return it.  ``extra`` keyword
        values are merged into the row (and the event attrs) verbatim —
        how the scan driver stamps ``scan_k`` (steps per dispatch) on a
        window's row; names must not end in ``_ms`` (those are reserved
        for the parts-sum-to-wall invariant)."""
        if self._t0 is None:
            raise RuntimeError("end_step without begin_step")
        bad = [k for k in extra if k.endswith("_ms")]
        if bad:
            raise ValueError(f"extra row field(s) {bad} collide with "
                             "the *_ms attribution namespace")
        wall = self._clock() - self._t0
        if step is None:
            step = self._step
        parts = dict(self._parts)
        other = max(0.0, wall - sum(parts.values()))
        row: Dict[str, Any] = {"step": step,
                               "wall_ms": wall * 1e3}
        row.update(extra)
        for name in WATERFALL_PARTS:
            row[f"{name}_ms"] = parts.pop(name, 0.0) * 1e3
        for name, v in sorted(parts.items()):  # non-canonical extras
            row[f"{name}_ms"] = v * 1e3
        row["other_ms"] = other * 1e3
        row["wall_device_ratio"] = (
            row["device_compute_ms"] / row["wall_ms"]
            if wall > 0.0 else 0.0)
        self._t0 = None
        self.rows.append(row)
        if sink is not None:
            attrs = {k: round(v, 4) if isinstance(v, float) else v
                     for k, v in row.items()
                     if k not in ("step", "wall_ms")}
            sink.emit(Event(time=time.time(), step=step, kind="attr",
                            name="step_waterfall",
                            value=round(row["wall_ms"], 4),
                            attrs=attrs))
        if self._on_row is not None:
            try:
                self._on_row(row)
            except Exception as e:
                logger.warning("waterfall on_row hook failed: %s",
                               str(e)[:160])
        return row


# ---------------------------------------------------------------------------
# Sync-free deferred telemetry
# ---------------------------------------------------------------------------

class MetricsBufferState(NamedTuple):
    """Device-resident ring state — a pytree, so it threads through a
    jitted step (and donates) like any other carry."""

    values: Any   # f32 [capacity, n_metrics]
    count: Any    # i32 scalar: total appends since init


class DeviceMetricsBuffer:
    """Fixed-capacity device ring of per-step scalar metrics.

    ``append`` is pure jnp (trace-safe — call it *inside* the jitted
    step); ``drain`` performs the only host transfer, one **explicit**
    ``jax.device_get`` of the whole ring, which the transfer guard's
    ``disallow`` level (implicit transfers) permits — that asymmetry is
    the zero-per-step-transfer proof ``analysis.sanitizer`` enforces.

    Values are stored as float32; at drain they convert to Python
    floats exactly, so a K=1 drain is bitwise-identical to the
    synchronous ``float(loss)`` readback it replaces.
    """

    DEFAULT_METRICS = ("loss", "grad_norm", "loss_scale", "overflow",
                       "steps_skipped")

    def __init__(self, capacity: int,
                 metrics: Tuple[str, ...] = DEFAULT_METRICS):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.metrics = tuple(metrics)

    def init(self) -> MetricsBufferState:
        import jax.numpy as jnp

        return MetricsBufferState(
            values=jnp.zeros((self.capacity, len(self.metrics)),
                             jnp.float32),
            count=jnp.zeros((), jnp.int32))

    def append(self, state: MetricsBufferState,
               **metrics) -> MetricsBufferState:
        """Append one row (trace-safe).  Every registered metric must
        be supplied; extras are rejected so a typo cannot silently
        drop a series."""
        import jax
        import jax.numpy as jnp

        unknown = set(metrics) - set(self.metrics)
        if unknown:
            raise ValueError(f"unregistered metric(s) {sorted(unknown)}; "
                             f"buffer records {self.metrics}")
        row = jnp.stack([
            jnp.asarray(metrics[m]).astype(jnp.float32).reshape(())
            for m in self.metrics])
        idx = jnp.mod(state.count, self.capacity)
        values = jax.lax.dynamic_update_slice(
            state.values, row[None, :], (idx, jnp.int32(0)))
        return MetricsBufferState(values=values, count=state.count + 1)

    def drain(self, state: MetricsBufferState,
              drained: int) -> Tuple[int, List[Tuple[int, Dict[str, float]]]]:
        """One explicit device→host fetch of the ring.  ``drained`` is
        how many appends previous drains consumed; returns the new
        count and ``[(append_index, {metric: value}), ...]`` for every
        un-drained row still resident (overwritten rows — more than
        ``capacity`` appends since the last drain — are lost and
        logged, never silently renumbered)."""
        import jax

        host = jax.device_get(state)
        count = int(host.count)
        start = max(int(drained), count - self.capacity)
        if start > drained:
            logger.warning(
                "DeviceMetricsBuffer overran: %d row(s) overwritten "
                "before drain (capacity %d)", start - drained,
                self.capacity)
        rows = []
        for j in range(start, count):
            vals = host.values[j % self.capacity]
            rows.append((j, {m: float(v)
                             for m, v in zip(self.metrics, vals)}))
        return count, rows


class DeferredTelemetry:
    """Loop-side manager for a :class:`DeviceMetricsBuffer`: threads
    the ring state through a deferred step function, drains every
    ``every`` appends, and emits the drained rows as the same
    ``metric`` / ``scale`` events the synchronous path produces (same
    names, same values — the step numbers are reconstructed from append
    order, so a deferred log summarizes identically).
    """

    def __init__(self, every: int, *,
                 buffer: Optional[DeviceMetricsBuffer] = None):
        self.every = max(1, int(every))
        self.buffer = buffer or DeviceMetricsBuffer(
            capacity=self.every)
        self.state = self.buffer.init()
        self._drained = 0
        self._drain_count = 0
        self._steps: List[int] = []   # step number per pending append
        self.last_metrics: Optional[Dict[str, float]] = None

    def step(self, step_fn, params, amp_state, *, step: int):
        """Run one deferred step: ``step_fn(params, amp_state, tstate)
        -> (params, amp_state, tstate, loss, gnorm, info)`` (the shape
        ``build_train_step(..., telemetry=buf)`` produces).  Keeps the
        returned ring state; no host transfer."""
        params, amp_state, self.state, loss, gnorm, info = step_fn(
            params, amp_state, self.state)
        self._steps.append(step)
        return params, amp_state, loss, gnorm, info

    def scan_window(self, step_fn, params, amp_state, *, start: int,
                    k: int):
        """Run one K-step scan window: ``step_fn(params, amp_state,
        tstate) -> (params, amp_state, tstate, loss, gnorm, info)``
        where the jitted body appended ``k`` rows to the ring (the
        shape ``build_train_step_scan(setup, k, telemetry=buf)``
        produces).  Records the window's step numbers
        ``[start, start+k)`` for drain-time renumbering; no host
        transfer.  The ring must hold a full window
        (``buffer.capacity >= k``) or rows would be overwritten before
        the drain."""
        if k > self.buffer.capacity:
            raise ValueError(
                f"scan window of {k} steps exceeds the telemetry ring "
                f"capacity {self.buffer.capacity}")
        params, amp_state, self.state, loss, gnorm, info = step_fn(
            params, amp_state, self.state)
        self._steps.extend(range(start, start + k))
        return params, amp_state, loss, gnorm, info

    @property
    def pending(self) -> int:
        return len(self._steps)

    @property
    def drains(self) -> int:
        """Completed drains so far (the ceil(N/K) proof counter)."""
        return self._drain_count

    def maybe_drain(self, monitor, force: bool = False) -> int:
        """Drain if ``every`` appends accumulated (or ``force``).
        Returns the number of rows emitted.  Each actual drain also
        emits one ``telemetry``/``telemetry_drain`` event (rows +
        drain ordinal) so a log proves the drain cadence — the
        ceil(N/K) count the scan-driver CI smoke asserts."""
        if not self._steps or (not force
                               and len(self._steps) < self.every):
            return 0
        count, rows = self.buffer.drain(self.state, self._drained)
        base = self._drained
        emitted = 0
        for j, metrics in rows:
            step = self._steps[j - base]
            self._emit_row(monitor, step, metrics)
            emitted += 1
        self._steps = self._steps[count - base:]
        self._drained = count
        self._drain_count += 1
        ev = getattr(monitor, "event", None)
        if ev is not None:
            ev("telemetry", "telemetry_drain", value=float(emitted),
               step=None, drain=self._drain_count, forced=bool(force))
        return emitted

    def _emit_row(self, monitor, step: int,
                  metrics: Dict[str, float]) -> None:
        self.last_metrics = dict(metrics, step=step)
        for name in ("loss", "grad_norm"):
            if name in metrics:
                monitor.event("metric", name, value=metrics[name],
                              step=step)
        if "loss_scale" in metrics:
            monitor.event("scale", "loss_scale",
                          value=metrics["loss_scale"], step=step,
                          steps_skipped=int(metrics.get(
                              "steps_skipped", 0)),
                          deferred=True)
        overflow = metrics.get("overflow")
        if overflow is not None and overflow > 0.5:
            monitor.event("scale", "overflow", value=1.0, step=step)
        wd = getattr(monitor, "watchdog", None)
        if wd is not None:
            wd.observe_step(step, loss=metrics.get("loss"),
                            overflow=None if overflow is None
                            else overflow > 0.5)


# ---------------------------------------------------------------------------
# On-demand capture
# ---------------------------------------------------------------------------

class CaptureTrigger:
    """Open a profiling window mid-run, on demand.

    Three trigger sources, each opening **exactly one** window per
    firing (re-triggers while a window is open are ignored):

    * file touch — ``trigger_file`` exists at a step boundary (the
      file is consumed);
    * SIGUSR1 (or any ``signum``) — the handler only sets a flag; the
      window opens at the next step boundary (same discipline as
      :class:`apex_tpu.resilience.AutoResume`);
    * auto-capture — :meth:`observe_ratio` requests a window when the
      waterfall's ``wall_device_ratio`` drops below ``ratio_min``
      (once per run by default: the first bad step is the evidence;
      continuous re-capture would *be* host overhead).

    The window is a :class:`apex_tpu.pyprof.ProfileWindow` over
    ``steps`` iterations (injectable ``window_factory`` for tests);
    lifecycle is recorded as ``trace`` events
    (``capture_requested`` / ``capture_started`` / ``capture_stopped``)
    so ``tools/monitor_summary.py`` can index captured traces.
    """

    def __init__(self, logdir: str, *, steps: int = 4,
                 trigger_file: Optional[str] = None,
                 signum: Optional[int] = None,
                 ratio_min: float = 0.0,
                 max_auto_captures: int = 1,
                 window_factory=None, sink: Optional[Sink] = None,
                 timers=None):
        self.logdir = logdir
        self.steps = max(1, int(steps))
        self.trigger_file = trigger_file
        self.ratio_min = float(ratio_min)
        self._max_auto = int(max_auto_captures)
        self._auto_done = 0
        self._sink = sink
        self._timers = timers
        if window_factory is None:
            from ..pyprof.profile import ProfileWindow

            window_factory = ProfileWindow
        self._factory = window_factory
        self._pending: Optional[str] = None  # trigger reason
        self._window = None
        self._window_stop = 0
        self._window_dir: Optional[str] = None
        self.captures = 0
        self._signum = signum
        self._prev_handler = None
        if signum is not None:
            import signal as _signal

            try:
                self._prev_handler = _signal.signal(
                    signum, lambda *_: self.request("signal"))
            except ValueError as e:
                # signal.signal only works on the main thread — a
                # trigger built elsewhere keeps its file/ratio sources
                logger.warning("signal trigger unavailable: %s",
                               str(e)[:120])
                self._signum = None

    def _event(self, name: str, step=None, **attrs) -> None:
        if self._sink is None:
            return
        self._sink.emit(Event(time=time.time(), step=step,
                              kind="trace", name=name, attrs=attrs))

    def request(self, reason: str) -> None:
        """Arm a capture; the window opens at the next ``poll``."""
        if self._pending is None and self._window is None:
            self._pending = reason

    def observe_ratio(self, ratio: Optional[float],
                      step: Optional[int] = None) -> None:
        """Auto-capture hook — wire as the waterfall's ``on_row`` via
        ``lambda row: trigger.observe_ratio(row["wall_device_ratio"],
        row["step"])``."""
        if (self.ratio_min <= 0.0 or ratio is None
                or ratio >= self.ratio_min
                or self._auto_done >= self._max_auto):
            return
        if self._pending is not None or self._window is not None:
            # a capture is already armed/open: the request would be
            # dropped, so the once-per-run budget must not be spent —
            # a later genuine degradation still gets its window
            return
        self._auto_done += 1
        self._event("capture_requested", step=step,
                    reason="wall_device_ratio", ratio=round(ratio, 4),
                    threshold=self.ratio_min)
        self.request("wall_device_ratio")

    def poll(self, iteration: int) -> None:
        """Call once per step boundary: consume triggers, open/step/
        close the window."""
        if (self.trigger_file is not None and self._pending is None
                and self._window is None
                and os.path.exists(self.trigger_file)):
            try:
                os.unlink(self.trigger_file)
            except OSError as e:
                logger.warning("capture trigger file unlink failed: %s",
                               str(e)[:120])
            self._event("capture_requested", step=iteration,
                        reason="file", path=self.trigger_file)
            self.request("file")
        if self._pending is not None and self._window is None:
            reason, self._pending = self._pending, None
            if reason == "signal":
                # the handler only sets the flag (telemetry from a
                # signal context is unsafe); the request event is
                # emitted here, at the step boundary that consumes it,
                # so the requested/opened accounting covers all three
                # trigger sources
                self._event("capture_requested", step=iteration,
                            reason="signal")
            start, stop = iteration, iteration + self.steps
            self._window_dir = os.path.join(
                self.logdir, f"capture_step{start}")
            try:
                self._window = self._factory(
                    self._window_dir, start, stop, timers=self._timers)
                self._window_stop = stop
                self.captures += 1
                self._event("capture_started", step=iteration,
                            reason=reason, trace_dir=self._window_dir,
                            start=start, stop=stop)
            except Exception as e:  # capture must never kill the run
                logger.warning("capture window failed to open: %s",
                               str(e)[:160])
                self._window = None
        if self._window is not None:
            try:
                self._window.step(iteration)
            except Exception as e:
                logger.warning("capture window step failed: %s",
                               str(e)[:160])
                # close the wreck: an abandoned window would leave the
                # global jax.profiler session open, breaking every
                # later capture and charging profiling overhead to the
                # rest of the run
                try:
                    self._window.close()
                except Exception as e2:
                    logger.warning("capture window close after step "
                                   "failure also failed: %s",
                                   str(e2)[:160])
                self._window = None
                self._event("capture_stopped", step=iteration,
                            trace_dir=self._window_dir,
                            error=str(e)[:160])
                return
            if iteration >= self._window_stop:
                self._window = None
                self._event("capture_stopped", step=iteration,
                            trace_dir=self._window_dir)

    def close(self) -> None:
        """Tear down: close an open window, restore the signal
        handler."""
        if self._window is not None:
            try:
                self._window.close()
            except Exception as e:
                logger.warning("capture window close failed: %s",
                               str(e)[:160])
            self._event("capture_stopped", trace_dir=self._window_dir,
                        at_close=True)
            self._window = None
        if self._signum is not None and self._prev_handler is not None:
            import signal as _signal

            _signal.signal(self._signum, self._prev_handler)
            self._prev_handler = None


# ---------------------------------------------------------------------------
# Session bundle — what the drivers wire
# ---------------------------------------------------------------------------

class TraceSession:
    """Tracer + waterfall + optional capture trigger, built together
    so a driver enables the whole attribution story with one object
    (``--trace DIR`` in the smoke drivers).  ``close`` flushes the
    remaining spans into the sink and writes the Chrome artifact
    (``<dir>/trace.chrome.json``, atomic)."""

    def __init__(self, directory: Optional[str] = None, *,
                 tracer: Optional[SpanTracer] = None,
                 capture: Optional[CaptureTrigger] = None,
                 on_row=None, max_spans: int = 250_000):
        self.directory = directory
        self.tracer = tracer or SpanTracer()
        self.capture = capture
        # bound on the session-lifetime span list backing the Chrome
        # artifact — an always-on ambient trace over a long run must
        # not grow host memory without limit (the JSONL events are the
        # complete record; the Chrome file keeps the first max_spans)
        self._max_spans = int(max_spans)
        self._session_dropped = 0

        def _row(row):
            if self.capture is not None:
                self.capture.observe_ratio(row.get("wall_device_ratio"),
                                           row.get("step"))
            if on_row is not None:
                on_row(row)

        self.waterfall = StepWaterfall(self.tracer, on_row=_row)
        self._all_spans: List[Span] = []

    @classmethod
    def from_flags(cls, directory: str, *, sink=None,
                   timers=None) -> "TraceSession":
        """Build from the ``APEX_TPU_TRACE_*`` registry flags.  The
        capture trigger is always armed on a traced run — SIGUSR1
        must open a window (not kill the process via the default
        disposition) whenever tracing is on, as the docs promise; the
        file trigger and the ratio auto-capture additionally engage
        when their flags are set."""
        import signal as _signal

        capture = CaptureTrigger(
            os.path.join(directory, "captures"),
            steps=flag_int("APEX_TPU_TRACE_CAPTURE_STEPS"),
            trigger_file=flag_str("APEX_TPU_TRACE_CAPTURE_FILE"),
            signum=getattr(_signal, "SIGUSR1", None),
            ratio_min=flag_float("APEX_TPU_TRACE_RATIO_MIN"),
            sink=sink, timers=timers)
        return cls(directory, capture=capture)

    def _keep(self, spans: List[Span]) -> None:
        room = self._max_spans - len(self._all_spans)
        if room >= len(spans):
            self._all_spans.extend(spans)
        else:
            if room > 0:
                self._all_spans.extend(spans[:room])
            self._session_dropped += len(spans) - max(room, 0)

    def flush(self, sink, step: Optional[int] = None) -> None:
        """Drain spans into ``sink`` (keeping bounded copies for the
        Chrome artifact) — called from the loop's ``telemetry_drain``
        part."""
        spans = self.tracer.drain()
        self._keep(spans)
        for s in spans:
            if s.step is None and step is not None:
                s = dataclasses.replace(s, step=step)
            sink.emit(s.to_event())

    def close(self, sink=None) -> Optional[str]:
        if sink is not None:
            self.flush(sink)
        else:
            self._keep(self.tracer.drain())
        if self.capture is not None:
            self.capture.close()
        if self._session_dropped:
            logger.warning(
                "chrome artifact truncated: %d span(s) beyond the "
                "%d-span session cap (the JSONL event log is the "
                "complete record)", self._session_dropped,
                self._max_spans)
        if self.directory is None:
            return None
        path = os.path.join(self.directory, "trace.chrome.json")
        try:
            os.makedirs(self.directory, exist_ok=True)
            return write_chrome_trace(
                path, self.tracer.chrome_trace(self._all_spans))
        except OSError as e:
            logger.warning("chrome trace write failed: %s",
                           str(e)[:160])
            return None


# ---------------------------------------------------------------------------
# Trace-smoke checker (tools/ci.sh step 9)
# ---------------------------------------------------------------------------

def check_trace(jsonl_path: str, chrome_path: Optional[str] = None, *,
                tolerance: float = 0.02,
                scan_k: Optional[int] = None,
                steps: Optional[int] = None) -> List[str]:
    """Validate a traced run: canonical spans present, every
    ``step_waterfall`` row's parts sum to ``wall_ms`` within
    ``tolerance``, and (when given) the Chrome artifact parses and
    carries both host spans and the canonical step parts.  Returns a
    list of failure strings (empty = pass).

    Scan mode (``scan_k``): the run used the batched-step driver, so
    each waterfall row covers one K-step window — every row must carry
    ``scan_k`` (== ``scan_k`` except a trailing remainder window), and
    with ``steps`` also given there must be exactly ``ceil(steps /
    scan_k)`` rows whose ``scan_k`` values sum to ``steps``.  The
    parts-sum-to-wall invariant is checked per window exactly as per
    step — amortizing dispatch must not break the attribution
    identity."""
    from .summary import load_events

    failures: List[str] = []
    events, malformed = load_events(jsonl_path)
    if malformed:
        failures.append(f"{malformed} malformed line(s) in {jsonl_path}")
    span_names = {e.name for e in events if e.kind == "span"}
    missing = [p for p in WATERFALL_PARTS if p not in span_names]
    if missing:
        failures.append(f"canonical span(s) missing from the event "
                        f"log: {missing}")
    rows = [e for e in events
            if e.kind == "attr" and e.name == "step_waterfall"]
    if not rows:
        failures.append("no step_waterfall attribution rows")
    for e in rows:
        wall = float(e.value)
        parts = sum(float(v) for k, v in e.attrs.items()
                    if k.endswith("_ms") and isinstance(v, (int, float)))
        if wall > 0 and abs(parts - wall) > tolerance * wall:
            failures.append(
                f"step {e.step}: parts sum {parts:.4f} ms != wall "
                f"{wall:.4f} ms (> {tolerance:.0%})")
    if scan_k is not None:
        ks = [e.attrs.get("scan_k") for e in rows]
        bad = [e.step for e, k in zip(rows, ks)
               if not isinstance(k, int)]
        if bad:
            failures.append(f"scan mode: waterfall row(s) at step(s) "
                            f"{bad} carry no scan_k window size")
        else:
            over = [e.step for e, k in zip(rows, ks) if k > scan_k]
            if over:
                failures.append(
                    f"scan mode: row(s) at step(s) {over} cover more "
                    f"than K={scan_k} steps")
            if steps is not None:
                want_rows = -(-steps // scan_k)  # ceil
                if len(rows) != want_rows or sum(ks) != steps:
                    failures.append(
                        f"scan mode: {len(rows)} window row(s) "
                        f"covering {sum(ks)} step(s) != ceil({steps}/"
                        f"{scan_k}) = {want_rows} windows / {steps} "
                        f"steps")
    if chrome_path is not None:
        try:
            with open(chrome_path) as f:
                trace = json.load(f)
            evs = trace.get("traceEvents", [])
            host = [t for t in evs if t.get("ph") == "X"]
            if not host:
                failures.append(f"{chrome_path}: no complete (X) "
                                "events")
            names = {t.get("name") for t in host}
            miss = [p for p in WATERFALL_PARTS if p not in names]
            if miss:
                failures.append(f"{chrome_path}: canonical part "
                                f"span(s) missing: {miss}")
        except (OSError, json.JSONDecodeError) as e:
            failures.append(f"{chrome_path}: unreadable Chrome trace "
                            f"({e})")
    return failures


def main(argv=None) -> int:
    """CLI: ``python -m apex_tpu.monitor.tracing --check RUN.jsonl
    [--chrome TRACE.json]`` — the CI trace-smoke assertion."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.monitor.tracing",
        description="Validate a traced run's event log and Chrome "
                    "artifact (ci.sh trace smoke).")
    ap.add_argument("jsonl", nargs="+",
                    help="monitor JSONL from a --trace run; with "
                         "--serve, several per-replica fleet logs "
                         "merge into one aggregate check")
    ap.add_argument("--chrome", default=None,
                    help="Chrome trace artifact to validate")
    ap.add_argument("--check", action="store_true",
                    help="(default action) run the validations")
    ap.add_argument("--tolerance", type=float, default=0.02,
                    help="parts-sum-to-wall tolerance (default 0.02)")
    ap.add_argument("--scan-k", type=int, default=None, metavar="K",
                    help="scan-driver run: every waterfall row must be "
                         "a K-step window (parts still sum to wall)")
    ap.add_argument("--steps", type=int, default=None, metavar="N",
                    help="with --scan-k: require ceil(N/K) window "
                         "rows covering exactly N steps")
    ap.add_argument("--serve", action="store_true",
                    help="serving-run mode: validate the per-request "
                         "lifecycle chains (every submitted rid ends "
                         "in exactly one terminal event, TTFT present "
                         "for every non-preempted rid, "
                         "queued+prefill+decode sums to the request "
                         "wall), engine gauges, and the per-request "
                         "Chrome lanes instead of the train-loop "
                         "waterfall")
    args = ap.parse_args(argv)
    if args.serve:
        failures = check_serve_trace(args.jsonl, args.chrome,
                                     tolerance=args.tolerance)
    else:
        if len(args.jsonl) > 1:
            ap.error("multiple JSONL paths are the --serve fleet "
                     "form; the waterfall check takes one run log")
        failures = check_trace(args.jsonl[0], args.chrome,
                               tolerance=args.tolerance,
                               scan_k=args.scan_k, steps=args.steps)
    for f in failures:
        print(f"[trace-check] FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    if args.serve:
        label = args.jsonl[0] if len(args.jsonl) == 1 \
            else f"{len(args.jsonl)} replica logs"
        print(f"[trace-check] OK: {label} "
              "carries complete request lifecycle chains"
              + (f"; {args.chrome} carries the per-request lanes"
                 if args.chrome else ""))
        return 0
    print(f"[trace-check] OK: {args.jsonl[0]} carries the canonical "
          "waterfall"
          + (f" ({-(-args.steps // args.scan_k)} K={args.scan_k} "
             "window(s))" if args.scan_k and args.steps else "")
          + (f"; {args.chrome} parses" if args.chrome else ""))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
