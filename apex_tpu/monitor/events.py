"""Telemetry events and sinks — the base layer of :mod:`apex_tpu.monitor`.

One frozen :class:`Event` record and pluggable :class:`Sink` targets.
The reference ships run observability as disconnected fragments (pyprof's
nvtx->parse->prof pipeline, Megatron ``Timers``, ad-hoc
``print_rank_last`` loss lines); every emitter here — step metrics, amp
scale transitions, watchdog alarms, pipeline phase timers, bench
sections — flows through the same record type into the same sink, so a
killed or stalled run leaves one inspectable log instead of scattered
prints.

:class:`JsonlSink` is crash-safe *by construction*: append-only, one
event per line, flushed per event — every committed line is valid JSON
on its own and there is no end-of-run rewrite to lose (the failure mode
that twice clobbered bench artifacts; see bench.py ``_ArtifactWriter``).
"""
from __future__ import annotations

import dataclasses
import json
import math
import threading
import time
from typing import Any, Dict, List, Mapping, Optional

SCHEMA_VERSION = 1

#: Canonical ``Event.kind`` values (open set — consumers must tolerate
#: unknown kinds):
#:   ``run``     run lifecycle (``run_start`` / ``run_end``)
#:   ``metric``  per-step scalars (loss, grad_norm, lr, step_ms,
#:               tokens_per_sec, mfu, ...)
#:   ``scale``   amp loss-scale state (``loss_scale``, ``overflow``)
#:   ``alarm``   watchdog alarms (``stall``, ``nonfinite_loss``,
#:               ``overflow_streak``) and their ``*_recovered`` pairs
#:   ``timer``   phase times exported from ``Timers.events`` (seconds)
#:   ``span``    host spans from :mod:`apex_tpu.monitor.tracing`
#:               (value = duration seconds; ``attrs.t0``/``tid``/
#:               ``depth`` reconstruct the Chrome timeline)
#:   ``attr``    per-step wall-time attribution rows
#:               (``step_waterfall``: value = wall ms, attrs carry the
#:               per-component ms + ``wall_device_ratio``)
#:   ``trace``   on-demand capture lifecycle (``capture_requested`` /
#:               ``capture_started`` / ``capture_stopped``)
#:   ``section`` bench/driver section lifecycle (``section_start`` /
#:               ``section_done`` / ``section_error``)
#:   ``resilience`` preemption / restart / checkpoint-integrity
#:               lifecycle (``termination_requested``, ``clean_exit``,
#:               ``run_resumed``, ``preempt_exit``, ``attempt_start`` /
#:               ``attempt_error`` / ``attempt_backoff`` /
#:               ``attempt_done`` / ``run_giveup``,
#:               ``escalation_abort``, ``ckpt_skipped`` / ``ckpt_gc``)
#:   ``telemetry`` deferred-telemetry drain bookkeeping
#:               (``telemetry_drain``: rows emitted + drain ordinal)
#:   ``serving`` request lifecycle + engine events from
#:               :mod:`apex_tpu.serving` (``request_submitted`` /
#:               ``request_rejected`` / ``request_admitted`` /
#:               ``request_first_token`` / ``request_done``,
#:               ``decode_step``, ``serve_compile``, ``serve_preempt``,
#:               ``serve_done``, ``engine_snapshot``; resilience:
#:               ``deadline_exceeded``, ``request_shed``,
#:               ``request_replayed``, ``journal_replay``,
#:               ``crash_reset``, ``alloc_rejected``,
#:               ``escalation_drain`` — ``request_done`` carries a
#:               ``terminal`` reason on every path)
#:   ``journal``  serving request-journal records
#:               (serving/resilience.RequestJournal: ``submit`` /
#:               ``progress`` / ``terminal`` / ``replay`` — its OWN
#:               JSONL file, not the run log)
#:   ``serve_tick`` per-tick engine gauges (batch / bucket shape /
#:               free+reserved blocks / queue depth / admissions+
#:               evictions+preemptions this window — the fleet-router
#:               feed, cadence ``APEX_TPU_SERVE_TICK_EVERY``)
#:   ``fleet_tick`` per-router-round fleet aggregation
#:               (:class:`apex_tpu.monitor.export.FleetAggregator`:
#:               summed queue depth / free-blocks-net / backlog, token
#:               and compile deltas over MEASURED per-replica engine
#:               ticks — the ``ticks`` attr is the rate denominator,
#:               never the nominal cadence — plus slope/EWMA trends)
#:   ``slo``      SLO bookkeeping from :mod:`apex_tpu.serving.metrics`
#:               (``slo_objectives`` — the objective definitions every
#:               ``slo_burn`` alarm must trace back to — and
#:               ``slo_recovered`` episode-clear records; the burn
#:               itself is kind ``alarm`` name ``slo_burn``, routed
#:               through the watchdog so escalation hooks see it)
#:   ``metrics``  exporter lifecycle (``metrics_server_started`` /
#:               ``metrics_server_stopped`` — trace_check pairs them)
KINDS = ("run", "metric", "scale", "alarm", "timer", "span", "attr",
         "trace", "section", "resilience", "telemetry", "serving",
         "serve_tick", "fleet_tick", "slo", "metrics")


def _jsonable(v: Any) -> Any:
    """Coerce device scalars / numpy types to plain JSON values.
    Mappings and sequences recurse, so a structured attr (the serving
    ``engine_snapshot`` request list, rejection-reason counts) lands
    as real JSON instead of a ``str()`` blob."""
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, float):
        # bare NaN/Infinity is not valid JSON; encode as a string so
        # every committed line parses everywhere
        return v if math.isfinite(v) else str(v)
    if isinstance(v, Mapping):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        f = float(v)
        return f if math.isfinite(f) else str(f)
    except (TypeError, ValueError):
        return str(v)


@dataclasses.dataclass(frozen=True)
class Event:
    """One telemetry record.

    ``value`` carries the single scalar most consumers want; anything
    richer rides ``attrs``.  ``time`` is host wall-clock (epoch
    seconds); ``step`` is the training step, ``None`` for run-level
    events.
    """

    time: float
    step: Optional[int]
    kind: str
    name: str
    value: Optional[float] = None
    attrs: Mapping[str, Any] = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        d: Dict[str, Any] = {
            "time": round(float(self.time), 6),
            "step": None if self.step is None else int(self.step),
            "kind": self.kind,
            "name": self.name,
            "value": _jsonable(self.value),
        }
        if self.attrs:
            d["attrs"] = {str(k): _jsonable(v)
                          for k, v in self.attrs.items()}
        return json.dumps(d, separators=(",", ":"))

    @staticmethod
    def from_json(line: str) -> "Event":
        d = json.loads(line)
        return Event(time=float(d["time"]),
                     step=d.get("step"),
                     kind=d["kind"],
                     name=d["name"],
                     value=d.get("value"),
                     attrs=d.get("attrs") or {})


def terminal_reason(attrs: Mapping[str, Any]) -> str:
    """The terminal reason of a serving ``request_done`` event's
    attrs: the ``terminal`` attr when present (finished / preempted /
    deadline / deadline_exceeded / shed), else the pre-ISSUE-13
    fallback on the ``preempted`` flag — ONE implementation shared by
    every consumer (summary digest, ``trace_check --serve``) so they
    cannot disagree about the same event."""
    return str(attrs.get("terminal")
               or ("preempted" if attrs.get("preempted")
                   else "finished"))


def emit_resilience(sink, name: str, *, value=None,
                    step: Optional[int] = None, clock=time.time,
                    **attrs) -> None:
    """Emit one ``resilience``-kind event into ``sink`` (no-op when
    ``sink`` is None) — the single construction point shared by
    :mod:`apex_tpu.resilience` and the checkpoint-integrity layer, so
    the record shape cannot drift between emitters."""
    if sink is None:
        return
    sink.emit(Event(time=clock(), step=step, kind="resilience",
                    name=name, value=value, attrs=attrs))


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------

class Sink:
    """Where events go.  Implementations must be cheap per event and
    must never raise out of ``emit`` into the training loop."""

    def emit(self, event: Event) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class MemorySink(Sink):
    """In-process event list — the test double."""

    def __init__(self):
        self.events: List[Event] = []

    def emit(self, event: Event) -> None:
        self.events.append(event)

    def by_kind(self, kind: str) -> List[Event]:
        return [e for e in self.events if e.kind == kind]

    def by_name(self, name: str) -> List[Event]:
        return [e for e in self.events if e.name == name]


class JsonlSink(Sink):
    """Append-only JSONL file, one event per line, flushed per line.

    Crash-safe by construction: a kill at any instant leaves a file
    whose every complete line is independently valid JSON (at worst one
    truncated trailing line, which :func:`~apex_tpu.monitor.summary.
    load_events` tolerates).  There is deliberately no buffering and no
    end-of-run rewrite.
    """

    def __init__(self, path: str, append: bool = True):
        self.path = path
        self._f = open(path, "a" if append else "w")
        self._lock = threading.Lock()

    def emit(self, event: Event) -> None:
        line = event.to_json()
        with self._lock:
            if self._f is None:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def flush(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


class TeeSink(Sink):
    """Fan one event stream out to several sinks."""

    def __init__(self, *sinks: Sink):
        self.sinks = list(sinks)

    def emit(self, event: Event) -> None:
        for s in self.sinks:
            s.emit(event)

    def flush(self) -> None:
        for s in self.sinks:
            s.flush()

    def close(self) -> None:
        for s in self.sinks:
            s.close()


class WriterSink(Sink):
    """Adapter: forward scalar-valued events to any TensorBoard-like
    object exposing ``add_scalar(tag, value, global_step)`` — an
    existing summary writer plugs into the monitor unchanged."""

    def __init__(self, writer: Any):
        self.writer = writer

    def emit(self, event: Event) -> None:
        if event.value is None or isinstance(event.value, str):
            return
        self.writer.add_scalar(f"{event.kind}/{event.name}",
                               float(event.value),
                               0 if event.step is None else event.step)


class BackgroundThreadError(RuntimeError):
    """A background thread died with an uncaught exception — surfaced
    by :class:`ThreadExceptionCapture` instead of vanishing into
    stderr."""


class ThreadExceptionCapture:
    """``threading.excepthook`` wiring: an uncaught exception in a
    background thread becomes a terminal ``run_error`` monitor event
    and a raisable failure, instead of a traceback on stderr and a
    silently dead thread (the default — a crashed watchdog heartbeat
    or fleet replica thread used to leave no machine-readable record
    and fail no test).

    ``target`` is anything with either the ``StepMonitor.event``
    signature or the :class:`Sink` ``emit`` one (or ``None``: record
    only — the conftest fixture reads ``failures`` at teardown).  The
    hook appends one record per crash (a single list append — no
    torn state to lock) and, with ``chain=True`` (the default),
    chains to the previously installed hook so the stderr traceback
    is not lost (``chain=False`` swallows it — for tests that crash
    threads on purpose and assert on the capture).  ``raise_first()``
    re-raises the first crash wrapped in
    :class:`BackgroundThreadError`; call it after join/teardown so a
    run whose main loop succeeded still fails when a thread it owned
    died.
    """

    def __init__(self, target: Any = None, *, clock=time.time,
                 chain: bool = True,
                 attrs: Optional[Dict[str, Any]] = None):
        self._target = target
        self._clock = clock
        self._chain = bool(chain)
        # merged into every emitted run_error's attrs — e.g. the
        # fleet driver stamps replica="fleet" so a crash logged
        # through one replica's sink is not misattributed to it
        self._attrs = dict(attrs or {})
        self._prev = None
        self._installed = False
        self.failures: List[Dict[str, Any]] = []

    def install(self) -> "ThreadExceptionCapture":
        if self._installed:
            return self
        self._prev = threading.excepthook
        threading.excepthook = self._hook
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.excepthook = self._prev
        self._prev = None
        self._installed = False

    def _hook(self, args) -> None:
        record = {
            "thread": getattr(args.thread, "name", None) or "?",
            "error": getattr(args.exc_type, "__name__",
                             str(args.exc_type)),
            "message": str(args.exc_value)[:200],
            "background": True,
            "exception": args.exc_value,
        }
        self.failures.append(record)
        try:
            self._emit(record)
        except Exception:  # apex-lint: disable=APX202 -- the hook runs on a dying thread; a sink failure here must not mask the original crash (recorded above)
            pass
        if self._chain:
            prev = self._prev or threading.__excepthook__
            prev(args)

    def _emit(self, record: Dict[str, Any]) -> None:
        t = self._target
        if t is None:
            return
        attrs = {k: v for k, v in record.items() if k != "exception"}
        attrs.update(self._attrs)
        ev = getattr(t, "event", None)
        if callable(ev):
            ev("run", "run_error", **attrs)
        else:
            t.emit(Event(time=self._clock(), step=None, kind="run",
                         name="run_error", attrs=attrs))

    def raise_first(self) -> None:
        """Raise :class:`BackgroundThreadError` for the first captured
        crash (no-op when every thread exited clean)."""
        if not self.failures:
            return
        rec = self.failures[0]
        raise BackgroundThreadError(
            f"background thread {rec['thread']!r} died: "
            f"{rec['error']}: {rec['message']}"
            + (f" (+{len(self.failures) - 1} more)"
               if len(self.failures) > 1 else "")
        ) from rec.get("exception")

    def __enter__(self) -> "ThreadExceptionCapture":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


class ScalarWriter:
    """The inverse adapter: an ``add_scalar``-style facade over a sink,
    so ``Timers.write(names, writer, iteration)``
    (apex_tpu/transformer/pipeline_parallel/utils.py) and any other
    add_scalar caller emits :class:`Event` s without modification."""

    def __init__(self, sink: Sink, kind: str = "timer",
                 clock=time.time):
        self.sink = sink
        self.kind = kind
        self._clock = clock

    def add_scalar(self, name: str, value: float,
                   global_step: Optional[int] = None) -> None:
        self.sink.emit(Event(time=self._clock(),
                             step=None if global_step is None
                             else int(global_step),
                             kind=self.kind, name=str(name),
                             value=float(value)))
