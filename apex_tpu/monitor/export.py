"""Live metrics plane: OpenMetrics export of the serving telemetry.

Everything the stack measures today terminates in the append-only
JSONL — legible only *after* the run, through ``monitor_summary``.
ROADMAP item 3 (router state over RPC, autoscaling from queue-depth /
pool trends, per-class SLO gating) needs the same signals live.  This
module is that plane's generic half — no serving imports, so the
monitor layer stays below :mod:`apex_tpu.serving`:

* :class:`MetricsRegistry` — counter / gauge / histogram families
  with label sets, rendered in the Prometheus text exposition format
  (version 0.0.4: ``# HELP`` / ``# TYPE`` headers, sorted label
  pairs, cumulative ``le`` histogram buckets with ``+Inf``).  The
  registry is an *adapter target*: the serving side builds one per
  publish from bookkeeping it already holds
  (``EngineGauges.router_snapshot()``, :class:`~apex_tpu.serving.
  metrics.ServeMetrics` distributions, watchdog episode counters) —
  no second bookkeeping path, and the one-fetch-per-tick device
  budget is untouched.
* :class:`MetricsExporter` — the lock-free hand-off between the
  engine tick and the scrape side: the publisher swaps ONE immutable
  :class:`PublishedState` reference per tick (a single attribute
  store, atomic under the GIL — no lock anywhere on the tick path),
  and every scrape renders from whatever reference it loaded,
  stamping how stale that snapshot is.  A scrape can therefore never
  block an engine tick, by construction.
* :class:`MetricsServer` — a stdlib ``http.server`` daemon thread
  exposing ``/metrics`` (exposition text), ``/healthz``
  (drain/shed/escalation/SLO-aware status, 200/503), and ``/varz``
  (the ``engine.snapshot_state()`` JSON — the same payload the
  SIGUSR1 :class:`~apex_tpu.serving.metrics.SnapshotTrigger` dumps).
  Handlers only read the exporter's published state; they never call
  into the engine.  Lifecycle events
  (``metrics_server_started`` / ``metrics_server_stopped``) pair up
  in the JSONL (``trace_check --serve`` asserts it).
* :class:`FleetAggregator` — merges N per-replica
  ``router_snapshot()`` dicts into fleet-level series held in
  bounded host rings (queue depth, free blocks net of reservations,
  backlog, tokens/tick, compile deltas) with windowed trends (least-
  squares slope + EWMA per series) — the autoscaling signal feed,
  emitted as one ``fleet_tick`` event per router round.  Rate math
  divides by the *measured* engine-tick delta stamped on the event
  (``ticks``), never by a nominal cadence.
* :func:`registry_from_serve_events` — rebuilds the exporter's
  counter/gauge state from a serve JSONL, proving the log stays the
  complete source of truth (property-tested in
  tests/test_monitor_export.py).

Worked example + healthz semantics table: docs/api/observability.md.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Iterable, List, Optional, \
    Sequence, Tuple

from ..utils.log_util import get_logger

logger = get_logger(__name__)

__all__ = ["MetricsRegistry", "MetricsExporter", "MetricsServer",
           "PublishedState", "FleetAggregator",
           "registry_from_serve_events", "replica_metrics_port"]


def replica_metrics_port(base: int, index: int) -> int:
    """The multi-replica metrics-port layout (ISSUE-18): the BASE
    port belongs to the supervisor's aggregated fleet view, replica
    ``k`` binds ``base + 1 + k``.  One flag
    (``APEX_TPU_METRICS_PORT``), N+1 non-colliding servers — the
    second-bind EADDRINUSE this replaces is a regression test."""
    if int(base) <= 0:
        raise ValueError(f"replica_metrics_port needs a real base "
                         f"port, got {base}")
    if int(index) < 0:
        raise ValueError(f"replica index must be >= 0, got {index}")
    return int(base) + 1 + int(index)

# metric-name prefix every serving series uses (the exposition
# convention: one namespace per exporter)
NAMESPACE = "apex_tpu"

# default histogram bucket bounds (milliseconds) for latency series
DEFAULT_MS_BUCKETS = (1.0, 2.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0)


def _fmt(v: float) -> str:
    """Prometheus sample-value formatting: integral floats print as
    integers (``3`` not ``3.0``) so goldens stay stable."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(v: Any) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_key(labels: Dict[str, Any]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class _Family:
    """One metric family: a name, a TYPE, and its labeled samples."""

    def __init__(self, name: str, kind: str, help_text: str):
        self.name = name
        self.kind = kind
        self.help = help_text
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        self._values[key] = self._values.get(key, 0.0) + float(value)

    def set(self, value: float, **labels) -> None:
        """Store an absolute value.  Legal on counters too: the
        serving adapters *mirror* cumulative counters the engine
        already keeps, they do not re-count."""
        self._values[_label_key(labels)] = float(value)

    def get(self, **labels) -> Optional[float]:
        return self._values.get(_label_key(labels))

    def samples(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        return dict(self._values)

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key in sorted(self._values):
            lines.append(f"{self.name}{_render_labels(key)} "
                         f"{_fmt(self._values[key])}")
        return lines


class _Histogram(_Family):
    """Cumulative-bucket histogram family (``le`` + ``+Inf``, plus
    ``_sum`` / ``_count``), the exposition-format shape scrapers
    expect for latency series."""

    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float] = DEFAULT_MS_BUCKETS):
        super().__init__(name, "histogram", help_text)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # label key -> [per-bucket counts..., +Inf count]
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._sums: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        counts = self._counts.setdefault(
            key, [0] * (len(self.buckets) + 1))
        v = float(value)
        for i, b in enumerate(self.buckets):
            if v <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        self._sums[key] = self._sums.get(key, 0.0) + v

    def samples(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        return {key: float(sum(counts))
                for key, counts in self._counts.items()}

    def render(self) -> List[str]:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.kind}"]
        for key in sorted(self._counts):
            counts = self._counts[key]
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                bkey = key + (("le", _fmt(b)),)
                lines.append(f"{self.name}_bucket"
                             f"{_render_labels(bkey)} {cum}")
            cum += counts[-1]
            ikey = key + (("le", "+Inf"),)
            lines.append(f"{self.name}_bucket{_render_labels(ikey)} "
                         f"{cum}")
            lines.append(f"{self.name}_sum{_render_labels(key)} "
                         f"{_fmt(self._sums.get(key, 0.0))}")
            lines.append(f"{self.name}_count{_render_labels(key)} "
                         f"{cum}")
        return lines


class MetricsRegistry:
    """A set of metric families rendered as one exposition document.

    Registration is idempotent by name (re-registering returns the
    existing family; a kind mismatch raises — one name, one TYPE, as
    the format requires).  The serving adapters build a FRESH registry
    per publish from state the engine already holds, then hand it to
    :meth:`MetricsExporter.publish` — after the swap nobody mutates
    it, which is what makes the scrape side lock-free."""

    def __init__(self):
        self._families: Dict[str, _Family] = {}

    def _register(self, name: str, kind: str, help_text: str,
                  factory: Callable[[], _Family]) -> _Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{fam.kind}, not {kind}")
            return fam
        fam = factory()
        self._families[name] = fam
        return fam

    def counter(self, name: str, help_text: str) -> _Family:
        return self._register(name, "counter", help_text,
                              lambda: _Family(name, "counter",
                                              help_text))

    def gauge(self, name: str, help_text: str) -> _Family:
        return self._register(name, "gauge", help_text,
                              lambda: _Family(name, "gauge",
                                              help_text))

    def histogram(self, name: str, help_text: str,
                  buckets: Sequence[float] = DEFAULT_MS_BUCKETS
                  ) -> _Histogram:
        return self._register(
            name, "histogram", help_text,
            lambda: _Histogram(name, help_text, buckets))

    def families(self) -> List[_Family]:
        return [self._families[n] for n in sorted(self._families)]

    def samples(self) -> Dict[str,
                              Dict[Tuple[Tuple[str, str], ...], float]]:
        """``{family name: {label key: value}}`` — the comparable
        state the reconstruction property test diffs (histograms
        collapse to their total observation count)."""
        return {name: fam.samples()
                for name, fam in sorted(self._families.items())}

    def render(self) -> str:
        lines: List[str] = []
        for fam in self.families():
            lines.extend(fam.render())
        return "\n".join(lines) + ("\n" if lines else "")


class PublishedState:
    """One immutable publish: the rendered exposition text plus the
    health and varz payloads, all frozen at the same engine tick.
    The exporter swaps a reference to one of these per tick; scrape
    handlers read whichever reference they loaded — torn reads are
    impossible because nothing here mutates after construction."""

    __slots__ = ("wall", "tick", "text", "health", "varz", "seq")

    def __init__(self, wall: float, tick: Optional[int], text: str,
                 health: Dict[str, Any], varz: Dict[str, Any],
                 seq: int):
        self.wall = wall
        self.tick = tick
        self.text = text
        self.health = health
        self.varz = varz
        self.seq = seq


class MetricsExporter:
    """Lock-free publish/scrape hand-off (single writer: the engine
    or router tick; any number of readers: the HTTP handler threads).

    ``publish`` renders the registry ON the publishing side (host
    string work, no device traffic) and stores one
    :class:`PublishedState`; ``render``/``healthz``/``varz`` serve
    from the last stored state and stamp its staleness — the scrape
    path does no work proportional to the serve and can never stall
    a tick."""

    def __init__(self, *, wall_clock: Callable[[], float] = time.time):
        self._wall = wall_clock
        self._state: Optional[PublishedState] = None
        self.publishes = 0

    def publish(self, registry: MetricsRegistry, *,
                tick: Optional[int] = None,
                health: Optional[Dict[str, Any]] = None,
                varz: Optional[Dict[str, Any]] = None) -> None:
        seq = self.publishes + 1
        state = PublishedState(self._wall(), tick, registry.render(),
                               dict(health or {"ok": True,
                                               "status": "ok"}),
                               dict(varz or {}), seq)
        # the swap: one attribute store, atomic under the GIL — the
        # whole synchronization story (no lock to rank for APX802,
        # nothing blocking to hold for APX804)
        self._state = state
        self.publishes = seq

    @property
    def state(self) -> Optional[PublishedState]:
        return self._state

    def staleness_s(self, state: Optional[PublishedState] = None
                    ) -> float:
        st = state if state is not None else self._state
        if st is None:
            return 0.0
        return max(0.0, self._wall() - st.wall)

    def render(self) -> str:
        st = self._state
        stale = self.staleness_s(st)
        tail = [
            "# HELP apex_tpu_exporter_staleness_seconds Seconds since"
            " the serving side last published a snapshot.",
            "# TYPE apex_tpu_exporter_staleness_seconds gauge",
            f"apex_tpu_exporter_staleness_seconds {stale:.6f}",
            "# HELP apex_tpu_exporter_publishes_total Snapshot"
            " publishes since exporter start.",
            "# TYPE apex_tpu_exporter_publishes_total counter",
            f"apex_tpu_exporter_publishes_total "
            f"{st.seq if st is not None else 0}",
        ]
        body = st.text if st is not None else ""
        return body + "\n".join(tail) + "\n"

    def healthz(self) -> Tuple[bool, Dict[str, Any]]:
        st = self._state
        if st is None:
            return True, {"ok": True, "status": "starting",
                          "staleness_s": 0.0}
        payload = dict(st.health)
        payload["staleness_s"] = round(self.staleness_s(st), 6)
        payload.setdefault("tick", st.tick)
        return bool(payload.get("ok", True)), payload

    def varz(self) -> Dict[str, Any]:
        st = self._state
        return dict(st.varz) if st is not None else {}


class _Handler(BaseHTTPRequestHandler):
    """Scrape handler: every route serves from the exporter's last
    published state — it never calls into the engine."""

    # set by MetricsServer when the handler class is specialized
    exporter: MetricsExporter = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"

    def _reply(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        st = self.exporter.state
        self.send_header("X-Apex-Staleness-Seconds",
                         f"{self.exporter.staleness_s(st):.6f}")
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib handler API)
        path = self.path.split("?", 1)[0]
        if path == "/metrics":
            self._reply(200, self.exporter.render().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/healthz":
            ok, payload = self.exporter.healthz()
            self._reply(200 if ok else 503,
                        (json.dumps(payload, sort_keys=True)
                         + "\n").encode(), "application/json")
        elif path == "/varz":
            self._reply(200, (json.dumps(self.exporter.varz(),
                                         sort_keys=True, default=str)
                              + "\n").encode(), "application/json")
        else:
            self._reply(404, b'{"error": "not found"}\n',
                        "application/json")

    def log_message(self, fmt: str, *args: Any) -> None:
        # scrape chatter must not pollute the driver's stdout (the CI
        # smoke greps it); route through the module logger at debug
        logger.debug("metrics http: " + fmt, *args)


class MetricsServer:
    """The ``/metrics`` + ``/healthz`` + ``/varz`` daemon.

    One stdlib :class:`ThreadingHTTPServer` on a daemon thread; per-
    request handler threads are stdlib-managed daemons too.  Handlers
    read only the exporter's published state, so no new lock is
    introduced anywhere (the APX801–805 auditor stays empty-baseline)
    and a slow scraper can never back-pressure the serve.  ``port=0``
    binds an ephemeral port (tests); :attr:`port` reports the real
    one after :meth:`start`.  Start/stop emit paired
    ``metrics_server_started`` / ``metrics_server_stopped`` events
    through the monitor so the JSONL records the exporter's uptime
    window (``trace_check --serve`` pairs them up)."""

    def __init__(self, exporter: MetricsExporter, *, port: int = 0,
                 host: str = "127.0.0.1", monitor=None):
        self.exporter = exporter
        self.host = host
        self._requested_port = int(port)
        self.monitor = monitor
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._server is not None:
            return int(self._server.server_address[1])
        return self._requested_port

    def url(self, path: str = "/metrics") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def _event(self, name: str, **attrs) -> None:
        if self.monitor is not None:
            self.monitor.event("metrics", name, **attrs)

    def start(self) -> int:
        if self._server is not None:
            return self.port
        handler = type("_BoundHandler", (_Handler,),
                       {"exporter": self.exporter})
        try:
            self._server = ThreadingHTTPServer(
                (self.host, self._requested_port), handler)
        except OSError as e:
            # the multi-replica foot-gun (ISSUE-18): one
            # APEX_TPU_METRICS_PORT flag, N replicas each trying to
            # bind it — the second bind used to die with a bare
            # EADDRINUSE traceback deep in socketserver.  Name the
            # port-assignment contract in the error instead.
            raise OSError(
                e.errno,
                f"MetricsServer could not bind "
                f"{self.host}:{self._requested_port}: {e.strerror}. "
                f"One port serves ONE exporter; a multi-replica host "
                f"gives each replica its own port "
                f"(replica_metrics_port(base, k) = base + 1 + k, the "
                f"process-fleet supervisor's layout — the base port "
                f"carries the aggregated fleet view) or binds "
                f"ephemeral with port=0.") from e
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="apex_tpu-metrics-server", daemon=True)
        self._thread.start()
        self._event("metrics_server_started", port=self.port,
                    host=self.host)
        return self.port

    def stop(self) -> None:
        if self._server is None:
            return
        port = self.port
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._server = None
        self._thread = None
        self._event("metrics_server_stopped", port=port)


# ---------------------------------------------------------------------------
# Fleet-level aggregation + trends
# ---------------------------------------------------------------------------

def _slope(points: Iterable[Tuple[float, float]]) -> float:
    """Least-squares slope of value over tick — the trend an
    autoscaler thresholds on.  0.0 until two distinct ticks exist."""
    pts = list(points)
    if len(pts) < 2:
        return 0.0
    n = float(len(pts))
    sx = sum(p[0] for p in pts)
    sy = sum(p[1] for p in pts)
    sxx = sum(p[0] * p[0] for p in pts)
    sxy = sum(p[0] * p[1] for p in pts)
    denom = n * sxx - sx * sx
    if denom <= 0.0:
        return 0.0
    return (n * sxy - sx * sy) / denom


class FleetAggregator:
    """Merge N per-replica ``router_snapshot()`` dicts into fleet
    series with windowed trends — ROADMAP item 3's autoscaling feed.

    Bounded host rings (``deque(maxlen=window)``) per series; one
    :meth:`observe` per router round computes the fleet sums, the
    per-series least-squares slope over the ring, and an EWMA.  Rate
    series (tokens, compiles) are deltas of the cumulative per-
    replica counters divided by the MEASURED engine-tick delta since
    the previous observe (stamped as ``ticks`` on the ``fleet_tick``
    event) — never by a nominal cadence, so a short trailing window
    or a swap-drain gap cannot skew the rate.  Single-writer (the
    router's drive loop); readers consume the emitted event or the
    exporter's published snapshot — no locks."""

    SERIES = ("queue_depth", "free_blocks_net", "backlog",
              "tokens_per_tick", "compiles_per_tick")

    def __init__(self, *, window: int = 64, ewma_alpha: float = 0.25):
        self.window = max(2, int(window))
        self.ewma_alpha = float(ewma_alpha)
        self._rings: Dict[str, deque] = {
            s: deque(maxlen=self.window) for s in self.SERIES}
        self._ewma: Dict[str, float] = {}
        # per-replica cumulative marks for delta series
        self._prev_tokens: Dict[str, int] = {}
        self._prev_compiles: Dict[str, int] = {}
        self._prev_ticks: Dict[str, int] = {}
        self.observations = 0

    def _delta(self, marks: Dict[str, int], rid: str,
               value: int) -> int:
        prev = marks.get(rid)
        marks[rid] = value
        if prev is None or value < prev:   # fresh replica / reset
            return 0
        return value - prev

    def observe(self, tick: int,
                snapshots: Dict[str, Dict[str, Any]]
                ) -> Dict[str, Any]:
        """Fold one round of per-replica snapshots; returns the
        ``fleet_tick`` event attrs (fleet levels + flattened
        ``slope_*`` / ``ewma_*`` trend keys + the true ``ticks``
        denominator)."""
        queue_depth = 0
        free_net = 0
        backlog = 0
        tokens_d = 0
        compiles_d = 0
        ticks_d = 0
        for rid, snap in sorted(snapshots.items()):
            queue_depth += int(snap.get("queue_depth", 0))
            free_net += (int(snap.get("available_blocks",
                                      snap.get("free_blocks", 0)))
                         - int(snap.get("reserved_blocks", 0)))
            backlog += (int(snap.get("queue_depth", 0))
                        + int(snap.get("prefilling", 0))
                        + int(snap.get("active", 0)))
            tokens_d += self._delta(
                self._prev_tokens, rid,
                int(snap.get("tokens_generated", 0)))
            compiles_d += self._delta(
                self._prev_compiles, rid,
                int(snap.get("compiles", 0)))
            ticks_d += self._delta(self._prev_ticks, rid,
                                   int(snap.get("tick", 0)))
        ticks = max(1, ticks_d)
        levels = {
            "queue_depth": float(queue_depth),
            "free_blocks_net": float(free_net),
            "backlog": float(backlog),
            "tokens_per_tick": tokens_d / ticks,
            "compiles_per_tick": compiles_d / ticks,
        }
        attrs: Dict[str, Any] = {
            "replicas": len(snapshots),
            "ticks": ticks_d,
            "queue_depth": queue_depth,
            "free_blocks_net": free_net,
            "backlog": backlog,
            "new_tokens": tokens_d,
            "new_compiles": compiles_d,
        }
        for name, v in levels.items():
            ring = self._rings[name]
            ring.append((float(tick), v))
            prev = self._ewma.get(name)
            self._ewma[name] = v if prev is None else (
                self.ewma_alpha * v + (1.0 - self.ewma_alpha) * prev)
            attrs[f"slope_{name}"] = round(_slope(ring), 6)
            attrs[f"ewma_{name}"] = round(self._ewma[name], 6)
        self.observations += 1
        return attrs

    def trends(self) -> Dict[str, Dict[str, float]]:
        """Current ``{series: {slope, ewma, n}}`` view (the exporter
        gauge source)."""
        out: Dict[str, Dict[str, float]] = {}
        for name in self.SERIES:
            ring = self._rings[name]
            out[name] = {
                "slope": round(_slope(ring), 6),
                "ewma": round(self._ewma.get(name, 0.0), 6),
                "n": float(len(ring)),
            }
        return out


# ---------------------------------------------------------------------------
# JSONL -> exporter-state reconstruction (source-of-truth proof)
# ---------------------------------------------------------------------------

def registry_from_serve_events(events: Sequence[Any],
                               ) -> MetricsRegistry:
    """Rebuild the exporter's counter/gauge state from a serve JSONL.

    The exporter is a VIEW over the event log, never a second ledger:
    every counter it publishes is recomputable from the ``serving`` /
    ``serve_tick`` / ``alarm`` events alone.  This function is that
    recomputation — the property test runs a serve with both paths
    live and asserts the sample dicts match exactly.  ``events`` are
    :class:`~apex_tpu.monitor.events.Event` objects (or anything with
    ``kind`` / ``name`` / ``step`` / ``attrs``), e.g. from
    :func:`~apex_tpu.monitor.summary.load_events`."""
    reg = MetricsRegistry()
    requests = reg.counter(
        "apex_tpu_serve_requests_total",
        "Terminal requests by terminal reason.")
    tokens = reg.counter(
        "apex_tpu_serve_tokens_total",
        "Generated tokens over terminal requests.")
    rejected = reg.counter(
        "apex_tpu_serve_rejected_total",
        "Submits the engine refused, by reason.")
    burns = reg.counter(
        "apex_tpu_slo_burn_episodes_total",
        "SLO burn-rate episodes by priority class and dimension.")
    last_tick: Dict[str, Dict[str, Any]] = {}
    for e in events:
        attrs = getattr(e, "attrs", None) or {}
        replica = attrs.get("replica")
        lbl = {"replica": replica} if replica is not None else {}
        if e.kind == "serving" and e.name == "request_done":
            requests.inc(1.0, terminal=attrs.get("terminal",
                                                 "finished"), **lbl)
            tokens.inc(float(attrs.get("new_tokens", 0)), **lbl)
        elif e.kind == "serving" and e.name == "request_rejected":
            rejected.inc(1.0, reason=attrs.get("reason", "unknown"),
                         **lbl)
        elif e.kind == "alarm" and e.name == "slo_burn":
            burns.inc(
                1.0,
                priority_class=attrs.get("priority_class", "*"),
                dimension=attrs.get("dimension", "unknown"))
        elif e.kind == "serve_tick":
            key = replica if replica is not None else ""
            last_tick[key] = dict(attrs, _step=e.step)
    for key, attrs in sorted(last_tick.items()):
        lbl = {"replica": key} if key else {}
        g = reg.gauge("apex_tpu_serve_queue_depth",
                      "Admission queue depth at the last tick.")
        g.set(float(attrs.get("queue_depth", 0)), **lbl)
        g = reg.gauge("apex_tpu_serve_free_blocks",
                      "Free KV pool blocks at the last tick.")
        g.set(float(attrs.get("free_blocks", 0)), **lbl)
        g = reg.gauge("apex_tpu_serve_pool_blocks",
                      "Usable KV pool blocks.")
        g.set(float(attrs.get("pool_blocks", 0)), **lbl)
        g = reg.gauge("apex_tpu_serve_tick",
                      "Engine tick of the last gauge window.")
        g.set(float(attrs.get("last_tick", attrs.get("_step") or 0)),
              **lbl)
        c = reg.counter("apex_tpu_serve_compiles_total",
                        "Cumulative compiled-program count.")
        c.set(float(attrs.get("compiles", 0)), **lbl)
    return reg
