"""apex_tpu.monitor — structured run telemetry.

The run-health spine the reference never had: its observability ships as
three disconnected pieces (pyprof's nvtx->parse->prof device-time
pipeline, Megatron-style ``Timers``, ad-hoc ``print_rank_last`` loss
lines).  This package gives drivers, amp, the pipeline schedules, and
bench.py ONE structured emission path, in three layers:

1. **Events + sinks** (:mod:`.events`) — a frozen :class:`Event` record
   (``time``, ``step``, ``kind``, ``name``, ``value``, ``attrs``) with
   pluggable sinks: :class:`JsonlSink` (append-only, one valid JSON line
   per event, crash-safe by construction), :class:`MemorySink` (tests),
   :class:`TeeSink`, plus adapters bridging the ``add_scalar`` world in
   both directions (:class:`ScalarWriter` lets ``Timers.write`` target a
   sink unchanged; :class:`WriterSink` forwards events to any
   TensorBoard-like writer).

2. **StepMonitor** (:mod:`.step_monitor`) — per-step recorder computing
   run-health metrics host-side (loss, grad-norm, lr, amp loss-scale /
   overflow via :func:`apex_tpu.amp.scaler.update_telemetry`, tokens/s,
   step wall ms, MFU against :func:`apex_tpu.pyprof.prof.device_spec`)
   with a :class:`Watchdog` (:mod:`.watchdog`) raising once-per-episode
   alarms on non-finite loss, overflow streaks, and wall-clock stalls
   (heartbeat thread; optional ``jax.profiler`` dump of a wedged step).

3. **Summary** (:mod:`.summary`) — parse a JSONL run back into a
   throughput / overflow / phase-time / alarm digest
   (``tools/monitor_summary.py`` is the CLI).

4. **Tracing** (:mod:`.tracing`) — the host side of the wall clock:
   :class:`SpanTracer` spans (Chrome-trace/Perfetto export),
   :class:`StepWaterfall` per-step wall attribution
   (``wall_ms = data_load + dispatch + device_compute +
   telemetry_drain + ckpt_io + other``, ``wall_device_ratio``),
   :class:`DeviceMetricsBuffer`/:class:`DeferredTelemetry` sync-free
   deferred metrics (zero per-step host transfers), and
   :class:`CaptureTrigger` on-demand profiling windows.

5. **Export** (:mod:`.export`) — the live half (ISSUE-17): an
   OpenMetrics :class:`MetricsRegistry` rendered in Prometheus text
   exposition format, the lock-free :class:`MetricsExporter`
   publish/scrape hand-off, the :class:`MetricsServer`
   (``/metrics`` + ``/healthz`` + ``/varz`` on a stdlib daemon
   thread), the :class:`FleetAggregator` trend rings, and
   :func:`registry_from_serve_events` proving the JSONL stays the
   complete source of truth.

When to reach for what: ``monitor`` = run health over time; ``pyprof`` =
where device time went; ``Timers`` = phase wall times (and they export
into the monitor log via ``Timers.events``).  Full story with the JSONL
schema: docs/api/observability.md.
"""
from .export import (
    FleetAggregator,
    MetricsExporter,
    MetricsRegistry,
    MetricsServer,
    PublishedState,
    registry_from_serve_events,
)
from .events import (
    KINDS,
    SCHEMA_VERSION,
    Event,
    JsonlSink,
    MemorySink,
    ScalarWriter,
    Sink,
    TeeSink,
    WriterSink,
    emit_resilience,
)
from .step_monitor import StepMonitor
from .summary import load_events, render, summarize
from .tracing import (
    CaptureTrigger,
    DeferredTelemetry,
    DeviceMetricsBuffer,
    SpanTracer,
    StepWaterfall,
    TraceSession,
    chrome_trace_from_events,
    get_tracer,
    set_tracer,
    span,
    write_chrome_trace,
)
from .watchdog import Watchdog

__all__ = [
    "Event", "Sink", "JsonlSink", "MemorySink", "TeeSink",
    "WriterSink", "ScalarWriter", "emit_resilience",
    "KINDS", "SCHEMA_VERSION",
    "StepMonitor", "Watchdog",
    "load_events", "summarize", "render",
    "SpanTracer", "get_tracer", "set_tracer", "span",
    "StepWaterfall", "TraceSession", "CaptureTrigger",
    "DeviceMetricsBuffer", "DeferredTelemetry",
    "chrome_trace_from_events", "write_chrome_trace",
    "MetricsRegistry", "MetricsExporter", "MetricsServer",
    "PublishedState", "FleetAggregator",
    "registry_from_serve_events",
]
