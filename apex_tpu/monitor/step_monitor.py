"""Per-step run-health recorder — the middle layer of
:mod:`apex_tpu.monitor`.

:class:`StepMonitor` turns a train loop's per-step aux outputs into
structured events: loss, grad-norm, learning rate, amp loss-scale and
overflow state (via :func:`apex_tpu.amp.scaler.update_telemetry`),
tokens/s, step wall ms, and MFU against the attached device's peak
(:func:`apex_tpu.pyprof.prof.device_spec`) — plus a
:class:`~apex_tpu.monitor.watchdog.Watchdog` raising alarms on
non-finite loss, overflow streaks, and wall-clock stalls.

Division of labor (see docs/api/observability.md):
``pyprof`` answers *where did device time go* (per-op attribution),
``Timers`` answers *how long did each phase take* (host phase timing),
``monitor.tracing`` answers *where did the wall time go* (per-step
host/device waterfall, deferred telemetry), ``monitor`` answers *is
the run healthy over time* — and the others feed into it
(``Timers.events`` exports phase times as ``timer`` events; the
waterfall emits ``attr`` rows and the span tracer ``span`` events
through the same sinks; MFU reads the pyprof device spec).
"""
from __future__ import annotations

import math
import time
from typing import Any, Dict, Optional

from ..utils.log_util import get_logger
from .events import Event, Sink

logger = get_logger(__name__)
from .watchdog import Watchdog


def _host_float(x: Any) -> Optional[float]:
    """Fetch a (device) scalar as a host float; None stays None."""
    if x is None:
        return None
    try:
        return float(x)
    except (TypeError, ValueError):
        return None


class StepMonitor:
    """Records one event stream for a training/serving run.

    Construction emits ``run_start``; :meth:`close` emits ``run_end``
    with totals.  Per step, call :meth:`start_step` before the work and
    :meth:`end_step` after it with whatever aux outputs the step
    produced — every argument is optional, so partial instrumentation
    still yields a useful log.

    ``StepMonitor`` also quacks like a :class:`~apex_tpu.monitor.events.
    Sink` (:meth:`emit`), so ``Timers.events(monitor, iteration)`` and
    any other sink consumer can write through it directly.
    """

    def __init__(self, sink: Sink, *,
                 tokens_per_step: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 watchdog: Optional[Watchdog] = None,
                 clock=time.perf_counter,
                 wall_clock=time.time,
                 run_attrs: Optional[Dict[str, Any]] = None,
                 close_sink: bool = True):
        self._sink = sink
        # close_sink=False when the sink is shared (another monitor, a
        # later Timers export): close() then leaves it open — a closed
        # JsonlSink silently drops every subsequent event.
        self._close_sink = bool(close_sink)
        self.tokens_per_step = tokens_per_step
        self.flops_per_step = flops_per_step
        self._peak_flops = peak_flops  # resolved lazily off pyprof
        self.watchdog = watchdog
        self._clock = clock
        self._wall = wall_clock
        self._step_t0: Optional[float] = None
        self._run_t0 = clock()
        self._steps_seen = 0
        self._last_step: Optional[int] = None
        self._scaler_prev: Optional[dict] = None
        attrs = dict(run_attrs or {})
        attrs.setdefault("schema", 1)
        self.event("run", "run_start", **attrs)
        if self.watchdog is not None:
            self.watchdog.start()

    # -- sink facade ---------------------------------------------------------

    def emit(self, event: Event) -> None:
        self._sink.emit(event)

    def event(self, kind: str, name: str, value=None,
              step: Optional[int] = None, **attrs) -> None:
        self._sink.emit(Event(time=self._wall(), step=step, kind=kind,
                              name=name, value=value, attrs=attrs))

    # -- per-step recording --------------------------------------------------

    def start_step(self, step: Optional[int] = None) -> None:
        self._step_t0 = self._clock()
        self._last_step = step

    def peak_flops(self) -> Optional[float]:
        """Device peak FLOP/s for the MFU denominator, resolved once
        from the pyprof device spec when not given explicitly."""
        if self._peak_flops is None:
            try:
                from ..pyprof.prof import device_spec

                self._peak_flops = device_spec().peak_bf16_tflops * 1e12
            except (ImportError, AttributeError, KeyError,
                    RuntimeError):  # no device spec -> no MFU
                self._peak_flops = 0.0
        return self._peak_flops or None

    def end_step(self, step: Optional[int] = None, *,
                 loss=None, grad_norm=None, lr=None,
                 scaler=None, tokens: Optional[float] = None,
                 **extra_metrics) -> None:
        """Record one completed step.

        ``loss`` / ``grad_norm`` / ``lr`` may be device scalars (one
        host sync each).  ``scaler`` accepts an
        :class:`~apex_tpu.amp.mixed_precision.StepInfo`, an
        :class:`~apex_tpu.amp.scaler.ScalerState`, or an ``AmpState``
        (its first scaler is read).  When ``grad_norm`` is omitted and
        ``scaler`` is a ``StepInfo`` carrying the fused pipeline's
        measured global norm (``StepInfo.grad_norm``), that value is
        recorded — no redundant host-side tree sweep needed.
        ``tokens`` overrides the constructor's ``tokens_per_step`` for
        this step.  Extra keyword scalars become additional ``metric``
        events.
        """
        if step is None:
            step = self._last_step
        if grad_norm is None and scaler is not None:
            grad_norm = getattr(scaler, "grad_norm", None)
        self._steps_seen += 1
        now = self._clock()
        dt = (now - self._step_t0) if self._step_t0 is not None else None
        self._step_t0 = None

        loss_f = _host_float(loss)
        metrics: Dict[str, Optional[float]] = {
            "loss": loss_f,
            "grad_norm": _host_float(grad_norm),
            "lr": _host_float(lr),
        }
        if dt is not None and dt > 0.0:
            metrics["step_ms"] = dt * 1e3
            n_tok = tokens if tokens is not None else self.tokens_per_step
            if n_tok:
                metrics["tokens_per_sec"] = float(n_tok) / dt
            peak = self.peak_flops()
            if self.flops_per_step and peak:
                metrics["mfu"] = self.flops_per_step / dt / peak
        for k, v in extra_metrics.items():
            metrics[k] = _host_float(v)

        for name, v in metrics.items():
            if v is None:
                continue
            if not math.isfinite(v):
                # bare NaN is not valid JSON; keep the record parseable
                self.event("metric", name, value=None, step=step,
                           nonfinite=str(v))
            else:
                self.event("metric", name, value=v, step=step)

        overflow = self._record_scaler(scaler, step)
        if self.watchdog is not None:
            self.watchdog.observe_step(step, loss=loss_f,
                                       overflow=overflow)

    def _record_scaler(self, scaler, step) -> Optional[bool]:
        """Emit amp ``scale`` events; returns this step's overflow flag
        (None when no scaler is being tracked)."""
        if scaler is None:
            return None
        try:
            from ..amp import scaler as _scaler

            if hasattr(scaler, "scalers"):  # AmpState
                scaler = scaler.scaler
            tel = _scaler.update_telemetry(self._scaler_prev, scaler)
        except Exception as e:  # telemetry must never kill the step
            logger.warning("scaler telemetry failed: %s", str(e)[:160])
            return None
        self.event("scale", "loss_scale", value=tel["loss_scale"],
                   step=step, steps_skipped=tel["steps_skipped"],
                   checked=tel["checked"])
        if tel["overflow"]:
            streak = (self.watchdog.overflow_count + 1
                      if self.watchdog is not None else None)
            self.event("scale", "overflow", value=1.0, step=step,
                       streak=streak)
        self._scaler_prev = {"loss_scale": tel["loss_scale"],
                             "steps_skipped": tel["steps_skipped"]}
        return tel["overflow"]

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
        self.event("run", "run_end",
                   steps=self._steps_seen,
                   wall_s=round(self._clock() - self._run_t0, 3))
        if self._close_sink:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
