"""Render a monitor JSONL run log into a human summary.

The read side of the telemetry spine: :func:`load_events` parses an
append-only event log back into :class:`~apex_tpu.monitor.events.Event`
records (tolerating the one truncated trailing line a kill mid-write can
leave), :func:`summarize` folds them into a run-health digest —
throughput, loss trajectory, amp overflow history, watchdog alarms,
resilience lifecycle (preempts / resumes / restart attempts /
checkpoint-integrity skips), phase-timer totals, wall-time attribution
(the :mod:`~apex_tpu.monitor.tracing` waterfall: mean/p50/p99 per
component + worst-step pointer), the captured-traces index, the
serving digest (request lifecycle outcomes, queue-wait/TTFT/ITL
percentiles, rejection reasons, pool high-water, per-bucket tick
counts, engine snapshots), bench section outcomes — and
:func:`render` prints it as tables.
``tools/monitor_summary.py`` is the CLI wrapper (``--chrome OUT.json``
additionally rebuilds a Perfetto-loadable Chrome trace from the log's
span/timer events).
"""
from __future__ import annotations

import statistics
import sys
from typing import Dict, List, Optional

from .events import Event, terminal_reason


def load_events(path: str) -> tuple:
    """Parse a JSONL event log.  Returns ``(events, malformed)`` where
    ``malformed`` counts undecodable lines (a crash-truncated tail is
    expected and must not sink the post-mortem — the whole point of the
    line-per-event format)."""
    events: List[Event] = []
    malformed = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(Event.from_json(line))
            except (ValueError, KeyError, TypeError):
                malformed += 1  # torn/garbled line: count, keep parsing
    return events, malformed


def _series(events: List[Event], kind: str, name: str) -> List[float]:
    return [float(e.value) for e in events
            if e.kind == kind and e.name == name
            and isinstance(e.value, (int, float))]


def _pct(vals: List[float], q: float) -> float:
    """Percentile by linear interpolation between closest ranks —
    stable for the handfuls of steps a smoke run produces (p99 of 3
    samples is the max, not an IndexError)."""
    s = sorted(vals)
    if len(s) == 1:
        return s[0]
    pos = (len(s) - 1) * q / 100.0
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (pos - lo)


def summarize(events: List[Event], malformed: int = 0) -> dict:
    """Fold an event stream into the run-health digest dict."""
    out: Dict[str, object] = {"n_events": len(events),
                              "malformed_lines": malformed}
    for e in events:
        if e.kind == "run" and e.name == "run_start":
            out["run"] = dict(e.attrs)
            break
    for e in reversed(events):
        if e.kind == "run" and e.name == "run_end":
            out["run_end"] = dict(e.attrs)
            break

    # step metrics --------------------------------------------------------
    losses = _series(events, "metric", "loss")
    step_ms = _series(events, "metric", "step_ms")
    tps = _series(events, "metric", "tokens_per_sec")
    mfu = _series(events, "metric", "mfu")
    steps = sorted({e.step for e in events
                    if e.kind == "metric" and e.step is not None})
    stats: Dict[str, object] = {"count": len(steps)}
    if steps:
        stats["first"], stats["last"] = steps[0], steps[-1]
    if losses:
        stats["loss_first"] = losses[0]
        stats["loss_last"] = losses[-1]
        stats["loss_min"] = min(losses)
    nonfinite = [e for e in events if e.kind == "metric"
                 and e.name == "loss" and "nonfinite" in e.attrs]
    if nonfinite:
        stats["nonfinite_losses"] = len(nonfinite)
    if step_ms:
        stats["step_ms_mean"] = statistics.fmean(step_ms)
        stats["step_ms_min"] = min(step_ms)
    if tps:
        stats["tokens_per_sec_mean"] = statistics.fmean(tps)
    if mfu:
        stats["mfu_mean"] = statistics.fmean(mfu)
    out["steps"] = stats

    # amp scale -----------------------------------------------------------
    scales = _series(events, "scale", "loss_scale")
    if scales:
        skipped = [e.attrs.get("steps_skipped") for e in events
                   if e.kind == "scale" and e.name == "loss_scale"]
        overflow_events = [e for e in events
                           if e.kind == "scale" and e.name == "overflow"]
        out["scale"] = {
            "first": scales[0], "last": scales[-1],
            "min": min(scales), "max": max(scales),
            "overflow_steps": len(overflow_events),
            "steps_skipped_total": next(
                (s for s in reversed(skipped) if s is not None), 0),
        }

    # alarms --------------------------------------------------------------
    alarms = [e for e in events if e.kind == "alarm"]
    if alarms:
        out["alarms"] = [
            {"name": e.name, "step": e.step, "value": e.value,
             **dict(e.attrs)} for e in alarms]

    # phase timers --------------------------------------------------------
    timers: Dict[str, Dict[str, float]] = {}
    for e in events:
        if e.kind != "timer" or not isinstance(e.value, (int, float)):
            continue
        t = timers.setdefault(e.name, {"count": 0, "total_s": 0.0})
        t["count"] += 1
        t["total_s"] += float(e.value)
    if timers:
        for t in timers.values():
            t["mean_ms"] = t["total_s"] * 1e3 / t["count"]
        out["timers"] = timers

    # wall-time attribution (tracing waterfall) ---------------------------
    wf_rows = [e for e in events
               if e.kind == "attr" and e.name == "step_waterfall"
               and isinstance(e.value, (int, float))]
    if wf_rows:
        comps: Dict[str, List[float]] = {"wall": []}
        ratios: List[float] = []
        worst = None
        for e in wf_rows:
            comps["wall"].append(float(e.value))
            for k, v in e.attrs.items():
                if k.endswith("_ms") and isinstance(v, (int, float)):
                    comps.setdefault(k[:-3], []).append(float(v))
            r = e.attrs.get("wall_device_ratio")
            if isinstance(r, (int, float)):
                ratios.append(float(r))
            if worst is None or float(e.value) > worst[1]:
                worst = (e.step, float(e.value), dict(e.attrs))
        wall_total = sum(comps["wall"]) or 1.0
        att: Dict[str, object] = {"steps": len(wf_rows), "components": {}}
        for name, vals in comps.items():
            att["components"][name] = {
                "mean_ms": statistics.fmean(vals),
                "p50_ms": _pct(vals, 50.0),
                "p99_ms": _pct(vals, 99.0),
                "share": sum(vals) / wall_total,
            }
        if ratios:
            att["wall_device_ratio_mean"] = statistics.fmean(ratios)
            att["wall_device_ratio_min"] = min(ratios)
        if worst is not None:
            att["worst_step"] = {"step": worst[0],
                                 "wall_ms": worst[1], **worst[2]}
        out["attribution"] = att

    # captured traces ------------------------------------------------------
    caps = [e for e in events if e.kind == "trace"]
    if caps:
        index: List[Dict[str, object]] = []
        for e in caps:
            if e.name == "capture_started":
                index.append({"step": e.step,
                              "reason": e.attrs.get("reason"),
                              "trace_dir": e.attrs.get("trace_dir"),
                              "stop": e.attrs.get("stop")})
            elif e.name == "capture_stopped" and index \
                    and "stopped_at" not in index[-1]:
                index[-1]["stopped_at"] = e.step
        requested = sum(1 for e in caps
                        if e.name == "capture_requested")
        out["captures"] = {"windows": index, "requested": requested}

    # resilience lifecycle ------------------------------------------------
    res = [e for e in events if e.kind == "resilience"]
    if res:
        counts: Dict[str, int] = {}
        for e in res:
            counts[e.name] = counts.get(e.name, 0) + 1
        digest: Dict[str, object] = {"counts": counts}
        resumed = [e for e in res if e.name == "run_resumed"]
        if resumed:
            digest["resumed_from"] = [int(e.value) for e in resumed
                                      if e.value is not None]
        preempt = [e for e in res if e.name == "preempt_exit"]
        if preempt:
            digest["preempted_at"] = [int(e.value) for e in preempt
                                      if e.value is not None]
        skipped = [e for e in res if e.name == "ckpt_skipped"]
        if skipped:
            digest["ckpt_skipped"] = [
                {"step": e.step, "reason": e.attrs.get("reason", "")}
                for e in skipped]
        giveup = [e for e in res if e.name == "run_giveup"]
        if giveup:
            digest["gave_up"] = dict(giveup[-1].attrs)
        out["resilience"] = digest

    # serving (request lifecycle + engine gauges) -------------------------
    srv = [e for e in events if e.kind == "serving"]
    ticks = [e for e in events if e.kind == "serve_tick"]
    fleet = [e for e in events if e.kind == "fleet"]
    # a supervisor-only log (ISSUE-18: kind='fleet' lifecycle events,
    # no request traffic of its own) still gets the serving section —
    # the control-plane ledger below must not require child logs in
    # the merge
    if srv or ticks or fleet:
        digest: Dict[str, object] = {}
        done_events = [e for e in srv if e.name == "request_done"]
        digest["submitted"] = sum(1 for e in srv
                                  if e.name == "request_submitted")

        def _terminal(e):
            return terminal_reason(e.attrs)

        digest["done"] = sum(1 for e in done_events
                             if _terminal(e) == "finished")
        digest["preempted"] = sum(1 for e in done_events
                                  if _terminal(e) == "preempted")
        # fleet runs (ISSUE-14): replica-stamped events aggregate to
        # one per-replica reconciliation table — N submitted must
        # equal N terminal per replica AND fleet-wide
        replicas: Dict[str, Dict[str, int]] = {}
        for e in srv:
            rep = e.attrs.get("replica")
            if rep is None or e.name not in ("request_submitted",
                                             "request_done"):
                continue
            row = replicas.setdefault(str(rep),
                                      {"submitted": 0, "terminal": 0})
            row["submitted" if e.name == "request_submitted"
                else "terminal"] += 1
        if replicas:
            digest["replicas"] = {k: replicas[k]
                                  for k in sorted(replicas)}
        if fleet:
            digest["fleet"] = {
                "routed": sum(1 for e in fleet
                              if e.name == "request_routed"),
                "kv_handoffs": sum(1 for e in fleet
                                   if e.name == "kv_handoff"),
                "swaps": sum(1 for e in fleet
                             if e.name == "swap_done"),
                "replica_restarts": sum(1 for e in fleet
                                        if e.name ==
                                        "replica_restart"),
            }
        # ISSUE-18 distributed control plane: the supervisor's
        # process-lifecycle ledger (spawn/reap pairing, restarts with
        # reasons, degraded RPCs, torn-handoff fallbacks, QoS
        # admission sheds) and the autoscale event trace — every
        # scaling decision with its round, direction, trigger and
        # resulting fleet size, in order
        spawned = [e for e in fleet if e.name == "replica_spawned"]
        if spawned:
            cp: Dict[str, object] = {
                "spawned": len(spawned),
                "reaped": sum(1 for e in fleet
                              if e.name == "replica_reaped"),
                "replayed_requests": sum(
                    int(e.attrs.get("replayed") or 0)
                    for e in spawned),
            }
            restarts = [e for e in fleet
                        if e.name == "replica_restart"]
            if restarts:
                cp["restarts"] = [
                    {"round": e.step,
                     "replica": e.attrs.get("replica"),
                     "reason": e.attrs.get("reason"),
                     "backoff_s": e.attrs.get("backoff_s")}
                    for e in restarts]
            rpc_to = sum(1 for e in fleet if e.name == "rpc_timeout")
            if rpc_to:
                cp["rpc_timeouts"] = rpc_to
            retries = sum(1 for e in fleet
                          if e.name == "kv_handoff_retry")
            if retries:
                cp["handoff_cold_fallbacks"] = retries
            sheds = [e for e in fleet
                     if e.name == "request_shed_admission"]
            if sheds:
                by_cls: Dict[str, int] = {}
                for e in sheds:
                    k = (f"{e.attrs.get('priority_class')}/"
                         f"{e.attrs.get('reason')}")
                    by_cls[k] = by_cls.get(k, 0) + 1
                cp["shed_admission"] = by_cls
            scale = [e for e in fleet if e.name == "autoscale"]
            if scale:
                cp["autoscale"] = [
                    {"round": e.step,
                     "action": e.attrs.get("action"),
                     "reason": e.attrs.get("reason"),
                     "replica": e.attrs.get("replica"),
                     "backlog": e.attrs.get("backlog"),
                     "replicas": e.attrs.get("replicas")}
                    for e in scale]
            digest["control_plane"] = cp
        # ISSUE-13 terminal paths: deadline expiry (queued OR
        # running) and load shedding — rendered so N submitted still
        # visibly reconciles against N terminal
        deadline = sum(1 for e in done_events
                       if _terminal(e).startswith("deadline"))
        shed = sum(1 for e in done_events if _terminal(e) == "shed")
        if deadline:
            digest["deadline_exceeded"] = deadline
        if shed:
            digest["shed"] = shed
        replays = [e for e in srv if e.name == "journal_replay"]
        if replays:
            digest["journal_replays"] = [
                {"tick": e.step,
                 "replayed": e.attrs.get("replayed"),
                 "skipped_terminal": e.attrs.get("skipped_terminal")}
                for e in replays]
        replayed = sum(1 for e in srv if e.name == "request_replayed")
        if replayed:
            digest["replayed_requests"] = replayed
        rejected: Dict[str, int] = {}
        for e in srv:
            if e.name == "request_rejected":
                r = str(e.attrs.get("reason", "unknown"))
                rejected[r] = rejected.get(r, 0) + 1
        if rejected:
            digest["rejected"] = rejected
        # distributions over the completed requests' terminal events
        # (queue wait / TTFT) and the decode ticks.  ITL is the tick
        # wall weighted by the tick's batch — every active request
        # gains one token per tick, so this is the same population as
        # the per-request samples ServeSummary.itl_p99_ms (and the
        # bench_gate serving_itl_p99_ms headline) draw from
        itl: List[float] = []
        for e in srv:
            if e.name == "decode_step" \
                    and isinstance(e.value, (int, float)):
                n = e.attrs.get("batch")
                itl.extend([float(e.value)]
                           * (n if isinstance(n, int) and n > 0
                              else 1))
        series = {
            "queue_wait_ms": [e.attrs["queue_wait_ms"]
                              for e in done_events
                              if isinstance(e.attrs.get(
                                  "queue_wait_ms"), (int, float))],
            "ttft_ms": [e.attrs["ttft_ms"] for e in done_events
                        if isinstance(e.attrs.get("ttft_ms"),
                                      (int, float))],
            "itl_ms": itl,
        }
        dists: Dict[str, object] = {}
        for name, vals in series.items():
            if vals:
                dists[name] = {"mean": statistics.fmean(vals),
                               "p50": _pct(vals, 50.0),
                               "p90": _pct(vals, 90.0),
                               "p99": _pct(vals, 99.0),
                               "n": len(vals)}
        if dists:
            digest["latency"] = dists
        # per-bucket tick counts (the compiled-program ladder in use)
        buckets: Dict[str, int] = {}
        for e in srv:
            if e.name != "decode_step":
                continue
            bb, pb = e.attrs.get("batch_bucket"), \
                e.attrs.get("pages_bucket")
            if bb is not None and pb is not None:
                key = f"b{bb}xp{pb}"
                buckets[key] = buckets.get(key, 0) + 1
        if buckets:
            digest["bucket_ticks"] = buckets
        # pool-utilization high-water mark from the engine gauges
        hw = [e.attrs.get("used_blocks_high_water") for e in ticks]
        hw = [v for v in hw if isinstance(v, (int, float))]
        pool = [e.attrs.get("pool_blocks") for e in ticks]
        pool = [v for v in pool if isinstance(v, (int, float))]
        if hw:
            digest["pool_high_water_blocks"] = int(max(hw))
            if pool and max(pool) > 0:
                digest["pool_high_water_share"] = \
                    max(hw) / max(pool)
        if ticks:
            digest["gauge_events"] = len(ticks)
        snaps = [e for e in srv if e.name == "engine_snapshot"]
        if snaps:
            digest["snapshots"] = [
                {"tick": e.step, "reason": e.attrs.get("reason"),
                 "active": e.attrs.get("active"),
                 "queued": e.attrs.get("queued")} for e in snaps]
        # ISSUE-17 live metrics plane: SLO burn-rate digest (the
        # slo_burn alarms already render in the alarm table; this
        # reconciles them against the objective definitions and the
        # recovery records), fleet aggregation rounds, and the
        # exporter lifecycle pair
        slo_events = [e for e in events if e.kind == "slo"]
        burns = [e for e in events
                 if e.kind == "alarm" and e.name == "slo_burn"]
        if slo_events or burns:
            slo: Dict[str, object] = {}
            defs = [e for e in slo_events
                    if e.name == "slo_objectives"]
            if defs:
                slo["objectives"] = dict(defs[-1].attrs)
            slo["burn_episodes"] = len(burns)
            slo["recoveries"] = sum(1 for e in slo_events
                                    if e.name == "slo_recovered")
            if burns:
                slo["burns"] = [
                    {"tick": e.step,
                     "class": e.attrs.get("priority_class"),
                     "dimension": e.attrs.get("dimension"),
                     "burn_fast": e.attrs.get("burn_fast"),
                     "burn_slow": e.attrs.get("burn_slow")}
                    for e in burns]
            digest["slo"] = slo
        fticks = [e for e in events if e.kind == "fleet_tick"]
        if fticks:
            digest["fleet_ticks"] = len(fticks)
        mev = [e for e in events if e.kind == "metrics"]
        if mev:
            digest["metrics_server"] = {
                "started": sum(1 for e in mev
                               if e.name ==
                               "metrics_server_started"),
                "stopped": sum(1 for e in mev
                               if e.name ==
                               "metrics_server_stopped"),
            }
        out["serving"] = digest

    # bench/driver sections ----------------------------------------------
    sections: Dict[str, Dict[str, object]] = {}
    for e in events:
        if e.kind != "section":
            continue
        s = sections.setdefault(e.attrs.get("section", e.name), {})
        if e.name == "section_start":
            s.setdefault("status", "started")
        elif e.name == "section_done":
            s["status"] = "done"
            if isinstance(e.value, (int, float)):
                s["seconds"] = float(e.value)
        elif e.name == "section_error":
            s["status"] = "error"
            s["error"] = e.attrs.get("error", "")
            if isinstance(e.value, (int, float)):
                s["seconds"] = float(e.value)
    if sections:
        out["sections"] = sections
    return out


def _fmt(v, nd=3) -> str:
    if isinstance(v, float):
        return f"{v:.{nd}f}" if abs(v) < 1e5 else f"{v:.3e}"
    return str(v)


def render(summary: dict) -> str:
    """Text tables for a terminal / CI log."""
    lines: List[str] = []
    run = summary.get("run", {})
    head = " ".join(f"{k}={v}" for k, v in run.items() if k != "schema")
    lines.append(f"run: {head or '(no run_start event)'}")
    if summary.get("malformed_lines"):
        lines.append(f"  ({summary['malformed_lines']} malformed line(s) "
                     "skipped — truncated tail from a killed run?)")

    st = summary.get("steps", {})
    if st.get("count"):
        lines.append("")
        lines.append(f"steps: {st['count']} "
                     f"({st.get('first')}..{st.get('last')})")
        row = []
        if "loss_first" in st:
            row.append(f"loss {_fmt(st['loss_first'], 4)} -> "
                       f"{_fmt(st['loss_last'], 4)} "
                       f"(min {_fmt(st['loss_min'], 4)})")
        if "nonfinite_losses" in st:
            row.append(f"NONFINITE x{st['nonfinite_losses']}")
        if "step_ms_mean" in st:
            row.append(f"step {_fmt(st['step_ms_mean'], 1)} ms mean "
                       f"/ {_fmt(st['step_ms_min'], 1)} ms best")
        if "tokens_per_sec_mean" in st:
            row.append(f"{_fmt(st['tokens_per_sec_mean'], 0)} tok/s")
        if "mfu_mean" in st:
            row.append(f"MFU {100.0 * st['mfu_mean']:.2f}%")
        for r in row:
            lines.append(f"  {r}")

    sc = summary.get("scale")
    if sc:
        lines.append("")
        lines.append(f"amp scale: {_fmt(sc['first'], 1)} -> "
                     f"{_fmt(sc['last'], 1)} "
                     f"[{_fmt(sc['min'], 1)}, {_fmt(sc['max'], 1)}], "
                     f"overflow steps {sc['overflow_steps']}, "
                     f"total skipped {sc['steps_skipped_total']}")

    alarms = summary.get("alarms")
    lines.append("")
    if alarms:
        lines.append(f"ALARMS ({len(alarms)}):")
        for a in alarms:
            extra = {k: v for k, v in a.items()
                     if k not in ("name", "step", "value")}
            lines.append(f"  {a['name']} @ step {a.get('step')} "
                         f"value={a.get('value')} {extra or ''}".rstrip())
    else:
        lines.append("alarms: none")

    res = summary.get("resilience")
    if res:
        lines.append("")
        counts = res.get("counts", {})
        lines.append("resilience: "
                     + " ".join(f"{k}={v}"
                                for k, v in sorted(counts.items())))
        if res.get("preempted_at"):
            lines.append(f"  preempted at step(s) {res['preempted_at']} "
                         "(clean exit)")
        if res.get("resumed_from"):
            lines.append(f"  resumed from step(s) {res['resumed_from']}")
        for s in res.get("ckpt_skipped", []):
            lines.append(f"  CKPT SKIPPED step {s['step']}: "
                         f"{s['reason']}")
        if res.get("gave_up"):
            lines.append(f"  GAVE UP: {res['gave_up']}")

    att = summary.get("attribution")
    if att:
        lines.append("")
        lines.append(f"wall-time attribution ({att['steps']} step(s)):")
        lines.append(f"{'component':<18} {'mean ms':>9} {'p50 ms':>9} "
                     f"{'p99 ms':>9} {'share':>7}")
        comps = att["components"]
        order = ["wall", "data_load", "dispatch", "device_compute",
                 "telemetry_drain", "ckpt_io", "other"]
        for name in order + sorted(set(comps) - set(order)):
            c = comps.get(name)
            if c is None:
                continue
            lines.append(
                f"{name:<18} {c['mean_ms']:>9.3f} {c['p50_ms']:>9.3f} "
                f"{c['p99_ms']:>9.3f} {100.0 * c['share']:>6.1f}%")
        if "wall_device_ratio_mean" in att:
            lines.append(
                f"  wall/device ratio: mean "
                f"{att['wall_device_ratio_mean']:.3f}, min "
                f"{att['wall_device_ratio_min']:.3f}")
        w = att.get("worst_step")
        if w is not None:
            parts = {k: v for k, v in w.items()
                     if k.endswith("_ms") and k != "wall_ms"
                     and isinstance(v, (int, float)) and v > 0.0}
            top = sorted(parts.items(), key=lambda kv: -kv[1])[:3]
            lines.append(
                f"  worst step: {w['step']} at "
                f"{_fmt(w['wall_ms'], 2)} ms ("
                + ", ".join(f"{k[:-3]} {_fmt(v, 2)}" for k, v in top)
                + ")")

    srv = summary.get("serving")
    if srv:
        lines.append("")
        head = (f"serving: {srv.get('submitted', 0)} submitted, "
                f"{srv.get('done', 0)} done, "
                f"{srv.get('preempted', 0)} preempted")
        if srv.get("deadline_exceeded"):
            head += f", {srv['deadline_exceeded']} deadline-expired"
        if srv.get("shed"):
            head += f", {srv['shed']} shed"
        rej = srv.get("rejected")
        if rej:
            head += (", rejected "
                     + " ".join(f"{k}={v}"
                                for k, v in sorted(rej.items())))
        lines.append(head)
        reps = srv.get("replicas")
        if reps:
            lines.append(
                "  fleet replicas: "
                + "  ".join(
                    f"{rid}: {row['submitted']} submitted / "
                    f"{row['terminal']} terminal"
                    + ("" if row["submitted"] == row["terminal"]
                       else "  [MISMATCH]")
                    for rid, row in reps.items()))
        fleet = srv.get("fleet")
        if fleet:
            lines.append(
                f"  fleet: {fleet['routed']} routed, "
                f"{fleet['kv_handoffs']} KV handoff(s), "
                f"{fleet['swaps']} rolling swap(s), "
                f"{fleet['replica_restarts']} replica restart(s)")
        cp = srv.get("control_plane")
        if cp:
            head = (f"  control plane: {cp['spawned']} spawned / "
                    f"{cp['reaped']} reaped"
                    + ("" if cp["spawned"] == cp["reaped"]
                       else "  [UNPAIRED]"))
            if cp.get("replayed_requests"):
                head += (f", {cp['replayed_requests']} request(s) "
                         f"journal-replayed")
            if cp.get("rpc_timeouts"):
                head += f", {cp['rpc_timeouts']} RPC timeout(s)"
            if cp.get("handoff_cold_fallbacks"):
                head += (f", {cp['handoff_cold_fallbacks']} cold "
                         f"prefill fallback(s)")
            lines.append(head)
            for r in cp.get("restarts", []):
                lines.append(
                    f"    RESTART {r.get('replica')} @ round "
                    f"{r.get('round')} [{r.get('reason')}] after "
                    f"{_fmt(r.get('backoff_s'), 3)}s backoff")
            shed = cp.get("shed_admission")
            if shed:
                lines.append(
                    "    QoS admission shed: "
                    + " ".join(f"{k}={v}"
                               for k, v in sorted(shed.items())))
            scale = cp.get("autoscale")
            if scale:
                lines.append(f"  autoscale trace ({len(scale)} "
                             f"event(s)):")
                for a in scale:
                    lines.append(
                        f"    round {a.get('round')}: "
                        f"{str(a.get('action')).upper():<4} "
                        f"{a.get('replica')} [{a.get('reason')}] "
                        f"backlog {_fmt(a.get('backlog'), 2)} -> "
                        f"{a.get('replicas')} replica(s)")
        for r in srv.get("journal_replays", []):
            lines.append(f"  JOURNAL REPLAY @ tick {r.get('tick')}: "
                         f"{r.get('replayed')} request(s) re-entered, "
                         f"{r.get('skipped_terminal')} already "
                         f"terminal")
        dists = srv.get("latency") or {}
        if dists:
            lines.append(f"{'series':<16} {'mean ms':>9} {'p50 ms':>9} "
                         f"{'p90 ms':>9} {'p99 ms':>9} {'n':>6}")
            for name in ("queue_wait_ms", "ttft_ms", "itl_ms"):
                d = dists.get(name)
                if d is None:
                    continue
                lines.append(
                    f"{name[:-3]:<16} {d['mean']:>9.3f} "
                    f"{d['p50']:>9.3f} {d['p90']:>9.3f} "
                    f"{d['p99']:>9.3f} {d['n']:>6}")
        if "pool_high_water_blocks" in srv:
            share = srv.get("pool_high_water_share")
            lines.append(
                f"  pool high water: "
                f"{srv['pool_high_water_blocks']} block(s)"
                + (f" ({100.0 * share:.0f}% of pool)"
                   if share is not None else ""))
        bt = srv.get("bucket_ticks")
        if bt:
            lines.append("  ticks per bucket: "
                         + " ".join(f"{k}={v}"
                                    for k, v in sorted(bt.items())))
        for s in srv.get("snapshots", []):
            lines.append(f"  SNAPSHOT @ tick {s.get('tick')} "
                         f"[{s.get('reason')}]: "
                         f"{s.get('active')} active, "
                         f"{s.get('queued')} queued")
        slo = srv.get("slo")
        if slo:
            lines.append(
                f"  SLO: {slo.get('burn_episodes', 0)} burn "
                f"episode(s), {slo.get('recoveries', 0)} "
                f"recovery(ies)")
            objs = (slo.get("objectives") or {}).get("objectives")
            if objs:
                for o in objs:
                    parts = [f"{k}={v}" for k, v in sorted(o.items())
                             if k != "priority_class" and v]
                    lines.append(
                        f"    objective [{o.get('priority_class')}]: "
                        + " ".join(parts))
            for b in slo.get("burns", []):
                lines.append(
                    f"    BURN @ tick {b.get('tick')} "
                    f"[{b.get('class')}/{b.get('dimension')}]: "
                    f"fast {_fmt(b.get('burn_fast'), 2)}x / "
                    f"slow {_fmt(b.get('burn_slow'), 2)}x budget")
        if srv.get("fleet_ticks"):
            lines.append(f"  fleet aggregation: "
                         f"{srv['fleet_ticks']} fleet_tick round(s)")
        ms = srv.get("metrics_server")
        if ms:
            lines.append(
                f"  metrics server: {ms['started']} started / "
                f"{ms['stopped']} stopped"
                + ("" if ms["started"] == ms["stopped"]
                   else "  [UNPAIRED]"))

    caps = summary.get("captures")
    if caps:
        lines.append("")
        lines.append(f"captured traces ({len(caps['windows'])} "
                     f"window(s), {caps['requested']} request(s)):")
        for c in caps["windows"]:
            # stopped_at None = the close()-time stop of a window that
            # was still open when the run tore down (its step-less
            # capture_stopped event)
            lines.append(
                f"  step {c.get('step')} [{c.get('reason')}] -> "
                f"{c.get('trace_dir')}"
                + (f" (closed @ {c['stopped_at']})"
                   if c.get("stopped_at") is not None
                   else " (open at exit)"))

    timers = summary.get("timers")
    if timers:
        lines.append("")
        lines.append(f"{'phase':<24} {'count':>6} {'total s':>10} "
                     f"{'mean ms':>10}")
        for name in sorted(timers):
            t = timers[name]
            lines.append(f"{name:<24} {t['count']:>6} "
                         f"{t['total_s']:>10.3f} {t['mean_ms']:>10.2f}")

    sections = summary.get("sections")
    if sections:
        lines.append("")
        lines.append(f"{'section':<24} {'status':<8} {'seconds':>10}")
        for name, s in sections.items():
            sec = s.get("seconds")
            lines.append(
                f"{name:<24} {s.get('status', '?'):<8} "
                f"{'' if sec is None else f'{sec:>10.2f}'}"
                + (f"  {s['error']}" if s.get("error") else ""))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    """CLI: ``monitor_summary.py RUN.jsonl [--chrome OUT.json]`` —
    exit 0 on a parseable log (alarms are reported, not fatal), 1 on
    missing/empty input, 2 on usage error.  ``--chrome`` additionally
    rebuilds a Perfetto-loadable Chrome trace from the log's span and
    timer events (:func:`apex_tpu.monitor.tracing.
    chrome_trace_from_events`)."""
    argv = sys.argv[1:] if argv is None else argv
    chrome = None
    if "--chrome" in argv:
        i = argv.index("--chrome")
        if i + 1 >= len(argv):
            print("monitor_summary: --chrome needs a path",
                  file=sys.stderr)
            return 2
        chrome = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: monitor_summary.py RUN.jsonl [MORE.jsonl ...] "
              "[--chrome OUT.json]   (several per-replica fleet logs "
              "merge into one summary)", file=sys.stderr)
        return 2
    events, malformed = [], 0
    try:
        for path in argv:
            evs, bad = load_events(path)
            events.extend(evs)
            malformed += bad
    except OSError as e:
        print(f"monitor_summary: {e}", file=sys.stderr)
        return 1
    if not events:
        print(f"monitor_summary: no events in {' '.join(argv)}",
              file=sys.stderr)
        return 1
    print(render(summarize(events, malformed)))
    if chrome is not None:
        from .tracing import chrome_trace_from_events, write_chrome_trace

        write_chrome_trace(chrome, chrome_trace_from_events(events))
        print(f"\nchrome trace -> {chrome}")
    return 0
