"""Run-health watchdog: structured alarms for wedged or diverging runs.

Three alarm classes, each firing **exactly once per episode** (an
episode ends when the triggering condition clears, re-arming the
alarm):

- ``nonfinite_loss`` — the host-visible loss went NaN/Inf.
- ``overflow_streak`` — >= K *consecutive* amp loss-scale overflow
  skips (a healthy dynamic scaler skips occasionally; a streak means
  the scale is collapsing or the model diverged in fp16).
- ``stall`` — no step completed for ``stall_timeout`` seconds.  The
  optional heartbeat thread (:meth:`Watchdog.start`) notices this even
  while the main thread is wedged inside a device call — the situation
  the alarm exists for — and can dump a ``jax.profiler`` trace of the
  wedged step (``trace_dir``) so the hang is attributable post-mortem.

Every check is driven through an injectable ``clock`` so tests prove
the episode semantics deterministically on CPU with a fake clock
(tests/test_monitor.py).
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Optional

from ..utils.log_util import get_logger
from .events import Event, Sink

logger = get_logger(__name__)

DEFAULT_OVERFLOW_STREAK = 8
DEFAULT_STALL_TIMEOUT_S = 300.0


def _finite(x: Optional[float]) -> bool:
    return x is not None and math.isfinite(x)


class Watchdog:
    """Observes step completions, raises ``alarm`` events into a sink.

    Drive it from a :class:`~apex_tpu.monitor.step_monitor.StepMonitor`
    (which calls :meth:`observe_step` for you) or directly.  The stall
    check runs either from the heartbeat thread (:meth:`start`) or by
    calling :meth:`check_stall` manually (the deterministic test path).
    """

    def __init__(self, sink: Sink, *,
                 overflow_streak: int = DEFAULT_OVERFLOW_STREAK,
                 stall_timeout: float = DEFAULT_STALL_TIMEOUT_S,
                 clock=time.monotonic,
                 wall_clock=time.time,
                 trace_dir: Optional[str] = None,
                 heartbeat_interval: Optional[float] = None,
                 on_alarm=None):
        self._sink = sink
        # Escalation hook: called with every emitted alarm Event (e.g.
        # apex_tpu.resilience.EscalationPolicy.notify turns alarms into
        # checkpoint-then-abort restarts).  May run on the heartbeat
        # thread but always OUTSIDE the watchdog lock (alarms are
        # collected under the lock and emitted after it is released —
        # sink I/O and hook work must not serialize the observers, and
        # a hook taking its own lock must not nest inside ours); it
        # must be cheap, must not call back into the watchdog, and
        # must never raise (a raise is swallowed: telemetry cannot
        # kill the run).
        self._on_alarm = on_alarm
        self.overflow_streak = int(overflow_streak)
        self.stall_timeout = float(stall_timeout)
        self._clock = clock
        self._wall = wall_clock
        self.trace_dir = trace_dir
        self.heartbeat_interval = (heartbeat_interval
                                   if heartbeat_interval is not None
                                   else max(0.05,
                                            min(self.stall_timeout / 4.0,
                                                10.0)))
        # episode state
        self._last_progress = clock()
        self._last_step: Optional[int] = None
        self._stall_fired = False
        self._stall_seq = 0     # bumps when a stall fires: the trace
        # liveness token (a recovery observed between the stall
        # decision and the profiler start invalidates the start)
        self._nonfinite_fired = False
        self._overflow_count = 0
        self._overflow_fired = False
        self._max_overflow_streak = 0
        self._tracing = False
        # heartbeat thread
        self._stop_evt: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        # serializes the jax.profiler start/stop transitions only
        # (never held around sink emission): the stall decision is
        # made under _lock, emission happens outside it (APX804), so
        # without this a recovery racing the stall could stop a trace
        # before it started and leak the started one until the next
        # episode
        self._trace_lock = threading.Lock()

    # -- alarm emission ------------------------------------------------------

    def _alarm(self, name: str, value=None, step=None, **attrs) -> None:
        event = Event(time=self._wall(), step=step, kind="alarm",
                      name=name, value=value, attrs=attrs)
        self._sink.emit(event)
        if self._on_alarm is not None:
            try:
                self._on_alarm(event)
            except Exception as e:
                logger.warning("on_alarm hook failed: %s", str(e)[:160])

    def alarm(self, name: str, value=None, step=None, **attrs) -> None:
        """Emit one externally-judged alarm through the watchdog's
        sink AND its escalation hook — the route the serving SLO
        layer uses for ``slo_burn`` events, so objective breaches hit
        the same once-per-episode alarm machinery as stalls and
        overflow streaks (the CALLER owns the episode latch; the
        watchdog stays a pass-through).  Never call this while
        holding a lock: emission does sink I/O and runs the hook
        (the APX804 discipline every internal alarm path already
        follows)."""
        self._alarm(name, value=value, step=step, **attrs)

    def alarm_counts(self) -> dict:
        """Fired-episode counters for the metrics exporter (read
        under the lock — the heartbeat thread writes them)."""
        with self._lock:
            return {
                "stall": self._stall_seq,
                "nonfinite_loss": 1 if self._nonfinite_fired else 0,
                "overflow_streak": 1 if self._overflow_fired else 0,
            }

    # -- observations (call on every completed step) -------------------------

    def observe_step(self, step: Optional[int] = None,
                     loss: Optional[float] = None,
                     overflow: Optional[bool] = None,
                     now: Optional[float] = None) -> None:
        """Record one completed step: feeds the stall heartbeat and the
        loss / overflow episode trackers.

        ``loss`` must already be a host float (``None`` = not tracked
        this step); ``overflow`` is this step's amp skip flag (``None``
        = no scaler in play).
        """
        # Episode state flips under the lock; alarm EMISSION (sink
        # I/O, the escalation hook, the profiler trace teardown)
        # happens after it is released, in the order the transitions
        # fired — APX804: a blocking call under the watchdog lock
        # would serialize the heartbeat thread behind the sink and
        # nest the hook's own lock inside ours.
        actions = []
        with self._lock:
            now = self._clock() if now is None else now
            self._last_progress = now
            self._last_step = step
            if self._stall_fired:
                # episode over: progress resumed
                self._stall_fired = False
                actions.append(("stall_recovered", dict(step=step)))
                actions.append(("stop_trace", None))
            if loss is not None:
                if not _finite(loss):
                    if not self._nonfinite_fired:
                        self._nonfinite_fired = True
                        actions.append(("nonfinite_loss",
                                        dict(step=step,
                                             loss=str(loss))))
                else:
                    self._nonfinite_fired = False
            if overflow is not None:
                if overflow:
                    self._overflow_count += 1
                    self._max_overflow_streak = max(
                        self._max_overflow_streak, self._overflow_count)
                    if (self._overflow_count >= self.overflow_streak
                            and not self._overflow_fired):
                        self._overflow_fired = True
                        actions.append(("overflow_streak",
                                        dict(step=step,
                                             value=self._overflow_count,
                                             threshold=self.
                                             overflow_streak)))
                else:
                    self._overflow_count = 0
                    self._overflow_fired = False
        for name, kw in actions:
            if name == "stop_trace":
                self._stop_trace()
            else:
                self._alarm(name, **kw)

    @property
    def overflow_count(self) -> int:
        """Current consecutive-overflow streak length."""
        with self._lock:
            return self._overflow_count

    # -- stall check ---------------------------------------------------------

    def check_stall(self, now: Optional[float] = None) -> bool:
        """Fire the ``stall`` alarm if no step completed for
        ``stall_timeout`` seconds.  Returns True iff an alarm was
        emitted by *this* call (once per episode)."""
        with self._lock:
            now = self._clock() if now is None else now
            stalled = (now - self._last_progress) >= self.stall_timeout
            if not stalled or self._stall_fired:
                return False
            self._stall_fired = True
            self._stall_seq += 1
            seq = self._stall_seq
            value = now - self._last_progress
            last_step = self._last_step
        # emit + trace capture outside the lock (see observe_step);
        # the _stall_fired latch above guarantees at most one thread
        # reaches this per episode.  A recovery racing in between can
        # reorder the stall/stall_recovered emissions (each carries
        # its own wall time; the pair is always complete) — but the
        # trace must not leak: _start_trace re-checks episode
        # liveness (seq) under the trace lock.
        self._alarm("stall", value=value, step=last_step,
                    timeout_s=self.stall_timeout,
                    last_step=last_step)
        self._start_trace(seq)
        return True

    # -- optional jax.profiler dump of the wedged step -----------------------

    def _start_trace(self, seq: int) -> None:
        """Start the wedged-step profiler trace for stall episode
        ``seq`` — a no-op when that episode already recovered (the
        check_stall thread lost the race to observe_step): starting
        then would leak an open trace until the NEXT recovery.  The
        trace lock serializes the start/stop transitions; a
        concurrent ``_stop_trace`` either runs first (liveness check
        fails, nothing starts) or queues behind and stops what was
        started."""
        if not self.trace_dir:
            return
        started = False
        with self._trace_lock:
            with self._lock:
                live = self._stall_fired and seq == self._stall_seq
            if live and not self._tracing:
                try:
                    import jax

                    jax.profiler.start_trace(self.trace_dir)
                    self._tracing = True
                    started = True
                except Exception as e:  # telemetry must never kill
                    logger.warning("stall trace failed to start: %s",
                                   str(e)[:160])
        if started:
            self._alarm("stall_trace_started",
                        trace_dir=self.trace_dir)

    def _stop_trace(self) -> None:
        stopped = False
        with self._trace_lock:
            if self._tracing:
                try:
                    import jax

                    jax.profiler.stop_trace()
                    stopped = True
                except Exception as e:
                    logger.warning("stall trace failed to stop: %s",
                                   str(e)[:160])
                self._tracing = False
        if stopped:
            self._alarm("stall_trace_stopped",
                        trace_dir=self.trace_dir)

    # -- heartbeat thread ----------------------------------------------------

    def start(self) -> "Watchdog":
        """Start the daemon heartbeat thread (idempotent).  It wakes
        every ``heartbeat_interval`` seconds and runs
        :meth:`check_stall` — the only piece that must live off the
        main thread, which is by definition wedged during a stall."""
        if self._thread is not None:
            return self
        self._stop_evt = threading.Event()

        def beat():
            while not self._stop_evt.wait(self.heartbeat_interval):
                try:
                    self.check_stall()
                except Exception as e:
                    logger.warning("heartbeat check failed: %s",
                                   str(e)[:160])

        self._thread = threading.Thread(
            target=beat, name="apex_tpu-monitor-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._stop_evt is not None:
            self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
            self._stop_evt = None
        self._stop_trace()
