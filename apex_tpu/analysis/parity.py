"""Kernel-parity audit: every Pallas kernel must have a jnp twin and a
test exercising both.

The repo's kernel discipline (docs/PARITY.md lineage) is that each
``pl.pallas_call`` site in ``apex_tpu/ops`` is an *implementation* of
math that also exists as a plain-jnp twin — the twin is the XLA
fallback inside ``shard_map`` manual axes, the CPU/interpret oracle in
tests, and the spec a reviewer diffs the kernel against.  A kernel
whose twin (or twin test) quietly disappears keeps passing CI right up
until a Mosaic regression ships.  This audit makes the pairing a
structural invariant:

* every function in ``apex_tpu/ops`` containing a ``pallas_call`` must
  appear in :data:`KERNEL_TWINS` (APX401);
* the registered twin must exist where the registry says (APX401);
* at least one registered test file must reference BOTH the public
  entry point and the twin by name (APX402).

Run via ``python -m apex_tpu.analysis --check`` (self-hosted in CI).
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from .linter import Finding

__all__ = ["KERNEL_TWINS", "TwinSpec", "audit_kernel_parity",
           "pallas_call_sites"]


@dataclasses.dataclass(frozen=True)
class TwinSpec:
    """Registry row for one kernel-bearing function."""

    public: str              # public symbol tests dispatch the kernel via
    twin: str                # jnp twin symbol
    twin_module: str         # repo-relative file defining the twin
    tests: Tuple[str, ...]   # test files that must reference public+twin


def _spec(public: str, twin: str, twin_module: str,
          *tests: str) -> TwinSpec:
    return TwinSpec(public=public, twin=twin, twin_module=twin_module,
                    tests=tuple(tests))


# (ops module basename, enclosing top-level function) -> TwinSpec
KERNEL_TWINS: Dict[Tuple[str, str], TwinSpec] = {
    # flash attention: every fwd/bwd/packed/E-layout kernel family is
    # specified by the dense mha_reference
    **{("flash_attention.py", fn): _spec(
        "flash_attention", "mha_reference",
        "apex_tpu/ops/flash_attention.py",
        "tests/test_flash_attention.py")
       for fn in ("_flash_fwd", "_flash_fwd_packed", "_flash_bwd",
                  "_flash_bwd_packed", "_flash_fwd_e",
                  "_flash_fwd_e_blocked", "_flash_bwd_e",
                  "_flash_bwd_e_blocked")},
    # flash decode: the paged single-query serving kernel is specified
    # by the dense gather-and-softmax reference (also the naive decode
    # baseline the serving bench row measures against)
    ("flash_decode.py", "_decode_paged"): _spec(
        "flash_decode", "paged_attention_reference",
        "apex_tpu/ops/flash_decode.py", "tests/test_serving.py"),
    # multi-token paged attention (ISSUE-12): the speculative-verify /
    # chunked-prefill chunk kernel, specified by the dense per-row
    # causal gather reference
    ("flash_decode.py", "_decode_paged_multi"): _spec(
        "flash_decode_multi", "paged_attention_multi_reference",
        "apex_tpu/ops/flash_decode.py", "tests/test_serving.py"),
    ("layer_norm.py", "_ln_forward"): _spec(
        "layer_norm", "_layer_norm_reference",
        "apex_tpu/ops/layer_norm.py", "tests/test_layer_norm.py"),
    ("layer_norm.py", "_ln_backward"): _spec(
        "layer_norm", "_layer_norm_reference",
        "apex_tpu/ops/layer_norm.py", "tests/test_layer_norm.py"),
    ("scaled_softmax.py", "_causal_fwd"): _spec(
        "scaled_upper_triang_masked_softmax", "_causal_softmax_xla",
        "apex_tpu/ops/scaled_softmax.py", "tests/test_fused_layers.py"),
    ("scaled_softmax.py", "_softmax_backward"): _spec(
        "scaled_upper_triang_masked_softmax", "_causal_softmax_xla",
        "apex_tpu/ops/scaled_softmax.py", "tests/test_fused_layers.py"),
    ("scaled_softmax.py", "_masked_fwd"): _spec(
        "scaled_masked_softmax", "_masked_softmax_xla",
        "apex_tpu/ops/scaled_softmax.py", "tests/test_fused_layers.py"),
    # the shared elementwise dispatcher carries every fused-optimizer
    # kernel; _adam_jnp is the per-leaf twin the optimizers fall back to
    ("fused_optim.py", "_elementwise_call"): _spec(
        "adam_update", "_adam_jnp",
        "apex_tpu/optimizers/fused_adam.py", "tests/test_optimizers.py",
        "tests/test_fused_pipeline.py"),
    ("fused_pipeline.py", "_norm_finite_pallas"): _spec(
        "grad_norm_finite", "_norm_finite_jnp",
        "apex_tpu/ops/fused_pipeline.py", "tests/test_fused_pipeline.py"),
    # int8 weight-only matmul (ISSUE-16 Q8 tier): GEMV decode path and
    # tiled prefill path, both specified by the scale-after-matmul
    # fp32 reference (also the XLA fallback off TPU)
    **{("quant_matmul.py", fn): _spec(
        "quant_matmul", "quant_matmul_reference",
        "apex_tpu/ops/quant_matmul.py", "tests/test_quant_matmul.py")
       for fn in ("_quant_gemv", "_quant_tiled")},
    # fused MoE routing + dispatch (ISSUE-19): softmax/top-k/capacity
    # slotting/scatter in one pass, specified by the GShard cumsum
    # reference (bit-identical keep/slot decisions across backends)
    ("moe_routing.py", "_route_dispatch_pallas"): _spec(
        "moe_route_dispatch", "moe_route_dispatch_reference",
        "apex_tpu/ops/moe_routing.py", "tests/test_moe_routing.py"),
}


def pallas_call_sites(ops_dir: Path) -> List[Tuple[str, str, int]]:
    """(module basename, enclosing top-level function, line) for every
    ``pallas_call`` under ``ops_dir``."""
    def is_pallas_call(sub: ast.AST) -> bool:
        if not isinstance(sub, ast.Call):
            return False
        f = sub.func
        name = f.attr if isinstance(f, ast.Attribute) else \
            getattr(f, "id", None)
        return name == "pallas_call"

    sites: List[Tuple[str, str, int]] = []
    for py in sorted(ops_dir.glob("*.py")):
        tree = ast.parse(py.read_text())
        claimed: set = set()
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if is_pallas_call(sub) and id(sub) not in claimed:
                        claimed.add(id(sub))
                        sites.append((py.name, node.name, sub.lineno))
        for sub in ast.walk(tree):  # module scope / lambda leftovers
            if is_pallas_call(sub) and id(sub) not in claimed:
                sites.append((py.name, "<module>", sub.lineno))
    return sites


def _defines(path: Path, symbol: str) -> bool:
    if not path.exists():
        return False
    tree = ast.parse(path.read_text())
    return any(isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef))
               and n.name == symbol for n in tree.body)


def audit_kernel_parity(*, repo_root: str = ".") -> List[Finding]:
    repo = Path(repo_root).resolve()
    ops_dir = repo / "apex_tpu" / "ops"
    findings: List[Finding] = []
    checked_specs = set()
    for module, fn, line in pallas_call_sites(ops_dir):
        rel = f"apex_tpu/ops/{module}"
        spec = KERNEL_TWINS.get((module, fn))
        if spec is None:
            findings.append(Finding(
                path=rel, line=line, col=0, rule="APX401",
                severity="error",
                message=f"pallas_call in '{fn}' has no registered jnp "
                        f"twin — add a KERNEL_TWINS entry in "
                        f"apex_tpu/analysis/parity.py",
                symbol=f"{fn}.unregistered"))
            continue
        if (module, fn) in checked_specs:
            continue
        checked_specs.add((module, fn))
        if not _defines(repo / spec.twin_module, spec.twin):
            findings.append(Finding(
                path=rel, line=line, col=0, rule="APX401",
                severity="error",
                message=f"registered twin '{spec.twin}' for kernel "
                        f"'{fn}' is not defined in {spec.twin_module}",
                symbol=f"{fn}.missing_twin"))
            continue
        referenced = False
        for test in spec.tests:
            tp = repo / test
            if not tp.exists():
                continue
            text = tp.read_text()
            if spec.public in text and spec.twin in text:
                referenced = True
                break
        if not referenced:
            findings.append(Finding(
                path=rel, line=line, col=0, rule="APX402",
                severity="error",
                message=f"no test in {list(spec.tests)} references "
                        f"both '{spec.public}' and twin '{spec.twin}' "
                        f"— kernel/twin parity is untested",
                symbol=f"{fn}.untested"))
    return findings
