"""Deterministic-interleaving schedule harness — the dynamic half of
the APX8xx host-concurrency audit.

The static auditor (:mod:`.concurrency`) proves the lock discipline
*as written*; this module stresses the discipline *as executed*: a
seeded cooperative scheduler serializes the threaded serving fleet's
replica threads at their tick boundaries in a *permuted, reproducible*
order, so the same request trace runs under many different
interleavings — and the terminal fleet digest (every request's output
tokens) must be **seed-invariant**.  A cross-thread race that feeds
back into outputs, a lost update in shared bookkeeping, or a
background thread dying silently shows up as a digest mismatch, a
lost request, or a captured ``threading.excepthook`` failure instead
of a once-a-month production mystery.

Three pieces:

* :class:`DeterministicScheduler` — a condition-variable gate every
  replica thread passes at each tick boundary
  (:meth:`~apex_tpu.serving.fleet.FleetRouter.serve_threaded`'s
  ``scheduler`` hook).  Exactly one thread runs between gates; the
  next runner is drawn from a ``random.Random(seed)`` stream, so one
  seed is one total order and five seeds are five genuinely different
  interleavings — each reproducible bit-for-bit.
* :func:`run_fleet_seed` / :func:`schedule_sweep` — build the smoke-
  GPT fleet (same construction as ``standalone_gpt --serve-fleet``),
  serve one fixed request trace per seed under the gate, and report
  per-seed digests plus any :class:`~apex_tpu.monitor.events.
  ThreadExceptionCapture` failures.
* the CLI — ``python -m apex_tpu.analysis.schedule`` (ci.sh step 14):
  N seeds (``APEX_TPU_SCHED_SEEDS``) x the 2-replica threaded fleet,
  asserting identical digests, zero lost requests, and zero uncaught
  thread exceptions.

Everything here is host-side and CPU-friendly; the scheduler is a
test/CI instrument, never a production code path.
"""
from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Set

from .flags import flag_int

__all__ = ["DeterministicScheduler", "ScheduleTimeout", "SeedRun",
           "SweepReport", "fleet_digest", "process_sweep",
           "run_fleet_seed", "run_process_fleet_seed",
           "schedule_sweep", "main"]


class ScheduleTimeout(RuntimeError):
    """A gated thread waited past the scheduler timeout — some other
    thread wedged while holding the schedule slot."""


class DeterministicScheduler:
    """Seeded cooperative serializer for thread tick boundaries.

    Threads are announced up-front with :meth:`expect` (main thread,
    before they start), call :meth:`gate` at every tick boundary, and
    :meth:`finish` on exit (``finally``).  At any instant at most one
    expected thread is *granted*; when the grant holder reaches its
    next gate (or finishes), the next holder is drawn from the seeded
    stream over the still-active threads.  The grant sequence
    (:attr:`grants`) is a pure function of the seed and the threads'
    lifetimes — the reproducible interleaving.

    The gate itself is the canonical condition-variable wait (the
    ``Condition.wait``-releases-the-lock idiom APX804 exempts); a
    thread that waits past ``timeout`` raises :class:`ScheduleTimeout`
    rather than hanging CI.
    """

    def __init__(self, seed: int, *, timeout: float = 120.0):
        self.seed = int(seed)
        self.timeout = float(timeout)
        self._rng = random.Random(int(seed))
        self._cv = threading.Condition()
        self._active: Set[str] = set()
        self._current: Optional[str] = None
        # a grant is *pending* until its thread passes the gate
        # (claimed); the holder's NEXT gate call releases it.  A
        # thread arriving at a grant it has not consumed yet takes it
        # — it must not re-roll someone else's turn away.
        self._claimed = False
        self.grants: List[str] = []

    def expect(self, name: str) -> None:
        """Announce a thread (call before it starts)."""
        with self._cv:
            self._active.add(str(name))

    def gate(self, name: str) -> None:
        """Tick boundary: release a held grant, then block until the
        seeded stream hands a fresh one back."""
        name = str(name)
        deadline = time.monotonic() + self.timeout
        with self._cv:
            if name not in self._active:
                return
            if self._current == name and self._claimed:
                self._current = None
                self._pick_locked()
            elif self._current is None:
                self._pick_locked()
            while not (self._current == name and not self._claimed):
                if name not in self._active:
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0.0:
                    raise ScheduleTimeout(
                        f"thread {name!r} starved at the schedule "
                        f"gate for {self.timeout:.0f}s (current "
                        f"grant: {self._current!r})")
                self._cv.wait(min(remaining, 1.0))
            self._claimed = True

    def finish(self, name: str) -> None:
        """Thread exit: leave the pool and hand the grant on."""
        with self._cv:
            name = str(name)
            self._active.discard(name)
            if self._current == name:
                self._current = None
                self._pick_locked()
            elif self._current is None and self._active:
                self._pick_locked()
            self._cv.notify_all()

    def _pick_locked(self) -> None:
        if self._current is None and self._active:
            self._current = self._rng.choice(sorted(self._active))
            self._claimed = False
            self.grants.append(self._current)
        self._cv.notify_all()


# ---------------------------------------------------------------------------
# The fleet stress sweep
# ---------------------------------------------------------------------------

def fleet_digest(router) -> str:
    """Deterministic digest of a whole fleet's terminal output: each
    replica's :meth:`~apex_tpu.serving.engine.ServingEngine.
    tokens_digest` folded in replica order.  Identical digests across
    scheduler seeds == token-for-token identical fleet output under
    every tried interleaving."""
    import hashlib

    h = hashlib.md5()
    for r in sorted(router.replicas, key=lambda x: str(x.replica_id)):
        h.update(f"{r.replica_id}="
                 f"{r.engine.tokens_digest()};".encode())
    return h.hexdigest()[:12]


@dataclasses.dataclass
class SeedRun:
    """One seed's outcome."""

    seed: int
    digest: str
    tokens: int
    requests_done: int
    lost: int
    grants: int                 # schedule hand-offs taken
    thread_failures: List[Dict[str, Any]]


@dataclasses.dataclass
class SweepReport:
    """What :func:`schedule_sweep` measured across every seed."""

    runs: List[SeedRun]

    @property
    def digests(self) -> Dict[int, str]:
        return {r.seed: r.digest for r in self.runs}

    @property
    def invariant(self) -> bool:
        return len({r.digest for r in self.runs}) <= 1

    def failures(self) -> List[str]:
        out = []
        if not self.invariant:
            out.append(f"terminal digest is NOT seed-invariant: "
                       f"{self.digests} — a thread interleaving "
                       f"changed the fleet's output")
        for r in self.runs:
            if r.lost:
                out.append(f"seed {r.seed}: {r.lost} lost request(s)")
            for f in r.thread_failures:
                out.append(f"seed {r.seed}: background thread "
                           f"{f.get('thread')!r} died: "
                           f"{f.get('error')}: {f.get('message')}")
        return out


def run_fleet_seed(seed: int, *, replicas: int = 2,
                   num_requests: int = 6, new_tokens: int = 4,
                   hidden: int = 32, num_layers: int = 2,
                   timeout: float = 120.0, **fleet_kw) -> SeedRun:
    """Serve one fixed request trace (request RNG pinned to 0) on a
    fresh threaded fleet under the seeded schedule gate.  Background-
    thread exceptions are captured (not just printed) and returned on
    the :class:`SeedRun`."""
    from ..monitor.events import (BackgroundThreadError,
                                  ThreadExceptionCapture)
    from ..serving import BucketLadder
    from ..testing.standalone_gpt import fleet_smoke

    sched = DeterministicScheduler(seed, timeout=timeout)
    cap = ThreadExceptionCapture().install()
    summary = router = None
    try:
        summary, router = fleet_smoke(
            num_requests, replicas=replicas, threads=True,
            scheduler=sched, max_new_tokens=new_tokens,
            hidden=hidden, num_layers=num_layers,
            ladder=BucketLadder(batch=(2, 4), pages=(2, 4)),
            num_blocks=32, block_size=4, seed=0,
            return_router=True, **fleet_kw)
    except BackgroundThreadError:
        # already captured in cap.failures; the SeedRun reports it
        pass
    finally:
        cap.uninstall()
    failures = [{k: v for k, v in f.items() if k != "exception"}
                for f in cap.failures]
    return SeedRun(
        seed=int(seed),
        digest=fleet_digest(router) if router is not None else "",
        tokens=summary.tokens_generated if summary else 0,
        requests_done=summary.requests_done if summary else 0,
        lost=summary.lost_requests if summary else num_requests,
        grants=len(sched.grants),
        thread_failures=failures)


def schedule_sweep(seeds: Sequence[int], **kw) -> SweepReport:
    """Run :func:`run_fleet_seed` for every seed; the report's
    :meth:`~SweepReport.failures` is empty iff the fleet's terminal
    digest is identical across all of them with zero lost requests
    and zero uncaught thread exceptions."""
    return SweepReport(runs=[run_fleet_seed(s, **kw) for s in seeds])


def run_process_fleet_seed(seed: int, *, replicas: int = 2,
                           num_requests: int = 4,
                           new_tokens: int = 3, hidden: int = 16,
                           num_layers: int = 1,
                           **fleet_kw) -> SeedRun:
    """The ISSUE-18 process-boundary twin of :func:`run_fleet_seed`:
    one fixed request trace (request RNG pinned to 0) served by the
    PROCESS-isolated fleet, with ``seed`` permuting the supervisor's
    per-round replica tick order instead of a thread schedule.  The
    fleet digest (journal-merged, routing-invariant) must not care —
    crash-reshuffled or seed-reshuffled, greedy decode is
    interleaving-invariant across process boundaries too.  ``grants``
    reports supervisor rounds (the closest analogue of schedule
    hand-offs)."""
    from ..testing.standalone_gpt import fleet_procs_smoke

    summary = fleet_procs_smoke(
        num_requests, replicas=replicas, max_new_tokens=new_tokens,
        hidden=hidden, num_layers=num_layers, num_heads=2,
        decode_attention="reference", seed=0, tick_seed=int(seed),
        **fleet_kw)
    return SeedRun(
        seed=int(seed), digest=summary.digest,
        tokens=summary.tokens_generated,
        requests_done=summary.requests_done,
        lost=summary.lost_requests, grants=summary.rounds,
        thread_failures=[])


def process_sweep(seeds: Sequence[int], **kw) -> SweepReport:
    """:func:`schedule_sweep` across the process boundary: every seed
    drives :func:`run_process_fleet_seed`; same :class:`SweepReport`
    invariant (identical digest, zero lost) over subprocess fleets."""
    return SweepReport(runs=[run_process_fleet_seed(s, **kw)
                             for s in seeds])


# ---------------------------------------------------------------------------
# CLI — ci.sh step 14's stress leg
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis.schedule",
        description="Seeded deterministic-schedule fleet stress: N "
                    "seeds x the threaded serving fleet under "
                    "permuted tick interleavings; fails unless every "
                    "seed produces the identical terminal digest "
                    "with zero lost requests and zero uncaught "
                    "background-thread exceptions.")
    ap.add_argument("--seeds", type=int, default=None,
                    help="number of seeds to sweep (default: "
                         "APEX_TPU_SCHED_SEEDS)")
    ap.add_argument("--base-seed", type=int, default=0,
                    help="first seed (default 0)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=6)
    ap.add_argument("--new-tokens", type=int, default=4)
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-gate starvation timeout (seconds)")
    ap.add_argument("--procs", action="store_true",
                    help="sweep the PROCESS-isolated fleet instead "
                         "(ISSUE-18): each seed permutes the "
                         "supervisor's per-round replica tick order "
                         "across subprocess boundaries; the journal-"
                         "merged fleet digest must be identical")
    args = ap.parse_args(argv)

    n = args.seeds if args.seeds is not None \
        else flag_int("APEX_TPU_SCHED_SEEDS")
    if n < 1:
        ap.error(f"--seeds must be >= 1, got {n} (a zero-seed sweep "
                 f"proves nothing)")
    if args.procs:
        report = process_sweep(
            range(args.base_seed, args.base_seed + n),
            replicas=args.replicas, num_requests=args.requests,
            new_tokens=args.new_tokens)
    else:
        report = schedule_sweep(
            range(args.base_seed, args.base_seed + n),
            replicas=args.replicas, num_requests=args.requests,
            new_tokens=args.new_tokens, timeout=args.timeout)
    for r in report.runs:
        print(f"[schedule] seed {r.seed}: digest={r.digest} "
              f"done={r.requests_done} tokens={r.tokens} "
              f"lost={r.lost} grants={r.grants} "
              f"thread_failures={len(r.thread_failures)}")
    failures = report.failures()
    for f in failures:
        print(f"[schedule] FAIL: {f}", file=sys.stderr)
    if failures:
        return 1
    print(f"[schedule] OK: {n} seed(s), identical terminal digest "
          f"{report.runs[0].digest} across every interleaving, "
          f"0 lost requests, 0 uncaught thread exceptions")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
