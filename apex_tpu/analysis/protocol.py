"""APX9xx wire-protocol + resource-lifecycle auditor.

PR 18's control plane made every fleet boundary a hand-rolled socket
protocol: string-dispatched ops, per-call timeout floats, ad-hoc
header dicts on both sides of an AF_UNIX frame.  The contract now
lives as data — :data:`~apex_tpu.serving.control_plane.PROTOCOL`, a
registry of :class:`~apex_tpu.serving.control_plane.ProtocolSpec`
entries (op → direction, required/optional header fields, blob
shape, timeout class, idempotency) that the child dispatch table and
the parent retry/timeout policy are derived from at runtime.  This
module is the STATIC half: an AST audit of ``serving/`` +
``resilience/`` against that registry, on the same machinery as the
PR-5 linter and the PR-15 concurrency auditor (structured
:class:`~.linter.Finding` s, reasoned inline suppressions, a
committed baseline with stale-entry-fails semantics, rule-registry
docs generation).

Rules (docs/api/analysis.md for the long-form table):

==========  ================================================================
APX901      RPC send/recv without an explicit deadline, or with a
            literal one: ``.call(op)`` / ``.post(op)`` missing a
            ``timeout=`` keyword, ``.wait(seq)`` missing one, or any
            of them (and ``.settimeout``) passing a NUMERIC LITERAL
            instead of a value routed through the registry's timeout
            class (``_op_timeout`` / the ``APEX_TPU_CP_*_TIMEOUT_S``
            flags).  Applies to modules that speak the protocol —
            ones that define or import the control-plane surface
            (``ReplicaProcess`` / ``ProcessFleet`` / ``send_frame``
            / ``recv_frame`` / a ``ProtocolSpec`` registry).
APX902      op drift, matched across every scanned module: an op
            sent (``.call``/``.post`` with a constant op, or a
            child→parent ``send_frame`` dict literal) that no
            receiving dispatch handles; a handler (``*_HANDLERS``
            dict key or ``op == "..."`` compare) for an op no sender
            emits — the dead branch; either side using an op the
            ``ProtocolSpec`` registry never declared; and a declared
            op with no sender or no handler (a stale spec entry).
APX903      header-field drift — the KeyError-at-3am class: a sender
            header literal carrying a field the op's spec doesn't
            declare (or missing a required one); a receiver
            ``.get()``/index on a reply or request header for a
            field the spec doesn't declare (reply reads are tracked
            through ``reply, _ = rp.call("op", ...)`` assignments,
            request reads through the handler table's functions, the
            hello handshake through ``hello``-named frames); a
            handler returning reply fields off-spec; and binary-blob
            shape — blobs passed on an op whose spec declares none.
APX904      resource lifecycle: a socket / accepted conn /
            subprocess / tempdir / journal sink acquired into a
            local and not guaranteed released on ALL paths — no
            release at all, or risky statements between the
            acquisition and the ``try``/``with``/ownership-transfer
            that protects it (finally/context-manager/close-on-error
            discipline).  Also: ``os.kill(pid, SIGKILL)`` in a
            function with no ``.join`` — SIGKILLed children must be
            reaped, not zombied (killing yourself via ``os.getpid()``
            is exempt; nothing runs after).
APX905      retry-safety: a ``retries=``>0 on an op whose spec is
            not marked idempotent (a blind re-send can double-apply
            work — escalate to restart + journal replay instead),
            and retry loops (``while``/``for range`` re-entering
            after catching an RPC/OS error) without a bound
            (``for range`` / a ``raise``/``break`` escape) or
            without backoff (``backoff_delay``/``sleep``/a
            ``*restart*`` escalation, which backs off internally).
==========  ================================================================

Suppression: the linter's inline form
(``# apex-lint: disable=APX904 -- <reason>``) or the committed
baseline ``tools/protocol_baseline.txt`` (same
``path:RULE:symbol  # reason`` format and the same stale-entry-fails
semantics as the other baselines; committed EMPTY — every finding at
introduction was fixed).  CI runs
``python -m apex_tpu.analysis --check-protocol`` self-hosted.

Import-light on purpose (stdlib ``ast`` only), like :mod:`.linter`:
the registry is read out of ``serving/control_plane.py``'s AST, not
imported — the auditor never pulls jax into the process.
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from .linter import (Finding, _iter_py, _suppressions, load_baseline,
                     write_baseline)

__all__ = ["lint_protocol_source", "lint_protocol_paths",
           "run_protocol_check", "write_protocol_baseline",
           "DEFAULT_BASELINE", "PROTOCOL_SCAN_TREES"]

DEFAULT_BASELINE = "tools/protocol_baseline.txt"

#: package-relative trees the auditor walks — the modules that speak
#: (or supervise) the control-plane wire protocol
PROTOCOL_SCAN_TREES = ("serving", "resilience")

#: framing-layer fields every op may carry (mirrors
#: ``control_plane.FRAME_FIELDS`` — kept literal here so the auditor
#: never imports the serving package)
_FRAME_FIELDS = {"op", "seq", "blobs", "error", "message"}

#: names whose presence marks a module as protocol-speaking (APX901's
#: scope gate)
_PROTOCOL_MARKERS = {"ReplicaProcess", "ProcessFleet", "send_frame",
                     "recv_frame", "ProtocolSpec"}

#: constructor/call tails whose result is an owned OS resource
_ACQUIRE_TAILS = {"socket", "accept", "mkdtemp", "mkstemp", "Popen",
                  "Process", "JsonlSink"}

#: attribute calls that release/retire a resource
_RELEASE_ATTRS = {"close", "kill", "terminate", "join", "stop",
                  "shutdown", "cleanup", "release", "unlink"}

#: free functions that release when handed the resource
_RELEASE_FUNCS = {"rmtree", "unlink", "remove", "closing"}

#: exception tails whose catch-and-continue marks a retry loop
_RETRYABLE_ERRORS = {"RpcError", "RpcTimeout", "ReplicaDead",
                     "RpcRemoteError", "OSError", "ConnectionError",
                     "TimeoutError", "timeout"}

#: call tails that count as backoff inside a retry loop (a
#: ``*restart*`` escalation counts: the restart path sleeps its own
#: bounded backoff before respawning)
_BACKOFF_TAILS = {"sleep", "backoff_delay"}


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _const_strs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            s = _const_str(e)
            if s is None:
                return None
            out.append(s)
        return tuple(out)
    return None


def _is_num(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, (int, float))
            and not isinstance(node.value, bool))


# ---------------------------------------------------------------------------
# per-module facts
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _OpSpec:
    """One ``ProtocolSpec(...)`` call, read out of the AST."""

    op: str
    direction: str = "parent_to_child"
    required: Tuple[str, ...] = ()
    optional: Tuple[str, ...] = ()
    reply: Tuple[str, ...] = ()
    request_blobs: bool = False
    idempotent: bool = False
    path: str = ""
    line: int = 0
    col: int = 0

    @property
    def request_fields(self) -> Set[str]:
        return set(self.required) | set(self.optional) | _FRAME_FIELDS

    @property
    def reply_fields(self) -> Set[str]:
        return set(self.reply) | _FRAME_FIELDS


@dataclasses.dataclass
class _Sender:
    """One op send site: ``X.call("op", {...})`` / ``X.post`` on the
    parent side, ``send_frame(conn, {"op": ..., ...})`` on the child
    side."""

    op: str
    path: str
    line: int
    col: int
    func: str                       # enclosing function name
    direction: str                  # 'parent' | 'child'
    keys: Optional[Tuple[str, ...]]  # header literal keys, if visible
    complete: bool                  # keys are the WHOLE header
    has_blobs: bool
    has_timeout: bool
    literal_timeout: bool
    retries_nonzero: bool


@dataclasses.dataclass
class _Handler:
    op: str
    path: str
    line: int
    col: int
    func: Optional[str]             # dispatch target, if a dict entry


@dataclasses.dataclass
class _FieldRead:
    op: str
    field: str
    side: str                       # 'reply' | 'request'
    path: str
    line: int
    col: int
    func: str


@dataclasses.dataclass
class _ReplyLiteral:
    op: str
    keys: Tuple[str, ...]
    path: str
    line: int
    col: int
    func: str


@dataclasses.dataclass
class _ModuleInfo:
    path: str
    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: Dict[int, Set[str]] = dataclasses.field(
        default_factory=dict)
    spec: Dict[str, _OpSpec] = dataclasses.field(default_factory=dict)
    senders: List[_Sender] = dataclasses.field(default_factory=list)
    handlers: List[_Handler] = dataclasses.field(default_factory=list)
    reads: List[_FieldRead] = dataclasses.field(default_factory=list)
    reply_literals: List[_ReplyLiteral] = dataclasses.field(
        default_factory=list)
    #: dispatch-table func name → op (for request-side field reads)
    handler_funcs: Dict[str, str] = dataclasses.field(
        default_factory=dict)


# ---------------------------------------------------------------------------
# extraction
# ---------------------------------------------------------------------------

def _extract_spec(tree: ast.Module, path: str) -> Dict[str, _OpSpec]:
    out: Dict[str, _OpSpec] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _tail(node.func) == "ProtocolSpec"):
            continue
        op = _const_str(node.args[0]) if node.args else None
        kw: Dict[str, Any] = {}
        for k in node.keywords:
            if k.arg == "op" and op is None:
                op = _const_str(k.value)
            elif k.arg == "direction":
                kw["direction"] = _const_str(k.value) or \
                    "parent_to_child"
            elif k.arg in ("required", "optional", "reply"):
                kw[k.arg] = _const_strs(k.value) or ()
            elif k.arg in ("request_blobs", "idempotent"):
                kw[k.arg] = bool(isinstance(k.value, ast.Constant)
                                 and k.value.value)
        if op is not None and op not in out:
            out[op] = _OpSpec(op=op, path=path, line=node.lineno,
                              col=node.col_offset, **kw)
    return out


def _speaks_protocol(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and "control_plane" in node.module:
                return True
            if any(a.name in _PROTOCOL_MARKERS
                   for a in node.names):
                return True
        elif isinstance(node, (ast.FunctionDef, ast.ClassDef)):
            if node.name in _PROTOCOL_MARKERS:
                return True
        elif isinstance(node, ast.Call):
            if _tail(node.func) == "ProtocolSpec":
                return True
    return False


def _kwarg(call: ast.Call, *names: str) -> Optional[ast.expr]:
    for k in call.keywords:
        if k.arg in names:
            return k.value
    return None


def _func_defs(tree: ast.Module):
    """Every (qualname-ish function name, FunctionDef) in the module."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _enclosing_funcs(tree: ast.Module) -> Dict[ast.AST, str]:
    """stmt/expr node → name of the innermost enclosing function."""
    owner: Dict[ast.AST, str] = {}

    def visit(node: ast.AST, fn: str) -> None:
        for child in ast.iter_child_nodes(node):
            here = fn
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                here = child.name
            owner[child] = here
            visit(child, here)

    owner[tree] = "<module>"
    visit(tree, "<module>")
    return owner


def _collect_senders(tree: ast.Module, path: str,
                     owner: Dict[ast.AST, str]) -> List[_Sender]:
    out: List[_Sender] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        tail = _tail(node.func)
        fn = owner.get(node, "<module>")
        if tail in ("call", "post") and isinstance(node.func,
                                                   ast.Attribute):
            op = _const_str(node.args[0]) if node.args else None
            if op is None:
                continue
            header = (node.args[1] if len(node.args) > 1
                      else _kwarg(node, "header"))
            keys: Optional[Tuple[str, ...]] = ()
            complete = True
            if isinstance(header, ast.Dict):
                ks = []
                complete = True
                for k in header.keys:
                    s = _const_str(k) if k is not None else None
                    if s is None:
                        complete = False   # ** / computed key
                        continue
                    ks.append(s)
                keys = tuple(ks)
            elif header is not None and not (
                    isinstance(header, ast.Constant)
                    and header.value is None):
                keys, complete = None, False
            blobs = (node.args[2] if len(node.args) > 2
                     else _kwarg(node, "blobs"))
            has_blobs = blobs is not None and not (
                isinstance(blobs, (ast.Tuple, ast.List))
                and not blobs.elts)
            timeout = _kwarg(node, "timeout", "timeout_s")
            retries = _kwarg(node, "retries")
            out.append(_Sender(
                op=op, path=path, line=node.lineno,
                col=node.col_offset, func=fn, direction="parent",
                keys=keys, complete=complete, has_blobs=has_blobs,
                has_timeout=timeout is not None,
                literal_timeout=(timeout is not None
                                 and _is_num(timeout)),
                retries_nonzero=(retries is not None and not (
                    isinstance(retries, ast.Constant)
                    and not retries.value))))
        elif tail == "send_frame" and len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Dict):
            d = node.args[1]
            fields: Dict[str, ast.expr] = {}
            complete = True
            for k, v in zip(d.keys, d.values):
                s = _const_str(k) if k is not None else None
                if s is None:
                    complete = False
                    continue
                fields[s] = v
            op = (_const_str(fields["op"])
                  if "op" in fields else None)
            if op is None:
                continue
            out.append(_Sender(
                op=op, path=path, line=node.lineno,
                col=node.col_offset, func=fn, direction="child",
                keys=tuple(fields), complete=complete,
                has_blobs=len(node.args) > 2
                or _kwarg(node, "blobs") is not None,
                has_timeout=True, literal_timeout=False,
                retries_nonzero=False))
    return out


def _collect_handlers(tree: ast.Module, path: str,
                      owner: Dict[ast.AST, str]
                      ) -> Tuple[List[_Handler], Dict[str, str]]:
    handlers: List[_Handler] = []
    funcs: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = (node.targets
                       if isinstance(node, ast.Assign)
                       else [node.target])
            value = node.value
            if not (isinstance(value, ast.Dict)
                    and any(isinstance(t, ast.Name)
                            and t.id.endswith("_HANDLERS")
                            for t in targets)):
                continue
            for k, v in zip(value.keys, value.values):
                op = _const_str(k) if k is not None else None
                if op is None:
                    continue
                fname = v.id if isinstance(v, ast.Name) else None
                handlers.append(_Handler(
                    op=op, path=path, line=k.lineno,
                    col=k.col_offset, func=fname))
                if fname:
                    funcs[fname] = op
        elif isinstance(node, ast.Compare):
            # the `op == "shutdown"` dispatch shape (and if/elif
            # chains in general)
            if (isinstance(node.left, ast.Name)
                    and node.left.id == "op"
                    and len(node.ops) == 1
                    and isinstance(node.ops[0], ast.Eq)):
                op = _const_str(node.comparators[0])
                if op is not None:
                    handlers.append(_Handler(
                        op=op, path=path, line=node.lineno,
                        col=node.col_offset, func=None))
    return handlers, funcs


def _reads_of(body: ast.AST, var: str, op: str, side: str,
              path: str, fn: str) -> List[_FieldRead]:
    out: List[_FieldRead] = []
    for node in ast.walk(body):
        field = None
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == var and node.args):
            field = _const_str(node.args[0])
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.value, ast.Name)
              and node.value.id == var):
            field = _const_str(node.slice)
        if field is not None:
            out.append(_FieldRead(
                op=op, field=field, side=side, path=path,
                line=node.lineno, col=node.col_offset, func=fn))
    return out


def _collect_reads(tree: ast.Module, path: str,
                   handler_funcs: Dict[str, str]
                   ) -> Tuple[List[_FieldRead], List[_ReplyLiteral]]:
    reads: List[_FieldRead] = []
    literals: List[_ReplyLiteral] = []
    for fdef in _func_defs(tree):
        # parent side: `reply, blobs = X.call("op", ...)` binds the
        # reply var to the op; `hello, _ = recv_frame(...)` (and a
        # parameter literally named `hello`) binds the handshake
        bound: Dict[str, str] = {}
        for node in ast.walk(fdef):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            tgt = node.targets[0]
            name = None
            if isinstance(tgt, ast.Tuple) and tgt.elts \
                    and isinstance(tgt.elts[0], ast.Name):
                name = tgt.elts[0].id
            elif isinstance(tgt, ast.Name):
                name = tgt.id
            if name is None:
                continue
            tail = _tail(node.value.func)
            if tail == "call" and node.value.args:
                op = _const_str(node.value.args[0])
                if op is not None:
                    bound[name] = op
            elif tail == "recv_frame" and name == "hello":
                bound[name] = "hello"
        for arg in fdef.args.args:
            if arg.arg == "hello":
                bound["hello"] = "hello"
        for var, op in bound.items():
            side = "request" if op == "hello" else "reply"
            reads.extend(_reads_of(fdef, var, op, side, path,
                                   fdef.name))
        # child side: a dispatch-table handler's header param
        op = handler_funcs.get(fdef.name)
        if op is not None:
            args = [a.arg for a in fdef.args.args]
            hdr = ("header" if "header" in args
                   else args[1] if len(args) > 1 else None)
            if hdr:
                reads.extend(_reads_of(fdef, hdr, op, "request",
                                       path, fdef.name))
            for node in ast.walk(fdef):
                if not isinstance(node, ast.Return) \
                        or node.value is None:
                    continue
                d = node.value
                if isinstance(d, ast.Tuple) and d.elts:
                    d = d.elts[0]
                if isinstance(d, ast.Dict):
                    ks = tuple(s for s in (
                        _const_str(k) for k in d.keys
                        if k is not None) if s is not None)
                    literals.append(_ReplyLiteral(
                        op=op, keys=ks, path=path, line=d.lineno,
                        col=d.col_offset, func=fdef.name))
    return reads, literals


# ---------------------------------------------------------------------------
# APX901 — explicit, registry-routed deadlines
# ---------------------------------------------------------------------------

def _timeout_findings(tree: ast.Module, path: str,
                      owner: Dict[ast.AST, str],
                      emit) -> None:
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        tail = node.func.attr
        fn = owner.get(node, "<module>")
        if tail == "settimeout" and node.args \
                and _is_num(node.args[0]):
            emit("APX901", node.lineno, node.col_offset,
                 f"settimeout with the literal deadline "
                 f"{node.args[0].value!r} — route it through the "
                 f"registry's timeout class (a configured "
                 f"*_TIMEOUT_S value)", f"{fn}.settimeout")
        elif tail in ("call", "post") and node.args \
                and _const_str(node.args[0]) is not None:
            op = _const_str(node.args[0])
            timeout = _kwarg(node, "timeout", "timeout_s")
            if timeout is None:
                emit("APX901", node.lineno, node.col_offset,
                     f"{tail}({op!r}) without an explicit timeout= "
                     f"— every RPC carries its op's deadline",
                     f"{fn}.{op}")
            elif _is_num(timeout):
                emit("APX901", node.lineno, node.col_offset,
                     f"{tail}({op!r}) with the literal deadline "
                     f"{timeout.value!r} — route it through the "
                     f"registry's timeout class", f"{fn}.{op}")
        elif tail == "wait" and node.args:
            timeout = _kwarg(node, "timeout", "timeout_s")
            if timeout is None:
                emit("APX901", node.lineno, node.col_offset,
                     "wait() without an explicit timeout= — a lost "
                     "reply must surface as RpcTimeout, not a hang",
                     f"{fn}.wait")
            elif _is_num(timeout):
                emit("APX901", node.lineno, node.col_offset,
                     f"wait() with the literal deadline "
                     f"{timeout.value!r} — route it through the "
                     f"registry's timeout class", f"{fn}.wait")


# ---------------------------------------------------------------------------
# APX904 — resource lifecycle
# ---------------------------------------------------------------------------

def _releases(node: ast.AST, var: str) -> bool:
    """Does ``node``'s subtree release ``var``?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            f = n.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in _RELEASE_ATTRS
                    and isinstance(f.value, ast.Name)
                    and f.value.id == var):
                return True
            if _tail(f) in _RELEASE_FUNCS and any(
                    isinstance(a, ast.Name) and a.id == var
                    for a in n.args):
                return True
        elif isinstance(n, ast.withitem):
            for m in ast.walk(n.context_expr):
                if isinstance(m, ast.Name) and m.id == var:
                    return True
    return False


def _transfers(fdef: ast.AST, var: str) -> bool:
    """Ownership leaves the function: returned, stored on an object
    attribute, or appended to a container."""
    for n in ast.walk(fdef):
        if isinstance(n, ast.Return) and n.value is not None:
            for m in ast.walk(n.value):
                if isinstance(m, ast.Name) and m.id == var:
                    return True
        elif isinstance(n, ast.Assign):
            if any(isinstance(t, ast.Attribute)
                   for t in n.targets) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id == var:
                return True
        elif isinstance(n, ast.Call):
            if (isinstance(n.func, ast.Attribute)
                    and n.func.attr == "append"
                    and any(isinstance(a, ast.Name) and a.id == var
                            for a in n.args)):
                return True
    return False


def _stmt_frames(fdef: ast.AST):
    """Every (statement, owning body list, index, parent statement)
    in the function, parents first."""
    frames = []

    def visit(stmt_list, parent):
        for i, s in enumerate(stmt_list):
            frames.append((s, stmt_list, i, parent))
            for name in ("body", "orelse", "finalbody"):
                visit(getattr(s, name, []) or [], s)
            for h in getattr(s, "handlers", []) or []:
                visit(h.body, s)

    visit(getattr(fdef, "body", []), None)
    return frames


def _is_protection(stmt: ast.AST, var: str) -> bool:
    if isinstance(stmt, ast.Try):
        if any(_releases(h, var) for h in stmt.handlers) \
                or (stmt.finalbody
                    and any(_releases(s, var)
                            for s in stmt.finalbody)):
            return True
        return False
    if isinstance(stmt, ast.Assign):
        return (any(isinstance(t, ast.Attribute)
                    for t in stmt.targets)
                and isinstance(stmt.value, ast.Name)
                and stmt.value.id == var)
    if isinstance(stmt, ast.Return):
        return (stmt.value is not None and any(
            isinstance(m, ast.Name) and m.id == var
            for m in ast.walk(stmt.value)))
    if isinstance(stmt, ast.With):
        return any(_releases(w, var) for w in stmt.items)
    if isinstance(stmt, ast.Expr):
        return _releases(stmt, var)
    return False


def _lifecycle_findings(tree: ast.Module, path: str, emit) -> None:
    for fdef in _func_defs(tree):
        frames = _stmt_frames(fdef)
        by_stmt = {id(s): (lst, i, parent)
                   for s, lst, i, parent in frames}
        for stmt, lst, i, parent in frames:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            value = stmt.value
            if not (isinstance(value, ast.Call)
                    and _tail(value.func) in _ACQUIRE_TAILS):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            tgt = targets[0]
            if isinstance(tgt, ast.Tuple) and tgt.elts:
                tgt = tgt.elts[0]
            if isinstance(tgt, ast.Attribute):
                continue              # self.x = acquire(): owned
            if not isinstance(tgt, ast.Name):
                continue
            var = tgt.id
            kind = _tail(value.func)
            released = _releases(fdef, var)
            transferred = _transfers(fdef, var)
            if not released and not transferred:
                emit("APX904", stmt.lineno, stmt.col_offset,
                     f"{kind}() acquired into {var!r} and never "
                     f"released — close it in a finally / context "
                     f"manager / on the error path",
                     f"{fdef.name}.{var}")
                continue
            # guaranteed-on-all-paths check: an enclosing try whose
            # finally/handler releases it, or the very next
            # statement protects/transfers — anything between the
            # acquire and the protection can raise and leak
            enclosed = False
            node, owner_stmt = stmt, parent
            while owner_stmt is not None:
                if isinstance(owner_stmt, ast.Try) \
                        and _is_protection(owner_stmt, var):
                    enclosed = True
                    break
                node = owner_stmt
                owner_stmt = by_stmt.get(id(owner_stmt),
                                         (None, 0, None))[2]
            if enclosed:
                continue
            cur, cur_list, cur_i = stmt, lst, i
            protected = False
            while True:
                if cur_i + 1 < len(cur_list):
                    protected = _is_protection(
                        cur_list[cur_i + 1], var)
                    break
                up = by_stmt.get(id(cur), (None, 0, None))[2]
                if up is None:
                    break
                up_list, up_i, _ = by_stmt.get(
                    id(up), (None, 0, None))
                if up_list is None:
                    break
                cur, cur_list, cur_i = up, up_list, up_i
            if not protected:
                emit("APX904", stmt.lineno, stmt.col_offset,
                     f"{kind}() acquired into {var!r} without "
                     f"guaranteed release on all paths — wrap the "
                     f"statements between the acquisition and its "
                     f"release/handoff in try/finally (or close on "
                     f"the error path)", f"{fdef.name}.{var}")
        # SIGKILL without a reap
        for node in ast.walk(fdef):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "kill"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "os"
                    and len(node.args) >= 2
                    and _tail(node.args[1]) == "SIGKILL"):
                continue
            target = node.args[0]
            if isinstance(target, ast.Call) \
                    and _tail(target.func) == "getpid":
                continue              # killing yourself: no reap
            joins = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr == "join"
                for n in ast.walk(fdef))
            if not joins:
                emit("APX904", node.lineno, node.col_offset,
                     "os.kill(pid, SIGKILL) with no join in the "
                     "same function — SIGKILLed children must be "
                     "reaped, not left as zombies",
                     f"{fdef.name}.sigkill")


# ---------------------------------------------------------------------------
# APX905 — retry loops
# ---------------------------------------------------------------------------

def _catches_retryable(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return False
    types = t.elts if isinstance(t, ast.Tuple) else [t]
    if not any(_tail(x) in _RETRYABLE_ERRORS for x in types):
        return False
    # only a handler that SWALLOWS the error re-enters the loop — a
    # handler whose last statement unconditionally raises/returns/
    # breaks is translation or escape, not retry
    last = handler.body[-1] if handler.body else None
    return not isinstance(last, (ast.Raise, ast.Return, ast.Break))


def _retry_findings(tree: ast.Module, path: str,
                    owner: Dict[ast.AST, str], emit) -> None:
    for node in ast.walk(tree):
        if isinstance(node, ast.While):
            bounded_by_shape = False
        elif isinstance(node, ast.For) \
                and isinstance(node.iter, ast.Call) \
                and _tail(node.iter.func) == "range":
            bounded_by_shape = True
        else:
            continue
        retryish = any(
            isinstance(n, ast.Try)
            and any(_catches_retryable(h) for h in n.handlers)
            for n in ast.walk(node))
        if not retryish:
            continue
        fn = owner.get(node, "<module>")
        bounded = bounded_by_shape or any(
            isinstance(n, (ast.Raise, ast.Break))
            for n in ast.walk(node))
        backoff = any(
            isinstance(n, ast.Call) and (
                (_tail(n.func) or "") in _BACKOFF_TAILS
                or "restart" in (_tail(n.func) or "")
                or "backoff" in (_tail(n.func) or ""))
            for n in ast.walk(node))
        if not bounded:
            emit("APX905", node.lineno, node.col_offset,
                 "retry loop without a bound — a wedged peer spins "
                 "this forever; count attempts or raise past a "
                 "deadline", f"{fn}.retry_bound")
        if not backoff:
            emit("APX905", node.lineno, node.col_offset,
                 "retry loop without backoff — re-sending at full "
                 "rate hammers a struggling peer; sleep a "
                 "backoff_delay (or escalate through a restart "
                 "path, which backs off internally)",
                 f"{fn}.retry_backoff")


# ---------------------------------------------------------------------------
# per-module collection + cross-module drift
# ---------------------------------------------------------------------------

def _collect_module(source: str, path: str) -> Optional[_ModuleInfo]:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None                   # the main linter owns APX000
    info = _ModuleInfo(path=path)
    info.suppressed, _ = _suppressions(source, path)

    def emit(rule: str, line: int, col: int, message: str,
             symbol: str) -> None:
        if rule in info.suppressed.get(line, ()):
            return
        info.findings.append(Finding(
            path=path, line=line, col=col, rule=rule,
            severity="error", message=message, symbol=symbol))

    owner = _enclosing_funcs(tree)
    info.spec = _extract_spec(tree, path)
    info.senders = _collect_senders(tree, path, owner)
    info.handlers, info.handler_funcs = _collect_handlers(
        tree, path, owner)
    info.reads, info.reply_literals = _collect_reads(
        tree, path, info.handler_funcs)
    if _speaks_protocol(tree):
        _timeout_findings(tree, path, owner, emit)
    _lifecycle_findings(tree, path, emit)
    _retry_findings(tree, path, owner, emit)
    return info


def _drift_findings(modules: Sequence[_ModuleInfo]) -> List[Finding]:
    spec: Dict[str, _OpSpec] = {}
    for m in modules:
        for op, s in m.spec.items():
            spec.setdefault(op, s)
    if not spec:
        return []                     # no registry in scope: no drift
    out: List[Finding] = []
    sup = {m.path: m.suppressed for m in modules}

    def emit(path: str, line: int, col: int, rule: str,
             message: str, symbol: str) -> None:
        if rule in sup.get(path, {}).get(line, ()):
            return
        out.append(Finding(path=path, line=line, col=col, rule=rule,
                           severity="error", message=message,
                           symbol=symbol))

    senders = [s for m in modules for s in m.senders]
    handlers = [h for m in modules for h in m.handlers]
    parent_sent = {s.op for s in senders if s.direction == "parent"}
    child_sent = {s.op for s in senders if s.direction == "child"}
    handled = {h.op for h in handlers}
    p2c = {op for op, s in spec.items()
           if s.direction == "parent_to_child"}
    c2p = {op for op, s in spec.items()
           if s.direction == "child_to_parent"}

    # APX902: op drift
    for s in senders:
        if s.direction == "parent" and s.op not in spec:
            emit(s.path, s.line, s.col, "APX902",
                 f"op {s.op!r} sent but not declared in the "
                 f"ProtocolSpec registry", f"{s.func}.{s.op}.sent")
        elif s.direction == "parent" and handlers \
                and s.op not in handled:
            emit(s.path, s.line, s.col, "APX902",
                 f"op {s.op!r} sent but no receiving dispatch "
                 f"handles it — the child will answer with an "
                 f"unknown-op error",
                 f"{s.func}.{s.op}.unhandled")
        elif s.direction == "child" and s.op not in spec:
            emit(s.path, s.line, s.col, "APX902",
                 f"child sends op {s.op!r} the ProtocolSpec "
                 f"registry never declared",
                 f"{s.func}.{s.op}.sent")
    for h in handlers:
        if h.op not in spec:
            emit(h.path, h.line, h.col, "APX902",
                 f"handler for op {h.op!r} not declared in the "
                 f"ProtocolSpec registry", f"handler.{h.op}.spec")
        elif senders and h.op in p2c and h.op not in parent_sent:
            emit(h.path, h.line, h.col, "APX902",
                 f"dead branch: handler for op {h.op!r} that no "
                 f"sender emits", f"handler.{h.op}.dead")
    for op in sorted(p2c):
        s = spec[op]
        if handlers and op not in handled:
            emit(s.path, s.line, s.col, "APX902",
                 f"op {op!r} declared but no dispatch handles it",
                 f"spec.{op}.unhandled")
        if senders and op not in parent_sent:
            emit(s.path, s.line, s.col, "APX902",
                 f"op {op!r} declared but no sender emits it",
                 f"spec.{op}.unsent")
    for op in sorted(c2p):
        s = spec[op]
        if senders and op not in child_sent:
            emit(s.path, s.line, s.col, "APX902",
                 f"child->parent op {op!r} declared but never "
                 f"sent", f"spec.{op}.unsent")

    # APX903: header-field drift + blob shape
    for s in senders:
        sp = spec.get(s.op)
        if sp is None or s.keys is None:
            continue
        declared = sp.request_fields
        for field in s.keys:
            if field not in declared:
                emit(s.path, s.line, s.col, "APX903",
                     f"sender sets header field {field!r} the "
                     f"{s.op!r} spec doesn't declare",
                     f"{s.func}.{s.op}.{field}")
        if s.complete and s.direction == "parent":
            for field in sp.required:
                if field not in s.keys:
                    emit(s.path, s.line, s.col, "APX903",
                         f"sender omits required {s.op!r} header "
                         f"field {field!r}",
                         f"{s.func}.{s.op}.missing.{field}")
        if s.direction == "parent" and s.has_blobs \
                and not sp.request_blobs:
            emit(s.path, s.line, s.col, "APX903",
                 f"op {s.op!r} sent with binary blobs but its spec "
                 f"declares none", f"{s.func}.{s.op}.blobs")
    for m in modules:
        for r in m.reads:
            sp = spec.get(r.op)
            if sp is None:
                continue
            declared = (sp.request_fields if r.side == "request"
                        else sp.reply_fields)
            if r.field not in declared:
                emit(r.path, r.line, r.col, "APX903",
                     f"receiver reads {r.side} field {r.field!r} "
                     f"the {r.op!r} spec doesn't declare — the "
                     f"KeyError-at-3am class",
                     f"{r.func}.{r.op}.{r.field}")
        for lit in m.reply_literals:
            sp = spec.get(lit.op)
            if sp is None:
                continue
            for field in lit.keys:
                if field not in sp.reply_fields:
                    emit(lit.path, lit.line, lit.col, "APX903",
                         f"handler replies with field {field!r} "
                         f"the {lit.op!r} spec doesn't declare",
                         f"{lit.func}.{lit.op}.{field}")

    # APX905 (spec half): retries on a non-idempotent op
    for s in senders:
        sp = spec.get(s.op)
        if sp is not None and s.retries_nonzero \
                and not sp.idempotent:
            f = Finding(
                path=s.path, line=s.line, col=s.col, rule="APX905",
                severity="error",
                message=(f"op {s.op!r} is retried but its spec is "
                         f"not marked idempotent — a blind re-send "
                         f"can double-apply work; escalate to "
                         f"restart + journal replay instead"),
                symbol=f"{s.func}.{s.op}.retries")
            if "APX905" not in sup.get(s.path, {}).get(s.line, ()):
                out.append(f)
    return out


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def lint_protocol_source(source: str, path: str) -> List[Finding]:
    """Audit ONE module (fixture/test surface): the per-file rules
    plus whatever drift is provable against a ``ProtocolSpec``
    registry defined in the same source."""
    info = _collect_module(source, path)
    if info is None:
        return []
    return info.findings + _drift_findings([info])


def _scan_roots(repo: Path, package_root: str) -> List[Path]:
    return [repo / package_root / tree
            for tree in PROTOCOL_SCAN_TREES]


def lint_protocol_paths(package_root: str = "apex_tpu", *,
                        repo_root: str = ".",
                        paths: Optional[Sequence[str]] = None
                        ) -> Tuple[List[Finding], int]:
    """Audit the protocol trees (``serving/`` + ``resilience/``
    under ``package_root``).  Op/field drift aggregates across every
    scanned module before judgment — no single file has to show both
    sides.  ``paths`` restricts to the named repo-relative files
    (the ``--check --paths`` fast path): each named file in scope
    gets the per-file rules, and drift is judged only against specs
    visible in the named set (a partial view proves presence, never
    absence).  Returns ``(findings, declared_op_count)``."""
    repo = Path(repo_root).resolve()
    scope = [(repo / package_root / t).resolve()
             for t in PROTOCOL_SCAN_TREES]

    def in_scope(p: Path) -> bool:
        rp = p.resolve()
        return any(rp == s or s in rp.parents for s in scope)

    files: List[Path] = []
    if paths is not None:
        for name in paths:
            p = repo / name
            if p.exists() and p.suffix == ".py" and in_scope(p):
                files.append(p)
    else:
        for root in scope:
            if root.exists():
                files.extend(_iter_py(root))
    modules: List[_ModuleInfo] = []
    for p in files:
        rel = p.resolve().relative_to(repo).as_posix()
        info = _collect_module(p.read_text(), rel)
        if info is not None:
            modules.append(info)
    findings = [f for m in modules for f in m.findings]
    findings.extend(_drift_findings(modules))
    n_ops = len({op for m in modules for op in m.spec})
    return findings, n_ops


def run_protocol_check(package_root: str = "apex_tpu", *,
                       baseline: str = DEFAULT_BASELINE,
                       repo_root: str = "."
                       ) -> Tuple[List[Finding], List[str], int]:
    """(unsuppressed findings, stale baseline keys, declared ops) —
    the ``--check-protocol`` body, with the linter baseline's
    semantics: a baseline entry whose finding no longer fires is
    stale and fails until deleted (baselines only shrink)."""
    findings, n_ops = lint_protocol_paths(package_root,
                                          repo_root=repo_root)
    base = load_baseline(baseline, repo_root=repo_root)
    live = {f.key for f in findings}
    unsuppressed = [f for f in findings if f.key not in base]
    stale = [k for k in base if k not in live]
    return unsuppressed, stale, n_ops


_PROTO_BASELINE_HEADER = (
    "# apex_tpu.analysis.protocol baseline — APX9xx findings",
    "# accepted with a reason.  New findings do NOT belong here:",
    "# fix them or suppress inline with '# apex-lint: disable=...'.",
    "# Committed EMPTY: every finding at introduction was fixed.",
    "# Format: <path>:<rule>:<symbol>  # <reason>",
)


def write_protocol_baseline(findings: Sequence[Finding],
                            path: str = DEFAULT_BASELINE, *,
                            repo_root: str = ".") -> None:
    write_baseline(findings, path, repo_root=repo_root,
                   header=_PROTO_BASELINE_HEADER)
