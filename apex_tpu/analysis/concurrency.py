"""APX8xx host-concurrency auditor — lock discipline and signal safety.

The analysis stack guards traced code (APX1xx), compiled graphs
(APX6xx), and SPMD topology (APX7xx); what it never guarded is the
layer that crashes **nondeterministically**: the host-side threading
the serving fleet made real (one thread per replica, the watchdog
heartbeat, SpanTracer per-thread buffers, SIGTERM/SIGINT/SIGUSR1
handlers).  The repo's concurrency discipline — flag-only signal
handlers, lock-guarded sinks, per-thread device pinning — was a
convention stated in docstrings.  This module makes it a checked
invariant, on the same machinery as the PR-5 linter (AST walk,
structured :class:`~.linter.Finding` s, reasoned inline suppressions,
a committed baseline with stale-entry-fails semantics, rule-registry
docs generation).

Rules (docs/api/analysis.md for the long-form table):

==========  ================================================================
APX801      shared mutable attribute accessed outside its guarding
            lock.  Guard inference: an attribute of a lock-bearing
            class that is *written* (outside ``__init__``) and
            accessed at least once inside a ``with self._lock:``
            region is lock-guarded; any access outside the lock is a
            finding.  The lock attribute itself is the class's
            declaration that its state is reached from more than one
            thread.  Two more entry-point-driven forms: a
            read-modify-write (``+=``) on a lock-bearing class's
            attribute outside the lock (increments are never atomic),
            and an attribute store inside a ``threading.Thread``
            target function when the same attribute is also stored
            elsewhere in the module (the shared-counter race the
            threaded fleet loop shipped with).  A method named
            ``*_locked`` is analyzed as if every class lock were held
            — the sanctioned convention for helpers whose contract is
            "caller holds the lock".
APX802      lock-acquisition-order cycle: ``with A:`` lexically
            nesting ``with B:`` (or ``B.acquire()``) records an
            ordering edge A→B; edges aggregate across *every* scanned
            module, and any cycle in the graph is a potential
            deadlock, reported with each edge's provenance.
APX803      signal handler doing more than flag-set / counter-
            increment — the repo's stated "flag-only handler"
            convention, enforced.  Allowed: attribute/name stores,
            ``Event.set()``, dict ``.get``, chaining to the previous
            handler (calling a saved callable, ``signal.signal`` +
            ``os.kill`` re-raise), and calls into same-class methods
            that are themselves flag-only.  Everything else —
            telemetry emission, logging, lock acquisition, I/O — is a
            finding: the handler runs between bytecodes of a thread
            that may hold any lock in the process.
APX804      blocking call while holding a lock: ``.join()`` /
            ``sleep()`` / ``Event.wait()`` / sink ``.emit()`` /
            monitor ``.event()`` / ``jax.device_get`` /
            ``.block_until_ready()`` lexically inside a lock region,
            including reached through a same-class method call (the
            ``self._alarm()``-under-lock shape).  A lock whose
            *purpose* is to serialize one file's writes (the
            crash-safe JSONL sink) stays legal: plain ``.write`` /
            ``.flush`` on an owned handle are not in the deny set.
APX805      jit dispatch from a thread-entry function outside a
            device-pinning context: ``jnp.*`` / ``jax.device_put`` /
            ``jax.device_get`` / ``.block_until_ready()`` / calling a
            name bound from ``jax.jit`` inside a
            ``threading.Thread(target=...)`` function with no
            enclosing ``with ...device_scope():`` /
            ``jax.default_device(...)`` — the exact bug class the
            threaded fleet found by hand when every replica's tick
            staging transited device 0 and aggregate tokens/s stayed
            flat.
==========  ================================================================

Suppression: the linter's inline form
(``# apex-lint: disable=APX804 -- <reason>``) or the committed
baseline ``tools/concurrency_baseline.txt`` (same
``path:RULE:symbol  # reason`` format and the same stale-entry-fails
semantics as ``tools/analysis_baseline.txt``; committed EMPTY — every
finding at introduction was fixed).  CI runs
``python -m apex_tpu.analysis --check-concurrency`` self-hosted.

Import-light on purpose (stdlib ``ast`` only), like :mod:`.linter`.
"""
from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .linter import (Finding, _iter_py, _suppressions, load_baseline,
                     write_baseline)

__all__ = ["lint_concurrency_source", "lint_concurrency_paths",
           "run_concurrency_check", "write_concurrency_baseline",
           "DEFAULT_BASELINE", "LockEdge"]

DEFAULT_BASELINE = "tools/concurrency_baseline.txt"

#: constructors whose result is a mutual-exclusion object — assigning
#: one to ``self.X`` (or a module-level name) declares a lock
_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

#: with-context callables that pin device placement for the enclosed
#: block (APX805's sanctioned shapes)
_PIN_CONTEXTS = {"device_scope", "default_device"}

#: call tails that block (or do unbounded work) — illegal while a lock
#: is held.  ``.write``/``.flush``/``.close`` on an owned handle are
#: deliberately absent: a lock whose purpose is to serialize one
#: file's appends (JsonlSink) is the repo's stated sink discipline.
_BLOCKING_TAILS = {"join", "sleep", "wait", "emit", "event",
                   "device_get", "block_until_ready"}

#: calls a flag-only signal handler may make (APX803): Event.set /
#: is_set, dict .get, the chain-to-previous-handler idiom
#: (``signal.signal`` + ``os.kill`` + calling the saved handler),
#: and cheap pure conversions
_HANDLER_ALLOWED_TAILS = {"set", "is_set", "get", "signal", "kill",
                          "getpid", "Signals", "str", "int",
                          "callable"}

#: dotted jax calls that dispatch device work (APX805 signals beyond
#: the ``jnp`` root and jitted names)
_DISPATCH_TAILS = {"device_put", "device_get", "block_until_ready"}


def _tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _root(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    if isinstance(node, ast.Name):
        return node.id
    return None


def _is_lock_factory(value: ast.AST) -> bool:
    return (isinstance(value, ast.Call)
            and _tail(value.func) in _LOCK_FACTORIES)


@dataclasses.dataclass(frozen=True)
class LockEdge:
    """One observed acquisition ordering: ``held`` was locked when
    ``acquired`` was taken.  ``path``/``line`` is the inner
    acquisition site (the provenance a cycle report prints)."""

    held: str
    acquired: str
    path: str
    line: int


@dataclasses.dataclass
class _Access:
    attr: str
    store: bool          # Assign/AugAssign target vs plain read
    aug: bool            # read-modify-write
    locks: Tuple[str, ...]   # class-lock attrs held (lexically)
    func: str
    line: int
    col: int


class _ClassModel:
    """Everything APX801/803/804 need about one class."""

    def __init__(self, name: str):
        self.name = name
        self.locks: Set[str] = set()
        self.accesses: List[_Access] = []
        # func name -> same-class methods it calls via self.X(...)
        self.self_calls: Dict[str, Set[str]] = {}
        # func name -> direct blocking-call sites (tail, line, col)
        self.blocking: Dict[str, List[Tuple[str, int, int]]] = {}
        # calls made while >=1 lock held:
        # (held locks, callee node, enclosing func, receiver lock key)
        self.locked_calls: List[Tuple[Tuple[str, ...], ast.Call,
                                      str, Optional[str]]] = []
        self.methods: Dict[str, ast.AST] = {}

    def transitively_blocking(self) -> Dict[str, Tuple[str, int]]:
        """func -> (blocking tail, line) for every method that
        performs a blocking call directly or through same-class
        calls — the interprocedural half of APX804."""
        out: Dict[str, Tuple[str, int]] = {
            f: (sites[0][0], sites[0][1])
            for f, sites in self.blocking.items() if sites}
        changed = True
        while changed:
            changed = False
            for f, callees in self.self_calls.items():
                if f in out:
                    continue
                for c in callees:
                    if c in out:
                        out[f] = out[c]
                        changed = True
                        break
        return out


def _module_lock_names(tree: ast.Module) -> Set[str]:
    """Module-level names bound to ``threading.Lock()``-class
    constructors — the pre-scan that lets another module's
    ``from .mod import LOCK`` resolve to the same qualified key."""
    out: Set[str] = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and _is_lock_factory(
                stmt.value):
            out.update(t.id for t in stmt.targets
                       if isinstance(t, ast.Name))
    return out


class _ModuleModel(ast.NodeVisitor):
    """One file's concurrency facts, collected in a single walk."""

    def __init__(self, path: str,
                 locks_by_stem: Optional[Dict[str, Set[str]]] = None):
        self.path = path
        self._locks_by_stem = locks_by_stem or {}
        # imported module-level locks: local alias -> qualified key
        self._external: Dict[str, str] = {}
        self.module_locks: Set[str] = set()
        self.classes: List[_ClassModel] = []
        self.edges: List[LockEdge] = []
        # handler expr nodes registered via signal.signal(sig, X),
        # paired with the class (if any) enclosing the registration
        self.handlers: List[Tuple[ast.AST, Optional[_ClassModel]]] = []
        # thread-target references: Name/Attribute nodes passed as
        # Thread(target=...), paired with the enclosing class
        self.thread_targets: List[Tuple[ast.AST,
                                        Optional[_ClassModel]]] = []
        # names bound (module scope or any function) from jax.jit(...)
        self.jitted_names: Set[str] = set()
        # every function def by name (module-wide; last wins) — used
        # to resolve thread targets and handler Names
        self.functions: Dict[str, ast.AST] = {}
        self.n_lock_regions = 0
        self._aug_targets: Set[int] = set()

    # -- collection ----------------------------------------------------------

    def build(self, tree: ast.Module) -> "_ModuleModel":
        self.module_locks = _module_lock_names(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                stem = (node.module or "").split(".")[-1]
                for alias in node.names:
                    if alias.name in self._locks_by_stem.get(
                            stem, ()):
                        self._external[alias.asname or alias.name] = \
                            f"{stem}.{alias.name}"
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
            if isinstance(node, ast.Assign):
                v = node.value
                jit_like = (isinstance(v, ast.Call)
                            and (_tail(v.func) == "jit"
                                 or (_tail(v.func) == "partial"
                                     and any(_tail(a) == "jit"
                                             for a in v.args))))
                if jit_like:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.jitted_names.add(t.id)
            if isinstance(node, ast.Call):
                self._scan_call(node)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
        # module-level lock regions (edges + locked calls live on a
        # synthetic "module" class so APX802/804 cover them too)
        mod_cls = _ClassModel(f"<module:{Path(self.path).stem}>")
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk(stmt, mod_cls, stmt.name, ())
        if mod_cls.locked_calls or mod_cls.blocking:
            self.classes.append(mod_cls)
        return self

    def _scan_call(self, node: ast.Call) -> None:
        """Thread targets and signal-handler registrations, wherever
        they occur."""
        if _tail(node.func) == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    self.thread_targets.append((kw.value, None))
        if (_tail(node.func) == "signal"
                and _root(node.func) in ("signal", "_signal")
                and len(node.args) >= 2):
            self.handlers.append((node.args[1], None))

    def _enclosing_fixups(self, tree_cls: ast.ClassDef,
                          model: _ClassModel) -> None:
        """Re-attribute thread targets / handlers registered inside
        this class's methods to the class, so ``self.X`` references
        resolve."""
        inside = {id(n) for n in ast.walk(tree_cls)}
        self.thread_targets = [
            (ref, model if id(ref) in inside else cls)
            for ref, cls in self.thread_targets]
        self.handlers = [
            (ref, model if id(ref) in inside else cls)
            for ref, cls in self.handlers]

    def _scan_class(self, cls: ast.ClassDef) -> None:
        model = _ClassModel(cls.name)
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and _is_lock_factory(
                    node.value):
                for t in node.targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        model.locks.add(t.attr)
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model.methods[stmt.name] = stmt
                self._walk(stmt, model, stmt.name, ())
        self.classes.append(model)
        self._enclosing_fixups(cls, model)

    # -- the lexical region walk --------------------------------------------

    def _lock_key(self, expr: ast.AST,
                  model: _ClassModel) -> Optional[str]:
        """Qualified name of a lock acquired by ``with expr:`` /
        ``expr.acquire()`` — ``Class.attr`` for self locks,
        ``<stem>.name`` for module-level ones."""
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and expr.attr in model.locks):
            return f"{model.name}.{expr.attr}"
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks:
                return f"{Path(self.path).stem}.{expr.id}"
            if expr.id in self._external:
                return self._external[expr.id]
        return None

    def _walk(self, node: ast.AST, model: _ClassModel, func: str,
              held: Tuple[str, ...]) -> None:
        """Recursive lexical walk tracking held locks (node-dispatch:
        every node is recorded exactly once).  Descending into a
        nested function def resets ``held`` — a closure's body does
        not run at definition time.  A method named ``*_locked`` is
        walked as if every class lock were held: the sanctioned
        naming convention for helpers whose contract is "caller holds
        the lock" (the lexical analysis cannot see the caller's
        ``with``)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            name = getattr(node, "name", func)
            inner_held: Tuple[str, ...] = ()
            if name.endswith("_locked") and model.locks:
                inner_held = tuple(f"{model.name}.{lk}"
                                   for lk in sorted(model.locks))
            for child in ast.iter_child_nodes(node):
                self._walk(child, model, name, inner_held)
            return
        if isinstance(node, ast.With):
            acquired = []
            for item in node.items:
                # the context exprs themselves evaluate under the
                # OUTER lock set
                self._walk(item.context_expr, model, func, held)
                key = self._lock_key(item.context_expr, model)
                if key is not None:
                    acquired.append((key, item.context_expr))
            for i, (key, expr) in enumerate(acquired):
                for h in held + tuple(k for k, _ in acquired[:i]):
                    self.edges.append(LockEdge(
                        held=h, acquired=key, path=self.path,
                        line=expr.lineno))
            if acquired:
                self.n_lock_regions += 1
            new_held = held + tuple(k for k, _ in acquired)
            for s in node.body:
                self._walk(s, model, func, new_held)
            return
        self._record_node(node, model, func, held)
        for child in ast.iter_child_nodes(node):
            self._walk(child, model, func, held)

    def _record_node(self, n: ast.AST, model: _ClassModel, func: str,
                     held: Tuple[str, ...]) -> None:
        """Record ONE node's concurrency facts (the walk visits every
        node exactly once)."""
        class_held = tuple(k for k in held
                           if k.startswith(model.name + "."))
        if (isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == "self"):
            aug = id(n) in self._aug_targets
            model.accesses.append(_Access(
                attr=n.attr,
                store=aug or isinstance(n.ctx, (ast.Store, ast.Del)),
                aug=aug, locks=class_held, func=func, line=n.lineno,
                col=n.col_offset))
        if isinstance(n, ast.AugAssign) and isinstance(
                n.target, ast.Attribute):
            self._aug_targets.add(id(n.target))
        if isinstance(n, ast.Call):
            tail = _tail(n.func)
            receiver_lock = None
            if isinstance(n.func, ast.Attribute):
                receiver_lock = self._lock_key(n.func.value, model)
            if tail == "acquire" and receiver_lock is not None:
                for h in held:
                    self.edges.append(LockEdge(
                        held=h, acquired=receiver_lock,
                        path=self.path, line=n.lineno))
            str_join = (tail == "join"
                        and isinstance(n.func, ast.Attribute)
                        and isinstance(n.func.value, ast.Constant))
            if tail in _BLOCKING_TAILS and not str_join and not (
                    tail == "wait" and receiver_lock is not None):
                # exempt: str.join on a literal separator, and
                # Condition.wait on the held condition (it releases
                # the lock — the canonical CV idiom)
                model.blocking.setdefault(func, []).append(
                    (tail, n.lineno, n.col_offset))
            if (isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self"):
                model.self_calls.setdefault(func, set()).add(
                    n.func.attr)
            if held:
                model.locked_calls.append((held, n, func,
                                           receiver_lock))


# ---------------------------------------------------------------------------
# rule passes over a built model
# ---------------------------------------------------------------------------

_INIT_EXEMPT = {"__init__", "__post_init__", "__new__", "__enter__"}


def _apx801_class(model: _ClassModel, emit) -> None:
    if not model.locks:
        return
    written = {a.attr for a in model.accesses
               if a.store and a.func not in _INIT_EXEMPT}
    guarded = {a.attr for a in model.accesses
               if a.locks and a.attr in written}
    for a in model.accesses:
        if a.func in _INIT_EXEMPT:
            continue
        if a.attr in guarded and not a.locks:
            kind = "written" if a.store else "read"
            emit(a.line, a.col, "APX801",
                 f"{model.name}.{a.attr} is lock-guarded (accessed "
                 f"under a '{model.name}' lock elsewhere) but {kind} "
                 f"in '{a.func}' with no lock held — take the lock "
                 f"or move the access inside an existing region",
                 f"{model.name}.{a.attr}@{a.func}")
        elif a.aug and not a.locks and a.attr not in guarded:
            emit(a.line, a.col, "APX801",
                 f"read-modify-write '{model.name}.{a.attr} += ...' "
                 f"in '{a.func}' of a lock-bearing class outside any "
                 f"lock — increments are not atomic across threads",
                 f"{model.name}.{a.attr}@{a.func}+=")


def _attr_store_targets(n: ast.AST) -> List[ast.Attribute]:
    if isinstance(n, ast.Assign):
        return [t for t in n.targets if isinstance(t, ast.Attribute)]
    if isinstance(n, ast.AugAssign) and isinstance(n.target,
                                                   ast.Attribute):
        return [n.target]
    return []


def _apx801_thread_writes(mod: _ModuleModel, tree: ast.Module,
                          emit) -> None:
    """Attribute stores inside a thread-target function racing with
    stores to the same attribute elsewhere in the module."""
    targets = _resolve_thread_targets(mod)
    if not targets:
        return
    target_ids = {id(n) for fn in targets for n in ast.walk(fn)}
    outside_attrs = {t.attr for n in ast.walk(tree)
                     if id(n) not in target_ids
                     for t in _attr_store_targets(n)}
    for fn in targets:
        fname = getattr(fn, "name", "<lambda>")
        for n in ast.walk(fn):
            for t in _attr_store_targets(n):
                if t.attr in outside_attrs \
                        and not _under_lock_with(fn, n):
                    emit(n.lineno, n.col_offset, "APX801",
                         f"thread target '{fname}' stores attribute "
                         f"'.{t.attr}' that is also stored outside "
                         f"it — a cross-thread shared write with no "
                         f"lock; collect per-thread results and "
                         f"aggregate after join(), or guard both "
                         f"sides",
                         f"thread.{fname}.{t.attr}")


def _under_lock_with(fn: ast.AST, node: ast.AST) -> bool:
    """Is ``node`` lexically inside any ``with`` whose context looks
    like a lock (named *lock*) within ``fn``?  Cheap containment probe
    for the thread-write rule only."""
    for w in ast.walk(fn):
        if isinstance(w, ast.With):
            looks_locked = any(
                (t := _tail(i.context_expr)) and "lock" in t.lower()
                for i in w.items)
            if looks_locked and any(n is node
                                    for n in ast.walk(w)):
                return True
    return False


def _resolve_thread_targets(mod: _ModuleModel) -> List[ast.AST]:
    out = []
    for ref, cls in mod.thread_targets:
        fn = None
        if isinstance(ref, ast.Name):
            fn = mod.functions.get(ref.id)
        elif (isinstance(ref, ast.Attribute)
              and isinstance(ref.value, ast.Name)
              and ref.value.id == "self" and cls is not None):
            fn = cls.methods.get(ref.attr)
        elif isinstance(ref, ast.Lambda):
            fn = ref
        if fn is not None:
            out.append(fn)
    return out


def _apx803(mod: _ModuleModel, emit) -> None:
    for ref, cls in mod.handlers:
        fn = None
        if isinstance(ref, ast.Name):
            fn = mod.functions.get(ref.id)
        elif isinstance(ref, ast.Lambda):
            fn = ref
        elif (isinstance(ref, ast.Attribute)
              and isinstance(ref.value, ast.Name)
              and ref.value.id == "self" and cls is not None):
            fn = cls.methods.get(ref.attr)
        if fn is None:
            continue  # restoring a saved handler / SIG_DFL: not ours
        _check_handler(fn, cls, emit, seen=set())


def _check_handler(fn: ast.AST, cls: Optional[_ClassModel],
                   emit, seen: Set[str]) -> bool:
    """Emit APX803 findings for non-flag-only operations; returns
    True when the body is clean (used for same-class recursion)."""
    name = getattr(fn, "name", "<lambda>")
    if name in seen:
        return True
    seen.add(name)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    # bare-name calls are legal only for callables the handler itself
    # bound (the saved-previous-handler chain idiom: `prev =
    # self._prev.get(signum); prev(...)`) — a bare `print(...)` or
    # `open(...)` is not a chain
    local_names: Set[str] = set()
    for top in body:
        for n in ast.walk(top):
            if isinstance(n, ast.Assign):
                local_names.update(t.id for t in n.targets
                                   if isinstance(t, ast.Name))
    clean = True
    for top in body:
        for n in ast.walk(top):
            if isinstance(n, ast.With):
                clean = False
                emit(n.lineno, n.col_offset, "APX803",
                     f"signal handler '{name}' enters a context "
                     f"manager — a handler interrupting the lock's "
                     f"holder deadlocks; set a flag and act at the "
                     f"next safe boundary",
                     f"handler.{name}.with")
            if not isinstance(n, ast.Call):
                continue
            tail = _tail(n.func)
            if tail in _HANDLER_ALLOWED_TAILS:
                continue
            if isinstance(n.func, ast.Name) \
                    and n.func.id in local_names:
                # calling a saved previous handler (a callable the
                # handler bound locally) — the chain idiom
                continue
            if (isinstance(n.func, ast.Attribute)
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == "self" and cls is not None
                    and n.func.attr in cls.methods):
                sub = cls.methods[n.func.attr]
                if _check_handler(sub, cls, _swallow, set(seen)):
                    continue
                clean = False
                emit(n.lineno, n.col_offset, "APX803",
                     f"signal handler '{name}' calls "
                     f"self.{n.func.attr}() which is not flag-only",
                     f"handler.{name}.{n.func.attr}")
                continue
            clean = False
            emit(n.lineno, n.col_offset, "APX803",
                 f"signal handler '{name}' calls "
                 f"'{_dotted(n.func)}' — more than flag-set/"
                 f"counter-increment (no telemetry, logging, locks, "
                 f"or I/O from a handler; it runs between bytecodes "
                 f"of a thread that may hold any lock)",
                 f"handler.{name}.{tail or 'call'}")
    return clean


def _swallow(*_a, **_k) -> None:
    """No-op emit used when probing whether a callee is flag-only."""


def _dotted(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts)) or "?"


def _apx804(model: _ClassModel, emit) -> None:
    transitive = model.transitively_blocking()
    for held, call, func, receiver_lock in model.locked_calls:
        tail = _tail(call.func)
        if tail == "wait" and receiver_lock in held:
            continue  # Condition.wait on the held lock releases it
        if (tail == "join" and isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Constant)):
            continue  # str.join on a literal separator
        if tail in _BLOCKING_TAILS:
            emit(call.lineno, call.col_offset, "APX804",
                 f"blocking call '.{tail}()' in '{func}' while "
                 f"holding {list(held)} — emit/join/sleep after "
                 f"releasing the lock (collect under the lock, act "
                 f"outside)",
                 f"{model.name}.{func}.{tail}")
        elif (isinstance(call.func, ast.Attribute)
              and isinstance(call.func.value, ast.Name)
              and call.func.value.id == "self"
              and call.func.attr in transitive
              and call.func.attr not in _INIT_EXEMPT):
            btail, bline = transitive[call.func.attr]
            emit(call.lineno, call.col_offset, "APX804",
                 f"'{func}' calls self.{call.func.attr}() while "
                 f"holding {list(held)}, which reaches blocking "
                 f"'.{btail}()' (line {bline}) — restructure so the "
                 f"blocking work happens outside the lock",
                 f"{model.name}.{func}.{call.func.attr}")


def _apx805(mod: _ModuleModel, emit) -> None:
    for fn in _resolve_thread_targets(mod):
        fname = getattr(fn, "name", "<lambda>")
        _walk_dispatch(fn, fname, mod, emit, pinned=False)


def _walk_dispatch(node: ast.AST, fname: str, mod: _ModuleModel,
                   emit, pinned: bool) -> None:
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.With):
            now_pinned = pinned or any(
                isinstance(i.context_expr, ast.Call)
                and _tail(i.context_expr.func) in _PIN_CONTEXTS
                for i in child.items)
            for s in child.body:
                _walk_dispatch(s, fname, mod, emit, now_pinned)
            continue
        if isinstance(child, ast.Call) and not pinned:
            root = _root(child.func)
            tail = _tail(child.func)
            dispatches = (root == "jnp"
                          or (root == "jax" and tail in _DISPATCH_TAILS)
                          or tail == "block_until_ready"
                          or (isinstance(child.func, ast.Name)
                              and child.func.id in mod.jitted_names))
            if dispatches:
                emit(child.lineno, child.col_offset, "APX805",
                     f"thread target '{fname}' dispatches device "
                     f"work ('{_dotted(child.func)}') outside a "
                     f"device-pinning context — off the main thread "
                     f"this lands on the process default device "
                     f"(device 0 serializes the fleet); wrap the "
                     f"tick in 'with replica.device_scope():' or "
                     f"'jax.default_device(dev)'",
                     f"thread.{fname}.{tail or 'dispatch'}")
        _walk_dispatch(child, fname, mod, emit, pinned)


# ---------------------------------------------------------------------------
# cycle detection over aggregated lock edges
# ---------------------------------------------------------------------------

def _find_cycles(edges: Sequence[LockEdge]
                 ) -> List[List[LockEdge]]:
    """Simple cycles in the acquisition-order graph, deduplicated by
    canonical rotation.  Graphs here are tiny (a handful of locks), so
    a DFS with an explicit path is plenty."""
    adj: Dict[str, Dict[str, LockEdge]] = {}
    for e in edges:
        if e.held == e.acquired:
            continue  # re-entrant self-acquire: RLock territory
        adj.setdefault(e.held, {}).setdefault(e.acquired, e)
    cycles: Dict[Tuple[str, ...], List[LockEdge]] = {}

    def dfs(start: str, node: str, path: List[str],
            trail: List[LockEdge]) -> None:
        for nxt, edge in sorted(adj.get(node, {}).items()):
            if nxt == start and trail:
                cyc = trail + [edge]
                names = [c.held for c in cyc]
                k = min(range(len(names)), key=lambda i: names[i])
                key = tuple(names[k:] + names[:k])
                cycles.setdefault(key, cyc[k:] + cyc[:k])
            elif nxt not in path:
                dfs(start, nxt, path + [nxt], trail + [edge])

    for n in sorted(adj):
        dfs(n, n, [n], [])
    return [cycles[k] for k in sorted(cycles)]


def _cycle_findings(edges: Sequence[LockEdge],
                    suppressed: Dict[str, Dict[int, Set[str]]]
                    ) -> List[Finding]:
    out = []
    for cyc in _find_cycles(edges):
        anchor = cyc[0]
        order = " -> ".join([c.held for c in cyc] + [cyc[0].held])
        prov = "; ".join(
            f"{c.held} then {c.acquired} at {c.path}:{c.line}"
            for c in cyc)
        if "APX802" in suppressed.get(anchor.path, {}).get(
                anchor.line, ()):
            continue
        out.append(Finding(
            path=anchor.path, line=anchor.line, col=0, rule="APX802",
            severity="error",
            message=f"lock-acquisition-order cycle {order} — two "
                    f"threads taking these locks in their observed "
                    f"orders deadlock ({prov}); pick one global "
                    f"order or release before acquiring",
            symbol="cycle:" + "->".join(c.held for c in cyc)))
    return out


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------

def _analyze_source(source: str, path: str,
                    locks_by_stem: Optional[Dict[str,
                                                 Set[str]]] = None,
                    tree: Optional[ast.Module] = None
                    ) -> Tuple[List[Finding], List[LockEdge],
                               Dict[int, Set[str]], int]:
    try:
        tree = ast.parse(source) if tree is None else tree
    except SyntaxError as e:
        return ([Finding(path=path, line=e.lineno or 0,
                         col=e.offset or 0, rule="APX000",
                         severity="error",
                         message=f"syntax error: {e.msg}",
                         symbol="syntax")], [], {}, 0)
    # reasoned inline suppressions (the APX900 malformed-suppression
    # finding stays the main linter's — one owner per rule)
    suppressed, _ = _suppressions(source, path)
    findings: List[Finding] = []

    def emit(line: int, col: int, rule: str, message: str,
             symbol: str) -> None:
        if rule in suppressed.get(line, ()):
            return
        findings.append(Finding(path=path, line=line, col=col,
                                rule=rule, severity="error",
                                message=message, symbol=symbol))

    mod = _ModuleModel(path, locks_by_stem).build(tree)
    for cls in mod.classes:
        _apx801_class(cls, emit)
        _apx804(cls, emit)
    _apx801_thread_writes(mod, tree, emit)
    _apx803(mod, emit)
    _apx805(mod, emit)
    return findings, mod.edges, suppressed, mod.n_lock_regions


def lint_concurrency_source(source: str, path: str) -> List[Finding]:
    """Lint one file, including lock-order cycles visible within it."""
    findings, edges, suppressed, _ = _analyze_source(source, path)
    findings.extend(_cycle_findings(edges, {path: suppressed}))
    return findings


def lint_concurrency_paths(package_root: str = "apex_tpu", *,
                           repo_root: str = "."
                           ) -> Tuple[List[Finding], int]:
    """Audit every ``.py`` under ``package_root``; lock-order edges
    aggregate across files before cycle detection (a deadlock needs
    no single file to show both orders).  Returns ``(findings,
    lock_region_count)``."""
    repo = Path(repo_root).resolve()
    findings: List[Finding] = []
    edges: List[LockEdge] = []
    suppress_maps: Dict[str, Dict[int, Set[str]]] = {}
    regions = 0
    sources: List[Tuple[str, str, Optional[ast.Module]]] = []
    locks_by_stem: Dict[str, Set[str]] = {}
    for p in _iter_py(repo / package_root):
        rel = p.relative_to(repo).as_posix()
        text = p.read_text()
        try:
            tree: Optional[ast.Module] = ast.parse(text)
        except SyntaxError:
            tree = None  # the per-file pass reports APX000
        sources.append((rel, text, tree))
        if tree is not None:
            names = _module_lock_names(tree)
            if names:
                locks_by_stem.setdefault(p.stem, set()).update(names)
    for rel, text, tree in sources:
        f, e, s, n = _analyze_source(text, rel, locks_by_stem,
                                     tree=tree)
        findings.extend(f)
        edges.extend(e)
        suppress_maps[rel] = s
        regions += n
    findings.extend(_cycle_findings(edges, suppress_maps))
    return findings, regions


def run_concurrency_check(package_root: str = "apex_tpu", *,
                          baseline: str = DEFAULT_BASELINE,
                          repo_root: str = "."
                          ) -> Tuple[List[Finding], List[str], int]:
    """(unsuppressed findings, stale baseline keys, lock regions) —
    the ``--check-concurrency`` body, with the linter baseline's
    semantics: a baseline entry whose finding no longer fires is
    stale and fails until deleted (baselines only shrink)."""
    findings, regions = lint_concurrency_paths(package_root,
                                               repo_root=repo_root)
    base = load_baseline(baseline, repo_root=repo_root)
    live = {f.key for f in findings}
    unsuppressed = [f for f in findings if f.key not in base]
    stale = [k for k in base if k not in live]
    return unsuppressed, stale, regions


_CONC_BASELINE_HEADER = (
    "# apex_tpu.analysis.concurrency baseline — APX8xx findings",
    "# accepted with a reason.  New findings do NOT belong here:",
    "# fix them or suppress inline with '# apex-lint: disable=...'.",
    "# Committed EMPTY: every finding at introduction was fixed.",
    "# Format: <path>:<rule>:<symbol>  # <reason>",
)


def write_concurrency_baseline(findings: Sequence[Finding],
                               path: str = DEFAULT_BASELINE, *,
                               repo_root: str = ".") -> None:
    write_baseline(findings, path, repo_root=repo_root,
                   header=_CONC_BASELINE_HEADER)
