"""Central registry of every ``APEX_TPU_*`` environment flag.

One declaration per flag — name, type, default, constraints, doc — and
typed accessors that read the environment **per call** (setting a flag
after import still takes effect wherever the consuming module reads
per call) with hard errors on malformed values
(``APEX_TPU_STEP_PALLAS_MIN=abc`` names the flag, the raw value, and
what was expected).

Library code must not touch ``os.environ``/``os.getenv`` directly: the
trace-safety linter (rule APX301) fails on any env read outside this
module, and the flag table in docs/api/ops.md is generated from this
registry (``python -m apex_tpu.analysis --flag-table``), so docs cannot
drift from code.

This module is import-light on purpose (stdlib only): ops/amp/monitor
modules import it at module scope.
"""
from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, Optional

__all__ = ["Flag", "FLAGS", "register_flag", "flag_bool", "flag_int",
           "flag_float", "flag_str", "flag_value", "render_flag_table"]

_TRUE = ("1", "true", "on", "yes")
_FALSE = ("0", "false", "off", "no")


class FlagValueError(ValueError):
    """A set environment flag failed to parse/validate."""


@dataclasses.dataclass(frozen=True)
class Flag:
    """One environment flag: the registry row and its parser."""

    name: str
    kind: str                    # 'bool' | 'int' | 'float' | 'str'
    default: Any
    doc: str
    lo: Optional[float] = None
    hi: Optional[float] = None
    multiple_of: Optional[int] = None

    def parse(self, raw: str) -> Any:
        val = self._convert(raw)
        if self.lo is not None and val < self.lo:
            raise FlagValueError(
                f"{self.name}={raw!r}: {val} below minimum {self.lo}")
        if self.hi is not None and val > self.hi:
            raise FlagValueError(
                f"{self.name}={raw!r}: {val} above maximum {self.hi}")
        if self.multiple_of is not None and val % self.multiple_of:
            raise FlagValueError(
                f"{self.name}={raw!r}: {val} must be a multiple of "
                f"{self.multiple_of}")
        return val

    def _convert(self, raw: str) -> Any:
        raw = raw.strip()
        if self.kind == "bool":
            low = raw.lower()
            if low in _TRUE:
                return True
            if low in _FALSE:
                return False
            raise FlagValueError(
                f"{self.name}={raw!r} is not a boolean "
                f"(use one of {_TRUE + _FALSE})")
        if self.kind == "int":
            try:
                return int(raw)
            except ValueError:
                raise FlagValueError(
                    f"{self.name}={raw!r} is not an integer") from None
        if self.kind == "float":
            try:
                val = float(raw)
            except ValueError:
                raise FlagValueError(
                    f"{self.name}={raw!r} is not a number") from None
            if not math.isfinite(val):
                # NaN slips every range check (nan < lo is False) and
                # poisons downstream comparisons silently
                raise FlagValueError(
                    f"{self.name}={raw!r} must be finite")
            return val
        return raw                                    # 'str'

    @property
    def default_str(self) -> str:
        if self.default is None:
            return "unset"
        if self.kind == "bool":
            return "1" if self.default else "0"
        return str(self.default)


FLAGS: Dict[str, Flag] = {}


def register_flag(name: str, kind: str, default: Any, doc: str,
                  **constraints) -> Flag:
    if kind not in ("bool", "int", "float", "str"):
        raise ValueError(f"unknown flag kind {kind!r}")
    if name in FLAGS:
        raise ValueError(f"duplicate flag registration: {name}")
    flag = Flag(name=name, kind=kind, default=default, doc=doc,
                **constraints)
    FLAGS[name] = flag
    return flag


def flag_value(name: str) -> Any:
    """Parsed value of a registered flag: the environment if set (with
    validation), else the registered default."""
    flag = FLAGS.get(name)
    if flag is None:
        raise KeyError(
            f"{name} is not a registered apex_tpu flag; declare it in "
            f"apex_tpu/analysis/flags.py (the registry is the single "
            f"source of truth for the docs table and the linter)")
    raw = os.environ.get(name)
    if raw is None:
        return flag.default
    return flag.parse(raw)


def _typed(name: str, kind: str) -> Any:
    flag = FLAGS.get(name)
    if flag is not None and flag.kind != kind:
        raise TypeError(f"{name} is a {flag.kind} flag, not {kind}")
    return flag_value(name)


def flag_bool(name: str) -> bool:
    return _typed(name, "bool")


def flag_int(name: str) -> int:
    return _typed(name, "int")


def flag_float(name: str) -> float:
    return _typed(name, "float")


def flag_str(name: str) -> Optional[str]:
    return _typed(name, "str")


def render_flag_table() -> str:
    """Markdown table of the registry, stable ordering — embedded in
    docs/api/ops.md between the flag-table markers and drift-guarded by
    ci.sh step 7."""
    lines = ["| Flag | Type | Default | Constraints | Meaning |",
             "|---|---|---|---|---|"]
    for name in sorted(FLAGS):
        f = FLAGS[name]
        cons = []
        if f.lo is not None:
            cons.append(f">= {f.lo:g}")
        if f.hi is not None:
            cons.append(f"<= {f.hi:g}")
        if f.multiple_of is not None:
            cons.append(f"multiple of {f.multiple_of}")
        lines.append(
            f"| `{name}` | {f.kind} | `{f.default_str}` | "
            f"{', '.join(cons) or '—'} | {f.doc} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The registry.  Every APEX_TPU_* flag the repo reads, in one place.
# ---------------------------------------------------------------------------

register_flag(
    "APEX_TPU_FUSED_PIPELINE", "bool", True,
    "Persistent packed optimizer pipeline under amp master weights "
    "(docs/api/optimizers.md#persistent-packed-pipeline). `0` is the "
    "escape hatch back to the per-stage unscale/check/step path.")
register_flag(
    "APEX_TPU_PIPELINE_PALLAS", "bool", False,
    "Route both fused-pipeline sweeps through the Pallas kernels "
    "instead of the jnp twins (auto stays jnp per the measured "
    "880-vs-190 GB/s elementwise-stream gap).")
register_flag(
    "APEX_TPU_PIPELINE_PACK_MIN_BYTES", "int", 1 << 27,
    "Packed-size cutoff (bytes of the model-dtype tree) below which "
    "the auto pipeline decision (AmpOptimizer(pipeline=None)) routes "
    "to direct per-leaf staged updates instead of the persistent "
    "packed pipeline — the measured 0.73x small-tree packing residue "
    "regime.  Explicit pipeline=True bypasses the cutoff; 0 packs "
    "every tree.", lo=0)
register_flag(
    "APEX_TPU_STEP_PALLAS_MIN", "int", 0,
    "Element-count floor above which single-pass STEP optimizer work "
    "(adam_step/sgd_step) dispatches the Pallas kernels; 0 keeps the "
    "measured-faster XLA fusion path.", lo=0)
register_flag(
    "APEX_TPU_MOE_FUSED_DISPATCH", "bool", True,
    "Route MoE token dispatch through the fused Pallas routing + "
    "capacity-drop kernel (apex_tpu/ops/moe_routing.py: softmax -> "
    "top-k -> cumsum slotting -> buffer scatter in one VMEM pass, jnp "
    "twin off TPU) instead of the legacy one-hot einsum/scatter "
    "formulation.  Routing decisions are bit-identical either way; "
    "`0` is the escape hatch back to the unfused path.")
register_flag(
    "APEX_TPU_MOE_A2A_CHUNKS", "int", 2,
    "Capacity-chunk count for the expert-parallel all-to-all overlap "
    "(transformer/expert_parallel.py): N>=2 splits the dispatch "
    "buffer along capacity and double-buffers chunk i+1's all_to_all "
    "against chunk i's expert matmul, hiding dispatch latency behind "
    "compute (the APX704 overlap advisory goes quiet).  1 restores "
    "the legacy single-shot exchange.  Clamped to the capacity; "
    "ExpertParallelMLP.mesh_plan re-prices the collective budget "
    "accordingly.", lo=1, hi=64)
register_flag(
    "APEX_TPU_DIRECT_MIN_ELEMS", "int", 0,
    "Element-count threshold below which multi-tensor ops pack leaves "
    "into flat buffers (legacy per-step packed path); 0 keeps every "
    "leaf on the native per-leaf path.", lo=0)
register_flag(
    "APEX_TPU_FLASH_BLOCK_Q", "int", 1024,
    "Flash-attention query block rows (read at import; bench-driven "
    "re-tuning knob).", lo=8, hi=4096)
register_flag(
    "APEX_TPU_FLASH_BLOCK_K", "int", 1024,
    "Flash-attention key block columns (read at import).", lo=8, hi=4096)
register_flag(
    "APEX_TPU_FLASH_PACK_D64", "bool", True,
    "d=64 head-pair packing into full 128-lane MXU tiles "
    "(docs/api/ops.md head-packing note). `0` forces the half-width "
    "per-head kernels.")
register_flag(
    "APEX_TPU_FLASH_E_MAX_SEQ", "int", 32768,
    "Longest padded sequence the blocked E-layout flash walk streams "
    "before falling back to the transposing path (bounds the "
    "lse/delta sideband HBM).", lo=128, hi=1 << 20)
register_flag(
    "APEX_TPU_FLASH_E_BLOCK", "int", 512,
    "E-layout flash walk block size (TPU lane grain).", lo=128, hi=4096, multiple_of=128)
register_flag(
    "APEX_TPU_FLASH_E_LANES", "int", 768,
    "Lane budget per head-group block in the E-layout kernels (VMEM "
    "sizing for the bwd score temporaries).", lo=8, hi=4096)
register_flag(
    "APEX_TPU_MONITOR_JSONL", "str", None,
    "Path for an apex_tpu.monitor JSONL event log in drivers that "
    "support ambient wiring (e.g. the 3D-parallel convergence runner).")
register_flag(
    "APEX_TPU_MONITOR_STALL_S", "float", 300.0,
    "Watchdog stall timeout (seconds) for ambient monitor wiring.", lo=0.0)
register_flag(
    "APEX_TPU_TRACE_DIR", "str", None,
    "Ambient wall-time tracing directory (apex_tpu.monitor.tracing): "
    "drivers that support it (the convergence runner) record host "
    "spans + the per-step waterfall and write trace.chrome.json "
    "there.  The smoke drivers take --trace DIR explicitly.")
register_flag(
    "APEX_TPU_TRACE_CAPTURE_FILE", "str", None,
    "On-demand capture trigger: touching this file at a step boundary "
    "opens a pyprof.ProfileWindow for APEX_TPU_TRACE_CAPTURE_STEPS "
    "steps (the file is consumed; one window per touch).")
register_flag(
    "APEX_TPU_TRACE_CAPTURE_STEPS", "int", 4,
    "Length (steps) of an on-demand / auto capture window.", lo=1)
register_flag(
    "APEX_TPU_TRACE_RATIO_MIN", "float", 0.0,
    "Auto-capture threshold: a step whose wall_device_ratio falls "
    "below this opens one profiling window (0 disables; the "
    "waterfall sibling of the Watchdog stall-trace hook).",
    lo=0.0, hi=1.0)
register_flag(
    "APEX_TPU_TELEMETRY_DRAIN_EVERY", "int", 0,
    "Deferred-telemetry cadence for the smoke drivers: K>=1 "
    "accumulates per-step scalars in a device ring "
    "(monitor.tracing.DeviceMetricsBuffer) drained every K steps — "
    "zero per-step host transfers; 0 keeps the classic synchronous "
    "per-step readback.", lo=0)
register_flag(
    "APEX_TPU_SCAN_STEPS", "int", 0,
    "Batched-step scan driver for the smoke drivers: K>=1 runs K train "
    "steps per jit call via lax.scan (params/amp state/telemetry ring "
    "threaded through the carry, all donated), amortizing per-dispatch "
    "host overhead across the window; telemetry drains and checkpoint/"
    "watchdog/waterfall boundaries land on K-step edges.  0 keeps the "
    "classic one-dispatch-per-step loop.  The smoke drivers' "
    "--scan-steps overrides.", lo=0)
register_flag(
    "APEX_TPU_COMPILE_CACHE_DIR", "str", None,
    "Persistent XLA compilation cache directory "
    "(utils.compile_cache.configure_compile_cache): when set, every "
    "driver/bench process wires jax's persistent cache here (size/"
    "compile-time floors relaxed so even smoke-sized programs cache), "
    "so a warmed host pays compile cost once — cold-start and retrace "
    "stop polluting wall rows.  One `python -m "
    "apex_tpu.testing.entry_points --aot` run pre-populates it for "
    "every registered entry point.")
register_flag(
    "APEX_TPU_BENCH_GATE_RATIO", "bool", False,
    "tools/bench_gate.py: escalate the wall_device_ratio check on the "
    "long_context and optimizer-pipeline rows from WARN to a gating "
    "regression (--ratio-min, default 0.9 — ROADMAP item 2's exit "
    "bar).  Off by default so the nightly bench tier arms it first.")
register_flag(
    "APEX_TPU_SERVE_KV_BLOCK", "int", 16,
    "Tokens per KV-cache block in the serving stack "
    "(docs/api/serving.md): the paging grain the flash-decode kernel "
    "gathers by and the unit the block pool allocates.  128 matches "
    "the MXU lane width on a real TPU; the smoke/CI default keeps "
    "tiny prompts multi-page so the paging paths are exercised.",
    lo=1, hi=4096)
register_flag(
    "APEX_TPU_SERVE_KV_DTYPE", "str", "model",
    "KV-cache storage dtype: 'model' stores k/v in the model compute "
    "dtype, 'bf16' forces bfloat16, 'int8' stores weight-only-"
    "quantized rows with per-token fp32 scales (appending never "
    "requantizes history; the kernel dequantizes per page in VMEM).")
register_flag(
    "APEX_TPU_SERVE_BLOCKS", "int", 64,
    "KV-cache pool size (blocks, INCLUDING the reserved dump block 0) "
    "for drivers that size the cache from flags (standalone_gpt "
    "--serve); engine callers may pass an explicit pool.", lo=2)
register_flag(
    "APEX_TPU_SERVE_BATCH_BUCKETS", "str", "1,2,4,8",
    "Registered decode batch-size ladder (comma-separated, "
    "ascending): a decode step's batch rounds up to the smallest "
    "rung, so steady-state serving compiles exactly one program per "
    "(batch, pages) bucket — the recompile budget sanitize() "
    "enforces.")
register_flag(
    "APEX_TPU_SERVE_PAGE_BUCKETS", "str", "1,2,4,8",
    "Registered page-span ladder: the decode step's block-table "
    "width (and the prefill padding, in blocks) rounds up to the "
    "smallest rung.  max rung x APEX_TPU_SERVE_KV_BLOCK bounds the "
    "servable sequence length.")
register_flag(
    "APEX_TPU_SERVE_SPECULATE_K", "int", 0,
    "Speculative decoding for the serving engine "
    "(docs/api/serving.md#speculative-decoding): K>=1 has the draft "
    "model propose K tokens per tick, the target model score all of "
    "them in ONE multi-token paged-attention call, and greedy-match "
    "acceptance keep the longest agreeing prefix plus one corrected "
    "token — output is token-for-token identical to non-speculative "
    "greedy decode; rejected tokens roll the KV write cursor back.  "
    "0 disables (one target call, one token per tick).  Requires a "
    "draft model (standalone_gpt --serve --speculate-k builds one).",
    lo=0, hi=16)
register_flag(
    "APEX_TPU_SERVE_PREFILL_CHUNK", "int", 0,
    "Chunked prefill (docs/api/serving.md#chunked-prefill): N>=1 "
    "splits prompt prefill into N-token chunks interleaved one per "
    "engine tick with running requests' decode steps, bounding the "
    "ITL spike a long-prompt admission inflicts.  The chunk size is "
    "a bucket dimension (AOT-warmed like the rest of the ladder, so "
    "the zero-steady-state-recompile contract holds).  0 prefills "
    "whole prompts synchronously at admission.", lo=0)
register_flag(
    "APEX_TPU_SERVE_PREFIX_SHARE", "bool", False,
    "Copy-on-write prompt-prefix sharing in the serving KV pool "
    "(docs/api/serving.md#prefix-sharing): full prompt blocks are "
    "content-chain-hashed into a shared read-only page index with "
    "refcounts; a warm prefix maps shared pages instead of "
    "re-prefilling them (prefill runs only on the unshared tail, and "
    "admission reserves only the tail), eviction parks zero-ref "
    "blocks in an idle LRU reclaimed under pool pressure, and any "
    "write into a shared page copies it first.")
register_flag(
    "APEX_TPU_SERVE_TICK_EVERY", "int", 1,
    "Engine-gauge cadence for the serving telemetry layer "
    "(serving/metrics.py): one kind=\"serve_tick\" event leaves every "
    "K engine ticks, carrying running batch, active bucket shape, "
    "free/reserved blocks, queue depth, and the window's admissions/"
    "evictions/preemptions/compiles — the feed a fleet router "
    "load-balances on.  Counters accumulate across the window; a "
    "trailing partial window flushes at run end.", lo=1)
register_flag(
    "APEX_TPU_SERVE_DEADLINE_MS", "float", 0.0,
    "Default request deadline (milliseconds, submit -> last token) "
    "for serving requests that do not carry their own: a queued "
    "request past its deadline is expired terminal "
    "`deadline_exceeded`, a running one evicted terminal `deadline` "
    "(blocks freed) — enforced at tick boundaries, AFTER the "
    "expiring tick's tokens were delivered.  0 disables "
    "(docs/api/resilience.md#serving-resilience).", lo=0.0)
register_flag(
    "APEX_TPU_SERVE_SHED_POOL_HW", "float", 0.0,
    "Load-shedding high-water mark on KV-pool pressure (fraction of "
    "usable blocks an allocation could not draw on): crossing it "
    "engages shedding — admissions stop and lowest-priority/"
    "shortest-progress work sheds — until pressure drops below the "
    "low-water mark (high-water minus 0.15, the hysteresis band).  "
    "0 disables the pool trigger.", lo=0.0, hi=1.0)
register_flag(
    "APEX_TPU_SERVE_SHED_QUEUE_HW", "int", 0,
    "Load-shedding high-water mark on the admission backlog (queued "
    "+ mid-prefill requests): crossing it engages shedding until the "
    "backlog drops below half the mark (hysteresis).  0 disables the "
    "queue trigger.", lo=0)
register_flag(
    "APEX_TPU_SERVE_JOURNAL_DIR", "str", None,
    "Directory for the serving request journal "
    "(serving/resilience.py): when set, the --serve driver records "
    "every request's submit/progress/terminal transitions to "
    "<dir>/serve.journal.jsonl (crash-safe append-only JSONL), and a "
    "supervised serve (--supervise) replays it after an engine-loop "
    "crash — every non-terminal request re-submitted, warm through "
    "prefix sharing.  The --journal CLI flag overrides.")
register_flag(
    "APEX_TPU_SERVE_SNAPSHOT_FILE", "str", None,
    "On-demand serving snapshot trigger: touching this file (or "
    "SIGUSR1 in the --serve driver) dumps the live engine state — "
    "queue depth, active requests and their progress, pool/"
    "reservation bookkeeping, compile counts — as ONE engine_snapshot "
    "JSON event at the next tick boundary (the file is consumed; "
    "exactly one snapshot per trigger).  The wedged-serve "
    "post-mortem hook (docs/api/serving.md).")
register_flag(
    "APEX_TPU_SERVE_REPLICAS", "int", 1,
    "Fleet size for the multi-replica serving driver (standalone_gpt "
    "--serve-fleet / docs/api/serving.md#fleet-serving): N "
    "ServingEngine replicas behind the gauge-fed FleetRouter, each "
    "with its own KV pool (and, with APEX_TPU_SERVE_TP, its own "
    "device slice).  The --replicas CLI flag overrides.", lo=1,
    hi=64)
register_flag(
    "APEX_TPU_SERVE_TP", "int", 0,
    "Tensor-parallel decode width per serving replica "
    "(serving/tp.py): T>=2 shards weights and the paged KV cache "
    "along a MeshPlan `tensor` axis (head-sharded attention, "
    "column/row-split MLP, 2 psums per layer — the audited "
    "gpt_decode_step_tp topology), greedy output token-identical to "
    "the single-chip engine.  0/1 keeps single-chip replicas.  The "
    "--tp CLI flag overrides.", lo=0, hi=64)
register_flag(
    "APEX_TPU_SERVE_EP", "int", 0,
    "Expert-parallel decode width for the serving engine "
    "(serving/ep.py): E>=2 shards a MoE model's expert weights along "
    "a MeshPlan `expert` axis (attention and the paged KV cache "
    "replicated, per-rank token slices routed through the overlapped "
    "all-to-all exchange — the audited gpt_decode_step_ep topology), "
    "greedy output token-identical to the dense single-chip engine "
    "on a 1-expert config.  0/1 keeps single-chip decode.  The --ep "
    "CLI flag overrides.", lo=0, hi=64)
register_flag(
    "APEX_TPU_SERVE_DISAGGREGATE", "bool", False,
    "Disaggregated prefill/decode for the serving fleet: prefill-role "
    "replicas run prompt admission only and stream finished KV blocks "
    "(block table as the wire format, int8/bf16 storage preserved) "
    "into decode replicas' paged pools, registered into the shared "
    "prefix index so the decode-side admission is warm "
    "(prefix_hit_tokens > 0).  Requires APEX_TPU_SERVE_PREFIX_SHARE "
    "semantics on every replica (the fleet driver arms it).  The "
    "--disaggregate CLI flag overrides.")
register_flag(
    "APEX_TPU_SERVE_ROUTER", "str", "gauges",
    "FleetRouter submission policy: 'gauges' scores replicas by the "
    "router_snapshot feed — sticky warm-prefix affinity first (chain-"
    "key intersection with each replica's shared index), then pool "
    "headroom net of in-flight reservations, then smallest backlog, "
    "avoiding shed-engaged replicas; 'round_robin' ignores all "
    "signals (the A/B control the bench row compares against).")
register_flag(
    "APEX_TPU_METRICS_PORT", "int", 0,
    "Live metrics plane (monitor/export.py): >0 starts the stdlib "
    "MetricsServer daemon thread on this port for the --serve / "
    "--serve-fleet drivers, exposing /metrics (Prometheus text "
    "exposition fed from the existing gauge/metrics structures — no "
    "second bookkeeping path), /healthz (drain/shed/escalation/"
    "SLO-burn aware, 503 while draining) and /varz (the same "
    "engine.snapshot_state() JSON as the SIGUSR1 trigger).  0 "
    "disables.  The --metrics-port CLI flag overrides; port 0 with "
    "the CLI flag picks an ephemeral port (printed in the "
    "metrics_server_started event).", lo=0, hi=65535)
register_flag(
    "APEX_TPU_CP_RPC_TIMEOUT_S", "float", 60.0,
    "Process-isolated control plane (serving/control_plane.py): "
    "per-attempt socket deadline in seconds for replica RPCs that "
    "carry work (tick/submit/gather/scatter).  A timed-out "
    "non-idempotent op escalates to SIGKILL + respawn + journal "
    "replay rather than a blind resend.  The ProcessFleet "
    "rpc_timeout_s ctor argument overrides.", lo=0.1)
register_flag(
    "APEX_TPU_CP_POLL_TIMEOUT_S", "float", 10.0,
    "Control plane gauge-poll deadline in seconds for the per-round "
    "router_snapshot RPC.  A timed-out poll never blocks the tick: "
    "the replica keeps its stale snapshot, its router score degrades "
    "(stale replicas sort last), and a heartbeat miss is charged.  "
    "The ProcessFleet poll_timeout_s ctor argument overrides.",
    lo=0.1)
register_flag(
    "APEX_TPU_CP_RPC_RETRIES", "int", 2,
    "Control plane retry budget for idempotent replica RPCs "
    "(snapshot/gather/summary/shutdown).  Each retry re-sends under "
    "a fresh sequence number after a bounded backoff; non-idempotent "
    "ops always run with zero retries and escalate to restart+replay "
    "instead.  The ProcessFleet rpc_retries ctor argument overrides.",
    lo=0, hi=16)
register_flag(
    "APEX_TPU_CP_SPAWN_TIMEOUT_S", "float", 300.0,
    "Control plane replica spawn deadline in seconds: the supervisor "
    "waits this long for a freshly spawned subprocess to connect its "
    "socket and send the hello frame (covers jax import + engine "
    "build + journal replay).  Exceeding it kills the child and "
    "counts a restart.  The ProcessFleet spawn_timeout_s ctor "
    "argument overrides.", lo=1.0)
register_flag(
    "APEX_TPU_CP_CONNECT_TIMEOUT_S", "float", 300.0,
    "Control plane child-side rendezvous deadline in seconds: how "
    "long a freshly spawned replica keeps retrying its AF_UNIX "
    "connect before giving up.  Normally unused — begin_spawn stamps "
    "the listener's own spawn_timeout_s into EngineSpec."
    "connect_timeout_s so both halves of the handshake run on one "
    "clock — this flag is the fallback for a worker entered outside "
    "ReplicaProcess (its default matches "
    "APEX_TPU_CP_SPAWN_TIMEOUT_S for the same reason).", lo=1.0)
register_flag(
    "APEX_TPU_CP_HEARTBEAT_MISSES", "int", 3,
    "Control plane liveness threshold: consecutive missed gauge "
    "polls (rpc_timeout on router_snapshot) a replica may accrue "
    "before the supervisor declares it hung, SIGKILLs it, and "
    "restarts it with journal replay under bounded backoff.  The "
    "ProcessFleet heartbeat_misses ctor argument overrides.",
    lo=1, hi=100)
register_flag(
    "APEX_TPU_SLO_TTFT_P99_MS", "float", 0.0,
    "Serving SLO: time-to-first-token p99 objective in milliseconds "
    "for ALL priority classes (serving/metrics.SLOTracker).  >0 arms "
    "dual-window burn-rate tracking — an slo_burn alarm fires "
    "(once per episode, through the watchdog escalation machinery) "
    "when both the fast and slow rolling windows burn error budget "
    "at >= the trip threshold.  0 disables the dimension.", lo=0.0)
register_flag(
    "APEX_TPU_SLO_ITL_P99_MS", "float", 0.0,
    "Serving SLO: inter-token-latency p99 objective in milliseconds, "
    "same burn-rate semantics as APEX_TPU_SLO_TTFT_P99_MS.  0 "
    "disables the dimension.", lo=0.0)
register_flag(
    "APEX_TPU_SLO_AVAILABILITY", "float", 0.0,
    "Serving SLO: availability target as a fraction (e.g. 0.999) — a "
    "request counts against it when it terminates shed or "
    "deadline_exceeded (preemptions are resumed work, not failures). "
    "Error budget is 1-target; burn-rate semantics as the latency "
    "objectives.  0 disables the dimension.", lo=0.0, hi=1.0)
register_flag(
    "APEX_TPU_SHARDING_MIN_BYTES", "int", 1024,
    "Size floor for the SPMD auditor's APX701 replication rule "
    "(docs/api/analysis.md): a plan-sharded tensor smaller than this "
    "may propagate replicated without failing — replicating a scalar "
    "step count costs nothing, and the rule exists for param/state/"
    "activation buffers whose 1/N sharding IS the memory plan.", lo=0)
register_flag(
    "APEX_TPU_SCHED_SEEDS", "int", 5,
    "Seed count for the deterministic-schedule fleet stress harness "
    "(python -m apex_tpu.analysis.schedule, ci.sh step 14): each "
    "seed serves the same request trace on the threaded fleet under "
    "a different reproducible thread interleaving; the terminal "
    "fleet digest must be identical across all of them, with zero "
    "lost requests and zero uncaught background-thread exceptions.",
    lo=1, hi=64)
register_flag(
    "APEX_TPU_FULL", "bool", False,
    "CI switch: run the full (slow-inclusive) test tier in "
    "tools/ci.sh.")
register_flag(
    "APEX_TPU_L1_FULL", "bool", False,
    "Run the full L1 amp x optimizer cross-product grid instead of "
    "the CI slice.")
register_flag(
    "APEX_TPU_BENCH_GATE", "bool", False,
    "tools/ci.sh step 8: also run `bench.py --quick` and gate the "
    "fresh artifact with tools/bench_gate.py (for bench hosts; the "
    "gate's self-test runs in CI regardless).")
