"""SPMD sharding auditor: declared vs propagated sharding, reshard
chains, overlap preconditions, and per-device memory over the
partitioned entry points.

PR 6's compiled-graph auditor (:mod:`.hlo`) proves donation, promotion,
and the collective census on the *logical* graph; this module audits
the **partitioned** artifact: every multichip entry in
:mod:`apex_tpu.testing.entry_points` that carries a
:class:`apex_tpu.mesh_plan.MeshPlan` is lowered AND compiled under its
mesh, and the partitioner's actual output — propagated argument/result
shardings, per-device memory, the collective schedule — is checked
against the plan.  Declared partitioning is a contract; a silently
replicated ZeRO shard or an accidental all-gather→reduce-scatter
round-trip is invisible at the source layer and only shows up as a TPU
bill at runtime.  Here it fails CI.

Rules (registered in :mod:`.rules`, table in docs/api/analysis.md):

* **APX701 unintended full replication** — a tensor above the
  ``APEX_TPU_SHARDING_MIN_BYTES`` floor whose plan spec shards it over
  an axis, but whose propagated sharding is fully replicated: the
  classic silent-ZeRO-regression (every device pays full-state memory
  while the plan promised 1/N).
* **APX702 reshard chain** — an ``all_gather`` whose result feeds a
  ``reduce_scatter`` or a ``dynamic_slice`` re-partition of the same
  operand (directly or through elementwise converts): the bytes were
  gathered only to be thrown away, with both ops' jaxpr provenance.
* **APX703 declared-vs-propagated drift** — a plan-declared spec the
  partitioner resolved differently (neither matching nor replicated —
  that case is APX701), a declared pattern matching no tensor (stale
  plan), or a collective-budget overrun / unbudgeted collective kind
  (census from the jaxpr, scan bodies priced by trip count, with the
  innermost repo frame named).
* **APX704 non-overlappable collective** *(advisory)* — an
  all_to_all / all_gather whose first consumer is the immediately
  following equation while later equations independent of it exist:
  the MoE a2a/expert-compute overlap precondition is not met as
  written, so the scheduler has nothing to hide the transfer behind.
  Advisory: printed, never red.
* **APX705 per-device peak-memory drift** — XLA's own per-device
  memory analysis of the partitioned executable (arguments + outputs +
  temps − donation-aliased), gated ±10% against the committed
  ``tools/sharding_baseline.json`` row per entry/topology.

The baseline file also commits each entry's plan (axes, sizes, kinds,
budget) — a topology change is a reviewed JSON diff, not a silent code
path.  APX701–703 findings suppress through
``tools/sharding_findings.txt`` (the PR-5 reasoned-baseline machinery;
committed EMPTY — the real finding at introduction, the ZeRO bench
driver's replicated state boundary, was FIXED).  CLI:
``python -m apex_tpu.analysis --check-sharding`` /
``--update-sharding-baseline`` (tools/ci.sh step 12, CPU lowerings on
the 8-device host-platform mesh).
"""
from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .hlo import (COLLECTIVE_PRIMS, _aval_bytes, _iter_eqns,
                  _provenance, _sub_jaxprs)
from .linter import Finding, load_baseline

__all__ = ["ShardingAudit", "audit_sharding", "run_sharding_check",
           "write_sharding_baseline", "DEFAULT_SHARDING_BASELINE",
           "DEFAULT_SHARDING_FINDINGS", "tensor_paths"]

DEFAULT_SHARDING_BASELINE = "tools/sharding_baseline.json"
DEFAULT_SHARDING_FINDINGS = "tools/sharding_findings.txt"

_MEM_TOL = 0.10  # APX705 gate, both directions (the drift is the signal)

# prims a gathered value may pass through and still count as "the same
# operand" for the APX702 chain walk
_PASSTHROUGH_PRIMS = {"convert_element_type", "copy"}
# consumers that re-partition a gathered operand
_REPARTITION_PRIMS = {"reduce_scatter", "dynamic_slice"}
# collectives whose latency wants hiding behind independent compute
_OVERLAP_PRIMS = {"all_to_all", "all_gather"}


def _min_bytes() -> int:
    from .flags import flag_int

    return flag_int("APEX_TPU_SHARDING_MIN_BYTES")


# ---------------------------------------------------------------------------
# tensor naming: flat leaves -> stable audit paths
# ---------------------------------------------------------------------------

def tensor_paths(tree: Any, prefix: str) -> List[str]:
    """One stable path string per flat leaf of ``tree``:
    ``in0['params']['w']``, ``out1.m[0]`` — what plan patterns match
    against.  Ordering == ``jax.tree_util.tree_leaves`` order (the
    lowering's flat argument order)."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [prefix + jax.tree_util.keystr(path) for path, _ in leaves]


def _arg_paths(args: Sequence[Any]) -> List[str]:
    out: List[str] = []
    for i, a in enumerate(args):
        out.extend(tensor_paths(a, f"in{i}"))
    return out


def _flatten_shardings(shardings: Any) -> List[Any]:
    import jax

    return jax.tree_util.tree_leaves(
        shardings, is_leaf=lambda s: hasattr(s, "shard_shape"))


# ---------------------------------------------------------------------------
# the per-entry audit
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ShardingAudit:
    """Everything the SPMD auditor measured for one planned entry."""

    name: str
    plan_json: Dict[str, Any]
    per_device_bytes: Optional[int]     # None when XLA reports nothing
    census: Dict[str, int]              # collective kind -> ops/step
    findings: List[Finding]             # APX701/702/703 (errors)
    advisories: List[Finding]           # APX704 (never red)

    def baseline_row(self) -> Dict[str, Any]:
        return {"plan": self.plan_json,
                "per_device_bytes": self.per_device_bytes,
                "collectives": dict(sorted(self.census.items()))}


def _spec_findings(entry: str, plan, paths: List[str],
                   shardings: List[Any], avals: List[Any],
                   repo_root: Path, *,
                   check_stale: bool = True) -> List[Finding]:
    """APX701/APX703 over one flat (path, sharding, aval) list.
    ``check_stale=False`` skips the pattern-matches-nothing rule —
    used when part of the path universe was dropped (misaligned
    flattening), where 'stale' would be a false accusation."""
    findings: List[Finding] = []
    floor = _min_bytes()
    matched_patterns = set()
    for path, sh, aval in zip(paths, shardings, avals):
        spec = plan.spec_for(path)
        if spec is None:
            continue
        matched_patterns.add(_pattern_of(plan, path))
        shape = tuple(getattr(aval, "shape", ()))
        nbytes = _aval_bytes(aval)
        try:
            want = plan.expected_shard_shape(shape, spec)
        except ValueError as e:
            findings.append(Finding(
                path=f"<entry:{entry}>", line=0, col=0, rule="APX703",
                severity="error",
                message=f"[{entry}] plan spec for {path} does not fit "
                        f"its shape: {e}",
                symbol=f"{entry}.spec.{_sym(path)}"))
            continue
        if sh is None:
            continue
        have = tuple(sh.shard_shape(shape))
        if have == want:
            continue
        if have == shape and want != shape:
            if nbytes < floor:
                continue  # replicating a scalar costs nothing
            # fully replicated where the plan shards: the silent-ZeRO
            # regression — every device pays sharded_factor x the
            # memory the plan promised
            findings.append(Finding(
                path=f"<entry:{entry}>", line=0, col=0, rule="APX701",
                severity="error",
                message=f"[{entry}] {path} ({nbytes} bytes) is fully "
                        f"REPLICATED but the plan shards it {spec} — "
                        f"per-device cost is the whole tensor, not "
                        f"{want}; the partitioner never saw the "
                        f"declared sharding (check the shard_map "
                        f"in/out_specs or in_shardings derive from "
                        f"the plan)",
                symbol=f"{entry}.replicated.{_sym(path)}"))
        else:
            findings.append(Finding(
                path=f"<entry:{entry}>", line=0, col=0, rule="APX703",
                severity="error",
                message=f"[{entry}] {path}: plan declares {spec} "
                        f"(per-device {want}) but the partitioner "
                        f"assigned per-device {have} of global "
                        f"{shape}",
                symbol=f"{entry}.drift.{_sym(path)}"))
    # a declared pattern matching NO tensor is a stale plan — the
    # contract must track reality or it checks nothing
    for pattern, _ in plan.tensor_specs if check_stale else ():
        if pattern in matched_patterns:
            continue
        if not any(_re_search(pattern, p) for p in paths):
            findings.append(Finding(
                path=f"<entry:{entry}>", line=0, col=0, rule="APX703",
                severity="error",
                message=f"[{entry}] plan pattern {pattern!r} matches "
                        f"no audited tensor — stale spec (update the "
                        f"plan with the entry)",
                symbol=f"{entry}.stale-pattern.{_sym(pattern)}"))
    return findings


def _re_search(pattern: str, path: str) -> bool:
    import re

    return re.search(pattern, path) is not None


def _pattern_of(plan, path: str) -> Optional[str]:
    for pattern, _ in plan.tensor_specs:
        if _re_search(pattern, path):
            return pattern
    return None


def _sym(path: str) -> str:
    """Stable, baseline-friendly symbol from an audit path."""
    return "".join(c if c.isalnum() or c in "._" else "-"
                   for c in path)


def _chain_findings(entry: str, jaxpr, repo_root: Path
                    ) -> Tuple[List[Finding], List[Finding]]:
    """APX702 (reshard chains) + APX704 (overlap advisories) over one
    jaxpr and its sub-jaxprs.  Each (sub-)jaxpr is walked linearly in
    trace order — the order XLA schedules absent other constraints."""
    core_mod = _jax_core()
    errors: List[Finding] = []
    advisories: List[Finding] = []

    def walk(jx):
        # var -> provenance of the all_gather that produced it (chased
        # through pass-through prims)
        gathered: Dict[Any, Tuple[str, int, str]] = {}
        eqns = list(jx.eqns)
        for idx, eqn in enumerate(eqns):
            prim = eqn.primitive.name
            invars = [v for v in eqn.invars
                      if isinstance(v, core_mod.Var)]
            if prim in _REPARTITION_PRIMS:
                for v in invars:
                    src = gathered.get(v)
                    if src is None:
                        continue
                    spath, sline, sfunc = src
                    path, line, func = _provenance(eqn, repo_root)
                    errors.append(Finding(
                        path=spath, line=sline, col=0, rule="APX702",
                        severity="error",
                        message=f"[{entry}] all_gather at "
                                f"{spath}:{sline} in '{sfunc}' feeds a "
                                f"{prim} re-partition of the same "
                                f"operand at {path}:{line} in "
                                f"'{func}' — the gathered bytes are "
                                f"immediately thrown away (keep the "
                                f"shard, or fuse the pair into the "
                                f"collective that says what you "
                                f"mean)",
                        symbol=f"{entry}.{sfunc}.{prim}"))
            if prim == "all_gather":
                for o in eqn.outvars:
                    gathered[o] = _provenance(eqn, repo_root)
            elif prim in _PASSTHROUGH_PRIMS and invars:
                src = gathered.get(invars[0])
                if src is not None:
                    for o in eqn.outvars:
                        gathered[o] = src
            if prim in _OVERLAP_PRIMS:
                adv = _overlap_advisory(entry, eqns, idx, core_mod,
                                        repo_root)
                if adv is not None:
                    advisories.append(adv)
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(jaxpr)
    return errors, advisories


def _overlap_advisory(entry: str, eqns, idx, core_mod,
                      repo_root: Path) -> Optional[Finding]:
    """APX704: the collective at ``eqns[idx]`` is non-overlappable as
    written when (a) the IMMEDIATELY next equation consumes its output
    (the schedule has zero slack), and (b) some later equation in the
    same jaxpr is independent of it (work existed that could have been
    hoisted in between).  A linear-order approximation on purpose:
    XLA may still reorder, but the trace order is what the author
    wrote, and the MoE overlap literature is about restructuring
    exactly this."""
    eqn = eqns[idx]
    outs = set(eqn.outvars)
    if idx + 1 >= len(eqns):
        return None
    nxt = eqns[idx + 1]
    nxt_in = {v for v in nxt.invars if isinstance(v, core_mod.Var)}
    if not (outs & nxt_in):
        return None  # slack already exists
    # transitively taint everything dependent on the collective; an
    # untainted later equation with real output bytes is independent
    # compute that could overlap the transfer
    tainted = set(outs)
    independent = None
    for later in eqns[idx + 1:]:
        lin = {v for v in later.invars if isinstance(v, core_mod.Var)}
        if lin & tainted:
            tainted.update(later.outvars)
            continue
        if later.primitive.name in COLLECTIVE_PRIMS:
            continue
        if sum(_aval_bytes(o.aval) for o in later.outvars) > 0:
            independent = later
            break
    if independent is None:
        return None
    path, line, func = _provenance(eqn, repo_root)
    ipath, iline, ifunc = _provenance(independent, repo_root)
    return Finding(
        path=path, line=line, col=0, rule="APX704",
        severity="advisory",
        message=f"[{entry}] {eqn.primitive.name} at {path}:{line} in "
                f"'{func}' is consumed by the immediately following "
                f"equation while independent compute exists later "
                f"({independent.primitive.name} at {ipath}:{iline} in "
                f"'{ifunc}') — reorder so the transfer overlaps it "
                f"(the MoE a2a/expert-compute precondition)",
        symbol=f"{entry}.{func}.{eqn.primitive.name}")


def _jax_core():
    import jax

    return jax.core


def _collective_census(jaxpr) -> Tuple[Dict[str, int],
                                       Dict[str, List[Any]]]:
    """kind -> ops/step (scan-multiplied), plus the eqns per kind for
    budget-overrun provenance."""
    census: Dict[str, int] = {}
    ops: Dict[str, List[Any]] = {}
    for eqn, mult in _iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMS:
            census[prim] = census.get(prim, 0) + mult
            ops.setdefault(prim, []).append(eqn)
    return census, ops


def _budget_findings(entry: str, plan, census: Dict[str, int],
                     ops: Dict[str, List[Any]], repo_root: Path
                     ) -> List[Finding]:
    budget = plan.budget()
    if not budget:
        return []  # a plan may decline to budget (specs-only contract)
    findings: List[Finding] = []
    for kind, count in sorted(census.items()):
        where = "; ".join(
            "{}:{} in {}".format(*_provenance(e, repo_root))
            for e in ops.get(kind, [])[:4])
        if kind not in budget:
            findings.append(Finding(
                path=f"<entry:{entry}>", line=0, col=0, rule="APX703",
                severity="error",
                message=f"[{entry}] UNBUDGETED collective kind "
                        f"'{kind}' ({count} op(s)/step) — the plan's "
                        f"budget {budget} does not mention it; emitted "
                        f"at {where}",
                symbol=f"{entry}.budget.{kind}.unbudgeted"))
        elif count > budget[kind]:
            findings.append(Finding(
                path=f"<entry:{entry}>", line=0, col=0, rule="APX703",
                severity="error",
                message=f"[{entry}] collective '{kind}' exceeds the "
                        f"plan budget: {count} op(s)/step > "
                        f"{budget[kind]} budgeted; emitted at {where}",
                symbol=f"{entry}.budget.{kind}.over"))
    return findings


def _per_device_bytes(compiled) -> Optional[int]:
    """XLA's own per-device footprint of the partitioned executable:
    arguments + outputs + temps, minus donation-aliased bytes (those
    buffers are reused, not re-allocated).  None when the backend
    reports nothing — an honest skip, never a zero."""
    try:
        ma = compiled.memory_analysis()
    except Exception:  # apex-lint: disable=APX202 -- backend-optional API: absence degrades to an honest null, not a crash
        return None
    if ma is None:
        return None
    total = 0
    for field in ("argument_size_in_bytes", "output_size_in_bytes",
                  "temp_size_in_bytes"):
        total += int(getattr(ma, field, 0) or 0)
    total -= int(getattr(ma, "alias_size_in_bytes", 0) or 0)
    return total if total > 0 else None


def _audit_one(name: str, ep, repo_root: Path) -> ShardingAudit:
    import jax

    plan = ep.plan()
    fn, args = ep.build()
    closed = jax.make_jaxpr(fn)(*args)
    compiled = fn.lower(*args).compile()

    findings: List[Finding] = []

    # --- declared vs propagated shardings (APX701/703) --------------------
    in_paths = _arg_paths(args)
    in_shardings = _flatten_shardings(compiled.input_shardings[0])
    in_avals = list(closed.in_avals)
    out_shardings = _flatten_shardings(compiled.output_shardings)
    out_avals = list(closed.out_avals)
    # output paths from the avals' positional structure alone (the
    # output pytree is not observable without executing) — plans name
    # outputs by flat position: out0, out1, ...
    out_paths = [f"out{i}" for i in range(len(out_avals))]
    paths, shardings, avals = [], [], []
    for kind, p, s, a in (("input", in_paths, in_shardings, in_avals),
                          ("output", out_paths, out_shardings,
                           out_avals)):
        if len(p) == len(s) == len(a):
            paths += p
            shardings += s
            avals += a
        else:
            # never mis-zip paths/shardings/avals: a backend that
            # flattens differently gets ONE honest loud finding, not
            # a wall of bogus drift/stale-spec errors from shifted
            # pairings
            findings.append(Finding(
                path=f"<entry:{name}>", line=0, col=0, rule="APX703",
                severity="error",
                message=f"[{name}] auditor could not align {kind} "
                        f"paths/shardings/avals "
                        f"({len(p)}/{len(s)}/{len(a)} leaves) — the "
                        f"backend flattened the {kind}s differently; "
                        f"{kind} spec checks skipped this run",
                symbol=f"{name}.misaligned.{kind}"))
    aligned = len(paths) == len(in_paths) + len(out_paths)
    findings.extend(_spec_findings(name, plan, paths, shardings,
                                   avals, repo_root,
                                   check_stale=aligned))

    # --- reshard chains + overlap advisories (APX702/704) ------------------
    errors, advisories = _chain_findings(name, closed.jaxpr, repo_root)
    findings.extend(errors)

    # --- collective budget (APX703) ----------------------------------------
    census, ops = _collective_census(closed.jaxpr)
    findings.extend(_budget_findings(name, plan, census, ops,
                                     repo_root))

    return ShardingAudit(
        name=name, plan_json=plan.to_json(),
        per_device_bytes=_per_device_bytes(compiled),
        census=census, findings=findings, advisories=advisories)


def audit_sharding(repo_root: str = ".",
                   names: Optional[Sequence[str]] = None
                   ) -> Dict[str, ShardingAudit]:
    """Audit every buildable entry point that carries a MeshPlan."""
    from ..testing.entry_points import available_entry_points

    root = Path(repo_root).resolve()
    audits = {}
    for name, ep in available_entry_points().items():
        if ep.plan is None:
            continue
        if names is not None and name not in names:
            continue
        audits[name] = _audit_one(name, ep, root)
    return audits


# ---------------------------------------------------------------------------
# baseline (plan + per-device memory) and the check entry
# ---------------------------------------------------------------------------

def load_sharding_baseline(path: str = DEFAULT_SHARDING_BASELINE, *,
                           repo_root: str = ".") -> Dict[str, Any]:
    p = Path(repo_root) / path
    if not p.exists():
        return {"entries": {}}
    return json.loads(p.read_text())


def write_sharding_baseline(audits: Dict[str, ShardingAudit],
                            path: str = DEFAULT_SHARDING_BASELINE, *,
                            repo_root: str = ".") -> None:
    """Rewrite the committed topology/memory baseline.  Same partial-
    update contract as the hlo baseline: entries not audited this run
    keep their rows; rows for unregistered entries are dropped."""
    import jax

    from ..testing.entry_points import ENTRY_POINTS

    existing = load_sharding_baseline(path, repo_root=repo_root).get(
        "entries", {})
    rows = {name: row for name, row in existing.items()
            if name in ENTRY_POINTS}
    rows.update({name: a.baseline_row() for name, a in audits.items()})
    payload = {
        "_comment": [
            "Committed MeshPlan topology + per-device memory baseline",
            "for the planned entry points "
            "(apex_tpu/testing/entry_points.py).",
            "Regenerate with: python -m apex_tpu.analysis "
            "--update-sharding-baseline",
            "(CPU lowerings, 8 host-platform devices — the tools/"
            "ci.sh step 12 configuration).",
            "A plan diff here IS the topology review; APX705 gates "
            "per_device_bytes at +/-10%.",
        ],
        "jax_version": jax.__version__,
        "entries": {name: rows[name] for name in sorted(rows)},
    }
    (Path(repo_root) / path).write_text(
        json.dumps(payload, indent=1, sort_keys=True) + "\n")


def _baseline_findings(name: str, audit: ShardingAudit,
                       base_row: Optional[Dict[str, Any]]
                       ) -> List[Finding]:
    out: List[Finding] = []

    def emit(rule: str, symbol: str, message: str) -> None:
        out.append(Finding(path=f"<entry:{name}>", line=0, col=0,
                           rule=rule, severity="error",
                           message=f"[{name}] {message}",
                           symbol=symbol))

    if base_row is None:
        emit("APX705", "unbaselined",
             "entry point has no committed sharding-baseline row — "
             "run 'python -m apex_tpu.analysis "
             "--update-sharding-baseline' and review the diff")
        return out
    if base_row.get("plan") != audit.plan_json:
        emit("APX703", "plan-drift",
             "MeshPlan changed vs the committed baseline (axes/sizes/"
             "kinds/specs/budget) — a topology change must be a "
             "reviewed baseline diff (--update-sharding-baseline)")
    base_mem = base_row.get("per_device_bytes")
    mem = audit.per_device_bytes
    if base_mem is not None and mem is not None:
        if mem > base_mem * (1 + _MEM_TOL):
            emit("APX705", "per-device-mem",
                 f"per-device memory grew >10%: {base_mem} -> {mem} "
                 f"bytes (arguments+outputs+temps per device, XLA "
                 f"memory analysis of the partitioned executable)")
        elif mem < base_mem * (1 - _MEM_TOL):
            emit("APX705", "per-device-mem",
                 f"per-device memory shrank >10% ({base_mem} -> {mem} "
                 f"bytes) — refresh the baseline to lock in the win")
    elif (base_mem is None) != (mem is None):
        emit("APX705", "per-device-mem",
             f"per-device memory availability changed "
             f"({base_mem} -> {mem}) — refresh the baseline")
    return out


def run_sharding_check(repo_root: str = ".", *,
                       baseline: str = DEFAULT_SHARDING_BASELINE,
                       findings_baseline: str = DEFAULT_SHARDING_FINDINGS,
                       names: Optional[Sequence[str]] = None
                       ) -> Tuple[List[Finding], List[Finding],
                                  List[str], Dict[str, ShardingAudit]]:
    """The ``--check-sharding`` engine.

    Returns ``(errors, advisories, stale suppression keys, audits)`` —
    non-empty errors or stale keys mean a red build; advisories
    (APX704) print but never fail.  Entries the host cannot build
    (device-count gate) skip without touching their baseline rows,
    mirroring the hlo checker's semantics.
    """
    from ..testing.entry_points import ENTRY_POINTS

    audits = audit_sharding(repo_root, names=names)
    base = load_sharding_baseline(baseline, repo_root=repo_root)
    entries = base.get("entries", {})
    findings: List[Finding] = []
    advisories: List[Finding] = []
    for name, audit in sorted(audits.items()):
        findings.extend(audit.findings)
        advisories.extend(audit.advisories)
        findings.extend(_baseline_findings(name, audit,
                                           entries.get(name)))
    planned = {n for n, ep in ENTRY_POINTS.items() if ep.plan is not None}
    for name in sorted(set(entries) - planned):
        findings.append(Finding(
            path=f"<entry:{name}>", line=0, col=0, rule="APX705",
            severity="error",
            message=f"[{name}] sharding-baseline row for an entry "
                    f"point that is no longer registered with a plan "
                    f"— delete it (--update-sharding-baseline)",
            symbol="stale-entry"))
    suppress = load_baseline(findings_baseline, repo_root=repo_root)
    live_keys = {f.key for f in findings}
    unsuppressed = [f for f in findings if f.key not in suppress]
    # staleness is only judged by a run that audited everything (the
    # hlo checker's rule): a device-gated or --entry-filtered run must
    # not demand deletion of a line full CI still needs
    full_run = names is None and set(audits) == planned

    def checked_this_run(key: str) -> bool:
        owner = _suppression_entry(key)
        if owner in audits:
            return True
        return full_run and (owner is None or owner not in ENTRY_POINTS)

    stale = [k for k in suppress
             if k not in live_keys and checked_this_run(k)]
    return unsuppressed, advisories, stale, audits


def _suppression_entry(key: str) -> Optional[str]:
    """Entry a suppression key belongs to: the ``<entry:NAME>`` path
    prefix (keys are ``<entry:NAME>:RULE:symbol`` — the path itself
    contains a colon, so match the closing ``>``), else the symbol's
    leading dotted component."""
    import re

    m = re.match(r"<entry:([^>]+)>:", key)
    if m:
        return m.group(1)
    sym = key.rsplit(":", 1)[-1]
    if "." in sym:
        return sym.split(".", 1)[0]
    return None
