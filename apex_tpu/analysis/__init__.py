"""apex_tpu.analysis — static and runtime correctness tooling.

The TPU-native counterpart of the reference repo's build/test matrix
(ref: tests/docker_extension_builds): instead of linting CUDA builds,
lint the *tracing* discipline the whole framework depends on.

Five pieces (rules registered centrally in :mod:`.rules`, docs table
generated from it):

* :mod:`.flags` — the central registry of every ``APEX_TPU_*``
  environment flag (name, type, default, doc) with typed accessors.
  Library code reads flags ONLY through it; the linter enforces that.
* :mod:`.linter` — AST trace-safety linter: host syncs on traced
  values, Python truthiness on tracers, env reads inside traced code,
  bare/broad excepts, direct ``jax.shard_map`` use (rule table in
  docs/api/analysis.md).
* :mod:`.parity` — kernel-parity audit: every ``pallas_call`` site in
  ``ops/`` must name a registered jnp twin and a test referencing both.
* :mod:`.hlo` — compiled-graph auditor over the lowered jaxprs /
  StableHLO of every registered entry point
  (:mod:`apex_tpu.testing.entry_points`): missed donations, silent
  dtype promotions, the collective census and a peak-live-memory
  estimate diffed against ``tools/hlo_baseline.json``.
* :mod:`.sharding` — SPMD sharding auditor over the *partitioned*
  multichip entries: declared :class:`apex_tpu.mesh_plan.MeshPlan`
  specs vs the partitioner's propagated shardings, reshard chains,
  overlap advisories, and per-device memory diffed against
  ``tools/sharding_baseline.json``.
* :mod:`.sanitizer` — runtime ``sanitize()`` context: JAX transfer
  guard plus a per-step recompile budget driven by ``jax_log_compiles``.
* :mod:`.concurrency` — host-concurrency auditor (APX801-805): lock
  discipline via guard inference over ``with self._lock:`` regions,
  lock-acquisition-order cycles aggregated cross-module, flag-only
  signal handlers, blocking-under-lock, and thread-target jit
  dispatch outside a device pin.
* :mod:`.protocol` — wire-protocol + resource-lifecycle auditor
  (APX901-905): ``serving/`` + ``resilience/`` audited against the
  declared ``ProtocolSpec`` registry in ``serving/control_plane.py``
  — deadline discipline, op and header-field drift matched across
  the parent/child modules, socket/subprocess/tempdir lifecycle,
  and retry-safety.
* :mod:`.schedule` — the dynamic half: a seeded deterministic-
  interleaving scheduler that steps the threaded serving fleet under
  permuted thread orderings and asserts the terminal digest is
  seed-invariant, with ``threading.excepthook`` capture so a
  background-thread crash is a failure, not a vanished thread.

CLI: ``python -m apex_tpu.analysis --check`` / ``--check-hlo`` /
``--check-sharding`` (self-hosted in tools/ci.sh steps 7, 8, and 12;
see ``--help`` for the rest).
"""
# flags is the one submodule production code imports at module scope
# (ops/amp/monitor read the registry on import); keep this package
# __init__ from dragging the linter/parity/sanitizer machinery into
# every library import path — tooling symbols resolve lazily (PEP 562).
from .flags import (FLAGS, Flag, flag_bool, flag_float, flag_int,
                    flag_str, render_flag_table)

_LAZY = {
    "Finding": "linter", "lint_paths": "linter",
    "load_baseline": "linter", "run_check": "linter",
    "audit_kernel_parity": "parity",
    "RecompileBudgetExceeded": "sanitizer", "Sanitizer": "sanitizer",
    "sanitize": "sanitizer", "sanitize_smoke": "sanitizer",
    "RULES": "rules", "Rule": "rules", "render_rule_table": "rules",
    "EntryAudit": "hlo", "audit_entry_points": "hlo",
    "run_hlo_check": "hlo", "peak_live_bytes": "hlo",
    "write_hlo_baseline": "hlo",
    "ShardingAudit": "sharding", "audit_sharding": "sharding",
    "run_sharding_check": "sharding",
    "write_sharding_baseline": "sharding",
    "lint_concurrency_source": "concurrency",
    "lint_concurrency_paths": "concurrency",
    "run_concurrency_check": "concurrency",
    "write_concurrency_baseline": "concurrency",
    "lint_protocol_source": "protocol",
    "lint_protocol_paths": "protocol",
    "run_protocol_check": "protocol",
    "write_protocol_baseline": "protocol",
    "DeterministicScheduler": "schedule",
    "fleet_digest": "schedule", "schedule_sweep": "schedule",
}

__all__ = [
    "FLAGS", "Flag", "flag_bool", "flag_float", "flag_int", "flag_str",
    "render_flag_table", *_LAZY,
]


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f".{mod}", __name__), name)
