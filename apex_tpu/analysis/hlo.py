"""Compiled-graph auditor: donation, dtype promotion, collective
census, host transfers, and peak-live-memory over lowered jaxprs.

The AST linter (:mod:`.linter`) proves the Python *source* is
trace-safe; this module audits what the tracer and XLA actually
*emitted* for the framework's registered entry points
(:mod:`apex_tpu.testing.entry_points`) — the artifact layer where a
missed ``donate_argnums``, a silent bf16→f32 promotion, or a collective
added by a transposition is invisible to any source-level pass.  It is
the static, CI-time counterpart of the runtime sanitizer: the
transfer-guard can only catch a compiled-in host callback after
deployment; here it fails the build.

Rules (registered in :mod:`.rules`, table in docs/api/analysis.md):

* **APX601 missed donation** — an input buffer the entry registry
  declares dead after the call, with a shape/dtype-matching output,
  but no ``tf.aliasing_output`` attribute in the lowered StableHLO
  module.  The attribute is the ground truth: it is what the runtime
  buffer-donation pass consumes, so auditing it catches a
  ``jax.jit`` that silently dropped (or never had) ``donate_argnums``.
* **APX602 silent dtype promotion** — a ``convert_element_type``
  bf16/f16 → f32 inside an O4/O5-policy entry whose provenance is not
  a sanctioned-fp32 region (layer-norm stats, softmax, loss, amp
  machinery): an upcast the precision policy did not ask for.
* **APX603 collective census** — every psum / all_gather /
  reduce_scatter / all_to_all / ppermute with element counts and bytes
  moved per step (scan bodies multiply by trip count), diffed against
  the committed ``tools/hlo_baseline.json``.  A new collective kind,
  more ops, or >10% byte growth fails CI with the offending op's
  jaxpr provenance; shrinks fail too (refresh the baseline — it only
  stays meaningful if it tracks reality).
* **APX604 host transfer** — callback/infeed/outfeed ops compiled into
  the graph (``pure_callback`` / ``io_callback`` / ``debug_callback``):
  a host round-trip every step.
* **APX606 dequantized weight residency** — the Q8 analogue of
  APX602: a ``convert_element_type`` int8 → f32/bf16 of a
  weight-sized tensor inside a Q8-policy entry whose provenance is
  not the quant kernel family (``ops/quant_matmul.py``, where dequant
  is tile-local in VMEM) or the int8-KV decode kernels.  An
  HLO-visible dense copy of an int8 operand means the graph
  materializes the fp32 weights it was quantized to avoid — the
  bandwidth win is silently forfeited.
* **APX605 peak-live-memory estimate** — buffer liveness over the
  lowered jaxpr (inputs+consts live at entry, equation outputs
  allocated in order, buffers freed after their last use, call-like
  sub-jaxprs contributing their internal excess), gated ±10% against
  the baseline per entry point.

Suppression uses the PR-5 machinery: the committed findings baseline
``tools/hlo_findings.txt`` (same ``path:RULE:symbol  # reason`` format,
empty — every finding at introduction was fixed), stale entries fail.
CLI: ``python -m apex_tpu.analysis --check-hlo`` /
``--update-hlo-baseline`` (tools/ci.sh step 8, on CPU lowerings with
an 8-device host-platform mesh for the multichip entries).
"""
from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from .linter import Finding, load_baseline

__all__ = ["CollectiveOp", "EntryAudit", "audit_entry_points",
           "run_hlo_check", "write_hlo_baseline", "peak_live_bytes",
           "DEFAULT_HLO_BASELINE", "DEFAULT_HLO_FINDINGS"]

DEFAULT_HLO_BASELINE = "tools/hlo_baseline.json"
DEFAULT_HLO_FINDINGS = "tools/hlo_findings.txt"

# jaxpr primitives that move data across devices (census classes).
COLLECTIVE_PRIMS = {"psum", "pmax", "pmin", "all_gather",
                    "reduce_scatter", "all_to_all", "ppermute",
                    "pgather"}
# jaxpr primitives XLA services from the host every execution.
HOST_TRANSFER_PRIMS = {"pure_callback", "io_callback",
                       "debug_callback", "infeed", "outfeed"}
# Low-precision source dtypes for the promotion rule.
_LOWP = ("bfloat16", "float16")

# APX606: modules whose int8 -> float converts are the POINT — the
# quant matmul family dequantizes tile-locally (its registered twin is
# the sanctioned XLA fallback on CPU lowerings), and the paged decode
# kernels dequantize int8 KV rows the same way.  Everywhere else a
# weight-sized int8 -> f32/bf16 convert is a materialized dequant.
Q8_DEQUANT_REGIONS = ("apex_tpu/ops/quant_matmul.py",
                      "apex_tpu/ops/flash_decode.py")
# ...and converts below this are scale vectors / scalars, not weights.
_DEQUANT_MIN_BYTES = 1024

# APX601 ignores buffers below this: donating a scalar loss-scale
# saves nothing, and matching tiny scalars by (shape, dtype) is pure
# coincidence.  Donation economics start at real parameter buffers.
_DONATION_MIN_BYTES = 1024

_GROWTH_TOL = 0.10  # APX603/605 byte tolerance, both directions


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------

def _core():
    import jax

    return jax.core


def _sub_jaxprs(eqn) -> Iterator[Any]:
    """Inner jaxprs of a call-like equation (pjit/scan/cond/shard_map/
    custom_vjp/pallas_call/... — anything carrying a jaxpr param)."""
    core = _core()
    for v in eqn.params.values():
        vals = v if isinstance(v, (list, tuple)) else (v,)
        for s in vals:
            if isinstance(s, core.ClosedJaxpr):
                yield s.jaxpr
            elif isinstance(s, core.Jaxpr):
                yield s


def _iter_eqns(jaxpr, mult: int = 1) -> Iterator[Tuple[Any, int]]:
    """Yield ``(eqn, trip_multiplier)`` over a jaxpr and every nested
    jaxpr.  A ``scan`` body's equations run ``length`` times per
    execution of the outer program — the census must price them per
    *step*, not per trace occurrence."""
    for eqn in jaxpr.eqns:
        yield eqn, mult
        inner_mult = mult
        if eqn.primitive.name == "scan":
            inner_mult = mult * int(eqn.params.get("length", 1) or 1)
        elif eqn.primitive.name == "while":
            # trip count is dynamic; price one iteration (documented
            # under-estimate, flagged in the op record)
            inner_mult = mult
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub, inner_mult)


def _aval_bytes(aval) -> int:
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * int(dtype.itemsize)


def _provenance(eqn, repo_root: Path) -> Tuple[str, int, str]:
    """(repo-relative file, line, function) of the innermost frame
    under the repo for this equation; the innermost user frame
    otherwise; ``("<unknown>", 0, "?")`` when the trace kept nothing."""
    try:
        from jax._src import source_info_util

        frames = list(source_info_util.user_frames(eqn.source_info))
    except Exception:  # apex-lint: disable=APX202 -- provenance is best-effort: a moved jax internal must degrade to "<unknown>", not kill the audit
        frames = []
    pick = None
    root = str(repo_root)
    for fr in frames:  # innermost-first
        if fr.file_name.startswith(root):
            pick = fr
            break
    if pick is None and frames:
        pick = frames[0]
    if pick is None:
        return "<unknown>", 0, "?"
    fname = pick.file_name
    if fname.startswith(root):
        fname = str(Path(fname).relative_to(repo_root).as_posix())
    return fname, int(pick.start_line), pick.function_name


# ---------------------------------------------------------------------------
# APX605: peak-live-memory estimate from buffer liveness
# ---------------------------------------------------------------------------

def peak_live_bytes(jaxpr) -> int:
    """Estimate the peak of live buffer bytes over one execution of
    ``jaxpr`` (a ``jax.core.Jaxpr``; pass ``closed.jaxpr``).

    Linear-scan liveness: inputs and constants are live at entry, each
    equation allocates its outputs, and a buffer is freed after its
    last use (jaxpr outputs live to the end).  Call-like equations
    (pjit, scan, remat, shard_map — anything carrying a sub-jaxpr)
    contribute their own internal peak *in excess of* their
    inputs+outputs while they execute.  This deliberately ignores
    XLA's rematerialization and fusion (which only shrink the true
    peak by eliding temporaries) — it is an upper-bound-flavored
    estimate whose *drift* is the signal, which is why the CLI gates
    it against the committed baseline instead of an absolute number.
    """
    return _peak(jaxpr, {})


def _peak(jaxpr, memo: Dict[int, int]) -> int:
    cached = memo.get(id(jaxpr))
    if cached is not None:
        return cached
    core = _core()
    last_use: Dict[Any, int] = {}
    for idx, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if isinstance(v, core.Var):
                last_use[v] = idx
    outset = {v for v in jaxpr.outvars if isinstance(v, core.Var)}
    roots = [v for v in list(jaxpr.constvars) + list(jaxpr.invars)]
    live = sum(_aval_bytes(v.aval) for v in roots)
    peak = live
    # inputs never read (donated pass-throughs aside) die immediately
    for v in roots:
        if v not in last_use and v not in outset:
            live -= _aval_bytes(v.aval)
    for idx, eqn in enumerate(jaxpr.eqns):
        outs = [o for o in eqn.outvars]
        alloc = sum(_aval_bytes(o.aval) for o in outs)
        inner_excess = 0
        for sub in _sub_jaxprs(eqn):
            io = sum(_aval_bytes(v.aval)
                     for v in list(sub.invars) + list(sub.outvars))
            inner_excess = max(inner_excess,
                               max(0, _peak(sub, memo) - io))
        live += alloc
        peak = max(peak, live + inner_excess)
        for o in outs:  # drop-vars are dead on arrival
            if isinstance(o, core.DropVar):
                live -= _aval_bytes(o.aval)
        for v in {v for v in eqn.invars if isinstance(v, core.Var)}:
            if last_use.get(v) == idx and v not in outset:
                live -= _aval_bytes(v.aval)
    memo[id(jaxpr)] = peak
    return peak


# ---------------------------------------------------------------------------
# APX601: donation ground truth from the lowered module
# ---------------------------------------------------------------------------

def _donated_args(stablehlo_text: str) -> Dict[int, int]:
    """{flat input index: aliased output index} parsed from the lowered
    module's argument attributes — the exact annotations XLA's
    buffer-donation pass consumes.  Single-device lowerings resolve the
    alias eagerly (``tf.aliasing_output = K``); SPMD lowerings defer the
    pairing to the compiler and mark ``jax.buffer_donor = true``
    (recorded here as output index ``-1``)."""
    start = stablehlo_text.find("@main(")
    if start < 0:
        return {}
    # walk to the close of the argument list by paren depth — arg
    # attribute dicts ({tf.aliasing_output = 0 : i32}) and loc(...)
    # annotations sit inside it, so a naive delimiter search truncates
    pos = start + len("@main(")
    depth = 1
    while pos < len(stablehlo_text) and depth:
        c = stablehlo_text[pos]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        pos += 1
    sig = stablehlo_text[start:pos]
    out: Dict[int, int] = {}
    for m in re.finditer(
            r"tf\.aliasing_output\s*=\s*(\d+)"
            r"|jax\.buffer_donor\s*=\s*true", sig):
        args_before = re.findall(r"%arg(\d+)", sig[: m.start()])
        if args_before:
            out[int(args_before[-1])] = (int(m.group(1))
                                         if m.group(1) is not None
                                         else -1)
    return out


def _arg_leaf_ranges(args: Sequence[Any]) -> List[Tuple[int, int]]:
    """[start, end) flat-leaf index range of each top-level positional
    argument — the lowered module's %argN order is the flattened
    pytree-leaf order of the call."""
    import jax

    ranges = []
    pos = 0
    for a in args:
        n = len(jax.tree_util.tree_leaves(a))
        ranges.append((pos, pos + n))
        pos += n
    return ranges


# ---------------------------------------------------------------------------
# the per-entry audit
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class CollectiveOp:
    """One collective equation in an entry's lowered jaxpr."""

    kind: str
    elements: int        # per execution of the op
    bytes: int           # elements * itemsize * trip multiplier
    count: int           # trip multiplier (scan bodies > 1)
    path: str
    line: int
    function: str


@dataclasses.dataclass
class EntryAudit:
    """Everything the auditor measured for one entry point."""

    name: str
    collectives: List[CollectiveOp]
    peak_live_bytes: int
    donated: Dict[int, int]            # flat arg index -> output index
    findings: List[Finding]            # APX601/602/604 (baseline-free)

    def census(self) -> Dict[str, Dict[str, int]]:
        """Aggregate: kind -> {count, bytes_per_step}."""
        agg: Dict[str, Dict[str, int]] = {}
        for op in self.collectives:
            row = agg.setdefault(op.kind, {"count": 0,
                                           "bytes_per_step": 0})
            row["count"] += op.count
            row["bytes_per_step"] += op.bytes
        return agg

    def baseline_row(self) -> Dict[str, Any]:
        return {"collectives": self.census(),
                "peak_live_bytes": int(self.peak_live_bytes),
                "donated_args": sorted(self.donated)}


def _audit_one(name: str, ep, repo_root: Path) -> EntryAudit:
    import jax

    fn, args = ep.build()
    closed = jax.make_jaxpr(fn)(*args)
    lowered_text = fn.lower(*args).as_text()
    donated = _donated_args(lowered_text)
    findings: List[Finding] = []

    # --- collective census + promotions + host transfers ------------------
    collectives: List[CollectiveOp] = []
    allow = tuple(ep.allow_upcast)
    if ep.policy in ("O4", "O5", "Q8"):
        from ..testing.entry_points import POLICY_FP32_REGIONS

        allow = allow + POLICY_FP32_REGIONS
    # APX606's allow list is deliberately NOT the fp32-region list:
    # those sanction ACTIVATION upcasts (softmax, layer-norm stats);
    # an int8 WEIGHT dequant is only ever legal inside the kernels
    q8_allow = tuple(ep.allow_upcast) + Q8_DEQUANT_REGIONS
    for eqn, mult in _iter_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        if prim in COLLECTIVE_PRIMS:
            path, line, func = _provenance(eqn, repo_root)
            nbytes = sum(_aval_bytes(o.aval) for o in eqn.outvars)
            nelems = sum(int(getattr(o.aval, "size", 0))
                         for o in eqn.outvars)
            collectives.append(CollectiveOp(
                kind=prim, elements=nelems, bytes=nbytes * mult,
                count=mult, path=path, line=line, function=func))
        elif prim == "convert_element_type" \
                and ep.policy in ("O4", "O5", "Q8"):
            src = getattr(eqn.invars[0].aval, "dtype", None)
            dst = eqn.params.get("new_dtype")
            if src is not None and str(src) in _LOWP \
                    and str(dst) == "float32":
                path, line, func = _provenance(eqn, repo_root)
                if not any(a in path for a in allow):
                    findings.append(Finding(
                        path=path, line=line, col=0, rule="APX602",
                        severity="error",
                        message=f"[{name}] silent {src}->float32 "
                                f"promotion in '{func}' — an upcast "
                                f"the {ep.policy} policy did not ask "
                                f"for (sanction the region in the "
                                f"entry registry or keep the math in "
                                f"{src})",
                        symbol=f"{name}.{func}.{src}"))
            if ep.policy == "Q8" and src is not None \
                    and str(src) == "int8" \
                    and str(dst) in ("float32", "bfloat16") \
                    and _aval_bytes(eqn.outvars[0].aval) \
                    >= _DEQUANT_MIN_BYTES:
                path, line, func = _provenance(eqn, repo_root)
                if not any(a in path for a in q8_allow):
                    findings.append(Finding(
                        path=path, line=line, col=0, rule="APX606",
                        severity="error",
                        message=f"[{name}] dequantized int8 weight "
                                f"resident: int8->{dst} of "
                                f"{_aval_bytes(eqn.outvars[0].aval)} "
                                f"bytes in '{func}' escapes the "
                                f"kernel into the compiled graph — "
                                f"Q8's contract is tile-local dequant "
                                f"(ops/quant_matmul.py); a dense "
                                f"float copy forfeits the bandwidth "
                                f"win quantization bought",
                        symbol=f"{name}.{func}.int8"))
        elif prim in HOST_TRANSFER_PRIMS:
            path, line, func = _provenance(eqn, repo_root)
            findings.append(Finding(
                path=path, line=line, col=0, rule="APX604",
                severity="error",
                message=f"[{name}] {prim} compiled into the graph in "
                        f"'{func}': XLA will round-trip the host on "
                        f"every step — the runtime transfer guard "
                        f"only catches this after deployment",
                symbol=f"{name}.{func}.{prim}"))

    # --- donation audit ----------------------------------------------------
    in_avals = list(closed.in_avals)
    out_avals = list(closed.out_avals)
    leaf_ranges = _arg_leaf_ranges(args)
    dead_leaves = set()
    for argnum in ep.dead_args:
        lo, hi = leaf_ranges[argnum]
        dead_leaves.update(range(lo, hi))
    # outputs already claimed by an existing alias are off the table
    free_outputs: Dict[Tuple[Any, Any], int] = {}
    claimed = {v for v in donated.values() if v >= 0}
    for i, aval in enumerate(out_avals):
        if i in claimed:
            continue
        key = (getattr(aval, "shape", None), getattr(aval, "dtype", None))
        free_outputs[key] = free_outputs.get(key, 0) + 1
    missed: Dict[int, Tuple[int, int]] = {}  # argnum -> (leaves, bytes)
    for leaf in sorted(dead_leaves):
        if leaf in donated or leaf >= len(in_avals):
            continue
        aval = in_avals[leaf]
        if _aval_bytes(aval) < _DONATION_MIN_BYTES:
            continue
        key = (getattr(aval, "shape", None), getattr(aval, "dtype", None))
        if free_outputs.get(key, 0) <= 0:
            continue
        free_outputs[key] -= 1
        argnum = next(i for i, (lo, hi) in enumerate(leaf_ranges)
                      if lo <= leaf < hi)
        n, b = missed.get(argnum, (0, 0))
        missed[argnum] = (n + 1, b + _aval_bytes(aval))
    for argnum, (n, b) in sorted(missed.items()):
        findings.append(Finding(
            path=f"<entry:{name}>", line=0, col=0, rule="APX601",
            severity="error",
            message=f"[{name}] arg {argnum} is dead after the call "
                    f"with {n} buffer(s) / {b} bytes matching "
                    f"undonated outputs — add it to donate_argnums "
                    f"(masters/optimizer state must be donated "
                    f"end-to-end)",
            symbol=f"arg{argnum}"))

    return EntryAudit(name=name, collectives=collectives,
                      peak_live_bytes=peak_live_bytes(closed.jaxpr),
                      donated=donated, findings=findings)


def audit_entry_points(repo_root: str = ".",
                       names: Optional[Sequence[str]] = None
                       ) -> Dict[str, EntryAudit]:
    """Audit every registered entry point buildable on this host."""
    from ..testing.entry_points import available_entry_points

    root = Path(repo_root).resolve()
    audits = {}
    for name, ep in available_entry_points().items():
        if names is not None and name not in names:
            continue
        audits[name] = _audit_one(name, ep, root)
    return audits


# ---------------------------------------------------------------------------
# baseline diff (APX603 / APX605) and the check entry
# ---------------------------------------------------------------------------

def load_hlo_baseline(path: str = DEFAULT_HLO_BASELINE, *,
                      repo_root: str = ".") -> Dict[str, Any]:
    p = Path(repo_root) / path
    if not p.exists():
        return {"entries": {}}
    return json.loads(p.read_text())


def write_hlo_baseline(audits: Dict[str, EntryAudit],
                       path: str = DEFAULT_HLO_BASELINE, *,
                       repo_root: str = ".") -> None:
    """Rewrite the census/memory baseline: audited entries get fresh
    rows, entries NOT audited this run (``--entry`` filter, or a host
    without the multichip device count) keep their committed rows —
    a partial update must never silently delete the rest of the
    baseline.  Rows for entry points that no longer exist are the one
    thing dropped (that is the stale cleanup --update exists for)."""
    import jax

    from ..testing.entry_points import ENTRY_POINTS

    existing = load_hlo_baseline(path, repo_root=repo_root).get(
        "entries", {})
    rows = {name: row for name, row in existing.items()
            if name in ENTRY_POINTS}
    rows.update({name: a.baseline_row() for name, a in audits.items()})
    payload = {
        "_comment": [
            "Committed collective-census + peak-live-memory baseline",
            "for the registered entry points "
            "(apex_tpu/testing/entry_points.py).",
            "Regenerate with: python -m apex_tpu.analysis "
            "--update-hlo-baseline",
            "(CPU lowerings, 8 host-platform devices — the tools/"
            "ci.sh step 8 configuration).",
            "APX603/APX605 gate every entry against these rows at "
            "+/-10%.",
        ],
        "jax_version": jax.__version__,
        "entries": {name: rows[name] for name in sorted(rows)},
    }
    (Path(repo_root) / path).write_text(
        json.dumps(payload, indent=1, sort_keys=True) + "\n")


def _census_findings(name: str, audit: EntryAudit,
                     base_row: Optional[Dict[str, Any]]
                     ) -> List[Finding]:
    out: List[Finding] = []

    def emit(rule: str, symbol: str, message: str) -> None:
        out.append(Finding(path=f"<entry:{name}>", line=0, col=0,
                           rule=rule, severity="error",
                           message=f"[{name}] {message}",
                           symbol=symbol))

    if base_row is None:
        emit("APX603", "unbaselined",
             "entry point has no committed census row — run "
             "'python -m apex_tpu.analysis --update-hlo-baseline' and "
             "review the diff")
        return out
    census = audit.census()
    base_cens = base_row.get("collectives", {})
    for kind, row in sorted(census.items()):
        ops = [op for op in audit.collectives if op.kind == kind]
        where = "; ".join(
            f"{op.path}:{op.line} in {op.function}"
            f"{f' x{op.count}' if op.count > 1 else ''}"
            for op in ops[:4])
        b = base_cens.get(kind)
        if b is None:
            emit("APX603", f"{kind}.new",
                 f"NEW collective kind '{kind}': {row['count']} op(s), "
                 f"{row['bytes_per_step']} bytes/step — emitted at "
                 f"{where}")
            continue
        if row["count"] > b["count"]:
            emit("APX603", f"{kind}.count",
                 f"collective '{kind}' count grew "
                 f"{b['count']} -> {row['count']} — new op(s) at "
                 f"{where}")
        elif row["count"] < b["count"]:
            emit("APX603", f"{kind}.count",
                 f"collective '{kind}' count shrank "
                 f"{b['count']} -> {row['count']} — refresh the "
                 f"baseline (--update-hlo-baseline) so the gate "
                 f"tracks the improvement")
        hi = b["bytes_per_step"] * (1 + _GROWTH_TOL)
        lo = b["bytes_per_step"] * (1 - _GROWTH_TOL)
        if row["bytes_per_step"] > hi:
            emit("APX603", f"{kind}.bytes",
                 f"collective '{kind}' bytes/step grew >10%: "
                 f"{b['bytes_per_step']} -> {row['bytes_per_step']} — "
                 f"ops at {where}")
        elif row["bytes_per_step"] < lo:
            emit("APX603", f"{kind}.bytes",
                 f"collective '{kind}' bytes/step shrank >10% "
                 f"({b['bytes_per_step']} -> {row['bytes_per_step']}) "
                 f"— refresh the baseline to lock in the win")
    for kind in sorted(set(base_cens) - set(census)):
        emit("APX603", f"{kind}.gone",
             f"baselined collective kind '{kind}' no longer emitted — "
             f"refresh the baseline")
    base_peak = base_row.get("peak_live_bytes", 0)
    peak = audit.peak_live_bytes
    if peak > base_peak * (1 + _GROWTH_TOL):
        emit("APX605", "peak",
             f"peak-live-memory estimate grew >10%: {base_peak} -> "
             f"{peak} bytes")
    elif peak < base_peak * (1 - _GROWTH_TOL):
        emit("APX605", "peak",
             f"peak-live-memory estimate shrank >10% ({base_peak} -> "
             f"{peak} bytes) — refresh the baseline to lock in the "
             f"win")
    return out


def run_hlo_check(repo_root: str = ".", *,
                  baseline: str = DEFAULT_HLO_BASELINE,
                  findings_baseline: str = DEFAULT_HLO_FINDINGS,
                  names: Optional[Sequence[str]] = None
                  ) -> Tuple[List[Finding], List[str],
                             Dict[str, EntryAudit]]:
    """The ``--check-hlo`` engine.

    Returns ``(unsuppressed findings, stale suppression keys, audits)``
    — non-empty findings or stale keys mean a red build.  Entries the
    host cannot build (device-count gate) are skipped without touching
    their baseline rows, so a single-device invocation never reports
    the multichip rows stale.
    """
    from ..testing.entry_points import ENTRY_POINTS

    audits = audit_entry_points(repo_root, names=names)
    base = load_hlo_baseline(baseline, repo_root=repo_root)
    entries = base.get("entries", {})
    findings: List[Finding] = []
    for name, audit in sorted(audits.items()):
        findings.extend(audit.findings)
        findings.extend(_census_findings(name, audit,
                                         entries.get(name)))
    # baseline rows for entry points that no longer exist at all are
    # stale (rows for merely-unbuildable entries are fine)
    for name in sorted(set(entries) - set(ENTRY_POINTS)):
        findings.append(Finding(
            path=f"<entry:{name}>", line=0, col=0, rule="APX603",
            severity="error",
            message=f"[{name}] baseline row for an entry point that "
                    f"is no longer registered — delete it "
                    f"(--update-hlo-baseline)",
            symbol="stale-entry"))
    suppress = load_baseline(findings_baseline, repo_root=repo_root)
    live_keys = {f.key for f in findings}
    unsuppressed = [f for f in findings if f.key not in suppress]
    # a suppression is stale only when the entry it belongs to was
    # actually audited this run: a device-gated or --entry-filtered
    # invocation must not demand deletion of a line the full CI run
    # still needs (mirror of the baseline-row rule above)
    full_run = set(audits) == set(ENTRY_POINTS)

    def checked_this_run(key: str) -> bool:
        owner = _suppression_entry(key)
        if owner in audits:
            return True
        # unattributable keys, and keys for entries that no longer
        # exist, can only be judged by a full run
        return full_run and (owner is None or owner not in ENTRY_POINTS)

    stale = [k for k in suppress
             if k not in live_keys and checked_this_run(k)]
    return unsuppressed, stale, audits


def _suppression_entry(key: str) -> Optional[str]:
    """Best-effort owning entry point of a suppression key.  APX601/
    603/605 keys carry it in the ``<entry:NAME>`` pseudo-path;
    APX602/604 keys carry it as the symbol's first dotted component
    (``{entry}.{function}.{detail}``)."""
    path = key.split(":", 1)[0]
    if path.startswith("<entry:") and path.endswith(">"):
        return path[len("<entry:"):-1]
    sym = key.rsplit(":", 1)[-1]
    if "." in sym:
        return sym.split(".", 1)[0]
    return None
