"""AST trace-safety linter for the apex_tpu package.

JAX correctness hazards are invisible to generic linters because they
depend on *where* code runs: ``float(x)`` is fine on the host and a
silent device sync (or a hard ``TracerConversionError``) inside a
``jax.jit``.  This linter reconstructs the traced regions statically —
functions reaching ``jax.jit`` / ``pl.pallas_call`` / ``shard_map`` /
``lax.scan`` bodies, by decorator, call-site reference, or lexical
nesting — and applies trace-discipline rules inside them, plus
package-wide hygiene rules everywhere.

Rules (docs/api/analysis.md for the long-form table):

==========  ================================================================
APX101      host-sync call on a traced value inside a traced region
            (``float()``/``int()``/``bool()``/``.item()``/``.tolist()``/
            ``np.asarray``/``np.array``/``jax.device_get``)
APX102      Python truthiness on a traced value in a boolean
            statement context (``if``/``while``/``assert`` tests,
            including ``not``/``and``/``or`` within them)
APX103      environment read inside a traced region (recompile bomb:
            the flag is baked into the trace, not re-read)
APX201      bare ``except:``
APX202      broad ``except Exception/BaseException`` that neither
            re-raises nor logs through a logger
APX301      ``os.environ``/``os.getenv`` read outside the flag registry
            (route ``APEX_TPU_*`` flags through
            :mod:`apex_tpu.analysis.flags`)
APX501      direct ``jax.shard_map`` / ``jax.experimental.shard_map``
            use (route through :mod:`apex_tpu._compat` — rule exists
            because old jax spells it differently)
APX900      malformed suppression comment (missing ``-- reason``)
==========  ================================================================

Suppression: append ``# apex-lint: disable=APX202 -- <reason>`` to the
offending line (the reason is mandatory), or record the finding's
stable key in the committed baseline file
(``tools/analysis_baseline.txt``) with a trailing ``# reason``.  CI
runs ``python -m apex_tpu.analysis --check`` self-hosted: zero
unsuppressed findings or the build is red.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = ["Finding", "lint_paths", "lint_source", "load_baseline",
           "run_check", "DEFAULT_BASELINE"]

DEFAULT_BASELINE = "tools/analysis_baseline.txt"

# Names that put a callee's body inside a trace when a local function is
# passed to them (first positional argument or ``body_fun``-style).
_TRACE_ENTRY_CALLS = {
    "jit", "pjit", "pallas_call", "shard_map", "scan", "while_loop",
    "fori_loop", "cond", "switch", "checkpoint", "remat", "vmap",
    "pmap", "grad", "value_and_grad", "custom_vjp", "custom_jvp",
    "named_call", "eval_shape", "make_jaxpr",
}
# Decorators that make the decorated function body traced.
_TRACE_DECORATORS = {
    "jit", "pjit", "checkpoint", "remat", "vmap", "pmap",
    "custom_vjp", "custom_jvp",
}
# Attribute reads that yield static (host) values even on tracers.
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize", "aval",
                 "sharding", "at"}
# Callables through which taint propagates (module aliases).
_ARRAY_MODULES = {"jnp", "lax", "np"}  # np only via asarray-class sinks
_HOST_SYNC_BUILTINS = {"float", "int", "bool", "complex"}
_HOST_SYNC_METHODS = {"item", "tolist", "__float__", "__int__"}
_NP_SYNC_FUNCS = {"asarray", "array", "float32", "float64", "int32",
                  "int64", "asanyarray"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}

_SUPPRESS_RE = re.compile(
    r"#\s*apex-lint:\s*disable=([A-Z0-9, ]+?)(?:\s*--\s*(.*?))?\s*$")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One structured lint finding."""

    path: str          # repo-relative, forward slashes
    line: int
    col: int
    rule: str          # e.g. 'APX101'
    severity: str      # 'error' | 'warning'
    message: str
    symbol: str        # stable anchor (function / env var / snippet)

    @property
    def key(self) -> str:
        """Line-number-free identity used by the baseline file."""
        return f"{self.path}:{self.rule}:{self.symbol}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}")


# ---------------------------------------------------------------------------
# suppression comments
# ---------------------------------------------------------------------------

def _suppressions(source: str, path: str) -> Tuple[Dict[int, Set[str]],
                                                   List[Finding]]:
    """Map line -> suppressed rule ids; malformed suppressions become
    APX900 findings so a reason can never be silently omitted."""
    by_line: Dict[int, Set[str]] = {}
    bad: List[Finding] = []
    for i, text in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        reason = (m.group(2) or "").strip()
        if not reason:
            bad.append(Finding(
                path=path, line=i, col=text.index("#"), rule="APX900",
                severity="error",
                message="suppression without a reason (write "
                        "'# apex-lint: disable=<RULE> -- why')",
                symbol=f"L{i}"))
            continue
        by_line[i] = rules
    return by_line, bad


# ---------------------------------------------------------------------------
# traced-region discovery
# ---------------------------------------------------------------------------

def _tail_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' -> 'scan'; 'jit' -> 'jit'."""
    while isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _decorator_traced(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if _tail_name(target) in _TRACE_DECORATORS:
            return True
        # functools.partial(jax.jit, ...) as decorator
        if isinstance(dec, ast.Call) and _tail_name(dec.func) == "partial":
            for a in dec.args:
                if _tail_name(a) in _TRACE_DECORATORS:
                    return True
    return False


class _TraceRegions(ast.NodeVisitor):
    """Collect function defs plus the set traced by decorator or by
    being passed (as a ``Name``) into a trace-entry call anywhere in
    the module."""

    def __init__(self) -> None:
        self.functions: List[ast.AST] = []
        # name -> (static positional prefix, static kwarg names): args
        # bound by functools.partial are PYTHON values at trace time,
        # not tracers (the pallas-kernel config-prefix idiom)
        self.traced_names: Dict[str, Tuple[int, Set[str]]] = {}
        self.decorated: List[ast.AST] = []

    def _record(self, name: str, prefix: int, kwargs: Set[str]) -> None:
        old = self.traced_names.get(name)
        if old is not None:
            # multiple references: a positional is static only if bound
            # at EVERY site (min); a keyword bound by partial anywhere
            # is config — sites that omit it use the static default
            prefix = min(prefix, old[0])
            kwargs = kwargs | old[1]
        self.traced_names[name] = (prefix, kwargs)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.functions.append(node)
        if _decorator_traced(node):
            self.decorated.append(node)
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Call(self, node: ast.Call) -> None:
        callee = _tail_name(node.func)
        if callee in _TRACE_ENTRY_CALLS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name):
                    self._record(arg.id, 0, set())
                if (isinstance(arg, ast.Call)
                        and _tail_name(arg.func) == "partial"):
                    fn_args = arg.args
                    if fn_args and isinstance(fn_args[0], ast.Name):
                        self._record(
                            fn_args[0].id, len(fn_args) - 1,
                            {kw.arg for kw in arg.keywords if kw.arg})
        self.generic_visit(node)


def _traced_functions(
        tree: ast.AST) -> List[Tuple[ast.AST, int, Set[str]]]:
    """(function, static positional prefix, static kwarg names) for
    every function def whose body is traced, including functions
    lexically nested inside traced ones."""
    finder = _TraceRegions()
    finder.visit(tree)
    traced: List[Tuple[ast.AST, int, Set[str]]] = [
        (f, 0, set()) for f in finder.decorated]
    traced += [(f, *finder.traced_names[f.name])
               for f in finder.functions
               if getattr(f, "name", None) in finder.traced_names
               and f not in finder.decorated]
    # lexical nesting: children of traced functions are traced
    seen = {id(f) for f, _, _ in traced}
    frontier = [f for f, _, _ in traced]
    while frontier:
        fn = frontier.pop()
        for node in ast.walk(fn):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda))
                    and node is not fn and id(node) not in seen):
                seen.add(id(node))
                traced.append((node, 0, set()))
                frontier.append(node)
    return traced


# ---------------------------------------------------------------------------
# taint walk inside one traced function
# ---------------------------------------------------------------------------

class _Taint:
    """Conservative value-taint: parameters of a traced function are
    traced values; taint flows through arithmetic, subscripts, jnp/lax
    calls and plain assignments.  ``.shape``-class attributes and
    non-array calls launder it (static at trace time)."""

    def __init__(self, fn: ast.AST, static_prefix: int = 0,
                 static_kwargs: Optional[Set[str]] = None) -> None:
        self.tainted: Set[str] = set()
        static_kwargs = static_kwargs or set()
        args = getattr(fn, "args", None)
        if args is not None:
            positional = list(args.posonlyargs) + list(args.args)
            for i, a in enumerate(positional):
                if i < static_prefix or a.arg in static_kwargs:
                    continue  # functools.partial-bound: static config
                if a.arg not in ("self", "cls"):
                    self.tainted.add(a.arg)
            for a in args.kwonlyargs:
                if a.arg not in static_kwargs:
                    self.tainted.add(a.arg)
            if args.vararg:
                self.tainted.add(args.vararg.arg)
            if args.kwarg:
                self.tainted.add(args.kwarg.arg)

    def expr_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.expr_tainted(node.value)
        if isinstance(node, (ast.BinOp,)):
            return (self.expr_tainted(node.left)
                    or self.expr_tainted(node.right))
        if isinstance(node, ast.UnaryOp):
            return self.expr_tainted(node.operand)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False          # identity tests are static
            return (self.expr_tainted(node.left)
                    or any(self.expr_tainted(c) for c in node.comparators))
        if isinstance(node, ast.BoolOp):
            return any(self.expr_tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return (self.expr_tainted(node.body)
                    or self.expr_tainted(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.expr_tainted(node.value)
        if isinstance(node, ast.Call):
            # jnp./lax. results stay traced; anything else launders
            # (len(), isinstance(), int-shape helpers, user calls we
            # cannot see into — conservative against false positives).
            head = node.func
            root = head
            while isinstance(root, ast.Attribute):
                root = root.value
            if (isinstance(root, ast.Name)
                    and root.id in ("jnp", "lax")):
                return True
            return False
        return False

    def assign(self, node: ast.AST) -> None:
        targets: List[ast.AST] = []
        value = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None:
            return
        is_tainted = self.expr_tainted(value)
        for t in targets:
            for name in ast.walk(t):
                if isinstance(name, ast.Name):
                    if is_tainted:
                        self.tainted.add(name.id)
                    else:
                        self.tainted.discard(name.id)


def _is_env_read(node: ast.Call | ast.Attribute | ast.Subscript) -> bool:
    """os.environ[...] / os.environ.get(...) / os.getenv(...) /
    environ.get(...)."""
    def names(n: ast.AST) -> str:
        if isinstance(n, ast.Attribute):
            return names(n.value) + "." + n.attr
        if isinstance(n, ast.Name):
            return n.id
        return "?"

    if isinstance(node, ast.Call):
        dotted = names(node.func)
        return dotted.endswith("getenv") or ".environ.get" in dotted \
            or dotted == "environ.get"
    if isinstance(node, ast.Subscript):
        node = node.value
    dotted = names(node)
    return dotted.endswith(".environ") or dotted == "environ"


def _env_symbol(node: ast.AST) -> str:
    """Best-effort env var name for the finding key."""
    target = None
    if isinstance(node, ast.Call) and node.args:
        target = node.args[0]
    elif isinstance(node, ast.Subscript):
        target = node.slice
    if isinstance(target, ast.Constant) and isinstance(target.value, str):
        return target.value
    return "dynamic"


# ---------------------------------------------------------------------------
# the lint pass
# ---------------------------------------------------------------------------

def lint_source(source: str, path: str, *,
                flags_module: bool = False) -> List[Finding]:
    """Lint one file's source.  ``flags_module`` marks the registry
    itself (its env read is the one legal one)."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(path=path, line=e.lineno or 0, col=e.offset or 0,
                        rule="APX000", severity="error",
                        message=f"syntax error: {e.msg}", symbol="syntax")]
    suppressed, bad_suppressions = _suppressions(source, path)
    findings.extend(bad_suppressions)

    def emit(node: ast.AST, rule: str, message: str, symbol: str,
             severity: str = "error") -> None:
        line = getattr(node, "lineno", 0)
        for probe in (line, getattr(node, "end_lineno", line)):
            if rule in suppressed.get(probe, ()):  # inline suppression
                return
        findings.append(Finding(path=path, line=line,
                                col=getattr(node, "col_offset", 0),
                                rule=rule, severity=severity,
                                message=message, symbol=symbol))

    # --- traced-region rules ---------------------------------------------
    traced_env_nodes: Set[int] = set()  # APX103 sites: skip dup APX301

    def fname(fn: ast.AST) -> str:
        return getattr(fn, "name", "<lambda>")

    for fn, static_prefix, static_kwargs in _traced_functions(tree):
        taint = _Taint(fn, static_prefix, static_kwargs)
        # two passes: assignments first (simple flow), then checks —
        # good enough for the straight-line bodies kernels actually have
        for node in ast.walk(fn):
            taint.assign(node)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = node.func
                if (isinstance(callee, ast.Name)
                        and callee.id in _HOST_SYNC_BUILTINS
                        and node.args
                        and taint.expr_tainted(node.args[0])):
                    emit(node, "APX101",
                         f"{callee.id}() on a traced value inside "
                         f"traced function '{fname(fn)}' forces a host "
                         f"sync / TracerConversionError",
                         f"{fname(fn)}.{callee.id}")
                if (isinstance(callee, ast.Attribute)
                        and callee.attr in _HOST_SYNC_METHODS
                        and taint.expr_tainted(callee.value)):
                    emit(node, "APX101",
                         f".{callee.attr}() on a traced value inside "
                         f"traced function '{fname(fn)}'",
                         f"{fname(fn)}.{callee.attr}")
                if (isinstance(callee, ast.Attribute)
                        and callee.attr in _NP_SYNC_FUNCS
                        and isinstance(callee.value, ast.Name)
                        and callee.value.id in ("np", "numpy")
                        and node.args
                        and taint.expr_tainted(node.args[0])):
                    emit(node, "APX101",
                         f"np.{callee.attr}() on a traced value inside "
                         f"traced function '{fname(fn)}' materializes "
                         f"on host",
                         f"{fname(fn)}.np.{callee.attr}")
                if (isinstance(callee, ast.Attribute)
                        and callee.attr == "device_get"):
                    emit(node, "APX101",
                         f"jax.device_get inside traced function "
                         f"'{fname(fn)}'", f"{fname(fn)}.device_get")
                if _is_env_read(node):
                    traced_env_nodes.add(id(node))
                    emit(node, "APX103",
                         f"environment read inside traced function "
                         f"'{fname(fn)}' is baked into the trace "
                         f"(recompile bomb / stale flag)",
                         f"{fname(fn)}.{_env_symbol(node)}")
            if isinstance(node, ast.Subscript) and _is_env_read(node):
                # environ.get(...) is handled above as a Call
                traced_env_nodes.add(id(node))
                emit(node, "APX103",
                     f"os.environ[...] inside traced function "
                     f"'{fname(fn)}'",
                     f"{fname(fn)}.{_env_symbol(node)}")
            if isinstance(node, (ast.If, ast.While)):
                if taint.expr_tainted(node.test):
                    emit(node, "APX102",
                         f"Python branch on a traced value in "
                         f"'{fname(fn)}' — use jnp.where/lax.cond",
                         f"{fname(fn)}.branch")
            if isinstance(node, ast.Assert):
                if taint.expr_tainted(node.test):
                    emit(node, "APX102",
                         f"assert on a traced value in '{fname(fn)}' "
                         f"— tracers have no truth value",
                         f"{fname(fn)}.assert")

    # --- whole-file rules --------------------------------------------------
    def _catches_broad(handler_type: ast.AST) -> bool:
        if isinstance(handler_type, ast.Tuple):
            return any(_catches_broad(e) for e in handler_type.elts)
        return _tail_name(handler_type) in ("Exception", "BaseException")

    for node in ast.walk(tree):
        if isinstance(node, ast.ExceptHandler):
            if node.type is None:
                emit(node, "APX201",
                     "bare 'except:' swallows KeyboardInterrupt and "
                     "SystemExit — name the exception types",
                     f"bare_except.L{node.lineno}")
            elif _catches_broad(node.type):
                body_reraises = any(
                    isinstance(s, ast.Raise) for s in node.body)
                body_logs = any(
                    isinstance(c, ast.Call)
                    and isinstance(c.func, ast.Attribute)
                    and c.func.attr in _LOG_METHODS
                    for s in node.body for c in ast.walk(s))
                if not body_reraises and not body_logs:
                    emit(node, "APX202",
                         f"broad 'except "
                         f"{_tail_name(node.type) or 'Exception (in tuple)'}"
                         f"' that "
                         f"neither re-raises nor logs — narrow it, log "
                         f"via utils.log_util, or suppress with a "
                         f"reason",
                         f"broad_except.L{node.lineno}", severity="error")
        if isinstance(node, ast.Call) and _is_env_read(node) \
                and not flags_module \
                and id(node) not in traced_env_nodes:
            emit(node, "APX301",
                 "environment read outside the flag registry — declare "
                 "the flag in apex_tpu/analysis/flags.py and use the "
                 "typed accessors",
                 _env_symbol(node))
        if isinstance(node, ast.Subscript) and _is_env_read(node) \
                and not flags_module \
                and id(node) not in traced_env_nodes:
            emit(node, "APX301",
                 "os.environ[...] outside the flag registry",
                 _env_symbol(node))
        if path.endswith("_compat.py"):
            continue  # the shim is the one legal shard_map resolver
        if isinstance(node, ast.Attribute) and node.attr == "shard_map":
            root = node.value
            dotted = []
            cur: ast.AST = node
            while isinstance(cur, ast.Attribute):
                dotted.append(cur.attr)
                cur = cur.value
            if isinstance(cur, ast.Name) and cur.id == "jax":
                emit(node, "APX501",
                     "direct jax.shard_map use — import it from "
                     "apex_tpu._compat (old jax spells it "
                     "jax.experimental.shard_map with check_rep)",
                     "jax.shard_map")
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod in ("jax", "jax.experimental",
                       "jax.experimental.shard_map") and any(
                    a.name == "shard_map" for a in node.names):
                emit(node, "APX501",
                     f"import shard_map from {mod} — use "
                     f"apex_tpu._compat.shard_map",
                     f"import.{mod}")
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.experimental.shard_map":
                    emit(node, "APX501",
                         "import jax.experimental.shard_map — use "
                         "apex_tpu._compat.shard_map",
                         "import.jax.experimental.shard_map")
    return findings


# ---------------------------------------------------------------------------
# repo walk + baseline
# ---------------------------------------------------------------------------

def _iter_py(root: Path) -> Iterable[Path]:
    for p in sorted(root.rglob("*.py")):
        if "__pycache__" in p.parts:
            continue
        yield p


# Trees outside the package that must stay routed through _compat
# (APX501 only — tests/benches legitimately read env vars and catch
# broadly): the old-jax tier-1 failures this repo cleared come back
# the moment a test reintroduces a bare jax.shard_map.
COMPAT_SCAN_PATHS = ("tests", "examples", "bench.py",
                     "__graft_entry__.py")


def lint_paths(package_root: str = "apex_tpu", *,
               repo_root: str = ".",
               paths: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every .py under ``package_root`` (repo-relative), plus the
    compat-routing rule (APX501) over :data:`COMPAT_SCAN_PATHS`.

    ``paths`` restricts the walk to the named repo-relative files —
    the changed-file pre-commit fast path (``--check --paths ...``,
    sub-second where the full walk costs seconds).  Each named file
    gets exactly the rule set the full walk would give it: full lint
    under ``package_root``, APX501-only under
    :data:`COMPAT_SCAN_PATHS`, nothing elsewhere (a data file or doc
    is not lint surface, not an error).  Missing files are skipped —
    a deleted file carries no findings, and pre-commit hands deletions
    over too."""
    repo = Path(repo_root).resolve()
    findings: List[Finding] = []

    def _in_package(rel: str) -> bool:
        return rel == package_root or rel.startswith(package_root + "/")

    def _compat_scope(rel: str) -> bool:
        return any(rel == entry or rel.startswith(entry + "/")
                   for entry in COMPAT_SCAN_PATHS)

    if paths is not None:
        for name in paths:
            p = repo / name
            if not p.exists() or p.suffix != ".py":
                continue
            try:
                rel = p.resolve().relative_to(repo).as_posix()
            except ValueError:
                continue  # outside the repo: not lint surface
            if _in_package(rel):
                findings.extend(lint_source(
                    p.read_text(), rel,
                    flags_module=rel.endswith("analysis/flags.py")))
            elif _compat_scope(rel):
                findings.extend(
                    f for f in lint_source(p.read_text(), rel)
                    if f.rule == "APX501")
        return findings

    for p in _iter_py(repo / package_root):
        rel = p.relative_to(repo).as_posix()
        is_flags = rel.endswith("analysis/flags.py")
        findings.extend(lint_source(p.read_text(), rel,
                                    flags_module=is_flags))
    for entry in COMPAT_SCAN_PATHS:
        target = repo / entry
        files = [target] if target.suffix == ".py" else             list(_iter_py(target)) if target.exists() else []
        for p in files:
            if not p.exists():
                continue
            rel = p.relative_to(repo).as_posix()
            findings.extend(
                f for f in lint_source(p.read_text(), rel)
                if f.rule == "APX501")
    return findings


def load_baseline(path: str = DEFAULT_BASELINE, *,
                  repo_root: str = ".") -> Dict[str, str]:
    """Baseline file -> {finding.key: reason}.  Lines:
    ``path:RULE:symbol  # reason``; '#'-prefixed lines are comments."""
    p = Path(repo_root) / path
    if not p.exists():
        return {}
    out: Dict[str, str] = {}
    for raw in p.read_text().splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        key, _, reason = line.partition("#")
        out[key.strip()] = reason.strip()
    return out


_BASELINE_HEADER = (
    "# apex_tpu.analysis baseline — pre-existing findings accepted",
    "# with a reason.  New findings do NOT belong here by default:",
    "# fix them or suppress inline with '# apex-lint: disable=...'.",
    "# Format: <path>:<rule>:<symbol>  # <reason>",
)


def write_baseline(findings: Sequence[Finding],
                   path: str = DEFAULT_BASELINE, *,
                   repo_root: str = ".",
                   header: Sequence[str] = _BASELINE_HEADER) -> None:
    """Serialize a baseline file (one implementation — the
    concurrency auditor delegates here with its own header/path), with
    curated reasons for already-listed keys preserved."""
    p = Path(repo_root) / path
    existing = load_baseline(path, repo_root=repo_root)
    lines = list(header)
    for key in sorted(set(fi.key for fi in findings)):
        reason = existing.get(key) or "accepted pre-existing finding"
        lines.append(f"{key}  # {reason}")
    p.write_text("\n".join(lines) + "\n")


def run_check(package_root: str = "apex_tpu", *,
              baseline: str = DEFAULT_BASELINE,
              repo_root: str = ".",
              paths: Optional[Sequence[str]] = None
              ) -> Tuple[List[Finding], List[str]]:
    """(unsuppressed findings, stale baseline keys).

    With ``paths`` (the pre-commit fast path) only those files are
    linted; the kernel-parity audit (whole-repo by construction) and
    baseline-staleness judgment (only a full walk can prove a
    suppression dead) are skipped — full CI keeps both.
    """
    findings = lint_paths(package_root, repo_root=repo_root,
                          paths=paths)
    if paths is None:
        from .parity import audit_kernel_parity

        findings.extend(audit_kernel_parity(repo_root=repo_root))
    base = load_baseline(baseline, repo_root=repo_root)
    live_keys = {f.key for f in findings}
    unsuppressed = [f for f in findings if f.key not in base]
    stale = ([] if paths is not None
             else [k for k in base if k not in live_keys])
    return unsuppressed, stale
