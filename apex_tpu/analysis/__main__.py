"""CLI for apex_tpu.analysis — the repo's self-hosted static pass.

    python -m apex_tpu.analysis --check          # lint + parity vs baseline
    python -m apex_tpu.analysis --check --paths a.py b.py   # changed-file
    python -m apex_tpu.analysis --check-hlo      # compiled-graph audit
    python -m apex_tpu.analysis --check-sharding # SPMD plan audit
    python -m apex_tpu.analysis --check-concurrency  # APX8xx lock/signal audit
    python -m apex_tpu.analysis --check-protocol # APX9xx wire-protocol audit
    python -m apex_tpu.analysis --update-baseline
    python -m apex_tpu.analysis --update-hlo-baseline
    python -m apex_tpu.analysis --update-sharding-baseline
    python -m apex_tpu.analysis --flag-table     # print the env-flag table
    python -m apex_tpu.analysis --rule-table     # print the APX rule table
    python -m apex_tpu.analysis --check-docs     # docs table drift guard
    python -m apex_tpu.analysis --write-docs     # regenerate the docs tables
    python -m apex_tpu.analysis --smoke          # sanitizer smoke (GPT step)

Exit status: 0 = clean, 1 = findings / drift / recompiles.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path

from .flags import render_flag_table
from .linter import DEFAULT_BASELINE, run_check, write_baseline, lint_paths
from .rules import render_rule_table

# Every generated docs table: (file, begin marker, end marker, render).
# --write-docs regenerates all of them in place; --check-docs fails on
# any drift.
_GEN = "(generated: python -m apex_tpu.analysis --write-docs)"
DOCS_TABLES = (
    ("docs/api/ops.md",
     f"<!-- apex-flag-table:begin {_GEN} -->",
     "<!-- apex-flag-table:end -->",
     render_flag_table),
    ("docs/api/analysis.md",
     f"<!-- apex-rule-table:begin {_GEN} -->",
     "<!-- apex-rule-table:end -->",
     render_rule_table),
)


def _docs_block(repo_root: str, doc: str, begin: str,
                end: str) -> tuple[Path, str, int, int]:
    p = Path(repo_root) / doc
    text = p.read_text()
    try:
        a = text.index(begin) + len(begin)
        b = text.index(end)
    except ValueError:
        raise SystemExit(
            f"{doc} is missing the table markers "
            f"({begin!r} ... {end!r})")
    return p, text, a, b


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--check", action="store_true",
                    help="lint apex_tpu + kernel-parity audit against "
                         "the baseline (default action)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to accept all current "
                         "findings")
    ap.add_argument("--check-hlo", action="store_true",
                    help="compiled-graph audit: lower every registered "
                         "entry point and check donation, dtype "
                         "promotion, the collective census, host "
                         "transfers, and peak live memory against "
                         "tools/hlo_baseline.json")
    ap.add_argument("--update-hlo-baseline", action="store_true",
                    help="rewrite tools/hlo_baseline.json from the "
                         "current lowerings (censuses + memory only; "
                         "APX601/602/604 findings must still be fixed "
                         "or suppressed)")
    ap.add_argument("--check-sharding", action="store_true",
                    help="SPMD sharding audit: compile every "
                         "plan-carrying entry point under its mesh "
                         "and check declared-vs-propagated shardings, "
                         "reshard chains, collective budgets, overlap "
                         "preconditions, and per-device memory "
                         "against tools/sharding_baseline.json "
                         "(APX701-705; needs the 8-device "
                         "host-platform mesh)")
    ap.add_argument("--check-concurrency", action="store_true",
                    help="host-concurrency audit (APX801-805): lock "
                         "discipline via guard inference, "
                         "lock-acquisition-order cycles aggregated "
                         "across modules, flag-only signal handlers, "
                         "blocking calls under locks, and thread-"
                         "target jit dispatch outside a device pin, "
                         "against tools/concurrency_baseline.txt "
                         "(committed empty; stale entries fail)")
    ap.add_argument("--update-concurrency-baseline",
                    action="store_true",
                    help="rewrite tools/concurrency_baseline.txt to "
                         "accept all current APX8xx findings (the "
                         "repo commits it EMPTY: fix, don't "
                         "baseline)")
    ap.add_argument("--check-protocol", action="store_true",
                    help="wire-protocol + resource-lifecycle audit "
                         "(APX901-905): serving/ + resilience/ "
                         "checked against the ProtocolSpec registry "
                         "in serving/control_plane.py — deadline "
                         "discipline, op/header-field drift matched "
                         "across parent and child, socket/subprocess/"
                         "tempdir lifecycle, retry-safety — against "
                         "tools/protocol_baseline.txt (committed "
                         "empty; stale entries fail)")
    ap.add_argument("--update-protocol-baseline", action="store_true",
                    help="rewrite tools/protocol_baseline.txt to "
                         "accept all current APX9xx findings (the "
                         "repo commits it EMPTY: fix, don't "
                         "baseline)")
    ap.add_argument("--update-sharding-baseline", action="store_true",
                    help="rewrite tools/sharding_baseline.json "
                         "(plans + per-device memory + censuses) from "
                         "the current compilations; APX701-703 "
                         "findings must still be fixed or suppressed")
    ap.add_argument("--entry", action="append", default=None,
                    help="restrict --check-hlo/--check-sharding/"
                         "--update-*-baseline to this entry point "
                         "(repeatable)")
    ap.add_argument("--paths", nargs="+", default=None, metavar="FILE",
                    help="with --check: lint ONLY these repo-relative "
                         "files (the changed-file pre-commit fast "
                         "path; skips the kernel-parity audit and "
                         "baseline-staleness judgment — full CI keeps "
                         "the full walk)")
    ap.add_argument("--flag-table", action="store_true",
                    help="print the generated env-flag markdown table")
    ap.add_argument("--rule-table", action="store_true",
                    help="print the generated APX rule markdown table")
    ap.add_argument("--check-docs", action="store_true",
                    help="fail if any generated docs table drifted "
                         "from its registry")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the docs tables in place")
    ap.add_argument("--smoke", action="store_true",
                    help="run the sanitizer smoke: the standalone-GPT "
                         "step must compile exactly once after warmup")
    ap.add_argument("--scan-steps", type=int, default=0, metavar="K",
                    help="with --smoke: drive the batched-step scan "
                         "driver (K steps per jit call) instead of "
                         "the per-step loop — one compile for the "
                         "whole N-step run")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline path (default {DEFAULT_BASELINE})")
    ap.add_argument("--root", default=".",
                    help="repo root to lint from (default .)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON lines")
    args = ap.parse_args(argv)

    if args.flag_table:
        print(render_flag_table())
        return 0

    if args.rule_table:
        print(render_rule_table())
        return 0

    if args.check_docs or args.write_docs:
        rc = 0
        for doc, begin, end, render in DOCS_TABLES:
            p, text, a, b = _docs_block(args.root, doc, begin, end)
            want = "\n" + render() + "\n"
            have = text[a:b]
            if args.write_docs:
                if have != want:
                    p.write_text(text[:a] + want + text[b:])
                    print(f"[analysis] {doc} table updated")
                else:
                    print(f"[analysis] {doc} table already current")
            elif have != want:
                print(f"[analysis] FAIL: {doc} table drifted from the "
                      f"registry — run 'python -m apex_tpu.analysis "
                      f"--write-docs'", file=sys.stderr)
                rc = 1
            else:
                print(f"[analysis] {doc} table matches the registry")
        return rc

    if args.check_hlo or args.update_hlo_baseline:
        from ..testing.entry_points import ENTRY_POINTS
        from .hlo import (audit_entry_points, run_hlo_check,
                          write_hlo_baseline)

        if args.entry:
            # a typo'd name must not produce a do-nothing audit that
            # exits 0 claiming "hlo clean" (same guard bench.py gives
            # --sections)
            unknown = sorted(set(args.entry) - set(ENTRY_POINTS))
            if unknown:
                ap.error(f"unknown entry point(s) {unknown}; "
                         f"registered: {sorted(ENTRY_POINTS)}")
        if args.update_hlo_baseline:
            audits = audit_entry_points(args.root, names=args.entry)
            leftover = [f for a in audits.values() for f in a.findings]
            write_hlo_baseline(audits, repo_root=args.root)
            print(f"[analysis] hlo baseline rewritten: "
                  f"{len(audits)} entry point(s)")
            for f in leftover:
                print(f"[analysis] note: unbaselined finding remains "
                      f"(fix or suppress): {f.render()}",
                      file=sys.stderr)
            return 0
        unsuppressed, stale, audits = run_hlo_check(args.root,
                                                    names=args.entry)
        for f in sorted(unsuppressed, key=lambda x: (x.path, x.line)):
            if args.json:
                print(json.dumps(dataclasses.asdict(f)))
            else:
                print(f.render())
        for k in sorted(stale):
            print(f"[analysis] stale hlo suppression (finding no "
                  f"longer fires — delete the line): {k}",
                  file=sys.stderr)
        if unsuppressed or stale:
            print(f"[analysis] FAIL: {len(unsuppressed)} unsuppressed "
                  f"hlo finding(s), {len(stale)} stale suppression(s)",
                  file=sys.stderr)
            return 1
        ncoll = sum(len(a.collectives) for a in audits.values())
        print(f"[analysis] hlo clean: {len(audits)} entry point(s) "
              f"audited, {ncoll} collective op(s) match the census, "
              f"0 unsuppressed findings")
        return 0

    if args.check_sharding or args.update_sharding_baseline:
        from ..testing.entry_points import ENTRY_POINTS
        from .sharding import (audit_sharding, run_sharding_check,
                               write_sharding_baseline)

        if args.entry:
            unknown = sorted(set(args.entry) - set(ENTRY_POINTS))
            if unknown:
                ap.error(f"unknown entry point(s) {unknown}; "
                         f"registered: {sorted(ENTRY_POINTS)}")
        if args.update_sharding_baseline:
            audits = audit_sharding(args.root, names=args.entry)
            write_sharding_baseline(audits, repo_root=args.root)
            print(f"[analysis] sharding baseline rewritten: "
                  f"{len(audits)} planned entry point(s)")
            leftover = [f for a in audits.values() for f in a.findings]
            for f in leftover:
                print(f"[analysis] note: unbaselined finding remains "
                      f"(fix or suppress): {f.render()}",
                      file=sys.stderr)
            return 0
        unsuppressed, advisories, stale, audits = run_sharding_check(
            args.root, names=args.entry)
        for f in sorted(advisories, key=lambda x: (x.path, x.line)):
            # APX704 is advisory by design: printed, never red
            print(f.render() if not args.json
                  else json.dumps(dataclasses.asdict(f)))
        for f in sorted(unsuppressed, key=lambda x: (x.path, x.line)):
            if args.json:
                print(json.dumps(dataclasses.asdict(f)))
            else:
                print(f.render())
        for k in sorted(stale):
            print(f"[analysis] stale sharding suppression (finding no "
                  f"longer fires — delete the line): {k}",
                  file=sys.stderr)
        if unsuppressed or stale:
            print(f"[analysis] FAIL: {len(unsuppressed)} unsuppressed "
                  f"sharding finding(s), {len(stale)} stale "
                  f"suppression(s)", file=sys.stderr)
            return 1
        ncoll = sum(sum(a.census.values()) for a in audits.values())
        print(f"[analysis] sharding clean: {len(audits)} planned "
              f"entry point(s) audited under their meshes, {ncoll} "
              f"collective op(s) within budget, "
              f"{len(advisories)} advisory(ies), 0 unsuppressed "
              f"findings")
        return 0

    if args.check_concurrency or args.update_concurrency_baseline:
        from .concurrency import (DEFAULT_BASELINE as CONC_BASELINE,
                                  lint_concurrency_paths,
                                  run_concurrency_check,
                                  write_concurrency_baseline)

        if args.update_concurrency_baseline:
            findings, _ = lint_concurrency_paths(repo_root=args.root)
            write_concurrency_baseline(findings, repo_root=args.root)
            print(f"[analysis] concurrency baseline rewritten with "
                  f"{len(set(f.key for f in findings))} entries")
            return 0
        unsuppressed, stale, regions = run_concurrency_check(
            repo_root=args.root)
        for f in sorted(unsuppressed, key=lambda x: (x.path, x.line)):
            if args.json:
                print(json.dumps(dataclasses.asdict(f)))
            else:
                print(f.render())
        for k in sorted(stale):
            print(f"[analysis] stale concurrency baseline entry "
                  f"(finding no longer fires — delete the line): {k}",
                  file=sys.stderr)
        if unsuppressed or stale:
            print(f"[analysis] FAIL: {len(unsuppressed)} unsuppressed "
                  f"concurrency finding(s), {len(stale)} stale "
                  f"baseline entr(ies)", file=sys.stderr)
            return 1
        print(f"[analysis] concurrency clean: {regions} lock "
              f"region(s) audited, 0 unsuppressed APX8xx findings "
              f"(baseline {CONC_BASELINE} empty-current)")
        return 0

    if args.check_protocol or args.update_protocol_baseline:
        from .protocol import (DEFAULT_BASELINE as PROTO_BASELINE,
                               lint_protocol_paths,
                               run_protocol_check,
                               write_protocol_baseline)

        if args.update_protocol_baseline:
            findings, _ = lint_protocol_paths(repo_root=args.root)
            write_protocol_baseline(findings, repo_root=args.root)
            print(f"[analysis] protocol baseline rewritten with "
                  f"{len(set(f.key for f in findings))} entries")
            return 0
        unsuppressed, stale, n_ops = run_protocol_check(
            repo_root=args.root)
        for f in sorted(unsuppressed, key=lambda x: (x.path, x.line)):
            if args.json:
                print(json.dumps(dataclasses.asdict(f)))
            else:
                print(f.render())
        for k in sorted(stale):
            print(f"[analysis] stale protocol baseline entry "
                  f"(finding no longer fires — delete the line): {k}",
                  file=sys.stderr)
        if unsuppressed or stale:
            print(f"[analysis] FAIL: {len(unsuppressed)} unsuppressed "
                  f"protocol finding(s), {len(stale)} stale "
                  f"baseline entr(ies)", file=sys.stderr)
            return 1
        print(f"[analysis] protocol clean: {n_ops} declared op(s) "
              f"audited across serving/ + resilience/, 0 "
              f"unsuppressed APX9xx findings (baseline "
              f"{PROTO_BASELINE} empty-current)")
        return 0

    if args.smoke:
        from .sanitizer import sanitize_smoke

        n = sanitize_smoke(scan_steps=args.scan_steps)
        return 0 if n == 0 else 1

    if args.update_baseline:
        findings = lint_paths(repo_root=args.root)
        from .parity import audit_kernel_parity

        findings.extend(audit_kernel_parity(repo_root=args.root))
        write_baseline(findings, args.baseline, repo_root=args.root)
        print(f"[analysis] baseline rewritten with "
              f"{len(set(f.key for f in findings))} entries")
        return 0

    # default: --check
    unsuppressed, stale = run_check(baseline=args.baseline,
                                    repo_root=args.root,
                                    paths=args.paths)
    if args.paths:
        # the changed-file fast path also covers the APX9xx protocol
        # rules for any named file inside the protocol trees (full
        # CI keeps the dedicated --check-protocol walk with its own
        # staleness judgment)
        from .protocol import (DEFAULT_BASELINE as PROTO_BASELINE,
                               lint_protocol_paths)

        proto, _ = lint_protocol_paths(repo_root=args.root,
                                       paths=args.paths)
        from .linter import load_baseline as _load_baseline

        proto_base = _load_baseline(PROTO_BASELINE,
                                    repo_root=args.root)
        unsuppressed = list(unsuppressed) + [
            f for f in proto if f.key not in proto_base]
    for f in sorted(unsuppressed, key=lambda x: (x.path, x.line)):
        if args.json:
            print(json.dumps(dataclasses.asdict(f)))
        else:
            print(f.render())
    for k in sorted(stale):
        print(f"[analysis] stale baseline entry (finding no longer "
              f"fires — delete the line): {k}", file=sys.stderr)
    if unsuppressed or stale:
        print(f"[analysis] FAIL: {len(unsuppressed)} unsuppressed "
              f"finding(s), {len(stale)} stale baseline entr(ies)",
              file=sys.stderr)
        return 1
    print("[analysis] clean: 0 unsuppressed findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
