"""CLI for apex_tpu.analysis — the repo's self-hosted static pass.

    python -m apex_tpu.analysis --check          # lint + parity vs baseline
    python -m apex_tpu.analysis --update-baseline
    python -m apex_tpu.analysis --flag-table     # print the env-flag table
    python -m apex_tpu.analysis --check-docs     # docs flag-table drift guard
    python -m apex_tpu.analysis --write-docs     # regenerate the docs table
    python -m apex_tpu.analysis --smoke          # sanitizer smoke (GPT step)

Exit status: 0 = clean, 1 = findings / drift / recompiles.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import sys
from pathlib import Path

from .flags import render_flag_table
from .linter import DEFAULT_BASELINE, run_check, write_baseline, lint_paths

_TABLE_BEGIN = "<!-- apex-flag-table:begin (generated: python -m apex_tpu.analysis --write-docs) -->"
_TABLE_END = "<!-- apex-flag-table:end -->"
DOCS_WITH_TABLE = "docs/api/ops.md"


def _docs_block(repo_root: str) -> tuple[Path, str, int, int]:
    p = Path(repo_root) / DOCS_WITH_TABLE
    text = p.read_text()
    try:
        a = text.index(_TABLE_BEGIN) + len(_TABLE_BEGIN)
        b = text.index(_TABLE_END)
    except ValueError:
        raise SystemExit(
            f"{DOCS_WITH_TABLE} is missing the flag-table markers "
            f"({_TABLE_BEGIN!r} ... {_TABLE_END!r})")
    return p, text, a, b


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.analysis",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--check", action="store_true",
                    help="lint apex_tpu + kernel-parity audit against "
                         "the baseline (default action)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to accept all current "
                         "findings")
    ap.add_argument("--flag-table", action="store_true",
                    help="print the generated env-flag markdown table")
    ap.add_argument("--check-docs", action="store_true",
                    help="fail if the docs flag table drifted from the "
                         "registry")
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the docs flag table in place")
    ap.add_argument("--smoke", action="store_true",
                    help="run the sanitizer smoke: the standalone-GPT "
                         "step must compile exactly once after warmup")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help=f"baseline path (default {DEFAULT_BASELINE})")
    ap.add_argument("--root", default=".",
                    help="repo root to lint from (default .)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON lines")
    args = ap.parse_args(argv)

    if args.flag_table:
        print(render_flag_table())
        return 0

    if args.check_docs or args.write_docs:
        p, text, a, b = _docs_block(args.root)
        want = "\n" + render_flag_table() + "\n"
        have = text[a:b]
        if args.write_docs:
            if have != want:
                p.write_text(text[:a] + want + text[b:])
                print(f"[analysis] {DOCS_WITH_TABLE} flag table updated")
            else:
                print(f"[analysis] {DOCS_WITH_TABLE} flag table already "
                      f"current")
            return 0
        if have != want:
            print(f"[analysis] FAIL: {DOCS_WITH_TABLE} flag table "
                  f"drifted from the registry — run "
                  f"'python -m apex_tpu.analysis --write-docs'",
                  file=sys.stderr)
            return 1
        print(f"[analysis] {DOCS_WITH_TABLE} flag table matches the "
              f"registry")
        return 0

    if args.smoke:
        from .sanitizer import sanitize_smoke

        n = sanitize_smoke()
        return 0 if n == 0 else 1

    if args.update_baseline:
        findings = lint_paths(repo_root=args.root)
        from .parity import audit_kernel_parity

        findings.extend(audit_kernel_parity(repo_root=args.root))
        write_baseline(findings, args.baseline, repo_root=args.root)
        print(f"[analysis] baseline rewritten with "
              f"{len(set(f.key for f in findings))} entries")
        return 0

    # default: --check
    unsuppressed, stale = run_check(baseline=args.baseline,
                                    repo_root=args.root)
    for f in sorted(unsuppressed, key=lambda x: (x.path, x.line)):
        if args.json:
            print(json.dumps(dataclasses.asdict(f)))
        else:
            print(f.render())
    for k in sorted(stale):
        print(f"[analysis] stale baseline entry (finding no longer "
              f"fires — delete the line): {k}", file=sys.stderr)
    if unsuppressed or stale:
        print(f"[analysis] FAIL: {len(unsuppressed)} unsuppressed "
              f"finding(s), {len(stale)} stale baseline entr(ies)",
              file=sys.stderr)
        return 1
    print("[analysis] clean: 0 unsuppressed findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
