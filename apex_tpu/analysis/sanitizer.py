"""Runtime sanitizer: transfer guard + per-step recompile budget.

The two classic silent performance killers in a JAX train loop are
host<->device transfers inside the step (a sync per step) and
recompilation after warmup (a shape or flag leaking into the trace —
minutes lost per occurrence at scale).  Both are invisible to tests
that only check numerics.  ``sanitize()`` makes a smoke run FAIL on
either:

    with sanitize(recompile_budget=0, warmup_steps=1) as san:
        for i in range(steps):
            out = step(...)
            san.step()          # step boundary: budget enforced here

* transfers — wires ``jax.transfer_guard(level)`` for the body
  (default ``"disallow"``): JAX itself raises on implicit transfers.
* recompiles — flips ``jax_log_compiles`` and captures the
  "Finished XLA compilation of <name>" records from the
  ``jax._src.dispatch`` logger.  Compilations observed after
  ``warmup_steps`` completed step boundaries count against
  ``recompile_budget``; exceeding it raises
  :class:`RecompileBudgetExceeded` naming every offending computation.

:func:`sanitize_smoke` is the CI acceptance path (tools/ci.sh step 7):
it drives the standalone-GPT train step under
``sanitize(recompile_budget=0, warmup_steps=1)`` and proves the step
function compiles exactly once after warmup.
"""
from __future__ import annotations

import contextlib
import logging
import re
from typing import List, Optional

__all__ = ["RecompileBudgetExceeded", "Sanitizer", "sanitize",
           "sanitize_smoke"]

_COMPILE_RE = re.compile(r"Finished XLA compilation of (.+?) in")
_DISPATCH_LOGGER = "jax._src.dispatch"


class RecompileBudgetExceeded(RuntimeError):
    """A traced computation recompiled after warmup."""

    def __init__(self, names: List[str], budget: int, step: int):
        self.names = list(names)
        self.budget = budget
        self.step = step
        super().__init__(
            f"{len(names)} compilation(s) after warmup exceeded the "
            f"per-run recompile budget of {budget} at step boundary "
            f"{step}: {names} — a shape, python scalar, or env flag is "
            f"leaking into the trace")


class _CompileCapture(logging.Handler):
    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.names: List[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        m = _COMPILE_RE.search(record.getMessage())
        if m:
            self.names.append(m.group(1))


class Sanitizer:
    """Collects compile events between :meth:`step` boundaries; see
    module docstring.  Not a context manager itself — use
    :func:`sanitize`."""

    def __init__(self, *, recompile_budget: int = 0,
                 warmup_steps: int = 1) -> None:
        self.recompile_budget = int(recompile_budget)
        self.warmup_steps = int(warmup_steps)
        self.steps_done = 0
        self.warmup_compiles: List[str] = []
        self.post_warmup_compiles: List[str] = []
        self._capture = _CompileCapture()

    # wired by sanitize()
    def _drain(self) -> List[str]:
        names, self._capture.names = self._capture.names, []
        return names

    def step(self) -> None:
        """Mark a completed train step.  After ``warmup_steps`` of
        these, any captured compilation is charged against the budget
        and the step that overflows it raises."""
        names = self._drain()
        if self.steps_done < self.warmup_steps:
            self.warmup_compiles.extend(names)
        else:
            self.post_warmup_compiles.extend(names)
        self.steps_done += 1
        if len(self.post_warmup_compiles) > self.recompile_budget:
            raise RecompileBudgetExceeded(
                self.post_warmup_compiles, self.recompile_budget,
                self.steps_done)

    def finish(self) -> None:
        """Final boundary check (for loops that end right after the
        offending step) — called automatically on context exit.
        Events drained here belong to step ``steps_done + 1``, which is
        post-warmup whenever ``steps_done >= warmup_steps``."""
        names = self._drain()
        if self.steps_done < self.warmup_steps:
            self.warmup_compiles.extend(names)
            return
        self.post_warmup_compiles.extend(names)
        if len(self.post_warmup_compiles) > self.recompile_budget:
            raise RecompileBudgetExceeded(
                self.post_warmup_compiles, self.recompile_budget,
                self.steps_done)


@contextlib.contextmanager
def sanitize(*, transfer_guard: Optional[str] = "disallow",
             transfer_scope: str = "all",
             recompile_budget: int = 0, warmup_steps: int = 1):
    """Context manager yielding a :class:`Sanitizer`.

    ``transfer_guard``: a ``jax.transfer_guard`` level ("allow",
    "log", "disallow", ...) or None to leave transfers unguarded.
    ``transfer_scope``: "all" guards every direction;
    "device_to_host" guards only d→h — the deferred-telemetry proof
    (monitor.tracing.DeviceMetricsBuffer): under ``disallow`` the
    ring's one explicit ``jax.device_get`` drain is permitted while
    any implicit per-step readback (``float(loss)``, ``np.asarray``)
    raises, so a passing run *is* the zero-per-step-transfer claim.
    ``recompile_budget``/``warmup_steps``: see :class:`Sanitizer`.
    """
    import jax

    if transfer_scope not in ("all", "device_to_host"):
        raise ValueError(f"unknown transfer_scope {transfer_scope!r} "
                         "(use 'all' or 'device_to_host')")
    san = Sanitizer(recompile_budget=recompile_budget,
                    warmup_steps=warmup_steps)
    logger = logging.getLogger(_DISPATCH_LOGGER)
    prior_level = logger.level
    prior_propagate = logger.propagate
    logger.addHandler(san._capture)
    # log_compiles emits at WARNING via this logger; make sure the
    # records reach handlers even if the app raised the level, and
    # keep them out of the user's console while we capture
    if logger.level > logging.WARNING:
        logger.setLevel(logging.WARNING)
    logger.propagate = False
    # pxla chats "Compiling <name> with global shapes" on the same
    # flag; silence it for the duration too
    pxla_logger = logging.getLogger("jax._src.interpreters.pxla")
    prior_pxla_propagate = pxla_logger.propagate
    pxla_logger.propagate = False
    pxla_null = logging.NullHandler()  # else logging.lastResort prints
    pxla_logger.addHandler(pxla_null)
    prior_flag = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    try:
        if transfer_guard is not None:
            guard = (jax.transfer_guard_device_to_host
                     if transfer_scope == "device_to_host"
                     else jax.transfer_guard)
            with guard(transfer_guard):
                yield san
        else:
            yield san
        san.finish()
    finally:
        jax.config.update("jax_log_compiles", prior_flag)
        logger.removeHandler(san._capture)
        logger.setLevel(prior_level)
        logger.propagate = prior_propagate
        pxla_logger.removeHandler(pxla_null)
        pxla_logger.propagate = prior_pxla_propagate


def sanitize_smoke(steps: int = 4, *, scan_steps: int = 0,
                   verbose: bool = True) -> int:
    """Drive the standalone-GPT smoke step under the sanitizer; the CI
    proof that the train step compiles exactly once after warmup.

    Returns the number of post-warmup recompiles (0 on success);
    raises :class:`RecompileBudgetExceeded` on any.  The model and
    step come from the SAME construction path the train-smoke loop and
    the hlo auditor use (``testing.standalone_gpt.make_smoke_setup`` /
    ``build_train_step`` — the shared entry-point list), so this smoke
    proves the exact step CI audits.

    ``scan_steps`` >= 1 drives the batched-step scan driver instead
    (``build_train_step_scan``, the ``gpt_train_step_scan`` audit
    entry): ``steps`` K-step windows per run, one ``san.step()``
    boundary per window — proving an N-step run (N = steps*K) costs
    exactly ONE compile after warmup, the scan half of ROADMAP item
    2's dispatch-amortization claim.
    """
    from ..testing.standalone_gpt import (build_train_step,
                                          build_train_step_scan,
                                          make_smoke_setup)

    setup = make_smoke_setup(opt_level="O2")
    if scan_steps and scan_steps > 0:
        step = build_train_step_scan(setup, scan_steps)
    else:
        step = build_train_step(setup)
    params, amp_state = setup.params, setup.amp_state

    # the init/initialize compiles above happen OUTSIDE the sanitizer;
    # transfer_guard stays off for the smoke (loss readout is an
    # explicit, expected device->host transfer)
    with sanitize(transfer_guard=None, recompile_budget=0,
                  warmup_steps=1) as san:
        for _ in range(steps):
            params, amp_state, loss, _, _ = step(params, amp_state)
            loss.block_until_ready()
            san.step()
    if verbose:
        total = steps * max(1, scan_steps)
        print(f"[sanitize-smoke] steps={total}"
              + (f" (scan K={scan_steps}, {steps} windows)"
                 if scan_steps else "")
              + f" warmup_compiles={len(san.warmup_compiles)} "
              f"post_warmup_compiles={len(san.post_warmup_compiles)} "
              f"loss={float(loss):.4f}")
    return len(san.post_warmup_compiles)
