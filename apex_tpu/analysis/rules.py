"""Central registry of every APX analysis rule.

One declaration per rule — id, analysis layer, scope, one-line
description — mirroring the flag registry's design: the rule table in
docs/api/analysis.md is GENERATED from this module
(``python -m apex_tpu.analysis --write-docs``) and drift-guarded in CI,
so the docs can never describe a rule the code doesn't implement (or
miss one it does).

Layers:

* ``source`` — the AST trace-safety linter (:mod:`.linter`): sees
  Python source only, never imports or lowers anything.
* ``kernel`` — the pallas/jnp parity audit (:mod:`.parity`).
* ``compiled`` — the jaxpr/StableHLO auditor (:mod:`.hlo`): sees what
  XLA was actually handed for the registered entry points
  (:mod:`apex_tpu.testing.entry_points`), which source-level review
  cannot (missed donations, promotion converts the tracer inserted,
  collectives emitted by transpositions).
* ``sharding`` — the SPMD auditor (:mod:`.sharding`): compiles the
  planned multichip entries under their mesh and checks the
  partitioner's actual output (propagated shardings, per-device
  memory, the collective schedule) against each entry's
  :class:`apex_tpu.mesh_plan.MeshPlan` contract.
* ``concurrency`` — the host-concurrency auditor
  (:mod:`.concurrency`): lock discipline, lock-order cycles,
  signal-handler safety, blocking-under-lock, and off-main-thread
  device dispatch over the threaded serving/monitor host layer.
* ``protocol`` — the wire-protocol + resource-lifecycle auditor
  (:mod:`.protocol`): audits ``serving/`` + ``resilience/`` against
  the declared ``ProtocolSpec`` registry in
  ``serving/control_plane.py`` — deadline discipline, op and
  header-field drift matched across the parent/child modules,
  socket/subprocess/tempdir lifecycle, and retry-safety.

Import-light on purpose (stdlib only), like :mod:`.flags`.
"""
from __future__ import annotations

import dataclasses
from typing import Dict

__all__ = ["Rule", "RULES", "register_rule", "render_rule_table"]


@dataclasses.dataclass(frozen=True)
class Rule:
    """One analysis rule: the registry row the docs render."""

    id: str          # 'APX601'
    layer: str       # 'source' | 'kernel' | 'compiled'
    scope: str       # where it applies, for the docs table
    doc: str         # one-line description


RULES: Dict[str, Rule] = {}


def register_rule(id: str, layer: str, scope: str, doc: str) -> Rule:
    if layer not in ("source", "kernel", "compiled", "sharding",
                     "concurrency", "protocol"):
        raise ValueError(f"unknown rule layer {layer!r}")
    if id in RULES:
        raise ValueError(f"duplicate rule registration: {id}")
    rule = Rule(id=id, layer=layer, scope=scope, doc=doc)
    RULES[id] = rule
    return rule


def render_rule_table() -> str:
    """Markdown table of the registry, stable (id) ordering — embedded
    in docs/api/analysis.md between the rule-table markers and
    drift-guarded by ci.sh."""
    lines = ["| rule | layer | scope | fires on |",
             "|---|---|---|---|"]
    for rid in sorted(RULES):
        r = RULES[rid]
        lines.append(f"| `{r.id}` | {r.layer} | {r.scope} | {r.doc} |")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# The registry.  Every rule any apex_tpu.analysis pass can emit.
# ---------------------------------------------------------------------------

register_rule(
    "APX000", "source", "everywhere",
    "file fails to parse (the linter cannot vouch for code it cannot "
    "read)")
register_rule(
    "APX101", "source", "traced regions",
    "host-sync call on a traced value: `float()` / `int()` / `bool()` "
    "/ `.item()` / `.tolist()` / `np.asarray` / `np.array` / "
    "`jax.device_get`")
register_rule(
    "APX102", "source", "traced regions",
    "Python truthiness on a traced value in `if` / `while` / `assert` "
    "tests, including `not`/`and`/`or` within them (identity tests "
    "`is None` are exempt — they are static)")
register_rule(
    "APX103", "source", "traced regions",
    "`os.environ` / `os.getenv` read — the value is baked into the "
    "trace (stale flag) and a new value means a silent recompile")
register_rule(
    "APX201", "source", "everywhere", "bare `except:`")
register_rule(
    "APX202", "source", "everywhere",
    "broad `except Exception/BaseException` that neither re-raises "
    "nor logs through a logger method")
register_rule(
    "APX301", "source", "everywhere",
    "env read outside `apex_tpu/analysis/flags.py` — route "
    "`APEX_TPU_*` flags through the registry")
register_rule(
    "APX401", "kernel", "`ops/`",
    "`pallas_call` site without a registered jnp twin, or a registered "
    "twin that does not exist")
register_rule(
    "APX402", "kernel", "`ops/`",
    "kernel/twin pair with no test referencing both symbols")
register_rule(
    "APX501", "source", "everywhere",
    "direct `jax.shard_map` / `from jax.experimental.shard_map import "
    "...` — use `apex_tpu._compat.shard_map` (old jax spells it "
    "differently; the shim also pins the grad-correct `check_rep` "
    "mapping)")
register_rule(
    "APX601", "compiled", "entry points",
    "missed donation: a jit input whose shape/dtype matches an output, "
    "declared dead after the call by the entry registry, but carrying "
    "no `tf.aliasing_output` in the lowered module — the buffer is "
    "copied instead of reused (masters/optimizer state must be "
    "donated end-to-end)")
register_rule(
    "APX602", "compiled", "entry points (O4/O5 policy)",
    "silent dtype promotion: a `convert_element_type` bf16/f16 → f32 "
    "the precision policy did not ask for (provenance outside the "
    "entry's sanctioned-fp32 region list)")
register_rule(
    "APX603", "compiled", "entry points",
    "collective census drift vs tools/hlo_baseline.json: a new "
    "collective kind, more collective ops, or a >10% growth in bytes "
    "moved per step (shrinks fail too — refresh the baseline so the "
    "gate stays tight)")
register_rule(
    "APX604", "compiled", "entry points",
    "host transfer compiled into the graph: callback / infeed / "
    "outfeed ops XLA will service from the host every step — the "
    "runtime transfer-guard can only catch these after deployment")
register_rule(
    "APX605", "compiled", "entry points",
    "peak-live-memory estimate drift: buffer liveness over the "
    "lowered jaxpr exceeds the committed baseline by >10% (shrinks "
    "fail too — refresh the baseline)")
register_rule(
    "APX606", "compiled", "entry points (Q8 policy)",
    "dequantized weight residency: a `convert_element_type` int8 → "
    "f32/bf16 of a weight-sized tensor whose provenance is outside "
    "the quant kernel family (`ops/quant_matmul.py` dequantizes "
    "tile-locally in VMEM) — the compiled graph materializes the "
    "dense float weights int8 storage was meant to avoid")
register_rule(
    "APX701", "sharding", "planned entry points",
    "unintended full replication: a tensor above the "
    "`APEX_TPU_SHARDING_MIN_BYTES` floor whose MeshPlan spec shards it "
    "over an axis but whose propagated sharding is fully replicated — "
    "the silent-ZeRO-regression (every device pays full-tensor memory "
    "where the plan promised 1/N)")
register_rule(
    "APX702", "sharding", "planned entry points",
    "reshard chain: an `all_gather` whose result feeds a "
    "`reduce_scatter` / `dynamic_slice` re-partition of the same "
    "operand — gathered bytes immediately thrown away, reported with "
    "both ops' jaxpr provenance")
register_rule(
    "APX703", "sharding", "planned entry points",
    "declared-vs-propagated drift: a plan spec the partitioner "
    "resolved differently, a plan pattern matching no tensor, a "
    "MeshPlan change vs the committed baseline, or a collective-budget "
    "overrun / unbudgeted collective kind (innermost repo frame named)")
register_rule(
    "APX704", "sharding", "planned entry points (advisory)",
    "non-overlappable collective: an all_to_all/all_gather consumed by "
    "the immediately following equation while later independent "
    "compute exists — the MoE a2a/expert-compute overlap precondition "
    "is not met as written; printed, never red")
register_rule(
    "APX705", "sharding", "planned entry points",
    "per-device memory drift: XLA's memory analysis of the partitioned "
    "executable (arguments+outputs+temps−aliased, per device) exceeds "
    "the committed tools/sharding_baseline.json row by >10% (shrinks "
    "fail too — refresh the baseline)")
register_rule(
    "APX801", "concurrency", "host threading",
    "shared mutable attribute accessed outside its guarding lock: an "
    "attribute of a lock-bearing class that is written and accessed "
    "under `with self._lock:` elsewhere (guard inference) but "
    "read/written lock-free; a `+=` read-modify-write outside the "
    "lock; or an attribute store inside a `threading.Thread` target "
    "racing a store to the same attribute elsewhere in the module")
register_rule(
    "APX802", "concurrency", "host threading (cross-module)",
    "lock-acquisition-order cycle: `with A:` nesting `with B:` "
    "records an A→B edge, edges aggregate across every scanned "
    "module, and any cycle is a potential deadlock — reported with "
    "each edge's file:line provenance")
register_rule(
    "APX803", "concurrency", "signal handlers",
    "signal handler doing more than flag-set / counter-increment — "
    "the flag-only-handler convention enforced: no telemetry, "
    "logging, locks, or I/O from a handler (it runs between "
    "bytecodes of a thread that may hold any lock); chaining to the "
    "previous handler and calls into same-class flag-only methods "
    "stay legal")
register_rule(
    "APX804", "concurrency", "host threading",
    "blocking call while holding a lock: `.join()` / `sleep()` / "
    "`Event.wait()` / sink `.emit()` / monitor `.event()` / "
    "`jax.device_get` / `.block_until_ready()` inside a lock region, "
    "including reached through a same-class method call — collect "
    "under the lock, emit/block after releasing it "
    "(`Condition.wait` on the held lock is exempt: it releases)")
register_rule(
    "APX805", "concurrency", "thread targets",
    "jit dispatch from a `threading.Thread` target outside a "
    "device-pinning context (`with replica.device_scope():` / "
    "`jax.default_device(...)`): off the main thread the staging "
    "lands on the process default device and every replica's tick "
    "transits device 0's stream — aggregate fleet throughput stays "
    "flat")
register_rule(
    "APX900", "source", "everywhere",
    "suppression comment without a reason")
register_rule(
    "APX901", "protocol", "serving/ + resilience/",
    "RPC send/recv without an explicit deadline, or with a numeric "
    "literal one: `.call(op)`/`.post(op)`/`.wait(seq)` missing "
    "`timeout=`, or any of them (and `.settimeout`) passing a "
    "literal instead of a value routed through the ProtocolSpec "
    "registry's timeout class (`_op_timeout` / the "
    "`APEX_TPU_CP_*_TIMEOUT_S` flags); applies to modules that "
    "define or import the control-plane surface")
register_rule(
    "APX902", "protocol", "serving/ + resilience/ (cross-module)",
    "op drift, matched across every scanned module: an op sent "
    "(`.call`/`.post` constant, or a child->parent `send_frame` "
    "header literal) that no dispatch handles; a handler "
    "(`*_HANDLERS` key or `op == ...` compare) for an op no sender "
    "emits — the dead branch; either side using an op the "
    "ProtocolSpec registry never declared; a declared op with no "
    "sender or no handler")
register_rule(
    "APX903", "protocol", "serving/ + resilience/ (cross-module)",
    "header-field drift against the op's ProtocolSpec: a sender "
    "header literal carrying an undeclared field or omitting a "
    "required one; a receiver `.get()`/index on a reply, handler "
    "request header, or the hello handshake for an undeclared "
    "field (the KeyError-at-3am class); a handler replying "
    "off-spec fields; blobs passed on an op whose spec declares "
    "none")
register_rule(
    "APX904", "protocol", "serving/ + resilience/",
    "resource lifecycle: a socket / accepted conn / subprocess / "
    "tempdir / journal sink acquired into a local without "
    "guaranteed release on all paths (no release at all, or risky "
    "statements between the acquisition and the try/with/ownership "
    "transfer that protects it); and `os.kill(pid, SIGKILL)` in a "
    "function with no `.join` — SIGKILLed children must be reaped "
    "(self-kill via `os.getpid()` is exempt)")
register_rule(
    "APX905", "protocol", "serving/ + resilience/",
    "retry-safety: `retries=` > 0 on an op whose ProtocolSpec is "
    "not marked idempotent (a blind re-send can double-apply work "
    "— escalate to restart + journal replay instead); and a retry "
    "loop (a `while`/`for range` that swallows an RPC/OS error and "
    "re-enters) without a bound or without backoff (a `*restart*` "
    "escalation counts: it backs off internally)")
