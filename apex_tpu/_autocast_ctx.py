"""Trace-time autocast context shared by amp.autocast and the fused
custom-VJP ops.

``amp.autocast`` cannot boundary-cast ``custom_vjp`` call sites at the
jaxpr level — the saved body jaxpr is dtype-frozen (re-binding a body
traced at fp32 with bf16 operands breaks on fp32 literals and Pallas
block specs).  Instead it sets this context while TRACING the wrapped
function; the framework's own custom-VJP entry points (flash attention,
fused layer norm) read it and cast their inputs before their
``custom_vjp`` wrapper binds, so the casts land in the traced graph
itself.  This mirrors the reference's O1 design: the patcher wraps the
call sites of ITS registered functions, arbitrary user functions are
untouched (ref: apex/amp/amp.py:76-150 ``init`` patch loop).

The state is registered with ``include_in_trace_context=True`` so JAX's
jit/pjit TRACE CACHES are keyed on it: a function jitted outside
autocast and then called under it (or vice versa) retraces instead of
silently reusing a jaxpr built under the other precision regime.  Falls
back to a plain contextvar (documented cache hazard) if the private
config API ever changes shape.

Lives in its own module so ``apex_tpu.ops`` never imports
``apex_tpu.amp`` (and vice versa) at module level.
"""
from __future__ import annotations

from typing import Any, Optional

try:
    from jax._src import config as _jax_config

    _STATE = _jax_config.optional_string_state(
        name="apex_tpu_autocast_dtype",
        default=None,
        help="Active apex_tpu amp.autocast compute dtype (trace-time).",
        include_in_trace_context=True,
    )

    def autocast_compute_dtype() -> Optional[Any]:
        """The active ``amp.autocast`` compute dtype, or None outside
        an autocast trace."""
        val = _STATE.value
        if val is None:
            return None
        import jax.numpy as jnp
        return jnp.dtype(val)

    class _Token:
        def __init__(self, mgr):
            self.mgr = mgr

    def set_autocast_dtype(dtype) -> Any:
        import jax.numpy as jnp
        mgr = _STATE(jnp.dtype(dtype).name)
        mgr.__enter__()
        return _Token(mgr)

    def reset_autocast_dtype(token) -> None:
        token.mgr.__exit__(None, None, None)

except (ImportError, AttributeError, TypeError):  # pragma: no cover
    import contextvars

    _AUTOCAST_DTYPE: contextvars.ContextVar[Optional[Any]] = \
        contextvars.ContextVar("apex_tpu_autocast_dtype", default=None)

    def autocast_compute_dtype() -> Optional[Any]:
        return _AUTOCAST_DTYPE.get()

    def set_autocast_dtype(dtype):
        return _AUTOCAST_DTYPE.set(dtype)

    def reset_autocast_dtype(token) -> None:
        _AUTOCAST_DTYPE.reset(token)
