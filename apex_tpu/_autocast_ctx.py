"""Trace-time autocast context shared by amp.autocast and the fused
custom-VJP ops.

``amp.autocast`` cannot boundary-cast ``custom_vjp`` call sites at the
jaxpr level — the saved body jaxpr is dtype-frozen (re-binding a body
traced at fp32 with bf16 operands breaks on fp32 literals and Pallas
block specs).  Instead it sets this context while TRACING the wrapped
function; the framework's own custom-VJP entry points (flash attention,
fused layer norm) read it and cast their inputs before their
``custom_vjp`` wrapper binds, so the casts land in the traced graph
itself.  This mirrors the reference's O1 design: the patcher wraps the
call sites of ITS registered functions, arbitrary user functions are
untouched (ref: apex/amp/amp.py:76-150 ``init`` patch loop).

The state is registered with ``include_in_trace_context=True`` so JAX's
jit/pjit TRACE CACHES are keyed on it: a function jitted outside
autocast and then called under it (or vice versa) retraces instead of
silently reusing a jaxpr built under the other precision regime.  Falls
back to a plain contextvar (documented cache hazard) if the private
config API ever changes shape.

Lives in its own module so ``apex_tpu.ops`` never imports
``apex_tpu.amp`` (and vice versa) at module level.
"""
from __future__ import annotations

from typing import Any, Optional

try:
    from jax._src import config as _jax_config

    _STATE = _jax_config.optional_string_state(
        name="apex_tpu_autocast_dtype",
        default=None,
        help="Active apex_tpu amp.autocast compute dtype (trace-time).",
        include_in_trace_context=True,
    )

    def autocast_compute_dtype() -> Optional[Any]:
        """The active ``amp.autocast`` compute dtype, or None outside
        an autocast trace."""
        val = _STATE.value
        if val is None:
            return None
        import jax.numpy as jnp
        return jnp.dtype(val)

    class _Token:
        def __init__(self, mgr):
            self.mgr = mgr

    def set_autocast_dtype(dtype) -> Any:
        import jax.numpy as jnp
        mgr = _STATE(jnp.dtype(dtype).name)
        mgr.__enter__()
        return _Token(mgr)

    def reset_autocast_dtype(token) -> None:
        token.mgr.__exit__(None, None, None)

except (ImportError, AttributeError, TypeError):
    # Old jax: no trace-context-keyed config states (0.4.x
    # ``include_in_jit_key`` exists but measurably does not key the
    # trace cache).  ``xla_metadata`` IS in ``trace_context()`` there,
    # so a metadata context supplies the cache keying while a plain
    # contextvar carries the value for ``autocast_compute_dtype``.
    import contextvars

    _AUTOCAST_DTYPE: contextvars.ContextVar[Optional[Any]] = \
        contextvars.ContextVar("apex_tpu_autocast_dtype", default=None)

    def autocast_compute_dtype() -> Optional[Any]:
        val = _AUTOCAST_DTYPE.get()
        if val is None:
            return None
        import jax.numpy as jnp
        return jnp.dtype(val)

    class _Token:  # noqa: F811 — fallback twin of the config-state token
        def __init__(self, var_token, meta_mgr):
            self.var_token = var_token
            self.meta_mgr = meta_mgr

    def set_autocast_dtype(dtype) -> Any:
        import jax.numpy as jnp
        name = jnp.dtype(dtype).name
        var_token = _AUTOCAST_DTYPE.set(name)
        meta_mgr = None
        try:
            from jax.experimental.xla_metadata import set_xla_metadata

            meta_mgr = set_xla_metadata(apex_tpu_autocast=name)
            meta_mgr.__enter__()
        except (ImportError, AttributeError, TypeError):
            meta_mgr = None  # documented cache hazard: no trace keying
        return _Token(var_token, meta_mgr)

    def reset_autocast_dtype(token) -> None:
        if token.meta_mgr is not None:
            token.meta_mgr.__exit__(None, None, None)
        _AUTOCAST_DTYPE.reset(token.var_token)
