"""apex_tpu.transformer — Megatron-style model parallelism, TPU-native.

Parity surface of ``apex.transformer`` (ref: apex/transformer/__init__.py):
tensor parallelism, pipeline parallelism, parallel transformer building
blocks, fused softmax, microbatch calculators, enums — over a
``jax.sharding.Mesh`` instead of NCCL process groups.
"""
from . import (expert_parallel, functional, microbatches,
               pipeline_parallel, sequence_parallel, tensor_parallel)
from .enums import AttnMaskType, AttnType, LayerType
from .layers import (ParallelMLP, ParallelSelfAttention,
                     ParallelTransformer, ParallelTransformerLayer)

__all__ = [
    "expert_parallel", "functional", "microbatches", "pipeline_parallel",
    "sequence_parallel", "tensor_parallel",
    "AttnMaskType", "AttnType", "LayerType",
    "ParallelMLP", "ParallelSelfAttention", "ParallelTransformer",
    "ParallelTransformerLayer",
]
