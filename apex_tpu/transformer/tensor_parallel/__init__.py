"""Tensor (model) parallelism — Megatron-parity layers over a mesh axis.

TPU-native re-design of ``apex.transformer.tensor_parallel``: the
collective algebra (ref: mappings.py), sharded layers (ref: layers.py),
vocab-parallel cross entropy (ref: cross_entropy.py), RNG domains
(ref: random.py), and supporting utilities — expressed as GSPMD
partitioning metadata + explicit ``shard_map`` collectives instead of
NCCL process groups.
"""
from .cross_entropy import vocab_parallel_cross_entropy
from .data import broadcast_data
from .layers import (ColumnParallelLinear, RowParallelLinear,
                     VocabParallelEmbedding, param_sharding_specs)
from .mappings import (copy_to_tensor_model_parallel_region,
                       gather_from_tensor_model_parallel_region,
                       reduce_from_tensor_model_parallel_region,
                       scatter_to_tensor_model_parallel_region)
from .memory import MemoryBuffer, RingMemBuffer
from .random import (CHECKPOINT_POLICIES, RNGStatesTracker, checkpoint,
                     get_rng_tracker, model_parallel_rng_key,
                     model_parallel_seed)
from .utils import (VocabUtility, divide, ensure_divisibility,
                    split_tensor_along_last_dim)

__all__ = [
    "vocab_parallel_cross_entropy", "broadcast_data",
    "ColumnParallelLinear", "RowParallelLinear", "VocabParallelEmbedding",
    "param_sharding_specs",
    "copy_to_tensor_model_parallel_region",
    "gather_from_tensor_model_parallel_region",
    "reduce_from_tensor_model_parallel_region",
    "scatter_to_tensor_model_parallel_region",
    "MemoryBuffer", "RingMemBuffer",
    "CHECKPOINT_POLICIES", "RNGStatesTracker", "checkpoint",
    "get_rng_tracker", "model_parallel_rng_key", "model_parallel_seed",
    "VocabUtility", "divide", "ensure_divisibility",
    "split_tensor_along_last_dim",
]
