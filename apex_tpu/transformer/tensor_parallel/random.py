"""Tensor-parallel RNG and activation checkpointing.

TPU-native replacement for the reference's CUDA RNG-state tracker and
checkpoint machinery (ref: apex/transformer/tensor_parallel/random.py):

* The reference forks a ``model-parallel-rng`` CUDA state seeded
  ``seed + 2718 + tp_rank`` so dropout differs across TP shards while
  data-parallel replicas stay identical (ref: random.py:193-224).  In JAX
  the same contract is a deterministic key derivation:
  ``fold_in(fold_in(key, _MODEL_PARALLEL_OFFSET), axis_index('tensor'))``
  — no mutable device state to save/restore.
* The reference's ``CheckpointFunction`` re-runs forward with saved RNG
  states (ref: random.py:224-290).  ``jax.checkpoint`` already replays
  with identical keys because keys are *values*; ``checkpoint`` below
  adds the reference's API shape plus TPU-appropriate remat policies.
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
from ..._compat import axis_index
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_policies as _policies

from ...parallel_state import TENSOR_AXIS

# The reference's magic offset for the model-parallel RNG domain
# (ref: apex/transformer/tensor_parallel/random.py:205: seed + 2718 + rank).
_MODEL_PARALLEL_OFFSET = 2718
_MODEL_PARALLEL_RNG = "model-parallel-rng"


def model_parallel_rng_key(key: jax.Array,
                           axis_name: str = TENSOR_AXIS) -> jax.Array:
    """Per-TP-shard key: same across DP replicas, distinct across TP ranks
    (the contract documented at ref: random.py:193-204)."""
    return jax.random.fold_in(
        jax.random.fold_in(key, _MODEL_PARALLEL_OFFSET),
        axis_index(axis_name))


class RNGStatesTracker:
    """API-parity tracker for named RNG domains
    (ref: ``CudaRNGStatesTracker``, random.py:113-190).

    JAX keys are immutable values, so "saving/restoring device RNG state"
    degenerates to bookkeeping: each named domain holds a key; ``fork``
    yields a fresh subkey and advances the domain.  Use outside jit to
    derive the rng dict passed into ``model.apply(..., rngs=...)``.
    """

    def __init__(self):
        self._states: Dict[str, jax.Array] = {}

    def reset(self):
        self._states.clear()

    def get_states(self) -> Dict[str, jax.Array]:
        return dict(self._states)

    def set_states(self, states: Dict[str, jax.Array]):
        self._states = dict(states)

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"rng state {name} already present")
        self._states[name] = jax.random.PRNGKey(seed)

    @contextlib.contextmanager
    def fork(self, name: str = _MODEL_PARALLEL_RNG):
        if name not in self._states:
            raise ValueError(f"rng state {name} is not added")
        key, next_key = jax.random.split(self._states[name])
        self._states[name] = next_key
        yield key


_GLOBAL_TRACKER = RNGStatesTracker()


def get_rng_tracker() -> RNGStatesTracker:
    """ref: get_cuda_rng_tracker (random.py:186-190)."""
    return _GLOBAL_TRACKER


def model_parallel_seed(seed: int) -> None:
    """Initialize the default domains from one global seed
    (ref: model_parallel_cuda_manual_seed, random.py:193-224).  The
    tensor-parallel offset is applied later, inside traced code, via
    :func:`model_parallel_rng_key` (rank is a mesh coordinate, not a
    process property)."""
    _GLOBAL_TRACKER.reset()
    _GLOBAL_TRACKER.add(_MODEL_PARALLEL_RNG, seed + _MODEL_PARALLEL_OFFSET)


# --- activation checkpointing ----------------------------------------------

#: Remat policies, TPU-tuned: ``dots_saveable`` keeps MXU outputs (the
#: sweet spot for transformer blocks — recompute elementwise, keep
#: matmuls); ``nothing_saveable`` is the reference's full-recompute
#: behavior (ref: random.py:224-290 recomputes the whole block).
CHECKPOINT_POLICIES = {
    "full": _policies.nothing_saveable,
    "dots": _policies.dots_saveable,
    "dots_with_no_batch_dims": _policies.dots_with_no_batch_dims_saveable,
    # Transformer sweet spot on TPU: save every residual EXCEPT the
    # 4x-wide FFN intermediates (tagged "ffn_wide" in ParallelMLP /
    # FusedDenseGeluDense) — those dominate per-layer activation HBM
    # (width 4h in bf16), and recomputing them in the backward costs one
    # h->4h matmul + gelu per layer (~+4% model FLOPs for GPT shapes).
    "all_but_ffn_wide":
        _policies.save_anything_except_these_names("ffn_wide"),
}


def checkpoint(fn, *args, policy: Optional[str] = "full",
               prevent_cse: bool = True):
    """Activation checkpointing with deterministic RNG replay
    (ref: CheckpointFunction, random.py:224-290).

    Dual calling convention: ``checkpoint(fn)`` returns the rematerialized
    function (decorator style); ``checkpoint(fn, *args)`` runs it
    immediately, matching the reference's executor signature
    (ref: random.py ``checkpoint(function, *args)``).  ``policy`` and
    ``prevent_cse`` are keyword-only so positional activation arguments
    can never bind to them.

    The reference stashes and restores CPU+CUDA RNG states around the
    replay; with JAX keys-as-values the replay is bitwise-identical by
    construction, so this reduces to ``jax.checkpoint`` with a policy.
    """
    pol = CHECKPOINT_POLICIES[policy] if isinstance(policy, str) else policy
    wrapped = jax.checkpoint(fn, policy=pol, prevent_cse=prevent_cse)
    if args:
        return wrapped(*args)
    return wrapped
