"""Shared tensor-parallel arithmetic helpers.

Parity with the reference's utilities
(ref: apex/transformer/tensor_parallel/utils.py:20-54): last-dim splitting
and the vocab range bookkeeping used by VocabParallelEmbedding and the
vocab-parallel cross entropy.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


def ensure_divisibility(numerator: int, denominator: int) -> None:
    if numerator % denominator != 0:
        raise ValueError(
            f"{numerator} is not divisible by {denominator}"
        )


def divide(numerator: int, denominator: int) -> int:
    """Exact integer division (ref: tensor_parallel/utils.py semantics)."""
    ensure_divisibility(numerator, denominator)
    return numerator // denominator


def split_tensor_along_last_dim(
    tensor: jnp.ndarray, num_partitions: int
) -> Sequence[jnp.ndarray]:
    """Split a tensor along its last dimension
    (ref: apex/transformer/tensor_parallel/utils.py:20-37).

    JAX arrays are immutable so the reference's ``contiguous_split_chunks``
    flag is moot — every chunk is already a standalone array.
    """
    divide(tensor.shape[-1], num_partitions)
    return jnp.split(tensor, num_partitions, axis=-1)


def masked_local_index(global_ids, first, per_partition: int):
    """Map global vocab ids to this shard's local indices.

    Returns ``(local_ids, in_range)`` where out-of-range ids are clamped
    to 0 and flagged False — the masked-lookup contract shared by
    VocabParallelEmbedding (ref: layers.py:176-205) and the
    vocab-parallel cross entropy (ref: cross_entropy.py:38-62); keeping
    it here ties both to VocabUtility's partitioning scheme.
    """
    local_ids = global_ids - first
    in_range = (local_ids >= 0) & (local_ids < per_partition)
    return jnp.where(in_range, local_ids, 0), in_range


class VocabUtility:
    """Vocab range math (ref: apex/transformer/tensor_parallel/utils.py:40-54).

    The vocabulary is partitioned into contiguous per-rank ranges
    [first, last); both class methods mirror the reference's signatures.
    """

    @staticmethod
    def vocab_range_from_per_partition_vocab_size(
        per_partition_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        index_f = rank * per_partition_vocab_size
        index_l = index_f + per_partition_vocab_size
        return index_f, index_l

    @staticmethod
    def vocab_range_from_global_vocab_size(
        global_vocab_size: int, rank, world_size: int
    ) -> Tuple:
        per_partition_vocab_size = divide(global_vocab_size, world_size)
        return VocabUtility.vocab_range_from_per_partition_vocab_size(
            per_partition_vocab_size, rank, world_size
        )
