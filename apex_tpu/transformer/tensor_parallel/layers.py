"""Tensor-parallel layers: Column/Row-parallel linear, vocab-parallel embedding.

Parity with the reference's Megatron layers
(ref: apex/transformer/tensor_parallel/layers.py:127,243,365) with a
TPU-native dual personality controlled by ``axis_name``:

* ``axis_name=None`` (default) — **GSPMD mode**: parameters are full
  logical arrays carrying flax partitioning metadata
  (kernel ``(None, 'tensor')`` for column, ``('tensor', None)`` for row,
  embedding ``('tensor', None)``); run under ``pjit`` over the registered
  mesh and XLA inserts the collectives the reference issues by hand.
* ``axis_name='tensor'`` — **explicit mode** for use inside
  ``jax.shard_map``: each shard holds the local parameter partition and
  the collective algebra from :mod:`.mappings` is applied exactly as the
  reference's autograd Functions are (copy -> local matmul -> gather /
  reduce).

The reference's per-parameter TP attributes
(``is_tensor_model_parallel``, ``partition_dim`` —
ref: layers.py:44-75) are carried by the flax ``Partitioned`` metadata
boxes; :func:`param_sharding_specs` recovers a ``PartitionSpec`` pytree
for ``pjit`` in_shardings.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
from ..._compat import axis_index, axis_size
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...parallel_state import TENSOR_AXIS
from .mappings import (copy_to_tensor_model_parallel_region,
                       gather_from_tensor_model_parallel_region,
                       reduce_from_tensor_model_parallel_region,
                       scatter_to_tensor_model_parallel_region)  # noqa: F401 (scatter re-exported)
from .utils import VocabUtility, divide, masked_local_index

Dtype = Any
Initializer = Callable[..., jnp.ndarray]


def _sliced_init(init: Initializer, axis_name: str, full_shape,
                 partition_dim: int) -> Initializer:
    """Draw the FULL logical weight and keep this shard's slice — the
    reference's master-weight-then-scatter initialization
    (ref: layers.py:78-124).  This preserves the initializer's
    distribution exactly (fan-in/fan-out computed from the full shape,
    not the shard), so weight statistics are identical across TP degrees
    and identical to GSPMD mode."""

    def wrapped(key, shape, dtype):
        full = init(key, full_shape, dtype)
        rank = axis_index(axis_name)
        chunk = shape[partition_dim]
        return jax.lax.dynamic_slice_in_dim(full, rank * chunk, chunk,
                                            axis=partition_dim)

    return wrapped


def _constrain(x, spec: P):
    """Best-effort sharding hint; a no-op when no mesh is registered."""
    from ... import parallel_state

    if not parallel_state.model_parallel_is_initialized():
        return x
    try:
        return jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(parallel_state.get_mesh(), spec))
    except (ValueError, RuntimeError):
        # Outside jit / mesh mismatch: hints are advisory only.
        return x


def param_sharding_specs(tree):
    """PartitionSpec pytree from flax Partitioned metadata (replicated for
    plain leaves) — the pjit-side view of the reference's TP attributes
    (ref: layers.py:44-75)."""
    return jax.tree_util.tree_map(
        lambda leaf: leaf.get_partition_spec()
        if isinstance(leaf, nn.Partitioned) else P(),
        tree, is_leaf=lambda leaf: isinstance(leaf, nn.Partitioned))


class ColumnParallelLinear(nn.Module):
    """Linear with output-dim partitioning, Y = XA + b with A split by
    columns (ref: apex/transformer/tensor_parallel/layers.py:243-363).

    ``gather_output`` mirrors the reference: True yields the full Y on
    every shard; False leaves Y partitioned for a following
    RowParallelLinear (ref: layers.py:257-262).
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    gather_output: bool = True
    init_method: Initializer = nn.initializers.lecun_normal()
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        if self.axis_name is not None:
            world = axis_size(self.axis_name)
            local_out = divide(self.output_size, world)
            kernel = self.param(
                "kernel",
                _sliced_init(self.init_method, self.axis_name,
                             (self.input_size, self.output_size), 1),
                (self.input_size, local_out), self.param_dtype)
            bias = self.param(
                "bias",
                _sliced_init(nn.initializers.zeros, self.axis_name,
                             (self.output_size,), 0),
                (local_out,), self.param_dtype) if self.use_bias else None
            x = copy_to_tensor_model_parallel_region(x, self.axis_name)
            y = x.astype(self.dtype) @ kernel.astype(self.dtype)
            if bias is not None:
                y = y + bias.astype(self.dtype)
            if self.gather_output:
                y = gather_from_tensor_model_parallel_region(
                    y, self.axis_name)
            return y

        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.init_method, (None, TENSOR_AXIS)),
            (self.input_size, self.output_size), self.param_dtype)
        bias = self.param(
            "bias", nn.with_partitioning(nn.initializers.zeros,
                                         (TENSOR_AXIS,)),
            (self.output_size,), self.param_dtype) if self.use_bias else None
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        if bias is not None:
            y = y + bias.astype(self.dtype)
        spec = (P(*([None] * (y.ndim - 1)), None) if self.gather_output
                else P(*([None] * (y.ndim - 1)), TENSOR_AXIS))
        return _constrain(y, spec)


class RowParallelLinear(nn.Module):
    """Linear with input-dim partitioning, Y = XA + b with A split by
    rows (ref: apex/transformer/tensor_parallel/layers.py:365-477).

    ``input_is_parallel``: True when X arrives already split (the usual
    pairing after ColumnParallelLinear(gather_output=False),
    ref: layers.py:380-384); the bias is added after the reduction so it
    is applied exactly once (ref: layers.py:472-477).
    """

    input_size: int
    output_size: int
    use_bias: bool = True
    input_is_parallel: bool = False
    init_method: Initializer = nn.initializers.lecun_normal()
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        if self.axis_name is not None:
            world = axis_size(self.axis_name)
            local_in = divide(self.input_size, world)
            kernel = self.param(
                "kernel",
                _sliced_init(self.init_method, self.axis_name,
                             (self.input_size, self.output_size), 0),
                (local_in, self.output_size), self.param_dtype)
            bias = self.param(
                "bias", nn.initializers.zeros,
                (self.output_size,), self.param_dtype) if self.use_bias \
                else None
            if not self.input_is_parallel:
                x = scatter_to_tensor_model_parallel_region(
                    x, self.axis_name)
            y = x.astype(self.dtype) @ kernel.astype(self.dtype)
            y = reduce_from_tensor_model_parallel_region(y, self.axis_name)
            if bias is not None:
                y = y + bias.astype(self.dtype)
            return y

        kernel = self.param(
            "kernel",
            nn.with_partitioning(self.init_method, (TENSOR_AXIS, None)),
            (self.input_size, self.output_size), self.param_dtype)
        bias = self.param(
            "bias", nn.initializers.zeros,
            (self.output_size,), self.param_dtype) if self.use_bias else None
        x = _constrain(x, P(*([None] * (x.ndim - 1)), TENSOR_AXIS))
        y = x.astype(self.dtype) @ kernel.astype(self.dtype)
        y = _constrain(y, P(*([None] * y.ndim)))
        if bias is not None:
            y = y + bias.astype(self.dtype)
        return y


class VocabParallelEmbedding(nn.Module):
    """Embedding partitioned along the vocabulary dimension
    (ref: apex/transformer/tensor_parallel/layers.py:127-206).

    Explicit mode reproduces the reference's masked lookup: ids outside
    this shard's [first, last) range read row 0 and are zeroed, then a
    psum combines the per-shard partial embeddings (ref: layers.py:176-205).
    """

    num_embeddings: int
    features: int
    init_method: Initializer = nn.initializers.normal(stddev=0.02)
    dtype: Dtype = jnp.float32
    param_dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    def setup(self):
        if self.axis_name is not None:
            world = axis_size(self.axis_name)
            per_part = divide(self.num_embeddings, world)
            self.embedding = self.param(
                "embedding",
                _sliced_init(self.init_method, self.axis_name,
                             (self.num_embeddings, self.features), 0),
                (per_part, self.features), self.param_dtype)
        else:
            self.embedding = self.param(
                "embedding",
                nn.with_partitioning(self.init_method, (TENSOR_AXIS, None)),
                (self.num_embeddings, self.features), self.param_dtype)

    def __call__(self, ids):
        table = self.embedding
        if isinstance(table, nn.Partitioned):
            table = table.unbox()
        if self.axis_name is not None:
            world = axis_size(self.axis_name)
            per_part = divide(self.num_embeddings, world)
            rank = axis_index(self.axis_name)
            first, _last = (
                VocabUtility.vocab_range_from_per_partition_vocab_size(
                    per_part, rank, world))
            local_ids, in_range = masked_local_index(ids, first, per_part)
            out = jnp.take(table.astype(self.dtype), local_ids, axis=0)
            out = jnp.where(in_range[..., None], out,
                            jnp.zeros((), self.dtype))
            return reduce_from_tensor_model_parallel_region(
                out, self.axis_name)
        return jnp.take(table.astype(self.dtype), ids, axis=0)

    def attend(self, x):
        """Tied LM head: project hidden states onto the (sharded) vocab —
        logits come back partitioned over the vocab dim in explicit mode
        (column-parallel semantics, feeding vocab_parallel_cross_entropy),
        the reference's embedding-weight reuse across first/last pipeline
        stages (ref: parallel_state.py:148-167 embedding group; the tied
        matmul itself is standalone_gpt.py's post_language_model_processing).
        """
        table = self.embedding
        if isinstance(table, nn.Partitioned):
            table = table.unbox()
        if self.axis_name is not None:
            x = copy_to_tensor_model_parallel_region(x, self.axis_name)
        return x.astype(self.dtype) @ table.astype(self.dtype).T
