"""Vocab-parallel cross entropy over logits sharded along the vocab dim.

Parity with the reference's ``_VocabParallelCrossEntropy``
(ref: apex/transformer/tensor_parallel/cross_entropy.py:23-100): stable
softmax cross entropy computed without ever materializing the full
[..., vocab] logits on one shard — a global max (pmax), a masked local
gather of each target's logit, and sums (psum) of the predicted logits
and the exp-sum.  The reference hand-writes the backward
(softmax - one_hot, ref :76-100); here JAX autodiff derives the same
collective-free-identical gradient through the psum/pmax algebra.

``vocab_parallel_cross_entropy`` must be called inside ``jax.shard_map``
with the logits' last dim sharded over ``axis_name``.
"""
from __future__ import annotations

import jax
from ..._compat import axis_index, axis_size
import jax.numpy as jnp

from ...parallel_state import TENSOR_AXIS
from .utils import VocabUtility, masked_local_index


def vocab_parallel_cross_entropy(vocab_parallel_logits, target,
                                 label_smoothing: float = 0.0,
                                 axis_name: str = TENSOR_AXIS):
    """Per-token loss from vocab-sharded logits.

    Args:
      vocab_parallel_logits: [..., vocab/world] local logit shard.
      target: [...] int ids into the *global* vocabulary.
      label_smoothing: optional uniform smoothing (the reference's contrib
        xentropy kernel offers smoothing; the TP CE grows the same knob).
      axis_name: mesh axis the vocab dim is sharded over.

    Returns per-token losses with ``target``'s shape (reference returns the
    unreduced loss as well, ref: cross_entropy.py:73-75).
    """
    logits = vocab_parallel_logits.astype(jnp.float32)
    world = axis_size(axis_name)
    rank = axis_index(axis_name)
    per_part = logits.shape[-1]
    vocab = per_part * world

    # Global max for numerical stability (ref :31-36).  The max shift
    # cancels in the gradient, so it is detached — which also sidesteps
    # pmax's missing differentiation rule (the reference likewise treats
    # it as a constant in its hand-written backward, ref :76-100).
    logits_max = jax.lax.pmax(
        jnp.max(jax.lax.stop_gradient(logits), axis=-1), axis_name)
    logits = logits - logits_max[..., None]

    # Masked local gather of the predicted (target) logit (ref :38-62).
    first, _last = VocabUtility.vocab_range_from_per_partition_vocab_size(
        per_part, rank, world)
    safe_target, in_range = masked_local_index(target, first, per_part)
    predicted = jnp.take_along_axis(
        logits, safe_target[..., None], axis=-1)[..., 0]
    predicted = jnp.where(in_range, predicted, 0.0)
    predicted = jax.lax.psum(predicted, axis_name)

    # Global exp-sum (ref :64-71).
    sum_exp = jax.lax.psum(jnp.sum(jnp.exp(logits), axis=-1), axis_name)
    log_z = jnp.log(sum_exp)
    loss = log_z - predicted

    if label_smoothing > 0.0:
        # Smoothed target distributes eps/vocab mass uniformly: loss
        # becomes (1-eps)*nll + eps * mean over classes of (log_z - logit).
        eps = label_smoothing
        mean_logit = (jax.lax.psum(jnp.sum(logits, axis=-1), axis_name)
                      / vocab)
        loss = (1.0 - eps) * loss + eps * (log_z - mean_logit)
    return loss
