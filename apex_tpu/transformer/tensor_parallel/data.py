"""Tensor-parallel data broadcast.

Parity with the reference's ``broadcast_data``
(ref: apex/transformer/tensor_parallel/data.py:77-113), which moves the
batch from TP-rank-0 to all TP ranks so every shard of a layer sees the
same tokens.  JAX is single-controller/SPMD: one logical batch array is
*already* visible to every shard, so the broadcast is the identity — what
remains useful is the reference's validation (consistent keys, one dtype)
and the dtype coercion, which are kept so user code ports unchanged.
"""
from __future__ import annotations

from typing import Dict, Sequence

import jax.numpy as jnp


def _check_data_types(keys: Sequence[str], data: Dict, target_dtype) -> None:
    """ref: data.py:17-23 — every broadcast tensor must share one dtype.

    Checked on the *input* values (numpy view) so the outcome does not
    depend on the jax_enable_x64 config silently downcasting 64-bit
    inputs before the comparison."""
    import numpy as np

    for key in keys:
        got = np.asarray(data[key]).dtype
        if got != target_dtype:
            raise ValueError(
                f"{key} has data type {got} which is different than "
                f"{target_dtype}")


def broadcast_data(keys: Sequence[str], data: Dict, dtype) -> Dict:
    """Return ``{key: jnp.asarray(data[key], dtype)}`` for each requested
    key (ref: data.py:77-113).  Size/numel bookkeeping that the reference
    ships over NCCL (ref :86-104) is unnecessary under one controller."""
    missing = [k for k in keys if k not in data]
    if missing:
        raise KeyError(f"broadcast_data: missing keys {missing}")
    _check_data_types(keys, data, jnp.dtype(dtype))
    return {key: jnp.asarray(data[key], dtype) for key in keys}
