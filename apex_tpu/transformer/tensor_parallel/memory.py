"""Preallocated activation memory buffers.

Parity with the reference's ``MemoryBuffer``/``RingMemBuffer``
(ref: apex/transformer/tensor_parallel/memory.py), which hand out views
into one large preallocated CUDA tensor to avoid allocator churn for
checkpointed activations.  On TPU, XLA owns HBM and donation/aliasing
make manual pooling unnecessary for compiled code; this functional
equivalent exists for API parity and for *host-side* staging buffers
(e.g. microbatch assembly), where reuse still saves allocations.
"""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp
import numpy as np


class MemoryBuffer:
    """One flat preallocated buffer handing out reshaped views
    (ref: memory.py — ``allocate``/``get``)."""

    def __init__(self, name: str, numel: int, dtype):
        self.name = name
        self.numel = numel
        self.dtype = jnp.dtype(dtype)
        self.data = np.zeros((numel,), dtype=self.dtype)
        self._start = 0

    def deallocate_all(self):
        self._start = 0

    def is_in_use(self) -> bool:
        return self._start > 0

    def get(self, shape: Sequence[int]):
        """Carve the next view of ``shape`` out of the flat buffer."""
        numel = int(np.prod(shape))
        if self._start + numel > self.numel:
            raise MemoryError(
                f"memory buffer {self.name}: out of space "
                f"({self._start}+{numel} > {self.numel})")
        view = self.data[self._start:self._start + numel].reshape(shape)
        self._start += numel
        return view


class RingMemBuffer:
    """Ring of N full-size MemoryBuffers (ref: memory.py RingMemBuffer:
    each slot is an independent ``numel``-element buffer, and handing out
    a buffer that is still in use is an error, not a silent recycle)."""

    def __init__(self, name: str, num_buffers: int, numel: int, dtype):
        self.num_buffers = num_buffers
        self.buffers = [
            MemoryBuffer(f"{name} {i}", numel, dtype)
            for i in range(num_buffers)]
        self._index = -1

    def get_next_buffer(self) -> MemoryBuffer:
        self._index = (self._index + 1) % self.num_buffers
        buf = self.buffers[self._index]
        if buf.is_in_use():
            raise RuntimeError(
                f"memory buffer {buf.name} is already in use; "
                f"deallocate_all() it before recycling")
        return buf
