"""Tensor-parallel collective mappings — the Megatron collective algebra.

Parity with the reference's autograd Functions
(ref: apex/transformer/tensor_parallel/mappings.py:23-143), expressed over
a named mesh axis for use inside ``jax.shard_map``:

    copy_to_tensor_model_parallel_region     fwd identity   bwd all-reduce
    reduce_from_tensor_model_parallel_region fwd all-reduce bwd identity
    scatter_to_tensor_model_parallel_region  fwd split      bwd all-gather
    gather_from_tensor_model_parallel_region fwd all-gather bwd split

The reference hand-writes each backward with torch.distributed calls
(ref: mappings.py:77-143) because each GPU runs autograd independently.
Under ``shard_map`` with varying-mesh-axes tracking (``check_vma=True``),
JAX's transpose rules derive exactly those backwards from the forward
collectives — reverse-mode AD is linear in cotangents, so the boundary
spec transposition inserts the psum/split the reference writes by hand.
These functions therefore stay plain (no ``custom_vjp``): the documented
fwd/bwd pairing above is what JAX derives, verified by
tests/test_tensor_parallel.py gradient checks.

``gather`` is implemented as a masked psum (pad the local chunk into the
full extent, then all-reduce) rather than ``lax.all_gather``: the result
is *invariant* over the axis, matching the reference's contract that
every rank holds the full tensor — and letting it cross a ``shard_map``
boundary with replicated out_specs.  XLA folds the pad+psum into an
all-gather-shaped collective on ICI.
"""
from __future__ import annotations

import jax
from ..._compat import axis_index, axis_size
import jax.numpy as jnp

from ...parallel_state import TENSOR_AXIS


def copy_to_tensor_model_parallel_region(x, axis_name: str = TENSOR_AXIS):
    """Identity forward; gradients psum over the axis (ref: mappings.py:77-90).

    The psum-in-backward arises from transposition: every shard consumes
    the same ``x``, so the cotangents from all shards sum."""
    del axis_name
    return x


def reduce_from_tensor_model_parallel_region(x,
                                             axis_name: str = TENSOR_AXIS):
    """All-reduce forward; identity backward (ref: mappings.py:93-106)."""
    return jax.lax.psum(x, axis_name)


def scatter_to_tensor_model_parallel_region(x,
                                            axis_name: str = TENSOR_AXIS):
    """Keep this shard's chunk of the last dim (ref: mappings.py:109-122)."""
    size = axis_size(axis_name)
    if x.shape[-1] % size != 0:
        raise ValueError(
            f"last dim {x.shape[-1]} not divisible by axis size {size}")
    chunk = x.shape[-1] // size
    rank = axis_index(axis_name)
    return jax.lax.dynamic_slice_in_dim(x, rank * chunk, chunk,
                                        axis=x.ndim - 1)


def gather_from_tensor_model_parallel_region(x,
                                             axis_name: str = TENSOR_AXIS):
    """All-gather along the last dim; every shard receives the full tensor
    (ref: mappings.py:125-138)."""
    size = axis_size(axis_name)
    rank = axis_index(axis_name)
    chunk = x.shape[-1]
    full_shape = x.shape[:-1] + (chunk * size,)
    start = (0,) * (x.ndim - 1) + (rank * chunk,)
    padded = jax.lax.dynamic_update_slice(
        jnp.zeros(full_shape, x.dtype), x, start)
    return jax.lax.psum(padded, axis_name)
