"""Megatron-style batch samplers.

Parity surface for ``apex/transformer/_data/_batchsampler.py`` (180 LoC;
itself based on Megatron-LM's data_samplers): index-level batch
scheduling that supports mid-training resume (``consumed_samples``),
per-data-parallel-rank sharding, and dynamic local minibatch size (the
rampup-batch-size hook).  No torch dependency: samplers yield plain
index lists a host input pipeline gathers with (numpy arrays,
tf.data, grain, ...).

Single-controller note: under GSPMD the host usually builds the GLOBAL
batch and lets ``jax.device_put`` shard it; pass
``data_parallel_rank=0, data_parallel_size=1`` for that mode, or per-host
values under multi-controller ``jax.distributed``.
"""
from __future__ import annotations

import abc

import numpy as np

__all__ = [
    "MegatronPretrainingSampler",
    "MegatronPretrainingRandomSampler",
]


class _Base(abc.ABC):
    """Base class for Megatron-style batch samplers (ref :16-35)."""

    @abc.abstractmethod
    def __len__(self) -> int:
        ...

    @abc.abstractmethod
    def __iter__(self):
        ...

    @property
    @abc.abstractmethod
    def local_minibatch_size(self) -> int:
        ...


class MegatronPretrainingSampler(_Base):
    """Sequential sampler with resume + DP sharding (ref :38-100)."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int, drop_last: bool = True):
        if total_samples <= 0:
            raise RuntimeError(f"no sample to consume: {total_samples}")
        if consumed_samples >= total_samples:
            raise RuntimeError(
                f"no samples left to consume: {consumed_samples}, "
                f"{total_samples}")
        if local_minibatch_size <= 0:
            raise RuntimeError(
                "local minibatch size must be greater than 0: "
                f"{local_minibatch_size}")
        if data_parallel_size <= 0:
            raise RuntimeError(
                f"data parallel size must be greater than 0: "
                f"{data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise RuntimeError(
                "data_parallel_rank should be smaller than data size: "
                f"{data_parallel_rank}, {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.drop_last = drop_last

    def __len__(self) -> int:
        return self.total_samples

    def get_start_end_idx(self):
        start = self.data_parallel_rank * self.local_minibatch_size
        return start, start + self.local_minibatch_size

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new_size: int) -> None:
        self._local_minibatch_size = new_size
        self.local_minibatch_times_data_parallel_size = (
            new_size * self.data_parallel_size)

    def __iter__(self):
        # NOTE: accumulate the GLOBAL chunk (local * dp_size) before
        # slicing the per-rank window.  The reference accumulates only
        # local_minibatch_size (ref :86-99), which makes every rank > 0
        # slice an empty window — the upstream Megatron-LM original this
        # code derives from accumulates the global chunk, so that is the
        # behavior reproduced here.
        batch = []
        for idx in range(self.consumed_samples, self.total_samples):
            batch.append(idx)
            if len(batch) == self.local_minibatch_times_data_parallel_size:
                start, end = self.get_start_end_idx()
                yield batch[start:end]
                batch = []
        if batch and not self.drop_last:
            start, end = self.get_start_end_idx()
            yield batch[start:end]


class MegatronPretrainingRandomSampler(_Base):
    """Shuffled sampler: per-rank bucket, per-epoch seeded permutation,
    resume via ``consumed_samples`` (ref :102-180)."""

    def __init__(self, total_samples: int, consumed_samples: int,
                 local_minibatch_size: int, data_parallel_rank: int,
                 data_parallel_size: int):
        if total_samples <= 0:
            raise ValueError(
                f"no sample to consume: total_samples of {total_samples}")
        if local_minibatch_size <= 0:
            raise ValueError(
                f"Invalid local_minibatch_size: {local_minibatch_size}")
        if data_parallel_size <= 0:
            raise ValueError(
                f"Invalid data_parallel_size: {data_parallel_size}")
        if data_parallel_rank >= data_parallel_size:
            raise ValueError(
                "data_parallel_rank should be smaller than data parallel "
                f"size: {data_parallel_rank} < {data_parallel_size}")
        self.total_samples = total_samples
        self.consumed_samples = consumed_samples
        self._local_minibatch_size = local_minibatch_size
        self.data_parallel_rank = data_parallel_rank
        self.data_parallel_size = data_parallel_size
        self.local_minibatch_times_data_parallel_size = (
            local_minibatch_size * data_parallel_size)
        self.last_batch_size = (
            total_samples % self.local_minibatch_times_data_parallel_size)

    def __len__(self) -> int:
        return self.total_samples

    @property
    def local_minibatch_size(self) -> int:
        return self._local_minibatch_size

    @local_minibatch_size.setter
    def local_minibatch_size(self, new_size: int) -> None:
        self._local_minibatch_size = new_size
        self.local_minibatch_times_data_parallel_size = (
            new_size * self.data_parallel_size)

    def __iter__(self):
        active_total_samples = self.total_samples - self.last_batch_size
        self.epoch = self.consumed_samples // active_total_samples
        current_epoch_samples = self.consumed_samples % active_total_samples

        bucket_size = (self.total_samples
                       // self.local_minibatch_times_data_parallel_size
                       ) * self.local_minibatch_size
        bucket_offset = current_epoch_samples // self.data_parallel_size
        start_idx = self.data_parallel_rank * bucket_size

        # epoch-seeded permutation (torch.Generator -> numpy Generator)
        rng = np.random.default_rng(self.epoch)
        random_idx = rng.permutation(bucket_size).tolist()
        idx_range = [start_idx + x for x in random_idx[bucket_offset:]]

        batch = []
        # Last batch if not complete will be dropped.
        for idx in idx_range:
            batch.append(idx)
            if len(batch) == self.local_minibatch_size:
                self.consumed_samples += (
                    self.local_minibatch_times_data_parallel_size)
                yield batch
                batch = []
