"""Data scheduling helpers (parity with ``apex/transformer/_data``)."""
from ._batchsampler import (
    MegatronPretrainingRandomSampler,
    MegatronPretrainingSampler,
)

__all__ = [
    "MegatronPretrainingSampler",
    "MegatronPretrainingRandomSampler",
]
