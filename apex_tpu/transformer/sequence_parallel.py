"""Sequence/context parallelism: the long-context scaling axis.

The reference treats sequence length as a LIMIT (fused softmax sk <=
2048, FMHA 512; SURVEY §2.10 "SP/CP: not present"); this framework
treats it as a sharding axis, first-class next to dp/tp/pp:

- :func:`ring_self_attention` — exact attention over a sequence-sharded
  axis via rotating K/V blocks (:mod:`apex_tpu.ops.ring_attention`).
- :func:`ulysses_self_attention` — all-to-all head<->sequence swap, full
  attention on a head subset.
- Megatron-style SP region mappings for the LN/dropout segments between
  TP blocks: :func:`scatter_to_sequence_parallel_region` /
  :func:`gather_from_sequence_parallel_region` /
  :func:`reduce_scatter_to_sequence_parallel_region` — under TP, the
  activations between the column/row-parallel pairs are replicated; SP
  shards them along sequence so LayerNorm+dropout memory scales 1/tp
  and the TP allreduce becomes allgather+reduce-scatter (same bytes,
  less activation memory).

All functions run inside ``shard_map`` over the named axis.
"""
from __future__ import annotations

from typing import Optional

import jax
from .._compat import axis_index, axis_size
import jax.numpy as jnp

from ..mesh_plan import MeshPlan
from ..ops.ring_attention import ring_attention, ulysses_attention
from ..parallel_state import TENSOR_AXIS

from ..parallel_state import SEQUENCE_AXIS  # noqa: F401


# --- SP region mappings ----------------------------------------------------

def scatter_to_sequence_parallel_region(x, axis_name: str = TENSOR_AXIS,
                                        seq_dim: int = 1):
    """Replicated (b, s, h) -> local sequence shard (b, s/P, h): each
    rank keeps its slice (the SP entry scatter)."""
    rank = axis_index(axis_name)
    n = axis_size(axis_name)
    s = x.shape[seq_dim]
    assert s % n == 0, f"sequence {s} not divisible by axis size {n}"
    return jax.lax.dynamic_slice_in_dim(x, rank * (s // n), s // n,
                                        seq_dim)


def gather_from_sequence_parallel_region(x, axis_name: str = TENSOR_AXIS,
                                         seq_dim: int = 1):
    """Local shard (b, s/P, h) -> full sequence (b, s, h) via
    all-gather (the SP->TP boundary gather)."""
    return jax.lax.all_gather(x, axis_name, axis=seq_dim, tiled=True)


def reduce_scatter_to_sequence_parallel_region(
        x, axis_name: str = TENSOR_AXIS, seq_dim: int = 1):
    """Partial sums (b, s, h) on every rank -> reduced local sequence
    shard (b, s/P, h).  This replaces the row-parallel output allreduce
    under SP (allreduce == allgather . reduce_scatter; SP keeps only
    the reduce_scatter half here and the allgather at the next block's
    entry — same total bytes, 1/P activation residency)."""
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=seq_dim,
                                tiled=True)


# --- sequence-parallel attention ------------------------------------------

def ring_self_attention(q, k, v, axis_name: str = SEQUENCE_AXIS,
                        scale: Optional[float] = None,
                        causal: bool = False,
                        use_flash: Optional[bool] = None,
                        dropout_rate: float = 0.0,
                        dropout_seed=None):
    """Exact self-attention with q/k/v sequence-sharded over
    ``axis_name`` (b, h, s_local, d per shard).  ``use_flash=True``
    runs each ring block through the Pallas flash partial — requires
    the enclosing ``shard_map`` to pass ``check_vma=False``.
    ``dropout_rate``/``dropout_seed``: global-mask attention dropout
    (see :func:`apex_tpu.ops.ring_attention.ring_attention`)."""
    return ring_attention(q, k, v, axis_name, scale=scale, causal=causal,
                          use_flash=use_flash,
                          dropout_rate=dropout_rate,
                          dropout_seed=dropout_seed)


class SequenceParallelSelfAttention:
    """Full attention block over a sequence-sharded activation: fused
    QKV projection, ring (or Ulysses) core, output projection — the
    sequence-parallel sibling of
    :class:`apex_tpu.transformer.layers.ParallelSelfAttention`.

    Functional container for shard_map mode (params are an explicit
    pytree; the per-token projections are embarrassingly parallel over
    the sequence shards, so only the attention core communicates):

    >>> attn = SequenceParallelSelfAttention(hidden, heads, causal=True)
    >>> params = attn.init(key)
    >>> y_local = attn.apply(params, x_local)  # inside shard_map,
    ...                                        # x (b, s_local, h)
    """

    def __init__(self, hidden_size: int, num_attention_heads: int,
                 causal: bool = True, mode: str = "ring",
                 axis_name: Optional[str] = SEQUENCE_AXIS,
                 use_flash: Optional[bool] = None,
                 attention_dropout: float = 0.0,
                 plan: Optional[MeshPlan] = None):
        assert hidden_size % num_attention_heads == 0
        assert mode in ("ring", "ulysses")
        if plan is not None:
            sp_axes = plan.axes_of_kind("sequence")
            if len(sp_axes) != 1:
                raise ValueError(
                    f"plan {plan.describe()!r} must carry exactly one "
                    f"sequence-kind axis to drive this layer, got "
                    f"{[a.name for a in sp_axes]}")
            if axis_name not in (None, SEQUENCE_AXIS,
                                 sp_axes[0].name):
                raise ValueError(
                    f"plan names the sequence axis "
                    f"{sp_axes[0].name!r} but axis_name="
                    f"{axis_name!r} was also given")
            axis_name = sp_axes[0].name
        self.hidden_size = hidden_size
        self.num_heads = num_attention_heads
        self.head_dim = hidden_size // num_attention_heads
        self.causal = causal
        self.mode = mode
        self.axis_name = axis_name
        # Pallas cores per shard: legal only under
        # shard_map(check_vma=False) — the caller owns that choice
        self.use_flash = use_flash
        self.attention_dropout = attention_dropout

    def mesh_plan(self, num_shards: int,
                  with_backward: bool = True) -> MeshPlan:
        """This attention's topology contract: ONE ``sequence``-kind
        axis, projections replicated, activations sequence-sharded on
        dim 1, and the mode's collective budget — ring rotates k and v
        once per non-local block (2·(P-1) ppermutes forward; training
        doubles it, the transposed reverse ring), Ulysses swaps
        seq<->heads with one all_to_all per operand + one back
        (4 forward, 8 with the backward)."""
        ax = self.axis_name or SEQUENCE_AXIS
        mult = 2 if with_backward else 1
        if self.mode == "ring":
            budget = {"ppermute": 2 * (num_shards - 1) * mult}
        else:
            budget = {"all_to_all": 4 * mult}
        return MeshPlan.build(
            axes=((ax, num_shards, "sequence"),),
            tensor_specs={
                # qkv/out projections + biases: per-token math,
                # replicated over the sequence shards
                r"\['(qkv|out)_(kernel|bias)'\]": (),
            },
            collective_budget=budget)

    def init(self, key) -> dict:
        k1, k2 = jax.random.split(key)
        h = self.hidden_size
        s = (1.0 / h) ** 0.5
        return {
            "qkv_kernel": jax.random.normal(k1, (h, 3 * h),
                                            jnp.float32) * s,
            "qkv_bias": jnp.zeros((3 * h,), jnp.float32),
            "out_kernel": jax.random.normal(k2, (h, h),
                                            jnp.float32) * s,
            "out_bias": jnp.zeros((h,), jnp.float32),
        }

    def apply(self, params: dict, x: jnp.ndarray,
              dropout_seed=None) -> jnp.ndarray:
        b, s_local, h = x.shape
        nh, d = self.num_heads, self.head_dim
        rate = self.attention_dropout if dropout_seed is not None \
            else 0.0
        qkv = x @ params["qkv_kernel"] + params["qkv_bias"]
        qkv = qkv.reshape(b, s_local, 3, nh, d)
        # (b, nh, s_local, d)
        q, k, v = (jnp.transpose(qkv[:, :, i], (0, 2, 1, 3))
                   for i in range(3))
        if self.axis_name is None:
            # dense single-device path: the canonical unfused reference
            # (fp32-accumulating, shared with the flash/ring parity
            # tests)
            from ..ops.flash_attention import mha_reference

            assert rate == 0.0, (
                "dense reference path has no dropout; use an SP mode")
            ctx = mha_reference(q, k, v, causal=self.causal)
        elif self.mode == "ring":
            ctx = ring_attention(q, k, v, self.axis_name,
                                 causal=self.causal,
                                 use_flash=self.use_flash,
                                 dropout_rate=rate,
                                 dropout_seed=dropout_seed)
        else:
            ctx = ulysses_attention(q, k, v, self.axis_name,
                                    causal=self.causal,
                                    use_flash=self.use_flash,
                                    dropout_rate=rate,
                                    dropout_seed=dropout_seed)
        ctx = jnp.transpose(ctx, (0, 2, 1, 3)).reshape(b, s_local, h)
        return ctx @ params["out_kernel"] + params["out_bias"]


def ulysses_self_attention(q, k, v, axis_name: str = SEQUENCE_AXIS,
                           scale: Optional[float] = None,
                           causal: bool = False,
                           use_flash: Optional[bool] = None,
                           dropout_rate: float = 0.0,
                           dropout_seed=None):
    return ulysses_attention(q, k, v, axis_name, scale=scale,
                             causal=causal, use_flash=use_flash,
                             dropout_rate=dropout_rate,
                             dropout_seed=dropout_seed)


class SequenceParallelTransformerLayer:
    """Pre-LN transformer layer over sequence-sharded activations: the
    end-to-end context-parallel building block.

    LayerNorm, MLP, and residuals are per-token (embarrassingly
    parallel over the sequence shards); only the attention core
    communicates (ring K/V rotation or Ulysses all-to-all).  The
    sequence-parallel sibling of
    :class:`apex_tpu.transformer.layers.ParallelTransformerLayer`, same
    pre-LN wiring (LN -> attn -> residual -> LN -> MLP -> residual, LN
    math in fp32).  ``axis_name=None`` runs the dense single-device
    reference for parity tests.
    """

    def __init__(self, hidden_size: int, num_attention_heads: int,
                 ffn_hidden_size: Optional[int] = None,
                 causal: bool = True, mode: str = "ring",
                 layernorm_epsilon: float = 1e-5,
                 axis_name: Optional[str] = SEQUENCE_AXIS,
                 use_flash: Optional[bool] = None,
                 attention_dropout: float = 0.0,
                 plan: Optional[MeshPlan] = None):
        self.hidden_size = hidden_size
        self.ffn_hidden_size = ffn_hidden_size or 4 * hidden_size
        self.eps = layernorm_epsilon
        self.attn = SequenceParallelSelfAttention(
            hidden_size, num_attention_heads, causal=causal, mode=mode,
            axis_name=axis_name, use_flash=use_flash,
            attention_dropout=attention_dropout, plan=plan)

    def mesh_plan(self, num_shards: int,
                  with_backward: bool = True) -> MeshPlan:
        """The full layer's contract = the attention core's (LN, MLP,
        and residuals are per-token — they add parameters but no
        collectives), extended with the layer's own replicated-param
        declarations."""
        return self.attn.mesh_plan(
            num_shards, with_backward=with_backward).with_specs({
                r"\['(ln[12]_(weight|bias)|mlp_[wb][io])'\]": (),
            })

    def init(self, key) -> dict:
        h, f = self.hidden_size, self.ffn_hidden_size
        ka, k1, k2 = jax.random.split(key, 3)
        return {
            "ln1_weight": jnp.ones((h,), jnp.float32),
            "ln1_bias": jnp.zeros((h,), jnp.float32),
            "attention": self.attn.init(ka),
            "ln2_weight": jnp.ones((h,), jnp.float32),
            "ln2_bias": jnp.zeros((h,), jnp.float32),
            "mlp_wi": jax.random.normal(k1, (h, f), jnp.float32)
            * (2.0 / h) ** 0.5,
            "mlp_bi": jnp.zeros((f,), jnp.float32),
            "mlp_wo": jax.random.normal(k2, (f, h), jnp.float32)
            * (1.0 / f) ** 0.5,
            "mlp_bo": jnp.zeros((h,), jnp.float32),
        }

    def apply(self, params: dict, x: jnp.ndarray,
              dropout_seed=None) -> jnp.ndarray:
        from ..ops.layer_norm import layer_norm

        # layer_norm returns x.dtype (fp32 internal math); both residual
        # branches cast back so a bf16 residual stream stays bf16 (the
        # ParallelTransformerLayer convention, layers.py).
        h = layer_norm(x, params["ln1_weight"], params["ln1_bias"],
                       eps=self.eps)
        x = x + self.attn.apply(params["attention"], h,
                                dropout_seed=dropout_seed
                                ).astype(x.dtype)
        h = layer_norm(x, params["ln2_weight"], params["ln2_bias"],
                       eps=self.eps)
        m = jax.nn.gelu(h @ params["mlp_wi"] + params["mlp_bi"])
        return x + (m @ params["mlp_wo"] + params["mlp_bo"]).astype(
            x.dtype)
