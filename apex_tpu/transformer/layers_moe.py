"""MoE transformer building blocks (flax / GSPMD mode).

Complements :mod:`apex_tpu.transformer.expert_parallel` (the explicit
shard_map layer): here the MoE FFN is a flax module whose expert weights
carry a leading ``(num_experts, ...)`` axis — under pjit, annotate that
axis with the ``expert`` mesh axis (``jax.sharding``) and XLA inserts
the all-to-alls; on one device it runs dense.  Dispatch rides the fused
routing path (:mod:`apex_tpu.ops.moe_routing`: softmax -> top-1 ->
capacity slotting -> scatter, static shapes, capacity drops) in its jnp
form — plain gather/scatter algebra GSPMD partitions cleanly, without
the legacy formulation's ``(T, E, capacity)`` one-hot dispatch tensor.
``APEX_TPU_MOE_FUSED_DISPATCH=0`` restores the one-hot einsum
formulation (bit-identical routing decisions either way).

The reference has no MoE (SURVEY §2.10); this is capability beyond it.
"""
from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..analysis.flags import flag_bool
from ..ops.moe_routing import moe_combine, moe_route_dispatch
from .enums import AttnMaskType
from .expert_parallel import _dispatch_indices, top1_router
from .layers import Dtype, ParallelTransformerLayer


class MoEMLP(nn.Module):
    """Switch-style MoE FFN, einsum-dispatch form.

    Input (b, s, h) -> output (b, s, h) plus the load-balancing
    auxiliary loss (collect it into the objective scaled by ~1e-2,
    Switch Transformer sec. 2.2).  Expert matmuls run in ``dtype``
    (bf16 for mixed precision) with fp32 accumulation; the router and
    gate stay fp32 as routing is numerically sensitive.
    """

    hidden_size: int
    ffn_hidden_size: int
    num_experts: int
    capacity_factor: float = 1.25
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        b, s, h = x.shape
        e, f = self.num_experts, self.ffn_hidden_size
        cdt = self.dtype
        tokens = x.reshape(b * s, h)
        T = b * s
        capacity = max(1, int(self.capacity_factor * T / e))

        router_w = self.param("router", nn.initializers.normal(0.02),
                              (h, e), jnp.float32)
        wi = self.param("wi", nn.initializers.variance_scaling(
            2.0, "fan_in", "normal"), (e, h, f), jnp.float32)
        wo = self.param("wo", nn.initializers.variance_scaling(
            2.0, "fan_in", "normal"), (e, f, h), jnp.float32)

        if flag_bool("APEX_TPU_MOE_FUSED_DISPATCH"):
            # fused routing front (jnp-twin form: XLA-native gather/
            # scatter that GSPMD partitions — a Pallas custom call
            # would wall off propagation under pjit); no (T, e,
            # capacity) dispatch tensor is ever built
            rd = moe_route_dispatch(
                tokens.astype(cdt),
                tokens.astype(jnp.float32) @ router_w,
                capacity=capacity, backend="xla")
            hmid = jax.nn.gelu(jnp.einsum(
                "ech,ehf->ecf", rd.buf.astype(cdt), wi.astype(cdt),
                preferred_element_type=jnp.float32))
            out = jnp.einsum("ecf,efh->ech", hmid.astype(cdt),
                             wo.astype(cdt),
                             preferred_element_type=jnp.float32)
            y = moe_combine(out, rd.expert_index, rd.slot, rd.keep,
                            rd.gate, out_dtype=jnp.float32)
            return (y.reshape(b, s, h).astype(x.dtype),
                    rd.load_balancing_loss)

        # legacy one-hot einsum formulation (GShard): (T, e, capacity)
        router = top1_router(tokens.astype(jnp.float32) @ router_w)
        slot, keep = _dispatch_indices(router.expert_index, e, capacity)

        disp = (jax.nn.one_hot(router.expert_index, e)[:, :, None]
                * jax.nn.one_hot(slot, capacity)[:, None, :]
                * keep[:, None, None]).astype(cdt)
        buf = jnp.einsum("th,tec->ech", tokens.astype(cdt), disp,
                         preferred_element_type=jnp.float32)
        hmid = jax.nn.gelu(jnp.einsum(
            "ech,ehf->ecf", buf.astype(cdt), wi.astype(cdt),
            preferred_element_type=jnp.float32))
        out = jnp.einsum("ecf,efh->ech", hmid.astype(cdt),
                         wo.astype(cdt),
                         preferred_element_type=jnp.float32)
        gate = jnp.where(keep, router.gate, 0.0)
        y = jnp.einsum("ech,tec,t->th", out,
                       disp.astype(jnp.float32), gate)
        return (y.reshape(b, s, h).astype(x.dtype),
                router.load_balancing_loss)


def MoEParallelTransformerLayer(hidden_size: int,
                                num_attention_heads: int,
                                num_experts: int,
                                ffn_hidden_size: Optional[int] = None,
                                capacity_factor: float = 1.25,
                                attn_mask_type: AttnMaskType =
                                AttnMaskType.causal,
                                attention_dropout: float = 0.1,
                                hidden_dropout: float = 0.1,
                                use_flash: bool = True,
                                layernorm_epsilon: float = 1e-5,
                                dtype: Dtype = jnp.float32,
                                axis_name: Optional[str] = None,
                                **kw) -> ParallelTransformerLayer:
    """Pre-LN transformer layer with an MoE FFN — the standard
    :class:`ParallelTransformerLayer` with its MLP swapped for
    :class:`MoEMLP` via the ``mlp_module`` hook (no duplicated
    LN/attention/residual wiring).  ``__call__`` returns
    ``(y, aux_loss)``.  TP attention composes with expert-sharded MoE
    weights under GSPMD (annotate attention weights on 'tensor', expert
    weights on 'expert')."""
    # NOTE: inside the layer this module is bound as attribute
    # ``mlp_module`` — that is its name in the param tree.
    moe = MoEMLP(hidden_size, ffn_hidden_size or 4 * hidden_size,
                 num_experts, capacity_factor=capacity_factor,
                 dtype=dtype)
    return ParallelTransformerLayer(
        hidden_size=hidden_size,
        num_attention_heads=num_attention_heads,
        ffn_hidden_size=ffn_hidden_size,
        attn_mask_type=attn_mask_type,
        attention_dropout=attention_dropout,
        hidden_dropout=hidden_dropout, use_flash=use_flash,
        layernorm_epsilon=layernorm_epsilon, dtype=dtype,
        axis_name=axis_name, mlp_module=moe, **kw)
