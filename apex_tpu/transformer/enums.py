"""Transformer enums (ref: apex/transformer/enums.py:1-30)."""
import enum


class LayerType(enum.Enum):
    encoder = 1
    decoder = 2


class AttnType(enum.Enum):
    self_attn = 1
    cross_attn = 2


class AttnMaskType(enum.Enum):
    padding = 1
    causal = 2
