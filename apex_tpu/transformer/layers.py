"""Parallel transformer building blocks: MLP, self-attention, layer, stack.

Library form of the model components the reference assembles in its
standalone test models (ref: apex/transformer/testing/standalone_gpt.py —
ParallelMLP, ParallelAttention/CoreAttention, ParallelTransformerLayer,
ParallelTransformer), built from the tensor-parallel layers and the
Pallas fused ops:

* MLP = ColumnParallelLinear(gather_output=False) -> gelu ->
  RowParallelLinear(input_is_parallel=True) — the canonical Megatron
  pairing (one psum per MLP).
* Attention = column-parallel fused QKV (heads sharded over the tensor
  axis), core attention (Pallas flash attention for the causal path,
  FusedScaleMaskSoftmax fallback for explicit masks), row-parallel
  output projection.
* LayerNorms run in fp32 regardless of compute dtype (the reference's
  MixedFusedLayerNorm contract,
  ref: apex/normalization/fused_layer_norm.py:202-218).

Dropout follows the reference's RNG domains
(ref: apex/transformer/tensor_parallel/random.py:193-224): attention
dropout draws from a key folded with the tensor-parallel rank (sharded
heads need independent masks); hidden dropout after the row-parallel
psum uses the unfolded key (activations are replicated across the tensor
axis, so the mask must be too).

``axis_name`` selects explicit shard_map mode ('tensor') or GSPMD mode
(None), exactly as in :mod:`.tensor_parallel.layers`.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
from .._compat import axis_size
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

from ..normalization import FusedLayerNorm
from ..ops.flash_attention import (dropout_seed_from_key,
                                   flash_attention_e)
from .enums import AttnMaskType
from .functional.fused_softmax import FusedScaleMaskSoftmax
from .tensor_parallel.layers import (ColumnParallelLinear,
                                     RowParallelLinear)
from .tensor_parallel.random import model_parallel_rng_key
from .tensor_parallel.utils import divide

Dtype = Any


def _maybe_axis_size(axis_name: Optional[str]) -> int:
    return 1 if axis_name is None else axis_size(axis_name)


class ParallelMLP(nn.Module):
    """h -> ffn_hidden -> h with tensor-parallel split on the ffn dim
    (ref: standalone_gpt.py ParallelMLP)."""

    hidden_size: int
    ffn_hidden_size: Optional[int] = None
    activation: Callable = jax.nn.gelu
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x):
        ffn = self.ffn_hidden_size or 4 * self.hidden_size
        h = ColumnParallelLinear(self.hidden_size, ffn, gather_output=False,
                                 dtype=self.dtype, axis_name=self.axis_name,
                                 name="dense_h_to_4h")(x)
        # Tag the wide intermediates so the "all_but_ffn_wide" remat
        # policy can drop exactly these (no-op under other policies).
        h = checkpoint_name(h, "ffn_wide")
        h = checkpoint_name(self.activation(h), "ffn_wide")
        return RowParallelLinear(ffn, self.hidden_size,
                                 input_is_parallel=True, dtype=self.dtype,
                                 axis_name=self.axis_name,
                                 name="dense_4h_to_h")(h)


class ParallelSelfAttention(nn.Module):
    """Multi-head self-attention with heads sharded over the tensor axis
    (ref: standalone_gpt.py ParallelAttention + CoreAttention).

    ``use_flash`` routes the causal no-explicit-mask path through the
    Pallas flash attention kernel (supersedes the reference's fmhalib /
    fast_multihead_attn extensions); otherwise scores materialize
    [b, heads, sq, sk] through FusedScaleMaskSoftmax, the reference's
    core-attention structure.
    """

    hidden_size: int
    num_attention_heads: int
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    attention_dropout: float = 0.1
    use_flash: bool = True
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True,
                 key_padding_mask=None):
        b, s, _ = x.shape
        world = _maybe_axis_size(self.axis_name)
        heads_local = divide(self.num_attention_heads, world)
        head_dim = divide(self.hidden_size, self.num_attention_heads)

        qkv = ColumnParallelLinear(self.hidden_size, 3 * self.hidden_size,
                                   gather_output=False, dtype=self.dtype,
                                   axis_name=self.axis_name,
                                   name="query_key_value")(x)
        qkv = qkv.reshape(b, s, heads_local, 3 * head_dim)

        causal = self.attn_mask_type == AttnMaskType.causal
        scale = head_dim ** -0.5
        if key_padding_mask is not None and attention_mask is not None:
            raise ValueError(
                "pass either attention_mask or key_padding_mask, not "
                "both (fold padding into the attention_mask yourself)")
        # flash handles causal and/or key-padding masks; an arbitrary
        # (b, 1, sq, sk) attention_mask takes the materializing path.
        if self.use_flash and attention_mask is None:
            # E-layout entry: consumes qkv's native (b, s, h, 3d) lane
            # order and emits (b, s, h*d) — the whole attention boundary
            # carries no relayout copies (measured ~14/16 ms/step of
            # bf16[b,h,s,d] transposes at GPT-345M/BERT-large on the
            # per-tensor entry; a packed (3,b,h,s,d) route was also
            # tried and LOST ~5 ms/step to its 5-D transpose).  Falls
            # back to the transposing path internally when the shape
            # doesn't qualify (see flash_e_supported).  Attention
            # dropout runs IN-KERNEL (counter-hash keep mask, the
            # reference's fused-MHA philox role) — training configs
            # with dropout keep the zero-relayout route.
            drop = 0.0
            seed = None
            if not deterministic and self.attention_dropout > 0.0:
                key = self.make_rng("dropout")
                if self.axis_name is not None:
                    key = model_parallel_rng_key(key, self.axis_name)
                seed = dropout_seed_from_key(key)
                drop = self.attention_dropout
            ctx = flash_attention_e(qkv, scale=scale, causal=causal,
                                    kv_mask=key_padding_mask,
                                    dropout_rate=drop,
                                    dropout_seed=seed)
        else:
            q, k, v = jnp.split(qkv, 3, axis=-1)
            # (b, heads, s, d)
            q = q.transpose(0, 2, 1, 3)
            k = k.transpose(0, 2, 1, 3)
            v = v.transpose(0, 2, 1, 3)

            softmax_mask_type = self.attn_mask_type
            if key_padding_mask is not None:
                # fold padding keys (and, for causal models, the
                # triangle — the causal-type softmax ignores its mask
                # argument) into one padding-type mask
                # (True = masked, the FusedScaleMaskSoftmax convention)
                kmask = ~key_padding_mask.astype(bool)[:, None, None, :]
                if causal:
                    kmask = kmask | ~jnp.tril(jnp.ones(
                        (s, key_padding_mask.shape[-1]), bool))[None,
                                                                None]
                attention_mask = jnp.broadcast_to(
                    kmask, (b, 1, s, key_padding_mask.shape[-1]))
                softmax_mask_type = AttnMaskType.padding
            scores = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                                preferred_element_type=jnp.float32)
            softmax = FusedScaleMaskSoftmax(
                input_in_fp16=self.dtype == jnp.float16,
                input_in_bf16=self.dtype == jnp.bfloat16,
                attn_mask_type=softmax_mask_type,
                scaled_masked_softmax_fusion=True,
                mask_func=None, softmax_in_fp32=True, scale=scale)
            # feed the fp32 scores straight in: the softmax is fp32
            # internally anyway, and the former scores.astype(dtype)
            # round-tripped the MXU's fp32 accumulate through bf16 —
            # a silent re-promotion on entry (APX602) plus a backward
            # convert pair, for strictly worse precision; probs are
            # cast once below, where the V matmul wants model dtype
            probs = softmax(scores, attention_mask)
            if not deterministic and self.attention_dropout > 0.0:
                key = self.make_rng("dropout")
                if self.axis_name is not None:
                    # sharded heads draw independent masks per TP rank
                    key = model_parallel_rng_key(key, self.axis_name)
                keep = jax.random.bernoulli(
                    key, 1.0 - self.attention_dropout, probs.shape)
                probs = jnp.where(keep,
                                  probs / (1.0 - self.attention_dropout),
                                  jnp.zeros((), probs.dtype))
            ctx = jnp.einsum("bhqk,bhkd->bhqd", probs.astype(self.dtype),
                             v)
            ctx = ctx.transpose(0, 2, 1, 3).reshape(
                b, s, heads_local * head_dim)
        return RowParallelLinear(self.hidden_size, self.hidden_size,
                                 input_is_parallel=True, dtype=self.dtype,
                                 axis_name=self.axis_name, name="dense")(ctx)


class ParallelTransformerLayer(nn.Module):
    """Pre-LN transformer layer (ref: standalone_gpt.py
    ParallelTransformerLayer): LN -> attention -> residual -> LN -> MLP
    -> residual, with fp32 layer norms and hidden dropout applied on the
    replicated (post-psum) activations."""

    hidden_size: int
    num_attention_heads: int
    ffn_hidden_size: Optional[int] = None
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    attention_dropout: float = 0.1
    hidden_dropout: float = 0.1
    use_flash: bool = True
    layernorm_epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None
    # Optional substitute for the dense ParallelMLP (e.g. an MoE FFN,
    # see layers_moe.MoEParallelTransformerLayer).  May return either
    # the activation or an (activation, aux_loss) pair; the layer's
    # return mirrors it.
    mlp_module: Optional[Any] = None

    def _dropout(self, x, deterministic):
        if deterministic or self.hidden_dropout == 0.0:
            return x
        # replicated across the tensor axis -> unfolded key (same mask on
        # every TP rank, the reference's get_cuda_rng_tracker-free path)
        key = self.make_rng("dropout")
        keep = jax.random.bernoulli(key, 1.0 - self.hidden_dropout, x.shape)
        return jnp.where(keep, x / (1.0 - self.hidden_dropout),
                         jnp.zeros((), x.dtype))

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True,
                 key_padding_mask=None):
        ln1 = FusedLayerNorm(self.hidden_size,
                             eps=self.layernorm_epsilon,
                             name="input_layernorm")
        attn_out = ParallelSelfAttention(
            self.hidden_size, self.num_attention_heads,
            attn_mask_type=self.attn_mask_type,
            attention_dropout=self.attention_dropout,
            use_flash=self.use_flash, dtype=self.dtype,
            axis_name=self.axis_name, name="self_attention")(
                ln1(x).astype(self.dtype), attention_mask, deterministic,
                key_padding_mask)
        x = x + self._dropout(attn_out, deterministic).astype(x.dtype)
        ln2 = FusedLayerNorm(self.hidden_size,
                             eps=self.layernorm_epsilon,
                             name="post_attention_layernorm")
        mlp = self.mlp_module if self.mlp_module is not None else \
            ParallelMLP(self.hidden_size, self.ffn_hidden_size,
                        dtype=self.dtype, axis_name=self.axis_name,
                        name="mlp")
        out = mlp(ln2(x).astype(self.dtype))
        if isinstance(out, tuple):
            mlp_out, aux = out
            return (x + self._dropout(mlp_out,
                                      deterministic).astype(x.dtype),
                    aux)
        return x + self._dropout(out, deterministic).astype(x.dtype)


class ParallelTransformer(nn.Module):
    """Stack of layers (ref: standalone_gpt.py ParallelTransformer).
    ``checkpoint_activations`` remats each layer (the reference's
    activation checkpointing, ref: tensor_parallel/random.py:224-290)."""

    num_layers: int
    hidden_size: int
    num_attention_heads: int
    ffn_hidden_size: Optional[int] = None
    attn_mask_type: AttnMaskType = AttnMaskType.causal
    attention_dropout: float = 0.1
    hidden_dropout: float = 0.1
    use_flash: bool = True
    checkpoint_activations: bool = False
    # Remat policy when checkpoint_activations is on: a key of
    # tensor_parallel.random.CHECKPOINT_POLICIES ("full" recomputes
    # everything; "dots"/"dots_with_no_batch_dims" keep matmul outputs
    # and recompute only the cheap elementwise tail — the usual
    # memory/compute sweet spot on TPU).
    checkpoint_policy: str = "full"
    layernorm_epsilon: float = 1e-5
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, attention_mask=None, deterministic: bool = True,
                 key_padding_mask=None):
        layer_cls = ParallelTransformerLayer
        if self.checkpoint_activations:
            from .tensor_parallel.random import CHECKPOINT_POLICIES
            if self.checkpoint_policy not in CHECKPOINT_POLICIES:
                raise ValueError(
                    f"unknown checkpoint_policy "
                    f"{self.checkpoint_policy!r}; expected one of "
                    f"{sorted(CHECKPOINT_POLICIES)}")
            layer_cls = nn.checkpoint(
                ParallelTransformerLayer, static_argnums=(3,),
                policy=CHECKPOINT_POLICIES[self.checkpoint_policy])
        for i in range(self.num_layers):
            x = layer_cls(self.hidden_size, self.num_attention_heads,
                          ffn_hidden_size=self.ffn_hidden_size,
                          attn_mask_type=self.attn_mask_type,
                          attention_dropout=self.attention_dropout,
                          hidden_dropout=self.hidden_dropout,
                          use_flash=self.use_flash,
                          layernorm_epsilon=self.layernorm_epsilon,
                          dtype=self.dtype, axis_name=self.axis_name,
                          name=f"layer_{i}")(x, attention_mask,
                                             deterministic,
                                             key_padding_mask)
        return FusedLayerNorm(self.hidden_size,
                              eps=self.layernorm_epsilon,
                              name="final_layernorm")(x).astype(self.dtype)
