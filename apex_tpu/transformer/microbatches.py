"""Microbatch calculators: constant and ramped global batch sizes.

Parity with the reference (ref: apex/transformer/microbatches.py:21-172):
the calculator owns the (global_batch_size, micro_batch_size,
data_parallel_size) arithmetic and, for the ramp-up variant, the
piecewise-linear growth of the global batch as samples are consumed.
Pure host-side Python — these values are *static* per compiled step on
TPU (a change of num_microbatches retraces the train step, which is the
XLA-correct behavior: microbatch count is a structural property of the
pipeline schedule, not a traced value).
"""
from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional, Sequence


def build_num_microbatches_calculator(
    rank: int,
    rampup_batch_size: Optional[Sequence[int]],
    global_batch_size: int,
    micro_batch_size: int,
    data_parallel_size: int,
):
    """ref: microbatches.py:21-65 — selects constant vs ramp-up."""
    if rampup_batch_size is None:
        calculator = ConstantNumMicroBatches(
            global_batch_size, micro_batch_size, data_parallel_size)
        if rank == 0:
            print(f"setting number of micro-batches to constant "
                  f"{calculator.get()}", flush=True)
        return calculator
    if len(rampup_batch_size) != 3:
        raise ValueError(
            "expected the following format: --rampup-batch-size "
            "<start batch size> <batch size increment> "
            "<ramp-up samples>")
    start, increment, samples = map(int, rampup_batch_size)
    if rank == 0:
        print(f"will use batch size rampup starting from global batch "
              f"size {start} to global batch size {global_batch_size} "
              f"with batch size increments {increment} over {samples} "
              f"samples.", flush=True)
    return RampupBatchsizeNumMicroBatches(
        start, increment, samples, global_batch_size, micro_batch_size,
        data_parallel_size)


class NumMicroBatchesCalculator(ABC):
    """ref: microbatches.py:68-82."""

    def __init__(self):
        self.num_micro_batches: Optional[int] = None
        self.current_global_batch_size: Optional[int] = None

    def get(self) -> int:
        return self.num_micro_batches

    def get_current_global_batch_size(self) -> int:
        return self.current_global_batch_size

    @abstractmethod
    def update(self, consumed_samples, consistency_check):
        ...


class ConstantNumMicroBatches(NumMicroBatchesCalculator):
    """ref: microbatches.py:84-99."""

    def __init__(self, global_batch_size: int, micro_batch_size: int,
                 data_parallel_size: int):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        micro_batch_times_dp = micro_batch_size * data_parallel_size
        if global_batch_size % micro_batch_times_dp != 0:
            raise ValueError(
                f"global batch size ({global_batch_size}) is not divisible "
                f"by micro batch size ({micro_batch_size}) times data "
                f"parallel size ({data_parallel_size})")
        self.num_micro_batches = global_batch_size // micro_batch_times_dp
        if self.num_micro_batches < 1:
            raise ValueError("number of microbatches must be at least 1")
        self.current_global_batch_size = global_batch_size

    def update(self, consumed_samples, consistency_check):
        pass


class RampupBatchsizeNumMicroBatches(NumMicroBatchesCalculator):
    """Piecewise-linear global-batch ramp (ref: microbatches.py:101-172)."""

    def __init__(self, start_batch_size: int, batch_size_increment: int,
                 ramup_samples: int, global_batch_size: int,
                 micro_batch_size: int, data_parallel_size: int):
        super().__init__()
        self.micro_batch_size = micro_batch_size
        self.data_parallel_size = data_parallel_size
        self.micro_batch_times_data_parallel_size = (
            micro_batch_size * data_parallel_size)
        if self.micro_batch_times_data_parallel_size <= 0:
            raise ValueError("micro batch size * dp size must be positive")
        if start_batch_size <= 0:
            raise ValueError("start batch size must be positive")
        self.start_batch_size = start_batch_size
        if global_batch_size <= 0:
            raise ValueError("global batch size must be positive")
        self.global_batch_size = global_batch_size
        diff_batch_size = self.global_batch_size - self.start_batch_size
        if diff_batch_size < 0:
            raise ValueError(
                "expected global batch size to be greater than or equal to "
                "start batch size")
        if batch_size_increment <= 0:
            raise ValueError("batch size increment must be positive")
        self.batch_size_increment = batch_size_increment
        if diff_batch_size % batch_size_increment != 0:
            raise ValueError(
                f"expected global batch size interval ({diff_batch_size}) "
                f"to be divisible by global batch size increment "
                f"({batch_size_increment})")
        num_increments = diff_batch_size // self.batch_size_increment
        self.ramup_samples = ramup_samples
        if self.ramup_samples < 0:
            raise ValueError("ramp-up samples must be non-negative")
        self.rampup_samples_per_increment = (
            self.ramup_samples / num_increments if num_increments > 0
            else 0)
        self.update(0, False)

    def update(self, consumed_samples: int, consistency_check: bool):
        """ref: microbatches.py:155-172."""
        if consumed_samples > self.ramup_samples or \
                self.rampup_samples_per_increment == 0:
            self.current_global_batch_size = self.global_batch_size
        else:
            steps = int(consumed_samples /
                        self.rampup_samples_per_increment)
            self.current_global_batch_size = (
                self.start_batch_size + steps * self.batch_size_increment)
            self.current_global_batch_size = min(
                self.current_global_batch_size, self.global_batch_size)
        if consistency_check and (
                self.current_global_batch_size %
                self.micro_batch_times_data_parallel_size != 0):
            raise ValueError(
                f"current global batch size "
                f"({self.current_global_batch_size}) is not divisible by "
                f"micro-batch-size ({self.micro_batch_size}) times data "
                f"parallel size ({self.data_parallel_size})")
        self.num_micro_batches = (
            self.current_global_batch_size //
            self.micro_batch_times_data_parallel_size)
