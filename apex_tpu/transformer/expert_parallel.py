"""Expert parallelism: Switch-style MoE with all-to-all token dispatch.

The reference has no MoE (SURVEY §2.10: "EP: not present anywhere");
this module completes the parallelism alphabet (dp/tp/pp/sp/**ep**) the
framework's mesh registry reserves.  Design follows Switch Transformer
(Fedus et al. 2021) / GShard dispatch algebra, TPU-first:

- experts are sharded over a mesh axis (one or more experts per shard);
- a top-1 router assigns each token an expert and a gate probability;
- tokens are packed into a fixed-capacity ``(experts, capacity, h)``
  dispatch buffer (static shapes — XLA requirement; overflow tokens are
  dropped, the standard capacity-factor contract) and exchanged with
  ``all_to_all`` over ICI;
- the exchange is **overlapped** (ISSUE-19): the buffer is chunked
  along capacity (``APEX_TPU_MOE_A2A_CHUNKS``, default 2) and chunk
  ``i+1``'s all_to_all is double-buffered against chunk ``i``'s expert
  matmul, so dispatch latency hides behind compute and the APX704
  overlap advisory goes quiet; ``a2a_chunks=1`` restores the legacy
  single-shot exchange (and the advisory — the un-overlapped trace is
  kept as the regression fixture);
- routing + slotting + the buffer scatter run through the fused Pallas
  kernel (:mod:`apex_tpu.ops.moe_routing`, jnp twin off TPU) when
  ``APEX_TPU_MOE_FUSED_DISPATCH`` is on (default) — bit-identical
  keep/slot decisions either way;
- the combine scatter multiplies by the gate so router gradients flow.

Everything runs inside ``shard_map`` over ``axis_name``; capacity math
is per-shard static.
"""
from __future__ import annotations

from typing import Callable, List, NamedTuple, Optional, Tuple

import jax
from .._compat import axis_size
import jax.numpy as jnp

from ..analysis.flags import flag_bool, flag_int
from ..mesh_plan import MeshPlan
from ..ops.moe_routing import moe_combine, moe_route_dispatch
from ..parallel_state import EXPERT_AXIS  # noqa: F401


class RouterOutput(NamedTuple):
    expert_index: jnp.ndarray   # (T,) int32 chosen expert per token
    gate: jnp.ndarray           # (T,) f32 chosen-expert probability
    load_balancing_loss: jnp.ndarray  # scalar aux loss (Switch eq. 4)


def top1_router(logits: jnp.ndarray) -> RouterOutput:
    """Top-1 gating with the Switch load-balancing auxiliary loss."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=1)[:, 0]
    num_experts = logits.shape[-1]
    # fraction of tokens per expert x mean router prob per expert
    frac = jnp.mean(
        jax.nn.one_hot(idx, num_experts, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return RouterOutput(idx.astype(jnp.int32), gate, aux)


class Top2RouterOutput(NamedTuple):
    expert_index: jnp.ndarray   # (2, T) int32 chosen experts per token
    gate: jnp.ndarray           # (2, T) f32 normalized gates
    load_balancing_loss: jnp.ndarray  # scalar aux loss


def top2_router(logits: jnp.ndarray,
                second_policy: str = "all",
                rng: Optional[jax.Array] = None) -> Top2RouterOutput:
    """Top-2 gating with the GShard algebra the module docstring cites
    (Lepikhin et al. 2020, eq. for Algorithm 1): each token routes to
    its two highest-probability experts, gates renormalized over the
    pair; the auxiliary loss is the top-1 fraction x mean-prob product
    (the differentiable load estimator, GShard l_aux).

    ``second_policy``: ``"all"`` always keeps the second expert;
    ``"random"`` keeps it with probability ``min(1, 2 * gate2)`` (the
    GShard Algorithm-1 dispatch-saving trick: confident-second tokens
    always dispatch, marginal ones dispatch proportionally, and E[kept
    dispatches] halves at the uniform-gate worst case).  ``rng`` is
    required for "random" — the draw is a pure function of the key, so
    the policy stays deterministic per key.  A dropped second choice
    carries gate 0, which :func:`moe_dispatch_combine` treats as
    "do not dispatch": it claims NO capacity slot (the saving) and
    contributes nothing to the combine.
    """
    if second_policy not in ("all", "random"):
        raise ValueError(
            f"second_policy must be 'all'|'random', got "
            f"{second_policy!r}")
    if second_policy == "random" and rng is None:
        raise ValueError("second_policy='random' requires rng")
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    num_experts = logits.shape[-1]
    idx1 = jnp.argmax(probs, axis=-1)
    gate1 = jnp.take_along_axis(probs, idx1[:, None], axis=1)[:, 0]
    masked = probs * (1.0 - jax.nn.one_hot(idx1, num_experts,
                                           dtype=probs.dtype))
    idx2 = jnp.argmax(masked, axis=-1)
    gate2 = jnp.take_along_axis(masked, idx2[:, None], axis=1)[:, 0]
    denom = jnp.maximum(gate1 + gate2, 1e-9)
    # aux loss over the FIRST choice (GShard: top-2's second choice is
    # excluded from the load estimator)
    frac = jnp.mean(
        jax.nn.one_hot(idx1, num_experts, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    g1n, g2n = gate1 / denom, gate2 / denom
    if second_policy == "random":
        u = jax.random.uniform(rng, g2n.shape)
        # stop_gradient on the threshold: the Bernoulli draw is not a
        # differentiable path (GShard treats it as a dispatch decision,
        # not a gate transformation)
        keep2 = u < jax.lax.stop_gradient(2.0 * g2n)
        g2n = jnp.where(keep2, g2n, 0.0)
    return Top2RouterOutput(
        jnp.stack([idx1, idx2]).astype(jnp.int32),
        jnp.stack([g1n, g2n]), aux)


def _dispatch_indices(expert_index: jnp.ndarray, num_experts: int,
                      capacity: int, valid=None):
    """Position of each token within its expert's capacity slots.

    Returns ``(slot, keep)``: slot in [0, capacity) and a keep mask
    (False = dropped by overflow or invalid).  Pure cumsum arithmetic —
    no sorting, no dynamic shapes.  ``valid`` (bool (T,)) marks entries
    that should not dispatch at all (e.g. second choices dropped by the
    GShard "random" policy): they claim NO slot — later entries slide
    into the freed capacity — and come back keep=False.
    """
    one_hot = jax.nn.one_hot(expert_index, num_experts, dtype=jnp.int32)
    if valid is not None:
        one_hot = one_hot * valid.astype(jnp.int32)[:, None]
    position_in_expert = jnp.cumsum(one_hot, axis=0) * one_hot  # 1-based
    # a dispatching entry's own one-hot contributes 1 to its cumsum, so
    # its slot is >= 0; invalid entries have an all-zero row -> slot -1
    slot = jnp.sum(position_in_expert, axis=1) - 1               # (T,)
    keep = (slot >= 0) & (slot < capacity)
    return jnp.clip(slot, 0, capacity - 1), keep


def _resolve_chunks(a2a_chunks: Optional[int]) -> int:
    """``None`` defers to APEX_TPU_MOE_A2A_CHUNKS (default 2: the
    overlapped schedule); an explicit int wins."""
    return (flag_int("APEX_TPU_MOE_A2A_CHUNKS") if a2a_chunks is None
            else int(a2a_chunks))


def _chunked_expert_exchange(buf: jnp.ndarray,
                             expert_fn: Callable,
                             axis_name: str,
                             chunks: int
                             ) -> Tuple[List[jnp.ndarray], int]:
    """Overlapped dispatch/compute/return schedule (ISSUE-19).

    Splits the ``(E, capacity, H)`` dispatch buffer into ``chunks``
    equal capacity slices and traces, in order: every dispatch
    all_to_all back-to-back, then per chunk the expert compute and its
    return all_to_all.  The trace order IS the overlap structure
    (APX704's linear-order model): no collective's output is consumed
    by the immediately following equation — each dispatch a2a is
    followed by the next chunk's a2a, and chunk ``i``'s return a2a is
    followed by chunk ``i+1``'s expert matmul on an already-arrived
    chunk, so every transfer has independent compute to hide behind.

    The backward is hand-scheduled too (custom_vjp): AD's transpose
    would emit each transposed a2a immediately before the transposed
    expert matmul that consumes it — re-tightening the very schedule
    the forward loosened — so the bwd rule mirrors the forward order
    on cotangents: every return-transpose a2a back-to-back, then per
    chunk the expert VJP and its dispatch-transpose a2a.  The expert
    closure's captured tracers (wi/wo under grad) become explicit
    custom_vjp operands via ``jax.closure_convert`` so their gradients
    survive the custom rule.  Differentiating under ``shard_map``
    requires ``check_vma=False`` (as every committed entry point
    already traces): the replication-rewrite machinery on this jax
    predates nested ``jax.vjp`` inside a custom rule.

    Returns ``(return_chunks, chunk_capacity)``; the caller combines
    per chunk (:func:`_chunked_combine`) — concatenating here would
    plant a consumer right behind the last return collective.
    """
    e, c, h = buf.shape
    cs = -(-c // chunks)
    if chunks * cs != c:
        buf = jnp.pad(buf, ((0, 0), (0, chunks * cs - c), (0, 0)))
    n_shards = axis_size(axis_name)
    piece = jax.ShapeDtypeStruct((e // n_shards, cs * n_shards, h),
                                 buf.dtype)
    closed, consts = jax.closure_convert(expert_fn, piece)

    def _disp(p):   # dispatch hop; also the transpose of _ret
        return jax.lax.all_to_all(p, axis_name, split_axis=0,
                                  concat_axis=1, tiled=True)

    def _ret(y):    # return hop; also the transpose of _disp
        return jax.lax.all_to_all(y, axis_name, split_axis=1,
                                  concat_axis=0, tiled=True)

    @jax.custom_vjp
    def run(buf, *consts):
        pieces = [buf[:, i * cs:(i + 1) * cs] for i in range(chunks)]
        arrived = [_disp(p) for p in pieces]
        return tuple(_ret(closed(d, *consts)) for d in arrived)

    def run_fwd(buf, *consts):
        pieces = [buf[:, i * cs:(i + 1) * cs] for i in range(chunks)]
        arrived = [_disp(p) for p in pieces]
        returns, pulls = [], []
        for d in arrived:
            y, pull = jax.vjp(closed, d, *consts)
            returns.append(_ret(y))
            pulls.append(pull)
        return tuple(returns), tuple(pulls)

    def run_bwd(pulls, cts):
        # mirror the forward: all return-transposes in flight first...
        ct_arrived = [_disp(ct) for ct in cts]
        ct_pieces, ct_consts = [], None
        for i, co in enumerate(ct_arrived):
            parts = pulls[i](co)    # chunk i+1's VJP compute trails
            ct_pieces.append(_ret(parts[0]))  # ...chunk i's a2a here
            rest = parts[1:]
            ct_consts = (list(rest) if ct_consts is None else
                         [jax.tree_util.tree_map(jnp.add, a, b)
                          for a, b in zip(ct_consts, rest)])
        ct_buf = jnp.concatenate(ct_pieces, axis=1)
        return (ct_buf,) + tuple(ct_consts)

    run.defvjp(run_fwd, run_bwd)
    return list(run(buf, *consts)), cs


def _chunked_combine(returns: List[jnp.ndarray], cs: int,
                     expert_index: jnp.ndarray, gate: jnp.ndarray,
                     slot: jnp.ndarray, keep: jnp.ndarray,
                     out_dtype) -> jnp.ndarray:
    """Per-chunk gate-weighted gather, accumulated in fp32.  Exactly
    one chunk holds each kept entry's slot, so the masked sum equals
    the single-buffer combine bit-for-bit.  The gate masking is traced
    FIRST — it is independent of every return chunk, which is what
    keeps the last return all_to_all overlappable."""
    k, t = expert_index.shape
    idx_flat = expert_index.reshape(-1)
    g = jnp.where(keep, gate.reshape(-1), 0.0).astype(jnp.float32)
    h = returns[0].shape[-1]
    acc = jnp.zeros((k * t, h), jnp.float32)
    for i, r in enumerate(returns):
        local = jnp.clip(slot - i * cs, 0, cs - 1)
        in_chunk = (slot >= i * cs) & (slot < (i + 1) * cs)
        tok = r[idx_flat, local].astype(jnp.float32)
        acc = acc + jnp.where(in_chunk[:, None], tok * g[:, None], 0.0)
    return acc.reshape(k, t, h).sum(0).astype(out_dtype)


def moe_dispatch_combine(x: jnp.ndarray,
                         router: RouterOutput,
                         expert_fn: Callable[[jnp.ndarray], jnp.ndarray],
                         num_experts: int,
                         capacity_factor: float = 1.25,
                         axis_name: Optional[str] = EXPERT_AXIS,
                         a2a_chunks: Optional[int] = None
                         ) -> jnp.ndarray:
    """Dispatch tokens to experts, apply, combine.

    ``x``: (T, H) local tokens.  ``expert_fn`` maps the LOCAL experts'
    buffer ``(local_experts, rows, H) -> same`` (vmapped expert MLP).
    With ``axis_name`` the global experts are sharded over that axis
    (``num_experts %% axis_size == 0``) and dispatch/return ride
    capacity-chunked ``all_to_all`` exchanges overlapped with expert
    compute (``a2a_chunks``, ``None`` -> APEX_TPU_MOE_A2A_CHUNKS;
    ``1`` keeps the legacy un-overlapped single-shot exchange);
    ``axis_name=None`` runs all experts locally (the dense-equivalent
    used for parity tests).

    ``router`` may be top-1 (``(T,)`` index/gate) or top-k
    (``(k, T)``, e.g. :func:`top2_router`): the k choices share the
    capacity buffer with first choices taking priority (choice-major
    cumsum — the GShard Algorithm 1 slotting), and the combine sums the
    gate-weighted expert outputs per token.
    """
    T, H = x.shape
    idx = jnp.atleast_2d(router.expert_index)          # (k, T)
    gates = jnp.atleast_2d(router.gate)
    k = idx.shape[0]
    capacity = max(1, int(capacity_factor * k * T / num_experts))
    # gate == 0 marks a choice the router decided not to dispatch
    # (GShard second_policy="random"): it claims no capacity slot
    valid = gates.reshape(-1) > 0.0
    slot, keep = _dispatch_indices(idx.reshape(-1), num_experts,
                                   capacity,           # choice-major
                                   valid=valid)

    # scatter tokens into (num_experts, capacity, H); each of a token's
    # k choices occupies its own slot
    buf = jnp.zeros((num_experts, capacity, H), x.dtype)
    xk = jnp.broadcast_to(x[None], (k, T, H)).reshape(k * T, H)
    buf = buf.at[idx.reshape(-1), slot].add(
        jnp.where(keep[:, None], xk, 0))

    return _exchange_and_combine(
        buf, expert_fn, idx, gates, slot, keep, num_experts, capacity,
        axis_name, _resolve_chunks(a2a_chunks), x.dtype)


def _exchange_and_combine(buf, expert_fn, idx, gates, slot, keep,
                          num_experts, capacity, axis_name, chunks,
                          out_dtype) -> jnp.ndarray:
    """Shared exchange tail for the fused and unfused dispatch fronts:
    local (no collective), legacy single-shot, or the overlapped
    chunked schedule."""
    if axis_name is None:
        out = expert_fn(buf)
        return moe_combine(out, idx, slot, keep, gates,
                           out_dtype=out_dtype)

    n_shards = axis_size(axis_name)
    assert num_experts % n_shards == 0
    n = max(1, min(chunks, capacity))
    if n == 1:
        # the legacy un-overlapped trace, kept verbatim: the expert
        # matmul consumes the dispatch a2a's output as the immediately
        # next equation (zero slack — APX704's regression fixture)
        buf = jax.lax.all_to_all(buf, axis_name, split_axis=0,
                                 concat_axis=1, tiled=True)
        out = expert_fn(buf)
        out = jax.lax.all_to_all(out, axis_name, split_axis=1,
                                 concat_axis=0, tiled=True)
        tok_out = out[idx.reshape(-1), slot]           # (k*T, H)
        gate = jnp.where(keep, gates.reshape(-1),
                         0.0).astype(jnp.float32)
        k, t = idx.shape
        combined = (tok_out.astype(jnp.float32) * gate[:, None]) \
            .reshape(k, t, -1).sum(0)
        return combined.astype(out_dtype)

    returns, cs = _chunked_expert_exchange(buf, expert_fn, axis_name,
                                           n)
    return _chunked_combine(returns, cs, idx, gates, slot, keep,
                            out_dtype)


def moe_dispatch_combine_fused(
        x: jnp.ndarray,
        logits: jnp.ndarray,
        expert_fn: Callable[[jnp.ndarray], jnp.ndarray],
        num_experts: int,
        capacity_factor: float = 1.25,
        axis_name: Optional[str] = EXPERT_AXIS,
        top_k: int = 1,
        second_policy: str = "all",
        rng: Optional[jax.Array] = None,
        a2a_chunks: Optional[int] = None,
        backend: Optional[str] = None
        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The fused front end: router softmax, top-k select, capacity
    slotting and the buffer scatter ride ONE Pallas pass
    (:func:`apex_tpu.ops.moe_routing.moe_route_dispatch`, jnp twin off
    TPU) instead of four XLA stages, then the same overlapped exchange
    as :func:`moe_dispatch_combine`.  Routing decisions are
    bit-identical to the unfused path.  Returns ``(y, aux_loss)``."""
    T, _ = x.shape
    capacity = max(1, int(capacity_factor * top_k * T / num_experts))
    rd = moe_route_dispatch(x, logits, capacity=capacity, top_k=top_k,
                            second_policy=second_policy, rng=rng,
                            backend=backend)
    y = _exchange_and_combine(
        rd.buf, expert_fn, rd.expert_index, rd.gate, rd.slot, rd.keep,
        num_experts, capacity, axis_name, _resolve_chunks(a2a_chunks),
        x.dtype)
    return y, rd.load_balancing_loss


class ExpertParallelMLP:
    """Switch-style MoE FFN layer over an expert mesh axis.

    Functional container (params are an explicit pytree, like the other
    shard_map-mode layers):

    >>> layer = ExpertParallelMLP(hidden, ffn_hidden, num_experts)
    >>> params = layer.init(key)              # experts stacked on axis 0
    >>> y, aux = layer.apply(params, x)       # inside shard_map:
    ...                                       # params sharded P(EXPERT_AXIS)
    """

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, capacity_factor: float = 1.25,
                 axis_name: Optional[str] = EXPERT_AXIS,
                 router: str = "top1", second_policy: str = "all",
                 plan: Optional[MeshPlan] = None,
                 a2a_chunks: Optional[int] = None,
                 fused_dispatch: Optional[bool] = None):
        if router not in ("top1", "top2"):
            raise ValueError(f"router must be top1|top2, got {router!r}")
        if second_policy not in ("all", "random"):
            raise ValueError(f"second_policy must be 'all'|'random', "
                             f"got {second_policy!r}")
        if plan is not None:
            # topology as data: the plan's expert axis IS the axis name
            # (passing both only to disagree is a config bug)
            ep_axes = plan.axes_of_kind("expert")
            if len(ep_axes) != 1:
                raise ValueError(
                    f"plan {plan.describe()!r} must carry exactly one "
                    f"expert-kind axis to drive ExpertParallelMLP, "
                    f"got {[a.name for a in ep_axes]}")
            if axis_name not in (None, EXPERT_AXIS, ep_axes[0].name):
                raise ValueError(
                    f"plan names the expert axis "
                    f"{ep_axes[0].name!r} but axis_name="
                    f"{axis_name!r} was also given")
            axis_name = ep_axes[0].name
        self.hidden_size = hidden_size
        self.ffn_hidden_size = ffn_hidden_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.axis_name = axis_name
        self.router = router
        self.second_policy = second_policy
        # resolved at construction so the layer and its mesh_plan
        # price the SAME schedule (flags are ambient; plans are data)
        self.a2a_chunks = _resolve_chunks(a2a_chunks)
        self.fused_dispatch = (
            flag_bool("APEX_TPU_MOE_FUSED_DISPATCH")
            if fused_dispatch is None else bool(fused_dispatch))

    def mesh_plan(self, num_shards: int,
                  with_backward: bool = True) -> MeshPlan:
        """This layer's topology contract: experts sharded over one
        ``expert``-kind axis, router replicated, and the GShard
        dispatch algebra's collective budget — ``a2a_chunks``
        all_to_all each way under the overlapped schedule (their
        transposes double it when the layer trains).  The budget is a
        ceiling: at runtime the chunk count clamps to the capacity, so
        fewer collectives may execute.  The auditor checks a compiled
        entry against exactly this object; the runtime builds its
        shard_map specs from it.
        """
        if self.num_experts % num_shards != 0:
            raise ValueError(
                f"num_experts {self.num_experts} not divisible by "
                f"{num_shards} shards")
        ax = self.axis_name or EXPERT_AXIS
        per_direction = max(1, self.a2a_chunks)
        return MeshPlan.build(
            axes=((ax, num_shards, "expert"),),
            tensor_specs={
                # expert weights: stacked on dim 0, one slice per shard
                r"\['w[io]'\]": (ax,),
                # the router is the one intentionally-replicated param:
                # every shard routes its own tokens with the same table
                r"\['router'\]": (),
            },
            collective_budget={
                "all_to_all": 2 * per_direction
                * (2 if with_backward else 1)})

    def init(self, key: jax.Array) -> dict:
        kr, k1, k2 = jax.random.split(key, 3)
        e, h, f = self.num_experts, self.hidden_size, self.ffn_hidden_size
        s1 = (2.0 / h) ** 0.5
        return {
            "router": jax.random.normal(kr, (h, e), jnp.float32) * 0.02,
            "wi": jax.random.normal(k1, (e, h, f), jnp.float32) * s1,
            "wo": jax.random.normal(k2, (e, f, h), jnp.float32)
            * (2.0 / f) ** 0.5,
        }

    def apply(self, params: dict, x: jnp.ndarray, rng=None):
        """(T, H) -> ((T, H), aux_loss).  Inside shard_map, pass expert
        weights sharded ``P(EXPERT_AXIS)`` on their leading axis and the
        router replicated; tokens may be data-sharded on any other
        axis.  ``rng``: required when ``second_policy='random'`` (the
        GShard dispatch-saving Bernoulli draw)."""
        logits = x.astype(jnp.float32) @ params["router"]

        def expert_fn(buf):  # (local_e, rows, H)
            h = jnp.einsum("erh,ehf->erf", buf.astype(jnp.float32),
                           params["wi"])
            h = jax.nn.gelu(h)
            return jnp.einsum("erf,efh->erh", h,
                              params["wo"]).astype(buf.dtype)

        if self.fused_dispatch:
            return moe_dispatch_combine_fused(
                x, logits, expert_fn, self.num_experts,
                capacity_factor=self.capacity_factor,
                axis_name=self.axis_name,
                top_k=2 if self.router == "top2" else 1,
                second_policy=self.second_policy, rng=rng,
                a2a_chunks=self.a2a_chunks)

        router = (top2_router(logits,
                              second_policy=self.second_policy,
                              rng=rng)
                  if self.router == "top2" else top1_router(logits))
        y = moe_dispatch_combine(
            x, router, expert_fn, self.num_experts,
            capacity_factor=self.capacity_factor,
            axis_name=self.axis_name, a2a_chunks=self.a2a_chunks)
        return y, router.load_balancing_loss
