"""Expert parallelism: Switch-style MoE with all-to-all token dispatch.

The reference has no MoE (SURVEY §2.10: "EP: not present anywhere");
this module completes the parallelism alphabet (dp/tp/pp/sp/**ep**) the
framework's mesh registry reserves.  Design follows Switch Transformer
(Fedus et al. 2021) / GShard dispatch algebra, TPU-first:

- experts are sharded over a mesh axis (one or more experts per shard);
- a top-1 router assigns each token an expert and a gate probability;
- tokens are packed into a fixed-capacity ``(experts, capacity, h)``
  dispatch buffer (static shapes — XLA requirement; overflow tokens are
  dropped, the standard capacity-factor contract) and exchanged with ONE
  ``all_to_all`` each way over ICI;
- the combine scatter multiplies by the gate so router gradients flow.

Everything runs inside ``shard_map`` over ``axis_name``; capacity math
is per-shard static.
"""
from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..parallel_state import EXPERT_AXIS  # noqa: F401


class RouterOutput(NamedTuple):
    expert_index: jnp.ndarray   # (T,) int32 chosen expert per token
    gate: jnp.ndarray           # (T,) f32 chosen-expert probability
    load_balancing_loss: jnp.ndarray  # scalar aux loss (Switch eq. 4)


def top1_router(logits: jnp.ndarray) -> RouterOutput:
    """Top-1 gating with the Switch load-balancing auxiliary loss."""
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    idx = jnp.argmax(probs, axis=-1)
    gate = jnp.take_along_axis(probs, idx[:, None], axis=1)[:, 0]
    num_experts = logits.shape[-1]
    # fraction of tokens per expert x mean router prob per expert
    frac = jnp.mean(
        jax.nn.one_hot(idx, num_experts, dtype=jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = num_experts * jnp.sum(frac * mean_prob)
    return RouterOutput(idx.astype(jnp.int32), gate, aux)


def _dispatch_indices(expert_index: jnp.ndarray, num_experts: int,
                      capacity: int):
    """Position of each token within its expert's capacity slots.

    Returns ``(slot, keep)``: slot in [0, capacity) and a keep mask
    (False = dropped by overflow).  Pure cumsum arithmetic — no sorting,
    no dynamic shapes.
    """
    one_hot = jax.nn.one_hot(expert_index, num_experts, dtype=jnp.int32)
    position_in_expert = jnp.cumsum(one_hot, axis=0) * one_hot  # 1-based
    # every token's own one-hot contributes 1 to its cumsum, so slot is
    # always >= 0; the only droppable state is capacity overflow
    slot = jnp.sum(position_in_expert, axis=1) - 1               # (T,)
    keep = slot < capacity
    return jnp.minimum(slot, capacity - 1), keep


def moe_dispatch_combine(x: jnp.ndarray,
                         router: RouterOutput,
                         expert_fn: Callable[[jnp.ndarray], jnp.ndarray],
                         num_experts: int,
                         capacity_factor: float = 1.25,
                         axis_name: Optional[str] = EXPERT_AXIS
                         ) -> jnp.ndarray:
    """Dispatch tokens to experts, apply, combine.

    ``x``: (T, H) local tokens.  ``expert_fn`` maps the LOCAL experts'
    buffer ``(local_experts, rows, H) -> same`` (vmapped expert MLP).
    With ``axis_name`` the global experts are sharded over that axis
    (``num_experts %% axis_size == 0``) and dispatch/return each ride one
    ``all_to_all``; ``axis_name=None`` runs all experts locally (the
    dense-equivalent used for parity tests).
    """
    T, H = x.shape
    capacity = max(1, int(capacity_factor * T / num_experts))
    slot, keep = _dispatch_indices(router.expert_index, num_experts,
                                   capacity)

    # scatter tokens into (num_experts, capacity, H)
    buf = jnp.zeros((num_experts, capacity, H), x.dtype)
    buf = buf.at[router.expert_index, slot].add(
        jnp.where(keep[:, None], x, 0))

    if axis_name is not None:
        n_shards = jax.lax.axis_size(axis_name)
        assert num_experts % n_shards == 0
        # shard e receives every peer's slice for its local experts:
        # (E, C, H) -> (E/P, P*C, H)
        buf = jax.lax.all_to_all(buf, axis_name, split_axis=0,
                                 concat_axis=1, tiled=True)

    out = expert_fn(buf)

    if axis_name is not None:
        out = jax.lax.all_to_all(out, axis_name, split_axis=1,
                                 concat_axis=0, tiled=True)

    # combine: gather each token's slot output, weight by its gate
    tok_out = out[router.expert_index, slot]
    gate = jnp.where(keep, router.gate, 0.0).astype(jnp.float32)
    return (tok_out.astype(jnp.float32) * gate[:, None]).astype(x.dtype)


class ExpertParallelMLP:
    """Switch-style MoE FFN layer over an expert mesh axis.

    Functional container (params are an explicit pytree, like the other
    shard_map-mode layers):

    >>> layer = ExpertParallelMLP(hidden, ffn_hidden, num_experts)
    >>> params = layer.init(key)              # experts stacked on axis 0
    >>> y, aux = layer.apply(params, x)       # inside shard_map:
    ...                                       # params sharded P(EXPERT_AXIS)
    """

    def __init__(self, hidden_size: int, ffn_hidden_size: int,
                 num_experts: int, capacity_factor: float = 1.25,
                 axis_name: Optional[str] = EXPERT_AXIS):
        self.hidden_size = hidden_size
        self.ffn_hidden_size = ffn_hidden_size
        self.num_experts = num_experts
        self.capacity_factor = capacity_factor
        self.axis_name = axis_name

    def init(self, key: jax.Array) -> dict:
        kr, k1, k2 = jax.random.split(key, 3)
        e, h, f = self.num_experts, self.hidden_size, self.ffn_hidden_size
        s1 = (2.0 / h) ** 0.5
        return {
            "router": jax.random.normal(kr, (h, e), jnp.float32) * 0.02,
            "wi": jax.random.normal(k1, (e, h, f), jnp.float32) * s1,
            "wo": jax.random.normal(k2, (e, f, h), jnp.float32)
            * (2.0 / f) ** 0.5,
        }

    def apply(self, params: dict, x: jnp.ndarray):
        """(T, H) -> ((T, H), aux_loss).  Inside shard_map, pass expert
        weights sharded ``P(EXPERT_AXIS)`` on their leading axis and the
        router replicated; tokens may be data-sharded on any other
        axis."""
        logits = x.astype(jnp.float32) @ params["router"]
        router = top1_router(logits)

        def expert_fn(buf):  # (local_e, rows, H)
            h = jnp.einsum("erh,ehf->erf", buf.astype(jnp.float32),
                           params["wi"])
            h = jax.nn.gelu(h)
            return jnp.einsum("erf,efh->erh", h,
                              params["wo"]).astype(buf.dtype)

        y = moe_dispatch_combine(
            x, router, expert_fn, self.num_experts,
            capacity_factor=self.capacity_factor,
            axis_name=self.axis_name)
        return y, router.load_balancing_loss
