"""FusedScaleMaskSoftmax — dispatching wrapper around the Pallas kernels.

Parity with the reference's module
(ref: apex/transformer/functional/fused_softmax.py:95-199): chooses the
fused kernel when eligible, else a plain XLA softmax optionally computed
in fp32 (``softmax_in_fp32``/``input_in_float16`` handling).  The
reference's eligibility window (fp16/bf16, 16 < sk <= 2048, sq % 4 == 0,
b*np % 4 == 0 — ref :151-170) exists because its CUDA kernels are
shape-specialized; the Pallas kernels handle any shape, so here
eligibility only requires a low-precision input (the fused path's reason
to exist), with the same ``is_kernel_available`` introspection surface.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax.numpy as jnp

from ...ops.scaled_softmax import (scaled_masked_softmax,
                                   scaled_upper_triang_masked_softmax)
from ..enums import AttnMaskType


class FusedScaleMaskSoftmax:
    """fused operation: scaling + mask + softmax
    (ref: apex/transformer/functional/fused_softmax.py:95-199).

    Arguments mirror the reference: ``input_in_fp16``/``input_in_bf16``,
    ``attn_mask_type`` (padding|causal), ``scaled_masked_softmax_fusion``,
    ``mask_func`` for the unfused fallback, ``softmax_in_fp32``, ``scale``.
    """

    def __init__(self,
                 input_in_fp16: bool,
                 input_in_bf16: bool,
                 attn_mask_type: AttnMaskType,
                 scaled_masked_softmax_fusion: bool,
                 mask_func: Optional[Callable],
                 softmax_in_fp32: bool,
                 scale: Optional[float]):
        self.input_in_fp16 = input_in_fp16
        self.input_in_bf16 = input_in_bf16
        if input_in_fp16 and input_in_bf16:
            raise RuntimeError(
                "both fp16 and bf16 flags cannot be active at the same "
                "time (ref: fused_softmax.py:118-120)")
        self.input_in_float16 = input_in_fp16 or input_in_bf16
        self.attn_mask_type = attn_mask_type
        self.scaled_masked_softmax_fusion = scaled_masked_softmax_fusion
        self.mask_func = mask_func
        self.softmax_in_fp32 = softmax_in_fp32
        self.scale = scale
        if scale is not None and not softmax_in_fp32:
            raise RuntimeError(
                "softmax should be in fp32 when scaled "
                "(ref: fused_softmax.py:128-130)")

    def is_kernel_available(self, mask, b, np_, sq, sk) -> bool:
        """Fused-path eligibility (ref: fused_softmax.py:151-170; the CUDA
        shape window is not needed for Pallas)."""
        return bool(self.scaled_masked_softmax_fusion
                    and self.input_in_float16
                    and sk > 1)

    def _model_dtype(self):
        """The dtype probs leave in, from the constructor flags — NOT
        the input dtype: callers may (should) hand in fp32 scores
        straight off the matmul's fp32 accumulate, and the downcast to
        model dtype is this sanctioned-fp32 region's own exit cast
        (re-deriving it from the input recreated the APX602
        fp32->bf16->fp32 round-trip the hlo auditor flagged)."""
        if self.input_in_fp16:
            return jnp.float16
        if self.input_in_bf16:
            return jnp.bfloat16
        return None

    def _exit_cast(self, probs):
        dtype = self._model_dtype()
        return probs.astype(dtype) if dtype is not None else probs

    def __call__(self, inputs: jnp.ndarray,
                 mask: Optional[jnp.ndarray]) -> jnp.ndarray:
        b, np_, sq, sk = inputs.shape
        if self.is_kernel_available(mask, b, np_, sq, sk):
            return self.forward_fused_softmax(inputs, mask)
        return self.forward_jax_softmax(inputs, mask)

    def forward_fused_softmax(self, inputs, mask):
        scale = self.scale if self.scale is not None else 1.0
        if self.attn_mask_type == AttnMaskType.causal:
            b, np_, sq, sk = inputs.shape
            assert sq == sk, "causal mask is only for self attention"
            probs = scaled_upper_triang_masked_softmax(
                inputs.reshape(-1, sq, sk), scale)
            return self._exit_cast(probs.reshape(b, np_, sq, sk))
        if mask is not None:
            return self._exit_cast(scaled_masked_softmax(inputs, mask,
                                                         scale))
        return self._exit_cast(scaled_masked_softmax(
            inputs, jnp.zeros((b, 1, sq, sk), jnp.int32), scale))

    def forward_jax_softmax(self, inputs, mask):
        """Unfused fallback (ref: forward_torch_softmax,
        fused_softmax.py:176-194)."""
        if self.input_in_float16 and self.softmax_in_fp32:
            inputs = inputs.astype(jnp.float32)
        if self.scale is not None:
            inputs = inputs * self.scale
        if self.attn_mask_type == AttnMaskType.causal:
            sq, sk = inputs.shape[-2:]
            causal = jnp.tril(jnp.ones((sq, sk), bool))
            inputs = jnp.where(causal, inputs, -10000.0)
        elif mask is not None:
            if self.mask_func is not None:
                inputs = self.mask_func(inputs, mask)
            else:
                # default attention_mask_func: fill masked (True)
                # positions (ref: the reference always installs
                # masked_fill(-10000); a None mask_func must not
                # silently DROP the mask)
                inputs = jnp.where(mask.astype(bool), -10000.0, inputs)
        probs = jnp.exp(inputs - jnp.max(inputs, -1, keepdims=True))
        probs = probs / jnp.sum(probs, -1, keepdims=True)
        if self.softmax_in_fp32:
            probs = self._exit_cast(probs)
        return probs
