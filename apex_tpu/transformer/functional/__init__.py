"""apex_tpu.transformer.functional — fused transformer ops."""
from .fused_softmax import FusedScaleMaskSoftmax

__all__ = ["FusedScaleMaskSoftmax"]
