"""Pipeline-parallel schedules: no-pipelining, 1F1B, interleaved.

TPU-native re-design of the reference's schedule zoo
(ref: apex/transformer/pipeline_parallel/schedules/__init__.py:16,
fwd_bwd_no_pipelining.py:29, fwd_bwd_pipelining_without_interleaving.py:22,
fwd_bwd_pipelining_with_interleaving.py:22).

The reference runs one Python process per stage and hand-schedules
warmup/steady(1F1B)/cooldown phases with NCCL p2p between them.  Under
XLA the whole pipeline is ONE program: a ``lax.scan`` over
``num_microbatches + num_stages - 1`` ticks inside ``shard_map`` over the
``pipe`` mesh axis.  Each tick, every stage applies its layer block to
its in-flight microbatch and hands the activation to its successor with
a single ``ppermute`` (ICI neighbour hop).  Bubble ticks (the triangle
the reference's warmup/cooldown phases walk) are masked compute — the
same utilization loss, expressed as data instead of control flow.

Reverse-mode AD through the scan yields the backward pipeline
automatically: ppermute transposes to the reverse hop, the scan reverses,
and each stage receives exactly the gradient exchange the reference
implements manually (send_backward_recv_backward).  Activation memory is
governed by ``jax.checkpoint`` on the stage function (``'full'`` policy
recomputes the block in backward — the reference's activation
checkpointing — bounding live activations per stage at the pipeline
depth, the same bound 1F1B provides).

Layout contract: stage parameters are stacked on a leading stage axis and
passed through ``shard_map`` with ``in_specs=P('pipe', ...)``; microbatch
inputs are ``[num_microbatches, micro_batch, ...]`` and replicated.  The
stage function must preserve the activation shape (uniform transformer
blocks); embedding and head run outside the pipelined region, matching
the reference's pre_process/post_process split
(ref: schedules/common.py:18-107).
"""
from __future__ import annotations

import functools
import warnings
from typing import Any, Callable, Optional

import jax
from ..._compat import axis_index, axis_size, pcast, psum_replicated, typeof
import jax.numpy as jnp

from ...mesh_plan import MeshPlan
from ...parallel_state import PIPE_AXIS
from ..tensor_parallel.random import CHECKPOINT_POLICIES
from . import p2p_communication


def pipeline_plan(num_stages: int, num_microbatches: int, *,
                  axis_name: str = PIPE_AXIS,
                  virtual_pipeline_size: Optional[int] = None,
                  with_backward: bool = True) -> MeshPlan:
    """The pipeline schedules' topology contract as data.

    One ``pipeline``-kind axis; stage parameters stacked on a leading
    stage axis and sharded over it; the collective budget prices the
    tick loop: every tick hands one activation to the successor with a
    single ``ppermute`` (2 per tick interleaved — activation feed plus
    the chunk-recirculation hop), over ``m + s·v - 1``-ish ticks, and
    training doubles it (the scan transposes every hop into the
    reverse ring).  The budget is a CEILING for the auditor's census,
    not an exact count — schedules may mask bubble ticks but never emit
    more hops than ticks.
    """
    v = virtual_pipeline_size or 1
    ticks = num_microbatches * v + num_stages - 1
    hops_per_tick = 2 if v > 1 else 1
    mult = 2 if with_backward else 1
    return MeshPlan.build(
        axes=((axis_name, num_stages, "pipeline"),),
        tensor_specs={
            # build_stage_params stacks per-stage trees on dim 0 (dim 0
            # is the vpp chunk when interleaving — the stage axis moves
            # to dim 1); both spell "one stage slice per device"
            r"stage": ((axis_name,) if v == 1 else (None, axis_name)),
        },
        collective_budget={"ppermute": ticks * hops_per_tick * mult})


def _tree_where(pred, a, b):
    return jax.tree.map(lambda x, y: jnp.where(pred, x, y), a, b)


def _tree_zeros_like(tree):
    return jax.tree.map(jnp.zeros_like, tree)


def pipeline_forward(stage_fn: Callable, stage_params: Any, microbatches: Any,
                     *, axis_name: str = PIPE_AXIS,
                     checkpoint_policy: Optional[str] = "full"):
    """Differentiable spatial pipeline over the ``pipe`` axis.

    Call inside ``shard_map``.  ``stage_fn(stage_params, x) -> y`` with
    ``y`` shaped like ``x``; ``microbatches`` is a pytree whose leaves
    are ``[M, ...]``.  Returns the last stage's outputs ``[M, ...]``,
    replicated over the axis (a psum of masked per-stage buffers).

    This is the single primitive behind both pipelined schedules —
    the reference's 1F1B tick structure
    (ref: fwd_bwd_pipelining_without_interleaving.py:61-170) appears
    here as the scan bounds: M + P - 1 ticks, microbatch ``t - rank``
    active on stage ``rank`` at tick ``t``.
    """
    nstages = axis_size(axis_name)
    rank = axis_index(axis_name)
    leaves = jax.tree.leaves(microbatches)
    num_micro = leaves[0].shape[0]

    fn = stage_fn
    if checkpoint_policy is not None:
        pol = (CHECKPOINT_POLICIES[checkpoint_policy]
               if isinstance(checkpoint_policy, str) else checkpoint_policy)
        fn = jax.checkpoint(stage_fn, policy=pol)

    def _varying(tree):
        # scan carries become axis-varying after the first ppermute/mask
        # (and inherit whatever varying axes the microbatch data carries,
        # e.g. 'data' when the batch is data-sharded); the initial zeros
        # must be marked identically for VMA type agreement
        def mark(x, ref):
            target = set(typeof(ref).vma) | {axis_name}
            missing = tuple(a for a in target if a not in typeof(x).vma)
            return pcast(x, missing, to="varying") if missing else x
        ref_leaves = jax.tree.leaves(jax.tree.map(lambda m: m[0],
                                                  microbatches))
        return jax.tree.map(
            mark, tree,
            jax.tree.unflatten(jax.tree.structure(tree), ref_leaves))

    first_mb = jax.tree.map(lambda x: x[0], microbatches)
    state0 = _varying(_tree_zeros_like(first_mb))
    out_shape = jax.eval_shape(lambda p, x: stage_fn(p, x),
                               stage_params, first_mb)
    jax.tree.map(lambda o, i: None if o.shape == i.shape else
                 (_ for _ in ()).throw(ValueError(
                     f"stage_fn must preserve activation shape, got "
                     f"{o.shape} from {i.shape}")), out_shape, first_mb)
    outputs0 = _varying(jax.tree.map(
        lambda x: jnp.zeros((num_micro,) + x.shape, x.dtype), first_mb))

    def tick(carry, t):
        state, outputs = carry
        mb_idx = t - rank
        feed_idx = jnp.clip(t, 0, num_micro - 1)
        fresh = jax.tree.map(
            lambda mb: jax.lax.dynamic_index_in_dim(mb, feed_idx, 0,
                                                    keepdims=False),
            microbatches)
        x = _tree_where(rank == 0, fresh, state)
        y = fn(stage_params, x)
        active = (mb_idx >= 0) & (mb_idx < num_micro)
        y = _tree_where(active, y, _tree_zeros_like(y))
        write_idx = jnp.clip(mb_idx, 0, num_micro - 1)
        write = (rank == nstages - 1) & active
        outputs = jax.tree.map(
            lambda buf, o: jnp.where(
                write,
                jax.lax.dynamic_update_index_in_dim(buf, o, write_idx, 0),
                buf),
            outputs, y)
        state = jax.tree.map(
            lambda o: p2p_communication.send_forward_recv_forward(
                o, axis_name), y)
        return (state, outputs), None

    (_, outputs), _ = jax.lax.scan(
        tick, (state0, outputs0), jnp.arange(num_micro + nstages - 1))
    # Only the last stage wrote non-zeros; psum replicates to every
    # stage (seed-once VJP semantics on old jax — see _compat).
    return jax.tree.map(lambda o: psum_replicated(o, axis_name), outputs)


def forward_backward_no_pipelining(loss_fn: Callable, params: Any,
                                   microbatches: Any, *,
                                   forward_only: bool = False):
    """Grad accumulation over microbatches without pipelining
    (ref: fwd_bwd_no_pipelining.py:29-77): run every microbatch through
    ``loss_fn(params, microbatch) -> scalar``, averaging losses and
    gradients.  The reference defers the DDP allreduce to the last
    microbatch (no_sync); under pjit the psum placement after the scan
    achieves the same single gradient reduction.
    """
    def body(acc, mb):
        if forward_only:
            loss = loss_fn(params, mb)
            return acc, loss
        loss, grads = jax.value_and_grad(loss_fn)(params, mb)
        acc = jax.tree.map(jnp.add, acc, grads)
        return acc, loss

    num_micro = jax.tree.leaves(microbatches)[0].shape[0]
    acc0 = None if forward_only else _tree_zeros_like(params)
    if forward_only:
        _, losses = jax.lax.scan(lambda c, mb: body(None, mb), None,
                                 microbatches)
        return jnp.mean(losses), None
    acc, losses = jax.lax.scan(body, acc0, microbatches)
    grads = jax.tree.map(lambda g: g / num_micro, acc)
    return jnp.mean(losses), grads


def forward_backward_pipelining_without_interleaving(
        stage_fn: Callable, loss_fn: Callable, stage_params: Any,
        microbatches: Any, *, forward_only: bool = False,
        axis_name: str = PIPE_AXIS,
        checkpoint_policy: Optional[str] = "full"):
    """Pipelined fwd+bwd over the ``pipe`` axis (1F1B-equivalent;
    ref: fwd_bwd_pipelining_without_interleaving.py:22-170).

    ``loss_fn(outputs_mb, k)`` maps the last stage's activation for
    microbatch ``k`` to a scalar (it closes over labels).  Returns
    ``(mean_loss, grads)`` with grads structured like ``stage_params``
    (each stage's shard holds its own gradient — the per-rank layout the
    reference's per-process autograd produces).
    """
    num_micro = jax.tree.leaves(microbatches)[0].shape[0]

    def total_loss(stage_params):
        outs = pipeline_forward(stage_fn, stage_params, microbatches,
                                axis_name=axis_name,
                                checkpoint_policy=checkpoint_policy)
        losses = jax.vmap(loss_fn)(outs, jnp.arange(num_micro))
        return jnp.mean(losses)

    if forward_only:
        return total_loss(stage_params), None
    loss, grads = jax.value_and_grad(total_loss)(stage_params)
    return loss, grads


def pipeline_forward_interleaved(stage_fn: Callable, chunk_params: Any,
                                 microbatches: Any, *,
                                 axis_name: str = PIPE_AXIS,
                                 checkpoint_policy: Optional[str] = "full"):
    """Interleaved (virtual-pipeline) forward: ONE scan, one block
    application per stage per tick, chunks overlapped in time.

    This is the true interleaved schedule, not sequential chunk sweeps:
    slot ``k`` of a stage processes chunk ``(k // P) %% vpp`` on
    microbatch ``(k // (vpp*P))*P + k %% P`` — the reference's
    chunk-major groups-of-P order
    (ref: fwd_bwd_pipelining_with_interleaving.py:100-140
    ``get_model_chunk_id``).  Stage ``s`` runs slot ``k`` at tick
    ``s + k``; a single *cyclic* ppermute per tick both feeds stage
    ``s+1`` and carries the chunk connector (last stage -> stage 0).
    Makespan is ``vpp*M + P`` ticks versus the sequential-sweep
    ``vpp*(M + P - 1)`` — the ``(vpp-1)*(P-1)`` bubble the interleaved
    schedule exists to remove is removed.
    """
    nstages = axis_size(axis_name)
    rank = axis_index(axis_name)
    vpp = jax.tree.leaves(chunk_params)[0].shape[0]
    num_micro = jax.tree.leaves(microbatches)[0].shape[0]
    K = vpp * num_micro
    group = vpp * nstages

    fn = stage_fn
    if checkpoint_policy is not None:
        pol = (CHECKPOINT_POLICIES[checkpoint_policy]
               if isinstance(checkpoint_policy, str) else checkpoint_policy)
        fn = jax.checkpoint(stage_fn, policy=pol)

    def decode(k):
        """slot -> (chunk, microbatch) in chunk-major groups of P."""
        a = k // group
        rem = k % group
        c = rem // nstages
        m = a * nstages + rem % nstages
        return c, m

    def _varying(tree):
        def mark(x, ref):
            target = set(typeof(ref).vma) | {axis_name}
            missing = tuple(a for a in target
                            if a not in typeof(x).vma)
            return pcast(x, missing, to="varying") if missing \
                else x
        ref_leaves = jax.tree.leaves(jax.tree.map(lambda m: m[0],
                                                  microbatches))
        return jax.tree.map(
            mark, tree,
            jax.tree.unflatten(jax.tree.structure(tree), ref_leaves))

    first_mb = jax.tree.map(lambda x: x[0], microbatches)
    state0 = _varying(_tree_zeros_like(first_mb))
    outputs0 = _varying(jax.tree.map(
        lambda x: jnp.zeros((num_micro,) + x.shape, x.dtype), first_mb))

    def tick(carry, t):
        state, outputs = carry
        k = t - rank
        active = (k >= 0) & (k < K)
        c, m = decode(jnp.clip(k, 0, K - 1))

        params_c = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0,
                                                   keepdims=False),
            chunk_params)
        fresh = jax.tree.map(
            lambda mb: jax.lax.dynamic_index_in_dim(
                mb, jnp.clip(m, 0, num_micro - 1), 0, keepdims=False),
            microbatches)
        # fresh data enters only at (stage 0, chunk 0); everything else
        # consumes the carry (pipeline input or chunk connector).
        x = _tree_where((rank == 0) & (c == 0), fresh, state)
        y = fn(params_c, x)
        y = _tree_where(active, y, _tree_zeros_like(y))

        # Collection: at stage 0, when the carry came from the last
        # stage's last chunk, it is a FINAL output for that microbatch.
        kprev = t - nstages
        cp, mp = decode(jnp.clip(kprev, 0, K - 1))
        collect = ((rank == 0) & (kprev >= 0) & (kprev < K)
                   & (cp == vpp - 1))
        wi = jnp.clip(mp, 0, num_micro - 1)
        outputs = jax.tree.map(
            lambda buf, s: jnp.where(
                collect,
                jax.lax.dynamic_update_index_in_dim(buf, s, wi, 0),
                buf),
            outputs, state)

        state = jax.tree.map(
            lambda o: p2p_communication.send_forward_recv_forward_cyclic(
                o, axis_name), y)
        return (state, outputs), None

    (_, outputs), _ = jax.lax.scan(
        tick, (state0, outputs0), jnp.arange(K + nstages))
    # Only stage 0 collected; psum replicates across the axis
    # (seed-once VJP semantics on old jax — see _compat).
    return jax.tree.map(lambda o: psum_replicated(o, axis_name), outputs)


def forward_backward_pipelining_with_interleaving(
        stage_fn: Callable, loss_fn: Callable, stage_params: Any,
        microbatches: Any, *, forward_only: bool = False,
        axis_name: str = PIPE_AXIS,
        checkpoint_policy: Optional[str] = "full",
        strict: bool = False):
    """Virtual-pipeline (interleaved) schedule
    (ref: fwd_bwd_pipelining_with_interleaving.py:22-308).

    ``stage_params`` carries a leading virtual-chunk axis: chunk ``c`` of
    stage ``s`` owns layer block ``c * num_stages + s`` — the reference's
    round-robin model-chunk assignment (ref: parallel_state.py:101-108).
    Chunks execute overlapped (one scan, one block per stage per tick —
    see :func:`pipeline_forward_interleaved`); reverse-mode AD through
    the scan yields the interleaved backward order.

    The interleaved slot mapping requires ``M %% P == 0``.  Other M fall
    back to sequential chunk sweeps — same math, but the bubble the
    caller asked to remove is back, so the fallback WARNS;
    ``strict=True`` raises instead (the reference's behavior, which
    asserts ``num_microbatches %% pipeline_parallel_size == 0``).
    """
    num_micro = jax.tree.leaves(microbatches)[0].shape[0]
    nstages = axis_size(axis_name)
    vpp = jax.tree.leaves(stage_params)[0].shape[0]
    if num_micro % nstages != 0:
        msg = (f"interleaved pipeline schedule needs num_microbatches "
               f"({num_micro}) divisible by pipeline stages ({nstages})"
               f"; falling back to sequential chunk sweeps — same "
               f"result, WITHOUT the interleaving bubble reduction")
        if strict:
            raise ValueError(msg.split(";")[0] + " (strict=True)")
        warnings.warn(msg, stacklevel=2)

    def total_loss(stage_params):
        if num_micro % nstages == 0:
            acts = pipeline_forward_interleaved(
                stage_fn, stage_params, microbatches,
                axis_name=axis_name,
                checkpoint_policy=checkpoint_policy)
        else:
            acts = microbatches
            for c in range(vpp):
                chunk = jax.tree.map(lambda p, c=c: p[c], stage_params)
                acts = pipeline_forward(
                    stage_fn, chunk, acts, axis_name=axis_name,
                    checkpoint_policy=checkpoint_policy)
        losses = jax.vmap(loss_fn)(acts, jnp.arange(num_micro))
        return jnp.mean(losses)

    if forward_only:
        return total_loss(stage_params), None
    loss, grads = jax.value_and_grad(total_loss)(stage_params)
    return loss, grads


def get_forward_backward_func(
        virtual_pipeline_model_parallel_size: Optional[int],
        pipeline_model_parallel_size: int):
    """Schedule selector (ref: schedules/__init__.py:16-29)."""
    if pipeline_model_parallel_size > 1:
        if virtual_pipeline_model_parallel_size is not None:
            return forward_backward_pipelining_with_interleaving
        return forward_backward_pipelining_without_interleaving
    return forward_backward_no_pipelining


def build_stage_params(init_fn: Callable, key: jax.Array, num_stages: int,
                       virtual_chunks: Optional[int] = None):
    """Stacked per-stage parameter construction — the functional analogue
    of the reference's ``build_model`` model-provider loop
    (ref: schedules/common.py:18-107): one init per (chunk, stage) with
    independent keys, stacked on leading [vpp?, stage] axes so
    ``shard_map`` in_specs ``P('pipe', ...)`` (after chunk indexing)
    place each stage's block on its devices.
    """
    chunks = virtual_chunks or 1
    keys = jax.random.split(key, chunks * num_stages)
    stacked = jax.vmap(init_fn)(keys)
    if virtual_chunks is None:
        return stacked
    return jax.tree.map(
        lambda x: x.reshape((chunks, num_stages) + x.shape[1:]), stacked)
