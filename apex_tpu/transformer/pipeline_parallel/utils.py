"""Pipeline-parallel utilities: microbatch bookkeeping, timers, helpers.

Parity with the reference
(ref: apex/transformer/pipeline_parallel/utils.py:41-307).
"""
from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from ... import parallel_state
from ..microbatches import (NumMicroBatchesCalculator,
                            build_num_microbatches_calculator)

_GLOBAL_NUM_MICROBATCHES_CALCULATOR: Optional[NumMicroBatchesCalculator] = \
    None
_GLOBAL_TIMERS = None
_GLOBAL_AUTORESUME = None


def listify_model(model: Union[Any, List[Any]]) -> List[Any]:
    """ref: utils.py:41-46."""
    if isinstance(model, list):
        return model
    return [model]


def _ensure_var_is_initialized(var, name):
    if var is None:
        raise RuntimeError(f"{name} is not initialized.")


def _ensure_var_is_not_initialized(var, name):
    if var is not None:
        raise RuntimeError(f"{name} is already initialized.")


def setup_microbatch_calculator(rank: int, rampup_batch_size,
                                global_batch_size: int,
                                micro_batch_size: int,
                                data_parallel_size: int) -> None:
    """ref: utils.py:57-70."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _ensure_var_is_not_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                                   "num microbatches calculator")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def _reconfigure_microbatch_calculator(rank: int, rampup_batch_size,
                                       global_batch_size: int,
                                       micro_batch_size: int,
                                       data_parallel_size: int) -> None:
    """ref: utils.py:71-85 — replace without the already-init check."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)


def destroy_microbatch_calculator() -> None:
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def get_micro_batch_size() -> int:
    """ref: utils.py:87-89."""
    _ensure_var_is_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                               "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.micro_batch_size


def get_num_microbatches() -> int:
    """ref: utils.py:91-93."""
    _ensure_var_is_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                               "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size() -> int:
    """ref: utils.py:95-97."""
    _ensure_var_is_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                               "num microbatches calculator")
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR. \
        get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int,
                            consistency_check: bool = True) -> None:
    """ref: utils.py:99-102."""
    _ensure_var_is_initialized(_GLOBAL_NUM_MICROBATCHES_CALCULATOR,
                               "num microbatches calculator")
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def split_batch_into_microbatches(batch, micro_batch_size: int):
    """Reshape a global-batch pytree into [M, micro, ...] leaves
    (ref: utils.py:104-128 _split_batch_into_microbatch /
    get_kth_microbatch — slicing becomes one reshape under SPMD)."""
    def split(x):
        b = x.shape[0]
        if b % micro_batch_size != 0:
            raise ValueError(
                f"batch dim {b} not divisible by micro batch size "
                f"{micro_batch_size}")
        return x.reshape((b // micro_batch_size, micro_batch_size)
                         + x.shape[1:])
    return jax.tree.map(split, batch)


def get_kth_microbatch(batch, k: int):
    """ref: utils.py:121-128."""
    return jax.tree.map(lambda x: x[k], batch)


def get_autoresume():
    """The ADLR autoresume hook, realized (ref: utils.py:131-133, where
    it always returned None).  Returns the installed
    :class:`apex_tpu.resilience.AutoResume` — Megatron-parity call
    sites poll ``get_autoresume().termination_requested()`` at step
    boundaries to cut a final checkpoint before the scheduler's
    SIGTERM deadline.  ``AutoResume.install()`` registers itself here;
    None until then."""
    return _GLOBAL_AUTORESUME


def set_autoresume(autoresume) -> None:
    """Publish (or clear, with None) the process-wide autoresume
    handler.  Called by ``AutoResume.install()``/``uninstall()``;
    replacing an existing handler is allowed — latest wins, as with
    signal handlers themselves."""
    global _GLOBAL_AUTORESUME
    _GLOBAL_AUTORESUME = autoresume


# --- timers ----------------------------------------------------------------

class _Timer:
    """Host-side timer with device-sync elapsed
    (ref: pipeline_parallel/_timers.py:6-40 — cuda synchronize becomes
    block_until_ready on the timed region's outputs)."""

    def __init__(self, name: str):
        self.name = name
        self._elapsed = 0.0
        self._started = False
        self._start_time = None

    @staticmethod
    def _sync(wait_on=None):
        """Device sync: block on the arrays produced in the timed region
        when given (the cuda.synchronize analogue — pass the step's
        outputs to ``stop``).  ``effects_barrier`` alone only awaits
        side-effecting computations, not in-flight pure dispatch, so a
        sentinel computation is enqueued as the fallback: devices
        execute their stream in order, so blocking on it drains prior
        work."""
        if wait_on is not None:
            jax.block_until_ready(wait_on)
        else:
            jax.block_until_ready(jnp.zeros(()) + 0.0)
        jax.effects_barrier()

    def start(self):
        import time
        if self._started:
            raise RuntimeError("timer has already been started")
        self._sync()
        self._start_time = time.perf_counter()
        self._started = True

    def stop(self, wait_on=None):
        import time
        if not self._started:
            raise RuntimeError("timer is not started")
        self._sync(wait_on)
        self._elapsed += time.perf_counter() - self._start_time
        self._started = False

    def reset(self):
        self._elapsed = 0.0
        self._started = False

    def elapsed(self, reset: bool = True) -> float:
        started = self._started
        if started:
            self.stop()
        total = self._elapsed
        if reset:
            self.reset()
        if started:
            self.start()
        return total


class Timers:
    """Named timer group (ref: _timers.py:43-70)."""

    def __init__(self):
        self.timers = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def write(self, names: Sequence[str], writer, iteration: int,
              normalizer: float = 1.0, reset: bool = False):
        """ref: _timers.py:55-62 — writer is any object with add_scalar
        (``apex_tpu.monitor.ScalarWriter`` adapts a telemetry sink).

        Names that were never started are skipped: a logging call must
        not crash the run over a phase that happened not to execute
        this interval (e.g. no exchange on a 1-stage pipeline).
        """
        for name in names:
            if name not in self.timers:
                continue
            value = self.timers[name].elapsed(reset=reset) / normalizer
            writer.add_scalar(f"{name}-time", value, iteration)

    def log(self, names: Sequence[str], normalizer: float = 1.0,
            reset: bool = True):
        """ref: _timers.py:63-70.  Never-started names are skipped, not
        a KeyError (see :meth:`write`)."""
        assert normalizer > 0.0
        string = "time (ms)"
        for name in names:
            if name not in self.timers:
                continue
            elapsed_time = (self.timers[name].elapsed(reset=reset) * 1000.0
                            / normalizer)
            string += f" | {name}: {elapsed_time:.2f}"
        print_rank_last(string)

    def events(self, sink, iteration: Optional[int] = None,
               names: Optional[Sequence[str]] = None,
               normalizer: float = 1.0, reset: bool = True):
        """Export phase times as ``timer`` events (seconds) into a
        telemetry sink — phase timings land in the same structured log
        as step metrics and watchdog alarms (docs/api/observability.md).

        ``sink`` is anything with ``emit(Event)``: a
        :class:`apex_tpu.monitor.Sink` or a ``StepMonitor``.  ``names``
        defaults to every timer ever started; missing names are skipped
        (same contract as :meth:`write`).
        """
        import time as _time

        from ...monitor.events import Event

        assert normalizer > 0.0
        if names is None:
            names = list(self.timers)
        for name in names:
            if name not in self.timers:
                continue
            value = self.timers[name].elapsed(reset=reset) / normalizer
            sink.emit(Event(time=_time.time(),
                            step=None if iteration is None
                            else int(iteration),
                            kind="timer", name=name, value=value))

    def chrome_events(self, tracer, iteration: Optional[int] = None,
                      names: Optional[Sequence[str]] = None,
                      reset: bool = True):
        """Export accumulated phase times into a
        :class:`apex_tpu.monitor.tracing.SpanTracer` as Chrome-trace
        ``complete`` events — each timer becomes one bar ending *now*
        on the tracer's timeline with its accumulated duration, so the
        schedule phases the transformer stack already times land in
        the same Perfetto view as the host spans (an aggregate bar,
        not a per-invocation timeline; ``timer`` JSONL events get the
        same treatment on the read side via
        ``chrome_trace_from_events``)."""
        now = tracer.now()
        if names is None:
            names = list(self.timers)
        for name in names:
            if name not in self.timers:
                continue
            dur = self.timers[name].elapsed(reset=reset)
            if dur > 0.0:
                tracer.add_complete(name, now - dur, dur,
                                    thread="timers", step=iteration)


def _set_timers():
    global _GLOBAL_TIMERS
    _ensure_var_is_not_initialized(_GLOBAL_TIMERS, "timers")
    _GLOBAL_TIMERS = Timers()


def get_timers() -> Timers:
    """ref: utils.py:142-146."""
    global _GLOBAL_TIMERS
    if _GLOBAL_TIMERS is None:
        _set_timers()
    return _GLOBAL_TIMERS


# --- printing / ranks -------------------------------------------------------

def print_rank_0(message: str) -> None:
    """ref: utils.py:148-155 — single-controller: process_index 0."""
    if jax.process_index() == 0:
        print(message, flush=True)


def is_last_rank() -> bool:
    """ref: utils.py:157-159."""
    return jax.process_index() == jax.process_count() - 1


def print_rank_last(message: str) -> None:
    """ref: utils.py:161-168."""
    if is_last_rank():
        print(message, flush=True)


# --- norms / loss averaging -------------------------------------------------

def param_l2_norm(params) -> jnp.ndarray:
    """Global l2 norm over a parameter pytree
    (ref: utils.py:189-216 calc_params_l2_norm — the reference's
    multi_tensor_l2norm over TP-owned params; under pjit the global norm
    over sharded params is one jnp expression, XLA inserts the psum)."""
    leaves = jax.tree.leaves(params)
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in leaves))


def average_losses_across_data_parallel_group(losses,
                                              axis_name: Optional[str] =
                                              None):
    """ref: utils.py:218-227 — pmean inside shard_map, identity (already
    global) under plain pjit."""
    stacked = jnp.stack([jnp.asarray(l) for l in losses])
    if axis_name is not None:
        return jax.lax.pmean(stacked, axis_name)
    return stacked


def report_memory(name: str) -> None:
    """ref: utils.py:229-239 — TPU HBM stats via device memory_stats."""
    stats = []
    for d in jax.local_devices():
        s = d.memory_stats() or {}
        inuse = s.get("bytes_in_use", 0) / (1024 ** 2)
        limit = s.get("bytes_limit", 0) / (1024 ** 2)
        stats.append(f"{d} in-use {inuse:.0f}MB limit {limit:.0f}MB")
    print_rank_0(f"[{name}] memory: " + "; ".join(stats))


def get_ltor_masks_and_position_ids(data: jnp.ndarray,
                                    eod_token: Optional[int] = None,
                                    reset_position_ids: bool = False,
                                    reset_attention_mask: bool = False,
                                    eod_mask_loss: bool = False):
    """Left-to-right (causal) masks + position ids for GPT batches
    (ref: utils.py:279-307).  Returns (attention_mask, loss_mask,
    position_ids).  The eod-reset variants require per-sequence scans;
    the common (False) paths are vectorized.

    Mask polarity matches the reference's final ``attention_mask < 0.5``
    (ref: utils.py:305): **True = masked out** (may NOT attend) — the
    convention expected by ``FusedScaleMaskSoftmax``'s padding path and
    the -10000 additive fill.
    """
    micro_batch_size, seq_length = data.shape
    attention_mask = jnp.tril(
        jnp.ones((seq_length, seq_length), dtype=bool))[None, None]
    loss_mask = jnp.ones(data.shape, dtype=jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(data == eod_token, 0.0, loss_mask)
    position_ids = jnp.broadcast_to(
        jnp.arange(seq_length, dtype=jnp.int32), data.shape)
    if (reset_position_ids or reset_attention_mask) and eod_token is not \
            None:
        # Per-document resets: position ids restart after each EOD and
        # attention cannot cross document boundaries.
        doc_id = jnp.cumsum((data == eod_token).astype(jnp.int32), axis=1)
        prev_doc = jnp.concatenate(
            [jnp.zeros((micro_batch_size, 1), jnp.int32), doc_id[:, :-1]],
            axis=1)
        if reset_position_ids:
            seg_start = jnp.concatenate(
                [jnp.zeros((micro_batch_size, 1), jnp.int32),
                 jnp.where(data[:, :-1] == eod_token,
                           jnp.arange(1, seq_length, dtype=jnp.int32)[None],
                           0)], axis=1)
            start_of_seg = jax.lax.cummax(seg_start, axis=1)
            position_ids = (jnp.arange(seq_length, dtype=jnp.int32)[None]
                            - start_of_seg)
        if reset_attention_mask:
            same_doc = prev_doc[:, :, None] == prev_doc[:, None, :]
            attention_mask = attention_mask & same_doc[:, None]
    # Flip to True=masked (ref: utils.py:305 `attention_mask < 0.5`).
    return ~attention_mask, loss_mask, position_ids
