"""Stage-to-stage activation hand-off over the pipeline mesh axis.

TPU-native replacement for the reference's NCCL point-to-point layer
(ref: apex/transformer/pipeline_parallel/p2p_communication.py:31-404).
The reference batches isend/irecv pairs between pipeline neighbours and
hard-synchronizes after each exchange (ref :163-164).  Under SPMD there
is no per-rank send/recv: the equivalent primitive is ``lax.ppermute``
over the ``pipe`` axis — every stage simultaneously passes its activation
to a neighbour, XLA schedules it on ICI, and "no peer" slots receive
zeros (non-participating edges of the permutation), which the schedules
mask out exactly where the reference skips the p2p call on first/last
stages (ref :183-232).

The reference's scatter-gather optimization (split the activation
1/tp_size across TP ranks in flight, allgather after —
ref :116-121,166-179) is a bandwidth trick XLA performs natively when
activations carry a sharding over the tensor axis; no code is needed.

All nine public combinators (ref :183-404) are provided; the *_recv_*
fused variants are single ppermutes (the fusion the reference builds
from batched isend/irecv falls out of the collective formulation).
"""
from __future__ import annotations

import jax
from ..._compat import axis_size

from ...parallel_state import PIPE_AXIS


def _shift(x, axis_name: str, forward: bool, wrap: bool = False):
    size = axis_size(axis_name)
    if forward:
        perm = [(i, (i + 1) % size) for i in range(size if wrap
                                                   else size - 1)]
    else:
        perm = [((i + 1) % size, i) for i in range(size if wrap
                                                   else size - 1)]
    return jax.lax.ppermute(x, axis_name, perm)


def send_forward_recv_forward_cyclic(output_tensor,
                                     axis_name: str = PIPE_AXIS):
    """Cyclic forward hop: the last stage's output arrives at stage 0 —
    the interleaved schedule's model-chunk "connector" (the wrap-around
    send the reference implements as an extra p2p between first and last
    stage, ref: fwd_bwd_pipelining_with_interleaving.py chunk
    hand-off)."""
    return _shift(output_tensor, axis_name, forward=True, wrap=True)


def send_forward_recv_forward(output_tensor, axis_name: str = PIPE_AXIS):
    """Pass activations one stage forward; stage 0 receives zeros
    (ref: p2p_communication.py:333-356)."""
    return _shift(output_tensor, axis_name, forward=True)


def send_backward_recv_backward(input_tensor_grad,
                                axis_name: str = PIPE_AXIS):
    """Pass gradients one stage backward; the last stage receives zeros
    (ref: p2p_communication.py:357-380)."""
    return _shift(input_tensor_grad, axis_name, forward=False)


def send_forward(output_tensor, axis_name: str = PIPE_AXIS):
    """ref: p2p_communication.py:233-258.  Collective SPMD pairs every
    send with the matching receive; this is the same ppermute as
    :func:`send_forward_recv_forward` — the value is meaningful on
    stages > 0 and zeros on stage 0."""
    return _shift(output_tensor, axis_name, forward=True)


def recv_forward(output_tensor, axis_name: str = PIPE_AXIS):
    """ref: p2p_communication.py:183-208.  Alias of :func:`send_forward`
    viewed from the receiving stage."""
    return _shift(output_tensor, axis_name, forward=True)


def send_backward(input_tensor_grad, axis_name: str = PIPE_AXIS):
    """ref: p2p_communication.py:259-282."""
    return _shift(input_tensor_grad, axis_name, forward=False)


def recv_backward(input_tensor_grad, axis_name: str = PIPE_AXIS):
    """ref: p2p_communication.py:209-232."""
    return _shift(input_tensor_grad, axis_name, forward=False)


def send_forward_recv_backward(output_tensor, input_tensor_grad,
                               axis_name: str = PIPE_AXIS):
    """Fused 1F1B steady-state exchange (ref: p2p_communication.py:283-307):
    activations go forward while gradients come backward.  Two disjoint
    ppermutes XLA can overlap on opposite ICI directions."""
    return (_shift(output_tensor, axis_name, forward=True),
            _shift(input_tensor_grad, axis_name, forward=False))


def send_backward_recv_forward(input_tensor_grad, output_tensor,
                               axis_name: str = PIPE_AXIS):
    """ref: p2p_communication.py:308-332."""
    return (_shift(input_tensor_grad, axis_name, forward=False),
            _shift(output_tensor, axis_name, forward=True))
