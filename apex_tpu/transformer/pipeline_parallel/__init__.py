"""Pipeline (inter-layer) parallelism over the ``pipe`` mesh axis.

TPU-native re-design of ``apex.transformer.pipeline_parallel``: the
reference's per-process 1F1B/interleaved schedules + NCCL p2p become one
compiled program — a ``lax.scan`` over pipeline ticks with ``ppermute``
stage hand-offs inside ``shard_map`` (see schedules.py for the full
design rationale).
"""
from . import p2p_communication
from .schedules import (build_stage_params, forward_backward_no_pipelining,
                        forward_backward_pipelining_with_interleaving,
                        forward_backward_pipelining_without_interleaving,
                        get_forward_backward_func, pipeline_forward,
                        pipeline_plan)
from .utils import (average_losses_across_data_parallel_group,
                    get_current_global_batch_size, get_kth_microbatch,
                    get_ltor_masks_and_position_ids, get_micro_batch_size,
                    get_num_microbatches, get_timers, listify_model,
                    param_l2_norm, print_rank_0, print_rank_last,
                    setup_microbatch_calculator,
                    split_batch_into_microbatches, update_num_microbatches)

__all__ = [
    "p2p_communication", "build_stage_params",
    "forward_backward_no_pipelining",
    "forward_backward_pipelining_with_interleaving",
    "forward_backward_pipelining_without_interleaving",
    "get_forward_backward_func", "pipeline_forward", "pipeline_plan",
    "average_losses_across_data_parallel_group",
    "get_current_global_batch_size", "get_kth_microbatch",
    "get_ltor_masks_and_position_ids", "get_micro_batch_size",
    "get_num_microbatches", "get_timers", "listify_model", "param_l2_norm",
    "print_rank_0", "print_rank_last", "setup_microbatch_calculator",
    "split_batch_into_microbatches", "update_num_microbatches",
]
