"""Version shims for the jax surface apex_tpu depends on.

The repo is written against the modern public API (``jax.shard_map``
with ``check_vma``, ``jax.typeof``); older jax releases still in the
deployment fleet ship the same machinery under
``jax.experimental.shard_map`` with the ``check_rep`` spelling and no
``typeof``.  Every apex_tpu module (and the repo's tests/benches) goes
through this shim instead of touching ``jax.shard_map`` directly — the
trace-safety linter enforces it (rule APX501) so a new call site cannot
silently reintroduce the version dependency.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map", "typeof", "axis_size", "axis_index", "pcast",
           "set_mesh", "psum_replicated", "HAS_NATIVE_SHARD_MAP",
           "HAS_VMA"]

HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")

if HAS_NATIVE_SHARD_MAP:
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    # Old shard_map's check_rep machinery predates a few primitives the
    # repo traces through it; give the pass-through ones the standard
    # "output replication = meet of inputs" rules so check_rep=True
    # (which we forward — see shard_map below) does not reject them.
    try:
        from jax.experimental import shard_map as _sm_module
        from jax._src.ad_checkpoint import name_p as _name_p

        _sm_module.register_standard_check(_name_p)
        _sm_module.register_standard_rewrite(_name_p)
    except (ImportError, AttributeError):
        pass  # registry spelling changed: only named-value traces lose

    def shard_map(f=None, *, mesh=None, in_specs=None, out_specs=None,
                  check_vma=None, check_rep=None, **kwargs):
        """``jax.shard_map`` resolved on old jax from
        ``jax.experimental.shard_map``.

        ``check_vma`` is the modern name of ``check_rep`` and MUST be
        forwarded, not dropped: replication tracking also drives the
        transpose rule (with it off, old shard_map psums the cotangent
        of every replicated input — grads w.r.t. replicated params come
        back multiplied by the axis size).
        """
        if check_vma is None:
            check_vma = True if check_rep is None else check_rep
        if f is None:  # kwargs-first partial form: shard_map(mesh=...)(f)
            def bind(g):
                return shard_map(g, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=check_vma,
                                 **kwargs)
            return bind
        return _exp_shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma,
                              **kwargs)


if hasattr(jax.lax, "axis_size"):
    axis_size = jax.lax.axis_size
else:
    def axis_size(axis_name):
        """``jax.lax.axis_size`` fallback.  ``psum`` of a static 1 is
        constant-folded to the bound axis size as a Python int on every
        jax that lacks ``axis_size`` — no collective is emitted."""
        return jax.lax.psum(1, axis_name)


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:
    def pcast(x, axis_name, *, to):
        """``jax.lax.pcast`` fallback: identity.  Old jax has no
        varying-mesh-axis types, so there is nothing to cast — callers
        (e.g. ``parallel.distributed.make_varying``) lose only the
        static vma annotation, not any math."""
        del axis_name, to
        return x


if hasattr(jax, "shard_map"):  # vma-era jaxlib lowers this correctly
    axis_index = jax.lax.axis_index
else:
    def axis_index(axis_name):
        """``jax.lax.axis_index`` that never emits ``partition_id``.

        Old jaxlib lowers ``axis_index`` under jit-of-shard_map to
        ``stablehlo.partition_id``, which the CPU SPMD partitioner
        rejects whenever the op escapes the manual region ("meaning is
        ambiguous").  Deriving the index from a ``psum_scatter`` of an
        iota uses only collectives every partitioner handles: the
        scatter hands rank ``r`` element ``r`` of the cross-replica sum
        ``n * arange(n)``.
        """
        import jax.numpy as jnp

        n = axis_size(axis_name)
        arr = jnp.arange(n, dtype=jnp.float32)
        summed = jax.lax.psum_scatter(arr, axis_name,
                                      scatter_dimension=0, tiled=True)
        return (summed[0] / n).astype(jnp.int32)


# The varying-mesh-axis type system (jax.lax.pvary et al.) changed the
# reverse-mode semantics of collectives: with it, the cotangent of a
# REPLICATED (unvarying) psum output seeds ONCE across the axis; without
# it, psum's transpose is psum — the identical per-rank seeds of a
# replicated loss get summed, scaling every upstream gradient by the
# axis size.
HAS_VMA = hasattr(jax.lax, "pvary")


def psum_replicated(x, axis_name):
    """``psum`` for the replicate-a-masked-buffer idiom (one rank holds
    the data, the rest hold zeros; the psum hands every rank the full
    value), safe to differentiate THROUGH inside ``shard_map``.

    On vma-era jax this is plain ``jax.lax.psum``.  On old jax the
    transpose of psum is psum, which multiplies the replicated
    cotangent by the axis size (measured: pipeline-schedule grads came
    back exactly ``num_stages``x); the ``custom_vjp`` pins the
    mathematically-correct seed-once cotangent instead.
    """
    if HAS_VMA:
        return jax.lax.psum(x, axis_name)

    @jax.custom_vjp
    def rep(v):
        return jax.lax.psum(v, axis_name)

    rep.defvjp(lambda v: (rep(v), None), lambda _, ct: (ct,))
    return rep(x)


def rewrite_trace_free(*operands) -> bool:
    """Old-jax legality probe for Pallas calls inside ``shard_map``.

    ``check_rep=True`` runs the body under the replication-rewrite
    interpreter (``RewriteTrace``), which has no rule for
    ``pallas_call``; ``check_rep=False`` (and plain jit) does not.  An
    operand traced by a RewriteTrace therefore proves a Pallas call
    here would fail.  Class-name sniffing on a private type is ugly,
    but it is confined to this shim and only reachable on pre-vma jax.
    """
    return not any(
        type(getattr(x, "_trace", None)).__name__ == "RewriteTrace"
        for x in operands)


if hasattr(jax, "set_mesh"):
    set_mesh = jax.set_mesh
else:
    def set_mesh(mesh):
        """``jax.set_mesh`` fallback: a ``Mesh`` is itself the context
        manager that installs it as the ambient resource env on old
        jax (``with mesh:``)."""
        return mesh


if hasattr(jax, "typeof"):
    typeof = jax.typeof
else:
    class _AvalView:
        """Forwarding proxy over an old-jax aval: old avals carry no
        ``.vma``; callers (vma marking in pipeline schedules,
        ``ops._context.in_manual_axis_context``) read it as "the set of
        varying axes", for which the faithful old-jax answer is the
        empty set — there is no varying-type system to vary in."""

        __slots__ = ("_aval",)

        def __init__(self, aval):
            object.__setattr__(self, "_aval", aval)

        def __getattr__(self, name):
            if name == "vma":
                return frozenset()
            return getattr(object.__getattribute__(self, "_aval"), name)

    def typeof(x):
        """``jax.typeof`` fallback: the abstract value of ``x``, with a
        ``.vma`` view (see :class:`_AvalView`)."""
        from jax._src import core as _core

        return _AvalView(_core.get_aval(x))
