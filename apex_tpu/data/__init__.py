"""Input pipeline: native prefetching loader + device-transfer overlap.

Counterpart of the reference training scripts' ``torch.utils.data``
usage (ref: examples/imagenet/main_amp.py:228-236); see
:mod:`apex_tpu.data.loader` for the TPU-first design notes.
"""
from .loader import DataLoader, device_prefetch, native_available

__all__ = ["DataLoader", "device_prefetch", "native_available"]
