"""Prefetching batch loader: native C++ workers behind a Python iterator.

The runtime counterpart of the reference's input pipeline
(ref: examples/imagenet/main_amp.py:228-236 ``torch.utils.data.DataLoader``
with ``num_workers`` + ``pin_memory``).  Redesigned for the TPU training
loop instead of translated:

* the dataset is a raw resident/memory-mapped array (numpy ``memmap`` or
  in-memory) — no per-item Python objects, no IPC serialization;
* a C++ thread pool (``apex_tpu/csrc/prefetch_loader.cpp``) assembles
  shuffled batches into a bounded ready-queue ahead of consumption;
  ``ctypes`` releases the GIL during the blocking ``next`` call, so
  assembly overlaps the device step;
* shuffling is a per-epoch stable sort by splitmix64 keys drawn from
  ``(seed, epoch)`` — bitwise deterministic across runs, restarts,
  worker counts, and toolchains (torch needs generator state in the
  checkpoint for that; here resume is ``start_batch=k``, O(1));
* optionally the iterator stays one step ahead in device transfers
  (``device_prefetch=True``), the `pin_memory` analogue — JAX's async
  dispatch overlaps the host->device copy with the running step.

A pure-Python fallback with identical semantics serves when no C++
toolchain exists; parity is asserted in tests.
"""
from __future__ import annotations

import ctypes
from typing import Iterator, Optional, Tuple

import numpy as np

from ._build import NativeBuildError, native_library_path

_lib = None


def _load_native():
    global _lib
    if _lib is None:
        path = native_library_path()
        lib = ctypes.CDLL(path)
        lib.loader_create.restype = ctypes.c_void_p
        lib.loader_create.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
        ]
        lib.loader_next.restype = ctypes.c_int64
        lib.loader_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_void_p]
        lib.loader_batches_per_epoch.restype = ctypes.c_int64
        lib.loader_batches_per_epoch.argtypes = [ctypes.c_void_p]
        lib.loader_destroy.restype = None
        lib.loader_destroy.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def native_available() -> bool:
    try:
        _load_native()
        return True
    except (NativeBuildError, OSError):
        return False


_MASK64 = (1 << 64) - 1


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorized splitmix64 over a uint64 array (wrapping arithmetic)."""
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _epoch_perm(n: int, seed: int, epoch: int) -> np.ndarray:
    """Per-epoch permutation: stable sort by per-index splitmix64 keys.

    Deliberately NOT Fisher-Yates with a stdlib RNG: sort-by-hash-key
    has no implementation-defined components, so the C++ workers
    (prefetch_loader.cpp perm_for) and this numpy mirror are bitwise
    identical under any toolchain, and the numpy form vectorizes
    (ImageNet-scale n shuffles in milliseconds).  seed=0 = no shuffle.
    """
    if seed == 0:
        return np.arange(n, dtype=np.int64)
    base = int(_splitmix64(np.uint64(
        (seed ^ (0x9E3779B97F4A7C15 * (epoch + 1))) & _MASK64)))
    with np.errstate(over="ignore"):
        key = _splitmix64(np.uint64(base)
                          + np.arange(n, dtype=np.uint64))
    return np.argsort(key, kind="stable").astype(np.int64)


class DataLoader:
    """``for x, y in DataLoader(images, labels, batch_size=...)``.

    ``images``: float32 ``(n, ...)`` served as-is, or uint8 normalized to
    ``(v/255 - mean) / std`` per trailing channel.  ``labels``: int
    ``(n,)``.  Yields float32/int32 numpy arrays; only full batches are
    served (``len(loader)`` per epoch), new shuffle each epoch from
    ``(seed, epoch)``; ``seed=0`` disables shuffling.

    ``backend="native"`` requires the C++ library, ``"python"`` forces
    the fallback, ``"auto"`` prefers native.
    """

    def __init__(self, images: np.ndarray, labels: np.ndarray,
                 batch_size: int, seed: int = 1,
                 mean: Optional[Tuple[float, ...]] = None,
                 std: Optional[Tuple[float, ...]] = None,
                 num_threads: int = 2, prefetch_depth: int = 2,
                 backend: str = "auto", start_batch: int = 0):
        if images.dtype == np.float32:
            self._dtype = 0
        elif images.dtype == np.uint8:
            self._dtype = 1
        else:
            raise ValueError(f"images dtype {images.dtype} unsupported "
                             "(float32 or uint8)")
        if len(images) != len(labels):
            raise ValueError("images/labels length mismatch")
        if batch_size > len(images):
            raise ValueError("batch_size exceeds dataset size")
        self.images = np.ascontiguousarray(images)
        self.labels = np.ascontiguousarray(labels, dtype=np.int32)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.item_shape = images.shape[1:]
        self.item_elems = int(np.prod(self.item_shape, dtype=np.int64))
        self.channels = int(self.item_shape[-1]) if self.item_shape else 1
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)
        for arr, nm in ((self.mean, "mean"), (self.std, "std")):
            if arr is not None and arr.shape != (self.channels,):
                raise ValueError(f"{nm} must have {self.channels} entries")
        self.num_threads = max(1, int(num_threads))
        self.prefetch_depth = max(1, int(prefetch_depth))
        if backend == "auto":
            backend = "native" if native_available() else "python"
        if backend == "native" and not native_available():
            raise NativeBuildError("native loader backend unavailable")
        self.backend = backend
        self._handle = None
        # O(1) deterministic resume: batches [0, start_batch) are never
        # assembled, the schedule continues as if they had been served.
        self._cursor = int(start_batch)

    def __len__(self) -> int:
        return len(self.images) // self.batch_size

    # -- native path --------------------------------------------------------

    def _ensure_native(self):
        if self._handle is None:
            lib = _load_native()
            mean_p = (self.mean.ctypes.data_as(ctypes.c_void_p)
                      if self.mean is not None else None)
            std_p = (self.std.ctypes.data_as(ctypes.c_void_p)
                     if self.std is not None else None)
            self._handle = lib.loader_create(
                self.images.ctypes.data_as(ctypes.c_void_p),
                self.labels.ctypes.data_as(ctypes.c_void_p),
                len(self.images), self.item_elems, self._dtype,
                mean_p, std_p, self.channels, self.batch_size,
                self.seed, self.num_threads, self.prefetch_depth,
                self._cursor)
            if not self._handle:
                raise NativeBuildError("loader_create failed")

    def _next_native(self):
        lib = _load_native()
        x = np.empty((self.batch_size,) + self.item_shape, np.float32)
        y = np.empty((self.batch_size,), np.int32)
        got = lib.loader_next(self._handle,
                              x.ctypes.data_as(ctypes.c_void_p),
                              y.ctypes.data_as(ctypes.c_void_p))
        if got < 0:
            raise RuntimeError("loader was closed while waiting for a "
                               "batch")
        return x, y

    # -- python fallback ----------------------------------------------------

    def _next_python(self):
        epoch, idx = divmod(self._cursor, len(self))
        perm = getattr(self, "_perm_cache", (None, None))
        if perm[0] != epoch:
            perm = (epoch, _epoch_perm(len(self.images), self.seed, epoch))
            self._perm_cache = perm
        rows = perm[1][idx * self.batch_size:(idx + 1) * self.batch_size]
        xb = self.images[rows]
        if self._dtype == 1:
            xb = xb.astype(np.float32) / 255.0
            if self.mean is not None:
                xb = xb - self.mean
            if self.std is not None:
                xb = xb / self.std
        else:
            xb = xb.astype(np.float32, copy=True)
        return xb, self.labels[rows].copy()

    # -- iteration ----------------------------------------------------------

    def __iter__(self) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self):
        if self.backend == "native":
            self._ensure_native()
            out = self._next_native()
        else:
            out = self._next_python()
        self._cursor += 1
        return out

    def close(self):
        if self._handle is not None:
            _load_native().loader_destroy(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:  # apex-lint: disable=APX202 -- GC-time close: the interpreter (or the native lib) may already be torn down; nothing to log to
            pass


def device_prefetch(iterator, size: int = 2):
    """Wrap a host-batch iterator so device transfers run ``size`` steps
    ahead (the ``pin_memory``/DALI-overlap analogue): ``jax.device_put``
    is async, so enqueueing the next batch while the current step runs
    hides the host->device copy."""
    import collections

    import jax

    queue = collections.deque()
    it = iter(iterator)

    def put(batch):
        return jax.tree_util.tree_map(jax.device_put, batch)

    try:
        while len(queue) < size:
            queue.append(put(next(it)))
    except StopIteration:
        pass
    while queue:
        nxt = queue.popleft()
        try:
            queue.append(put(next(it)))
        except StopIteration:
            pass
        yield nxt
