"""Compile-on-first-use build for the native loader.

No pip, no cmake: one ``g++ -O3 -shared -fPIC -pthread`` invocation,
cached next to the source keyed by source mtime.  Absence of a compiler
degrades gracefully — the Python fallback loader has identical
semantics (tests assert parity).
"""
from __future__ import annotations

import os
import shutil
import subprocess
import threading

_LOCK = threading.Lock()
_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "csrc", "prefetch_loader.cpp")
_OUT = os.path.join(os.path.dirname(_SRC), "_build",
                    "libprefetch_loader.so")


class NativeBuildError(RuntimeError):
    pass


def native_library_path(rebuild: bool = False) -> str:
    """Return the path of the compiled shared library, building it if
    the cache is stale.  Raises :class:`NativeBuildError` when no
    compiler is available or compilation fails."""
    with _LOCK:
        if (not rebuild and os.path.exists(_OUT)
                and os.path.getmtime(_OUT) >= os.path.getmtime(_SRC)):
            return _OUT
        cxx = (os.environ.get("CXX")  # apex-lint: disable=APX301 -- CXX is the standard build-toolchain contract var, not an apex flag
               or shutil.which("g++") or shutil.which("c++"))
        if cxx is None:
            raise NativeBuildError("no C++ compiler on PATH")
        os.makedirs(os.path.dirname(_OUT), exist_ok=True)
        tmp = _OUT + ".tmp"
        cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SRC, "-o", tmp]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"{' '.join(cmd)} failed:\n{proc.stderr[-2000:]}")
        os.replace(tmp, _OUT)
        return _OUT
