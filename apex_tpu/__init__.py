"""apex_tpu — a TPU-native training-acceleration framework.

Brand-new JAX/XLA/Pallas implementation of the capability surface of
NVIDIA Apex (reference: guolinke/apex):

- :mod:`apex_tpu.amp` — mixed precision (O0–O5 policies, functional loss
  scaling, fp32 master weights).
- :mod:`apex_tpu.optimizers` — fused optimizers (Adam, LAMB, SGD,
  NovoGrad, Adagrad, mixed-precision LAMB) as Pallas kernels behind
  optax-compatible transformations.
- :mod:`apex_tpu.parallel` — data parallelism (gradient sync with DDP
  knob parity, SyncBatchNorm, LARC, ZeRO-sharded optimizers).
- :mod:`apex_tpu.transformer` — Megatron-style tensor/pipeline model
  parallelism over a ``jax.sharding.Mesh``.
- :mod:`apex_tpu.normalization`, :mod:`apex_tpu.ops` — fused layers and
  Pallas kernels (LayerNorm, scaled-masked softmax, fused cross-entropy,
  flash attention).
- :mod:`apex_tpu.parallel_state` — the mesh-axis registry.

No CUDA, no torch: compute lowers to XLA/Pallas; collectives ride the
ICI/DCN mesh.
"""
from . import parallel_state  # noqa: F401
# ONE rank-stamped handler on the "apex_tpu" root, installed by the one
# configurator (ref: apex/__init__.py:29-42's logger setup).  The
# formatter is re-exported here for parity; utils.log_util.get_logger is
# how library modules obtain loggers.
from .utils.log_util import (  # noqa: F401
    RankInfoFormatter,
    _configure_library_root_logger,
)

__version__ = "0.1.0"

_configure_library_root_logger()


def __getattr__(name):
    # Lazy subpackage imports keep `import apex_tpu` light.
    import importlib
    if name in ("amp", "optimizers", "ops", "normalization", "parallel",
                "transformer", "models", "utils", "contrib", "fp16_utils",
                "mlp", "fused_dense", "reparameterization", "testing",
                "pyprof", "data", "monitor"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")
