"""Persistent XLA compilation-cache wiring (ROADMAP item 2, ISSUE-8).

Compile cost is the other half of the wall-vs-device gap: the scan
driver amortizes per-step dispatch, but every *process* still pays the
full XLA compile of each entry point it touches — minutes of apparent
"wall" on a cold host that have nothing to do with the step being
measured.  JAX's persistent compilation cache keys a lowered module to
a disk entry; :func:`configure_compile_cache` points it at the
``APEX_TPU_COMPILE_CACHE_DIR`` registry flag (or an explicit
directory) and relaxes the min-size/min-compile-time floors so even
smoke-sized programs are cached — exactly the programs CI and the
drivers recompile most often.

One ``python -m apex_tpu.testing.entry_points --aot`` run per host
pre-populates the cache for every registered entry point
(``jit(...).lower().compile()`` — no execution); every later process
warm-starts from disk.  tests/test_scan_driver.py proves the
second-process hit with jax's own compile/cache-hit log records.
"""
from __future__ import annotations

import os
from typing import Optional

from ..analysis.flags import flag_str
from .log_util import get_logger

__all__ = ["configure_compile_cache"]

logger = get_logger(__name__)

_configured: Optional[str] = None


def configure_compile_cache(directory: Optional[str] = None,
                            ) -> Optional[str]:
    """Wire jax's persistent compilation cache to ``directory`` (default:
    the ``APEX_TPU_COMPILE_CACHE_DIR`` flag).  Returns the directory in
    effect, or None when the flag is unset (no-op — callers wire this
    unconditionally).  Idempotent; re-pointing at a different directory
    logs and re-configures.

    The min-entry-size and min-compile-time floors are relaxed so the
    smoke/test-tier programs (fast compiles, small modules) are cached
    too — on a laptop-class CPU host those floors would exclude exactly
    the programs whose cold-start this cache exists to kill.
    """
    global _configured
    if directory is None:
        directory = flag_str("APEX_TPU_COMPILE_CACHE_DIR")
    if not directory:
        return None
    if _configured == directory:
        return directory
    import jax

    os.makedirs(directory, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", directory)
    for name, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1)):
        if hasattr(jax.config, name):
            jax.config.update(name, val)
    # jax initializes the cache AT MOST ONCE, on the first compile: if
    # any compile ran before this call (or the dir changed), the
    # latched no-cache/old-dir state silently wins and every later
    # config.update is a no-op.  Reset so the next compile re-reads
    # the directory (verified against jax 0.4.37
    # compilation_cache._initialize_cache).
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)

        _cc.reset_cache()
    except (ImportError, AttributeError) as e:
        logger.warning(
            "compilation-cache reset unavailable (%s): the persistent "
            "cache only takes effect if no compile preceded this "
            "call", str(e)[:120])
    if _configured is not None:
        logger.info("compile cache re-pointed: %s -> %s", _configured,
                    directory)
    _configured = directory
    return directory
