"""Shared library utilities: rank-stamped logging + sharded checkpoints.

Parity surface for the reference's library-level observability glue —
the root-logger ``RankInfoFormatter`` (ref: apex/__init__.py:29-42) and
``apex/transformer/log_util.py`` — plus the Orbax-backed sharded/async
checkpoint layer (:mod:`apex_tpu.utils.checkpoint`), the TPU-native
upgrade of the reference's state-dict save/resume flow.

Checkpoint symbols resolve lazily: ``apex_tpu/__init__`` configures the
library logger through :mod:`.log_util` at import time, and pulling the
Orbax stack along with it would undo the package's lazy-import design.
"""
from .log_util import (
    RankInfoFormatter,
    get_logger,
    get_transformer_logger,
    set_logging_level,
)

__all__ = [
    "CheckpointFormatMismatch",
    "CheckpointManager",
    "latest_valid_step",
    "load_checkpoint",
    "save_checkpoint",
    "RankInfoFormatter",
    "get_logger",
    "get_transformer_logger",
    "set_logging_level",
]

_CHECKPOINT_SYMBOLS = ("CheckpointManager", "load_checkpoint",
                       "save_checkpoint", "latest_valid_step",
                       "CheckpointFormatMismatch")


def __getattr__(name):
    if name in _CHECKPOINT_SYMBOLS:
        from . import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(
        f"module 'apex_tpu.utils' has no attribute {name!r}")
