"""Shared library utilities: rank-stamped logging.

Parity surface for the reference's library-level observability glue —
the root-logger ``RankInfoFormatter`` (ref: apex/__init__.py:29-42) and
``apex/transformer/log_util.py``.
"""
from .log_util import (
    RankInfoFormatter,
    get_logger,
    get_transformer_logger,
    set_logging_level,
)

__all__ = [
    "RankInfoFormatter",
    "get_logger",
    "get_transformer_logger",
    "set_logging_level",
]
