"""Shared library utilities: rank-stamped logging + sharded checkpoints.

Parity surface for the reference's library-level observability glue —
the root-logger ``RankInfoFormatter`` (ref: apex/__init__.py:29-42) and
``apex/transformer/log_util.py`` — plus the Orbax-backed sharded/async
checkpoint layer (:mod:`apex_tpu.utils.checkpoint`), the TPU-native
upgrade of the reference's state-dict save/resume flow.
"""
from .checkpoint import CheckpointManager, load_checkpoint, save_checkpoint
from .log_util import (
    RankInfoFormatter,
    get_logger,
    get_transformer_logger,
    set_logging_level,
)

__all__ = [
    "CheckpointManager",
    "load_checkpoint",
    "save_checkpoint",
    "RankInfoFormatter",
    "get_logger",
    "get_transformer_logger",
    "set_logging_level",
]
