"""Library logging with (tp, pp, dp) rank stamping.

Parity surface for the reference's root-logger setup
(ref: apex/__init__.py:29-42 ``RankInfoFormatter`` + handler install) and
``apex/transformer/log_util.py`` (``get_transformer_logger``,
``set_logging_level``).  On TPU the "rank" of a single-controller process
is its mesh coordinates, read from :mod:`apex_tpu.parallel_state`; under
multi-controller ``jax.distributed`` each host process stamps its own
coordinates, which is exactly the reference's per-rank behavior.
"""
from __future__ import annotations

import logging
import os


class RankInfoFormatter(logging.Formatter):
    """Stamp every record with parallel-rank info
    (ref: apex/__init__.py:30-36)."""

    def format(self, record):
        from .. import parallel_state
        try:
            record.rank_info = parallel_state.get_rank_info()
        except Exception:  # apex-lint: disable=APX202 -- a log formatter must never raise: it would turn every log call into the crash it reports
            record.rank_info = "(tp=?, pp=?, dp=?)"
        return super().format(record)


_LIBRARY_ROOT_LOGGER_NAME = "apex_tpu"
_library_root_logger = logging.getLogger(_LIBRARY_ROOT_LOGGER_NAME)
_configured = False


def _configure_library_root_logger() -> None:
    """Install the rank-stamped stream handler once
    (ref: apex/__init__.py:38-42; non-propagating so user logging config
    is untouched)."""
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler()
    handler.setFormatter(RankInfoFormatter(
        "%(asctime)s - %(name)s - %(levelname)s - %(rank_info)s - "
        "%(message)s"))
    _library_root_logger.addHandler(handler)
    _library_root_logger.propagate = False
    # Pin the library default explicitly: with NOTSET the effective
    # level would track the ROOT logger, so an app turning on its own
    # DEBUG logging would suddenly surface apex_tpu INFO chatter.
    # set_logging_level remains the knob to change it.
    _library_root_logger.setLevel(logging.WARNING)
    _configured = True


def get_transformer_logger(name: str) -> logging.Logger:
    """Child logger keyed by module file name
    (ref: apex/transformer/log_util.py:7-9)."""
    _configure_library_root_logger()
    name_wo_ext = os.path.splitext(os.path.basename(name))[0]
    return logging.getLogger(
        f"{_LIBRARY_ROOT_LOGGER_NAME}.{name_wo_ext}")


def get_logger(name: str) -> logging.Logger:
    """Library logger for any subsystem — the ONE way apex_tpu modules
    obtain a logger, so exactly one rank-stamped handler ever exists on
    the ``apex_tpu`` root (the duplicate-handler bug this replaces:
    ``apex_tpu/__init__`` and this module each installed one).

    Accepts a dotted module ``__name__`` (used as-is, rooted under
    ``apex_tpu``) or a file path (the :func:`get_transformer_logger`
    idiom: basename without extension).
    """
    _configure_library_root_logger()
    if os.sep in name or name.endswith(".py"):
        name = os.path.splitext(os.path.basename(name))[0]
    if name != _LIBRARY_ROOT_LOGGER_NAME and \
            not name.startswith(_LIBRARY_ROOT_LOGGER_NAME + "."):
        name = f"{_LIBRARY_ROOT_LOGGER_NAME}.{name}"
    return logging.getLogger(name)


def set_logging_level(verbosity) -> None:
    """Change root library-logger severity
    (ref: apex/transformer/log_util.py:12-19)."""
    _configure_library_root_logger()
    _library_root_logger.setLevel(verbosity)
