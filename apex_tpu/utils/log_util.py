"""Library logging with (tp, pp, dp) rank stamping.

Parity surface for the reference's root-logger setup
(ref: apex/__init__.py:29-42 ``RankInfoFormatter`` + handler install) and
``apex/transformer/log_util.py`` (``get_transformer_logger``,
``set_logging_level``).  On TPU the "rank" of a single-controller process
is its mesh coordinates, read from :mod:`apex_tpu.parallel_state`; under
multi-controller ``jax.distributed`` each host process stamps its own
coordinates, which is exactly the reference's per-rank behavior.
"""
from __future__ import annotations

import logging
import os


class RankInfoFormatter(logging.Formatter):
    """Stamp every record with parallel-rank info
    (ref: apex/__init__.py:30-36)."""

    def format(self, record):
        from .. import parallel_state
        try:
            record.rank_info = parallel_state.get_rank_info()
        except Exception:
            record.rank_info = "(tp=?, pp=?, dp=?)"
        return super().format(record)


_LIBRARY_ROOT_LOGGER_NAME = "apex_tpu"
_library_root_logger = logging.getLogger(_LIBRARY_ROOT_LOGGER_NAME)
_configured = False


def _configure_library_root_logger() -> None:
    """Install the rank-stamped stream handler once
    (ref: apex/__init__.py:38-42; non-propagating so user logging config
    is untouched)."""
    global _configured
    if _configured:
        return
    handler = logging.StreamHandler()
    handler.setFormatter(RankInfoFormatter(
        "%(asctime)s - %(name)s - %(levelname)s - %(rank_info)s - "
        "%(message)s"))
    _library_root_logger.addHandler(handler)
    _library_root_logger.propagate = False
    _configured = True


def get_transformer_logger(name: str) -> logging.Logger:
    """Child logger keyed by module file name
    (ref: apex/transformer/log_util.py:7-9)."""
    _configure_library_root_logger()
    name_wo_ext = os.path.splitext(os.path.basename(name))[0]
    return logging.getLogger(
        f"{_LIBRARY_ROOT_LOGGER_NAME}.{name_wo_ext}")


# General-purpose alias: the library logger for any subsystem.
get_logger = get_transformer_logger


def set_logging_level(verbosity) -> None:
    """Change root library-logger severity
    (ref: apex/transformer/log_util.py:12-19)."""
    _configure_library_root_logger()
    _library_root_logger.setLevel(verbosity)
