"""Sharded, async, multi-host-ready checkpointing over Orbax.

The reference's checkpoint story is single-process ``state_dict``
pickling (ref: apex/amp/frontend.py:428-454 amp scaler serialization,
examples/imagenet/main_amp.py --resume flow); the flax-bytes helpers in
``examples/imagenet/main_amp.py`` mirror that path.  This module is the
TPU-native upgrade the reference never needed: under ``jax.sharding``
every process owns only its shard of the params/optimizer state, so a
checkpoint must be written collectively — Orbax's TensorStore backend
writes each shard from its owning host and restores with any (possibly
different) target sharding, enabling elastic resume across mesh shapes.

Semantics preserved from the amp flow:

* precision portability — when masters exist they are saved (fp32), and
  model params are re-cast from them on restore (the O2/O5 state-dict
  hook, ref: apex/amp/_initialize.py:133-142);
* the scaler state rides along via ``AmpOptimizer.state_dict`` exactly
  as ``amp.state_dict()`` does;
* ``save`` is asynchronous: the training loop continues while shards
  flush (call ``wait()``/``close`` — or rely on the context manager —
  before exiting).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax

from .. import amp as _amp


def _manager(directory: str, keep: int):
    import orbax.checkpoint as ocp

    # Only absolutize plain filesystem paths — abspath would mangle
    # URI-scheme destinations (gs://bucket/... -> <cwd>/gs:/bucket/...).
    if "://" not in directory:
        directory = os.path.abspath(directory)
    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=keep, create=True, enable_async_checkpointing=True),
    )


class CheckpointManager:
    """``with CheckpointManager(dir) as mgr: mgr.save(step, ...)``.

    Thin policy layer over ``orbax.checkpoint.CheckpointManager`` that
    knows the amp layout (masters / scalers / model-dtype writeback).
    ``extra`` carries any additional pytrees (batch_stats, data-loader
    cursors, ...) — they are restored by structure.
    """

    def __init__(self, directory: str, keep: int = 3):
        self._mgr = _manager(directory, keep)

    # -- save ---------------------------------------------------------------

    def save(self, step: int, params: Any, amp_opt=None, amp_state=None,
             extra: Optional[dict] = None) -> None:
        """Async-save a training state at ``step``.

        With amp: the fp32 masters are written instead of the cast
        params (precision portability); scaler scalars ride in the
        ``amp`` entry.  Without amp: ``params`` is written as-is.
        """
        import orbax.checkpoint as ocp

        if amp_state is not None and amp_state.master_params is not None:
            tree = {"params": amp_state.master_params,
                    "inner_state": amp_state.inner_state}
        else:
            tree = {"params": params,
                    "inner_state": None if amp_state is None
                    else amp_state.inner_state}
        items = {"state": ocp.args.StandardSave(tree)}
        meta = {"step": int(step)}
        if amp_opt is not None and amp_state is not None:
            meta["amp"] = amp_opt.state_dict(amp_state)
        items["meta"] = ocp.args.JsonSave(meta)
        if extra:
            items["extra"] = ocp.args.StandardSave(extra)
        self._mgr.save(step, args=ocp.args.Composite(**items))

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, params: Any, amp_opt=None, amp_state=None,
                extra: Optional[dict] = None, step: Optional[int] = None):
        """Restore into the shapes/shardings of the given templates.

        Returns ``(params, amp_state, extra, step)`` — params in the
        model dtype (re-cast from restored masters when amp is active),
        restored onto whatever sharding the template arrays carry (a
        different mesh than the one saved from is fine).
        """
        import orbax.checkpoint as ocp

        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        if amp_state is not None and amp_state.master_params is not None:
            tree = {"params": amp_state.master_params,
                    "inner_state": amp_state.inner_state}
        else:
            tree = {"params": params,
                    "inner_state": None if amp_state is None
                    else amp_state.inner_state}
        items = {"state": ocp.args.StandardRestore(tree),
                 "meta": ocp.args.JsonRestore()}
        if extra:
            items["extra"] = ocp.args.StandardRestore(extra)
        out = self._mgr.restore(step, args=ocp.args.Composite(**items))
        tree = out["state"]
        meta = out["meta"]
        new_extra = out.get("extra") if extra else None

        if amp_state is not None and amp_state.master_params is not None:
            masters = tree["params"]
            new_params = _amp.restore_dtypes(masters, params)
            amp_state = amp_state._replace(
                master_params=masters, inner_state=tree["inner_state"])
        else:
            new_params = tree["params"]
            if amp_state is not None:
                amp_state = amp_state._replace(
                    inner_state=tree["inner_state"])
        if amp_opt is not None and amp_state is not None \
                and "amp" in meta:
            amp_state = amp_opt.load_state_dict(amp_state, meta["amp"])
        return new_params, amp_state, new_extra, int(meta["step"])

    # -- lifecycle ----------------------------------------------------------

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def save_checkpoint(directory: str, step: int, params, amp_opt=None,
                    amp_state=None, extra=None, keep: int = 3) -> None:
    """One-shot synchronous convenience wrapper."""
    with CheckpointManager(directory, keep=keep) as mgr:
        mgr.save(step, params, amp_opt, amp_state, extra)


def load_checkpoint(directory: str, params, amp_opt=None, amp_state=None,
                    extra=None, step: Optional[int] = None):
    """One-shot restore; see :meth:`CheckpointManager.restore`."""
    with CheckpointManager(directory) as mgr:
        return mgr.restore(params, amp_opt, amp_state, extra, step)


# Re-exported under jax.distributed multihost usage: every process must
# call save/restore collectively (Orbax coordinates via the JAX
# distributed client); no extra wiring needed here.
