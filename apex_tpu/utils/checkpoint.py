"""Sharded, async, multi-host-ready checkpointing over Orbax.

The reference's checkpoint story is single-process ``state_dict``
pickling (ref: apex/amp/frontend.py:428-454 amp scaler serialization,
examples/imagenet/main_amp.py --resume flow); the flax-bytes helpers in
``examples/imagenet/main_amp.py`` mirror that path.  This module is the
TPU-native upgrade the reference never needed: under ``jax.sharding``
every process owns only its shard of the params/optimizer state, so a
checkpoint must be written collectively — Orbax's TensorStore backend
writes each shard from its owning host and restores with any (possibly
different) target sharding, enabling elastic resume across mesh shapes.

Semantics preserved from the amp flow:

* precision portability — when masters exist they are saved (fp32), and
  model params are re-cast from them on restore (the O2/O5 state-dict
  hook, ref: apex/amp/_initialize.py:133-142);
* the scaler state rides along via ``AmpOptimizer.state_dict`` exactly
  as ``amp.state_dict()`` does;
* ``save`` is asynchronous: the training loop continues while shards
  flush (call ``wait()``/``close`` — or rely on the context manager —
  before exiting).

New for the resilience layer (:mod:`apex_tpu.resilience`): checkpoint
**integrity**.  A preempted or crashed run leaves garbage on disk — a
step dir killed before its commit marker, or payload files torn
mid-flush — and a restore that trips over it must not take the run
down.  :meth:`CheckpointManager.latest_valid_step` spots structural
garbage cheaply; :meth:`CheckpointManager.restore` (``step=None``)
additionally survives deep corruption by falling back step-by-step to
the newest checkpoint that actually restores, logging/emitting what was
skipped (``ckpt_skipped`` / ``ckpt_gc`` ``resilience`` events into an
optional ``sink``) and moving the garbage out of the way — structural
trash deleted, torn-restore steps quarantined as ``<step>.corrupt`` —
so it cannot shadow good steps forever.  An explicitly requested
missing step raises a
``FileNotFoundError`` naming the directory and the available steps —
not a raw Orbax traceback.
"""
from __future__ import annotations

import os
import shutil
from typing import Any, List, Optional, Tuple

import jax

from .. import amp as _amp
from .log_util import get_logger

#: Orbax's atomic-commit marker: written last, so its absence means the
#: step dir never finished (or was tampered with) — never restore it.
_FINALIZE_MARKER = "_CHECKPOINT_METADATA"


class CheckpointFormatMismatch(RuntimeError):
    """The checkpoint's master-weight layout does not match the restore
    template: one side is the persistent packed pipeline's
    ``PackedMasters`` flat buffers, the other the per-leaf master tree.
    Raised INSTEAD of letting Orbax fail on an opaque tree-structure
    mismatch (which the integrity fallback would then mistake for a
    torn payload and quarantine a perfectly good checkpoint).  Re-run
    with the matching mode — ``APEX_TPU_FUSED_PIPELINE=0`` /
    ``AmpOptimizer(pipeline=...)`` — or re-save under the new one."""


def _manager(directory: str, keep: int):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        directory,
        options=ocp.CheckpointManagerOptions(
            max_to_keep=keep, create=True, enable_async_checkpointing=True),
    )


def _fs_steps(directory: str) -> List[int]:
    """Numeric step dirs actually on disk (tmp dirs from a killed async
    save carry an ``.orbax-checkpoint-tmp`` suffix and don't parse)."""
    try:
        return sorted(int(n) for n in os.listdir(directory)
                      if n.isdigit()
                      and os.path.isdir(os.path.join(directory, n)))
    except (FileNotFoundError, NotADirectoryError):
        return []


def _step_integrity(step_dir: str) -> Tuple[bool, str]:
    """Cheap structural validity of one Orbax step dir: finalize marker
    present, every item subdir non-empty, the Standard/Json item
    metadata files in place.  Catches kill-before-commit and gross
    tampering; torn payload *contents* are only caught by the restore
    attempt itself (see :meth:`CheckpointManager.restore`)."""
    if not os.path.isfile(os.path.join(step_dir, _FINALIZE_MARKER)):
        return False, "unfinalized (no _CHECKPOINT_METADATA)"
    items = [n for n in os.listdir(step_dir)
             if os.path.isdir(os.path.join(step_dir, n))]
    if not items:
        return False, "no checkpoint items"
    for item in items:
        if not os.listdir(os.path.join(step_dir, item)):
            return False, f"empty item {item!r}"
    state_meta = os.path.join(step_dir, "state", "_METADATA")
    if os.path.isdir(os.path.dirname(state_meta)) \
            and not os.path.isfile(state_meta):
        return False, "state item missing _METADATA"
    meta_file = os.path.join(step_dir, "meta", "metadata")
    if os.path.isdir(os.path.dirname(meta_file)) and (
            not os.path.isfile(meta_file)
            or os.path.getsize(meta_file) == 0):
        return False, "meta item missing/empty"
    return True, ""


def latest_valid_step(directory: str) -> Optional[int]:
    """Newest step under ``directory`` that passes the structural
    integrity scan (None if there is none).  Module-level twin of
    :meth:`CheckpointManager.latest_valid_step` for callers that only
    need to *decide whether* to resume."""
    if "://" in directory:
        raise ValueError("integrity scan requires a filesystem path; "
                         "use CheckpointManager for URI destinations")
    for step in reversed(_fs_steps(directory)):
        ok, _ = _step_integrity(os.path.join(directory, str(step)))
        if ok:
            return step
    return None


class CheckpointManager:
    """``with CheckpointManager(dir) as mgr: mgr.save(step, ...)``.

    Thin policy layer over ``orbax.checkpoint.CheckpointManager`` that
    knows the amp layout (masters / scalers / model-dtype writeback).
    ``extra`` carries any additional pytrees (batch_stats, data-loader
    cursors, ...) — they are restored by structure.

    ``sink`` (optional, any :class:`apex_tpu.monitor.Sink`) receives
    ``resilience`` events when restore has to skip or GC a damaged
    step, so integrity fallbacks land in the same JSONL as the rest of
    the run's telemetry.
    """

    def __init__(self, directory: str, keep: int = 3, sink=None):
        # Only absolutize plain filesystem paths — abspath would mangle
        # URI-scheme destinations (gs://b/... -> <cwd>/gs:/b/...).
        if "://" not in directory:
            directory = os.path.abspath(directory)
        self.directory = directory
        self._keep = int(keep)
        self._sink = sink
        self._log = get_logger(__name__)
        # Sweep BEFORE Orbax opens: a structurally-invalid step dir
        # left by a dead process would otherwise sit in Orbax's step
        # list, where it silently vetoes any re-save of that step
        # number (save() returns False) while never being restorable.
        if self._fs_backed():
            self._sweep_invalid()
        self._mgr = _manager(directory, keep)

    def _sweep_invalid(self) -> None:
        """Quarantine structurally-invalid step dirs as
        ``<step>.corrupt`` at open (process 0 only under multihost;
        rename keeps the payload for a post-mortem while freeing the
        step number).  Assumes the single-writer model this module is
        built on: no *other* manager may have an async save in flight
        on this directory at open time (a step dir is briefly
        marker-less mid-finalize).  Tolerant of rename races: a
        concurrently swept dir is simply gone."""
        if jax.process_index() != 0:
            return
        for s in _fs_steps(self.directory):
            step_dir = os.path.join(self.directory, str(s))
            ok, reason = _step_integrity(step_dir)
            if ok:
                continue
            try:
                dst = step_dir + ".corrupt"
                shutil.rmtree(dst, ignore_errors=True)
                os.rename(step_dir, dst)
            except OSError:
                continue
            self._log.warning(
                "checkpoint step %d in %s quarantined at open: %s",
                s, self.directory, reason)
            self._emit("ckpt_quarantined", step=s, reason=reason,
                       directory=self.directory)

    # -- integrity surface ---------------------------------------------------

    def _fs_backed(self) -> bool:
        return "://" not in self.directory

    def available_steps(self) -> List[int]:
        """Steps present on disk (or known to Orbax for URI backends),
        regardless of validity."""
        if self._fs_backed():
            return _fs_steps(self.directory)
        return sorted(self._mgr.all_steps())

    def latest_valid_step(self) -> Optional[int]:
        """Newest step passing the structural integrity scan — the step
        ``restore(step=None)`` will try first.  Falls back to Orbax's
        own ``latest_step`` on URI backends (no local scan possible)."""
        if not self._fs_backed():
            return self._mgr.latest_step()
        return latest_valid_step(self.directory)

    def _emit(self, name: str, value=None, step=None, **attrs) -> None:
        from ..monitor.events import emit_resilience

        emit_resilience(self._sink, name, value=value, step=step,
                        **attrs)

    def _reopen(self) -> None:
        """Recreate the Orbax manager after step dirs were removed
        behind its back (its step cache must not resurrect them)."""
        self._mgr.close()
        self._mgr = _manager(self.directory, self._keep)

    # -- save ---------------------------------------------------------------

    def save(self, step: int, params: Any, amp_opt=None, amp_state=None,
             extra: Optional[dict] = None) -> None:
        """Async-save a training state at ``step``.

        With amp: the fp32 masters are written instead of the cast
        params (precision portability); scaler scalars ride in the
        ``amp`` entry.  Without amp: ``params`` is written as-is.
        """
        import orbax.checkpoint as ocp

        if amp_state is not None and amp_state.master_params is not None:
            tree = {"params": amp_state.master_params,
                    "inner_state": amp_state.inner_state}
        else:
            tree = {"params": params,
                    "inner_state": None if amp_state is None
                    else amp_state.inner_state}
        items = {"state": ocp.args.StandardSave(tree)}
        meta = {"step": int(step)}
        if amp_state is not None and amp_state.master_params is not None:
            # Record the master layout so a mixed-mode restore fails
            # with a clear CheckpointFormatMismatch, not an opaque
            # Orbax structure error (absent key = pre-pipeline
            # checkpoint = per-leaf masters).
            meta["packed_masters"] = hasattr(
                amp_state.master_params, "to_model")
        if amp_opt is not None and amp_state is not None:
            meta["amp"] = amp_opt.state_dict(amp_state)
        items["meta"] = ocp.args.JsonSave(meta)
        if extra:
            items["extra"] = ocp.args.StandardSave(extra)
        accepted = self._mgr.save(step, args=ocp.args.Composite(**items))
        if accepted is False:
            # Orbax skips (returns False) instead of raising when the
            # step number already exists on disk — a silent drop here
            # would let a clean-exit marker claim durability the store
            # doesn't have.
            raise RuntimeError(
                f"checkpoint save of step {step} under "
                f"{self.directory} was declined by Orbax (step already "
                f"on disk?); existing steps: {self.available_steps()}")

    # -- restore ------------------------------------------------------------

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, params: Any, amp_opt=None, amp_state=None,
                extra: Optional[dict] = None, step: Optional[int] = None,
                gc_invalid: bool = True):
        """Restore into the shapes/shardings of the given templates.

        Returns ``(params, amp_state, extra, step)`` — params in the
        model dtype (re-cast from restored masters when amp is active),
        restored onto whatever sharding the template arrays carry (a
        different mesh than the one saved from is fine).

        With ``step=None`` the restore is **integrity-checked**: steps
        failing the structural scan are skipped outright, and a
        structurally-sound step whose payload is torn (restore raises)
        falls back to the next-newest candidate — each skip logged and
        emitted as a ``ckpt_skipped`` event, and (``gc_invalid=True``)
        the damaged dirs moved out of the way so they never shadow a
        good step again (structural garbage deleted, restore failures
        quarantined as ``<step>.corrupt``).  An explicit ``step`` that
        does not exist raises a
        ``FileNotFoundError`` naming this directory and the available
        steps.
        """
        if step is not None:
            available = self.available_steps()
            if step not in available:
                raise FileNotFoundError(
                    f"checkpoint step {step} not found under "
                    f"{self.directory}; available steps: "
                    f"{available if available else 'none'}")
            return self._restore_step(step, params, amp_opt, amp_state,
                                      extra)
        if not self._fs_backed():
            # URI backend: no local integrity scan; plain Orbax path.
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found under {self.directory}")
            return self._restore_step(step, params, amp_opt, amp_state,
                                      extra)

        skipped: List[Tuple[int, str]] = []
        candidates: List[int] = []
        for s in sorted(_fs_steps(self.directory), reverse=True):
            ok, reason = _step_integrity(
                os.path.join(self.directory, str(s)))
            if ok:
                candidates.append(s)
            else:
                skipped.append((s, reason))
        result = None
        for s in candidates:
            try:
                result = self._restore_step(s, params, amp_opt,
                                            amp_state, extra)
                break
            except CheckpointFormatMismatch:
                # a good checkpoint in the OTHER master layout is not
                # damage — never quarantine it, surface the real error
                raise
            except Exception as e:  # apex-lint: disable=APX202 -- deep-restore fallback: ANY torn-payload error must become a skip entry (recorded + ckpt_skipped event upstream), never a crash
                skipped.append(
                    (s, f"restore failed: {type(e).__name__}: "
                        f"{str(e)[:200]}"))
        if result is None:
            detail = "".join(f"\n  step {s}: {r}" for s, r in skipped)
            raise FileNotFoundError(
                f"no valid checkpoint found under {self.directory}"
                + (f"; skipped:{detail}" if skipped else ""))
        restored_step = result[3]
        # Report/GC only what the fallback actually stepped over —
        # steps older than the one restored are not in the way.
        stale = sorted((s, r) for s, r in skipped if s > restored_step)
        for s, reason in stale:
            self._log.warning(
                "checkpoint step %d in %s skipped: %s (restored %d)",
                s, self.directory, reason, restored_step)
            self._emit("ckpt_skipped", step=s, reason=reason,
                       restored_step=restored_step,
                       directory=self.directory)
        if gc_invalid and stale:
            # Structural garbage (no commit marker / empty items) is
            # incomplete by construction — delete it.  A structurally
            # sound step whose *restore* failed could in principle be a
            # transient host error rather than a torn payload, so it is
            # quarantined (renamed ``<step>.corrupt``) instead of
            # destroyed — out of the step namespace, but recoverable
            # for a post-mortem.
            removed, quarantined = [], []
            for s, reason in stale:
                src = os.path.join(self.directory, str(s))
                if reason.startswith("restore failed"):
                    dst = src + ".corrupt"
                    shutil.rmtree(dst, ignore_errors=True)
                    os.rename(src, dst)
                    quarantined.append(s)
                else:
                    shutil.rmtree(src, ignore_errors=True)
                    removed.append(s)
            self._log.warning(
                "garbage-collected %d invalid checkpoint step(s): "
                "deleted %s, quarantined as .corrupt %s",
                len(stale), removed, quarantined)
            self._emit("ckpt_gc", value=len(stale),
                       steps=[s for s, _ in stale],
                       removed=removed, quarantined=quarantined,
                       directory=self.directory)
            self._reopen()
        return result

    def _restore_step(self, step: int, params: Any, amp_opt=None,
                      amp_state=None, extra: Optional[dict] = None):
        import orbax.checkpoint as ocp

        # Meta restores first, alone: it carries the master-layout flag
        # the format pre-check needs, and fetching it once here (reused
        # below, not re-restored in the Composite) keeps the amp path
        # at a single storage round-trip for the JSON item.
        meta = self._mgr.restore(
            step, args=ocp.args.Composite(
                meta=ocp.args.JsonRestore()))["meta"]
        if amp_state is not None and amp_state.master_params is not None:
            # Format pre-check before the full restore: a packed-vs-
            # leafwise master mismatch must raise the dedicated error,
            # not an Orbax structure failure the integrity fallback
            # would quarantine as a torn payload.
            want_packed = hasattr(amp_state.master_params, "to_model")
            have_packed = bool(meta.get("packed_masters", False))
            if want_packed != have_packed:
                raise CheckpointFormatMismatch(
                    f"checkpoint step {step} under {self.directory} "
                    f"stores {'packed' if have_packed else 'per-leaf'} "
                    f"master weights but the restore template is "
                    f"{'packed' if want_packed else 'per-leaf'} — the "
                    "persistent-pipeline mode changed between save and "
                    "restore.  Re-run with the matching mode "
                    "(APEX_TPU_FUSED_PIPELINE / AmpOptimizer("
                    "pipeline=...)) or re-save the checkpoint.")
            tree = {"params": amp_state.master_params,
                    "inner_state": amp_state.inner_state}
        else:
            tree = {"params": params,
                    "inner_state": None if amp_state is None
                    else amp_state.inner_state}
        items = {"state": ocp.args.StandardRestore(tree)}
        if extra:
            items["extra"] = ocp.args.StandardRestore(extra)
        out = self._mgr.restore(step, args=ocp.args.Composite(**items))
        tree = out["state"]
        new_extra = out.get("extra") if extra else None

        if amp_state is not None and amp_state.master_params is not None:
            masters = tree["params"]
            if hasattr(masters, "to_model"):
                # Persistent packed pipeline mode: masters are a
                # PackedMasters (flat fp32 buffers + static layout) —
                # assemble the model-dtype params from the packed
                # buffers instead of a leafwise re-cast.
                new_params = masters.to_model(params)
            else:
                new_params = _amp.restore_dtypes(masters, params)
            amp_state = amp_state._replace(
                master_params=masters, inner_state=tree["inner_state"])
        else:
            new_params = tree["params"]
            if amp_state is not None:
                amp_state = amp_state._replace(
                    inner_state=tree["inner_state"])
        if amp_opt is not None and amp_state is not None \
                and "amp" in meta:
            amp_state = amp_opt.load_state_dict(amp_state, meta["amp"])
        return new_params, amp_state, new_extra, int(meta["step"])

    # -- lifecycle ----------------------------------------------------------

    def wait(self) -> None:
        """Block until pending async saves are durable."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def save_checkpoint(directory: str, step: int, params, amp_opt=None,
                    amp_state=None, extra=None, keep: int = 3) -> None:
    """One-shot synchronous convenience wrapper."""
    with CheckpointManager(directory, keep=keep) as mgr:
        mgr.save(step, params, amp_opt, amp_state, extra)


def load_checkpoint(directory: str, params, amp_opt=None, amp_state=None,
                    extra=None, step: Optional[int] = None):
    """One-shot restore; see :meth:`CheckpointManager.restore`."""
    with CheckpointManager(directory) as mgr:
        return mgr.restore(params, amp_opt, amp_state, extra, step)


# Re-exported under jax.distributed multihost usage: every process must
# call save/restore collectively (Orbax coordinates via the JAX
# distributed client); no extra wiring needed here.
