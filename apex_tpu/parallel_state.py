"""Model/data-parallel topology registry over a ``jax.sharding.Mesh``.

TPU-native replacement for the reference's process-group registry
(ref: apex/transformer/parallel_state.py:58-230).  Where the reference
factorizes world ranks into NCCL process groups (TP x PP x DP), here the
factorization is a named device mesh; XLA emits the collectives.  Rank
layout follows the reference's ordering contract
(ref: apex/transformer/parallel_state.py:68-83): tensor-parallel ranks are
adjacent devices (innermost mesh axis -> nearest ICI neighbours), data
parallel next, pipeline outermost (the axis that can tolerate DCN hops).

Usage::

    parallel_state.initialize_model_parallel(tensor_model_parallel_size=2,
                                             pipeline_model_parallel_size=2)
    mesh = parallel_state.get_mesh()
    with mesh:
        ...  # pjit / shard_map code using axis names 'data', 'pipe', 'tensor'
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
from ._compat import axis_index
import numpy as np
from jax.sharding import Mesh

from .mesh_plan import MeshAxis, MeshPlan  # noqa: F401  (re-export)

# Canonical mesh-axis names.  Everything in apex_tpu refers to these.
DATA_AXIS = "data"
PIPE_AXIS = "pipe"
TENSOR_AXIS = "tensor"
# Reserved names for the sequence/context- and expert-parallel modules
# (apex_tpu.transformer.{sequence,expert}_parallel); they build their
# own meshes today but share the canonical naming.
SEQUENCE_AXIS = "sequence"
EXPERT_AXIS = "expert"
# Device-order convention: ('pipe', 'data', 'tensor') — tensor innermost.
MESH_AXIS_ORDER = (PIPE_AXIS, DATA_AXIS, TENSOR_AXIS)


@dataclasses.dataclass
class _ParallelState:
    mesh: Optional[Mesh] = None
    tensor_model_parallel_size: int = 1
    pipeline_model_parallel_size: int = 1
    data_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: Optional[int] = None
    virtual_pipeline_model_parallel_rank: Optional[int] = None
    plan: Optional[MeshPlan] = None


_STATE = _ParallelState()


class ParallelStateError(RuntimeError):
    pass


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build and register the global mesh.

    Mirrors ``initialize_model_parallel``
    (ref: apex/transformer/parallel_state.py:58-167) with devices instead of
    ranks: world_size must be divisible by tp*pp; the remainder is the data
    parallel size.
    """
    if devices is None:
        devices = jax.devices()
    world_size = len(devices)
    tp = int(tensor_model_parallel_size)
    pp = int(pipeline_model_parallel_size)
    if tp < 1 or pp < 1:
        raise ParallelStateError(
            f"parallel sizes must be >=1, got tp={tp} pp={pp}"
        )
    if world_size % (tp * pp) != 0:
        raise ParallelStateError(
            f"world size ({world_size}) is not divisible by "
            f"tensor_model_parallel_size ({tp}) x "
            f"pipeline_model_parallel_size ({pp})"
        )
    dp = world_size // (tp * pp)
    if virtual_pipeline_model_parallel_size is not None and pp <= 2:
        # Same constraint as the reference: interleaving needs >2 stages.
        # (ref: apex/transformer/parallel_state.py:101-108)
        raise ParallelStateError(
            "virtual (interleaved) pipeline requires "
            "pipeline_model_parallel_size > 2"
        )

    device_grid = np.asarray(devices, dtype=object).reshape(pp, dp, tp)
    mesh = Mesh(device_grid, MESH_AXIS_ORDER)

    _STATE.plan = MeshPlan.build(
        axes=((PIPE_AXIS, pp, "pipeline"), (DATA_AXIS, dp, "data"),
              (TENSOR_AXIS, tp, "tensor")))
    _STATE.mesh = mesh
    _STATE.tensor_model_parallel_size = tp
    _STATE.pipeline_model_parallel_size = pp
    _STATE.data_parallel_size = dp
    _STATE.virtual_pipeline_model_parallel_size = (
        virtual_pipeline_model_parallel_size
    )
    _STATE.virtual_pipeline_model_parallel_rank = (
        0 if virtual_pipeline_model_parallel_size is not None else None
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    return _STATE.mesh is not None


def get_mesh() -> Mesh:
    if _STATE.mesh is None:
        raise ParallelStateError(
            "parallel state is not initialized; call "
            "initialize_model_parallel() first"
        )
    return _STATE.mesh


def get_mesh_plan() -> MeshPlan:
    """The registered topology as data (:class:`MeshPlan`): what the
    dryrun stamps into MULTICHIP rows and the SPMD auditor checks
    entries against."""
    if _STATE.plan is None:
        raise ParallelStateError(
            "parallel state is not initialized; call "
            "initialize_model_parallel() first"
        )
    return _STATE.plan


def destroy_model_parallel() -> None:
    """Drop the registered mesh (ref: parallel_state.py destroy at bottom)."""
    global _STATE
    _STATE = _ParallelState()


# --- world sizes (static; usable outside traced code) ----------------------

def get_tensor_model_parallel_world_size() -> int:
    return _STATE.tensor_model_parallel_size if _STATE.mesh is not None else 1


def get_pipeline_model_parallel_world_size() -> int:
    return _STATE.pipeline_model_parallel_size if _STATE.mesh is not None else 1


def get_data_parallel_world_size() -> int:
    return _STATE.data_parallel_size if _STATE.mesh is not None else 1


def get_world_size() -> int:
    return (
        get_tensor_model_parallel_world_size()
        * get_pipeline_model_parallel_world_size()
        * get_data_parallel_world_size()
    )


# --- ranks (traced; only valid inside shard_map/pjit over the mesh) --------

def get_tensor_model_parallel_rank():
    """Traced TP rank of the current shard (inside shard_map only)."""
    return axis_index(TENSOR_AXIS)


def get_pipeline_model_parallel_rank():
    return axis_index(PIPE_AXIS)


def get_data_parallel_rank():
    return axis_index(DATA_AXIS)


def is_pipeline_first_stage(ignore_virtual: bool = False):
    """Traced predicate (ref: parallel_state.py:188-205 semantics).

    NOTE: the virtual-pipeline component is read from Python state at
    *trace* time — call this only where a changed virtual rank forces a
    retrace (the pipeline schedules pass chunk indices explicitly instead
    of relying on this inside one compiled step)."""
    if not ignore_virtual and _STATE.virtual_pipeline_model_parallel_size:
        if get_virtual_pipeline_model_parallel_rank() != 0:
            return False
    return axis_index(PIPE_AXIS) == 0


def is_pipeline_last_stage(ignore_virtual: bool = False):
    if not ignore_virtual and _STATE.virtual_pipeline_model_parallel_size:
        vpp = _STATE.virtual_pipeline_model_parallel_size
        if get_virtual_pipeline_model_parallel_rank() != vpp - 1:
            return False
    return (
        axis_index(PIPE_AXIS)
        == get_pipeline_model_parallel_world_size() - 1
    )


# --- virtual (interleaved) pipeline bookkeeping ----------------------------

def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _STATE.virtual_pipeline_model_parallel_size


def get_virtual_pipeline_model_parallel_rank() -> Optional[int]:
    return _STATE.virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: Optional[int]) -> None:
    _STATE.virtual_pipeline_model_parallel_rank = rank


# --- logging / observability ----------------------------------------------

def get_rank_info() -> str:
    """Topology summary for log records (ref: parallel_state.py:169-179).

    JAX is single-controller: there is no per-process TP/PP/DP rank to stamp;
    instead we stamp the topology and the process index (multi-host)."""
    if _STATE.mesh is None:
        return "uninitialized"
    return (
        f"proc={jax.process_index()} "
        f"tp={_STATE.tensor_model_parallel_size} "
        f"pp={_STATE.pipeline_model_parallel_size} "
        f"dp={_STATE.data_parallel_size}"
    )
