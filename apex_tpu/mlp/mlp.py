"""MLP — fused multi-layer perceptron.

Parity with the reference's ``apex.mlp.MLP``
(ref: apex/mlp/mlp.py:8-79 over mlp_cuda, csrc/mlp_cuda.cu: cuBLAS GEMM
chain with bias/activation epilogues).  On TPU, XLA fuses the
dot+bias+activation chain natively (the epilogue fusion the reference
hand-codes), so this module is the API-parity surface lowering to
``dot_general`` chains; activations: none / relu / sigmoid.  Registered
with amp as a low-precision function (the reference registers via
``amp.half_function``, ref: apex/mlp/mlp.py:24).
"""
from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    """``MLP(mlp_sizes, bias=True, activation='relu')``
    (ref: apex/mlp/mlp.py:31-62).  ``mlp_sizes`` includes the input size:
    layers are ``mlp_sizes[i] -> mlp_sizes[i+1]``."""

    mlp_sizes: Sequence[int]
    bias: bool = True
    activation: str = "relu"
    dtype: jnp.dtype = None
    param_dtype: jnp.dtype = jnp.float32

    def setup(self):
        if self.activation not in ("none", "relu", "sigmoid"):
            raise TypeError(f"activation {self.activation} not supported "
                            "(ref: apex/mlp/mlp.py:43-50)")
        if len(self.mlp_sizes) < 2:
            raise ValueError("mlp_sizes needs at least input and one layer")

    @nn.compact
    def __call__(self, x):
        for i in range(1, len(self.mlp_sizes)):
            x = nn.Dense(self.mlp_sizes[i], use_bias=self.bias,
                         dtype=self.dtype, param_dtype=self.param_dtype,
                         name=f"layer_{i - 1}")(x)
            # Activation follows every GEMM, the last included
            # (ref: csrc/mlp.cpp epilogue; tests/L0/run_mlp/test_mlp.py
            # builds Linear+ReLU pairs for all layers).
            if self.activation == "relu":
                x = jnp.maximum(x, 0)
            elif self.activation == "sigmoid":
                x = 1.0 / (1.0 + jnp.exp(-x))
        return x
