"""apex_tpu.mlp — fused MLP (ref: apex/mlp)."""
from .mlp import MLP

__all__ = ["MLP"]
