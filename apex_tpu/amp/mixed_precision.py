"""Mixed-precision optimizer wrapper: master weights + scaler + skip-on-inf.

This is the functional equivalent of the reference's optimizer surgery
(ref: apex/amp/_process_optimizer.py:28-256 — master-weight swap, patched
``step``/``zero_grad``, ``_post_amp_backward`` unscale) combined with the
``scale_loss`` exit path (ref: apex/amp/handle.py:118-158).  Instead of
monkey-patching a stateful optimizer, the whole per-step pipeline —
unscale, fused finite-check, conditional update, master->model writeback,
scale adjustment — is one pure function compiled into the train step.
Overflow skip is a ``lax.cond`` (both branches compiled once, no recompile
churn, no host sync).  The finite-check/skip applies to *dynamic* scaling;
static scales step unconditionally like the reference (see
``AmpOptimizer.check_finite``).
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from . import cast as _cast
from . import scaler as _scaler
from ..ops import fused_pipeline as _pipeline
from ..ops import multi_tensor as _mt
from .policy import Policy, get_policy


class AmpState(NamedTuple):
    """Everything amp owns for one optimizer (a pytree).

    ``scalers`` is one :class:`ScalerState` per loss
    (ref: apex/amp/_initialize.py:227-231 creates ``num_losses`` scalers);
    masters and inner optimizer state are shared across losses, exactly as
    the reference shares one optimizer across ``loss_id``s.
    """

    inner_state: optax.OptState
    # fp32 master copy of params when the policy asks for master weights,
    # else None (inner optimizer then steps the model params directly).
    master_params: Optional[Any]
    scalers: Tuple[_scaler.ScalerState, ...]

    @property
    def scaler(self) -> _scaler.ScalerState:
        return self.scalers[0]


class StepInfo(NamedTuple):
    # With a *dynamic* scaler this is the measured finite flag; with a
    # static scaler gradients are not inspected (reference parity: the
    # static LossScaler steps regardless of overflow) and this reports
    # constant True.  ``grads_checked`` distinguishes the two: telemetry
    # that alerts on overflow must gate on ``grads_checked`` before
    # reading ``grads_finite`` — pass check_finite=True to AmpOptimizer
    # to measure (and skip) under static scaling too.
    grads_finite: jnp.ndarray
    loss_scale: jnp.ndarray
    steps_skipped: jnp.ndarray
    # Static (Python) flag: False when the step ran without inspecting
    # gradients, so grads_finite==True means "unchecked", not "healthy".
    grads_checked: bool = True
    # Unscaled global gradient L2 norm, measured by the fused
    # pipeline's norm sweep (None on the per-stage path, which never
    # computes one).  Telemetry consumers (StepMonitor) read it from
    # here instead of re-sweeping the gradient tree host-side; under
    # shard_map it is the LOCAL shard's norm.
    grad_norm: Optional[jnp.ndarray] = None


class AmpOptimizer:
    """Pairs an optax ``GradientTransformation`` with a precision policy.

    Functional analogue of ``amp.initialize(model, optimizer, ...)``
    (ref: apex/amp/frontend.py:258): parameters stay in the policy's model
    dtype; fp32 masters live in :class:`AmpState`; gradients arriving at
    :meth:`apply_gradients` are the *scaled* gradients of a loss produced by
    :func:`scale_loss`.
    """

    def __init__(self, tx: optax.GradientTransformation, policy: Policy,
                 num_losses: int = 1, axis_names=None,
                 check_finite: Optional[bool] = None,
                 pipeline: Optional[bool] = None):
        self.tx = tx
        self.policy = policy
        self.num_losses = int(num_losses)
        self.use_masters = bool(policy.master_weights)
        # Persistent packed pipeline (ops/fused_pipeline.py): masters +
        # optimizer state live in packed flat fp32 buffers across
        # steps and the whole post-backward step is two fused sweeps.
        # None resolves via APEX_TPU_FUSED_PIPELINE (default ON; "0"
        # is the escape hatch back to the per-stage path), read at
        # construction.  Requires master weights and an optimizer with
        # a pipeline form (fused_adam / fused_sgd / fused_lamb); under
        # the auto default anything else keeps the per-stage path, but
        # an EXPLICIT pipeline=True with missing prerequisites raises —
        # a silent staged fallback would corrupt pipeline-vs-staged
        # comparisons (bench) and user expectations.
        capable = (self.use_masters
                   and getattr(tx, "pipeline_step", None) is not None)
        if pipeline and not capable:
            raise ValueError(
                "pipeline=True requires master weights (policy."
                "master_weights) and an optimizer with a pipeline form "
                f"(fused_adam/fused_sgd/fused_lamb); got policy "
                f"{policy.opt_level!r} master_weights="
                f"{bool(policy.master_weights)}, tx "
                f"{type(tx).__name__} with pipeline_step="
                f"{getattr(tx, 'pipeline_step', None)}")
        self.use_pipeline = capable and _pipeline.pipeline_enabled(
            pipeline)
        # An explicit pipeline=True is a hard routing request (bench
        # pipeline-vs-staged comparisons depend on it); the auto
        # decision additionally applies the packed-size cutoff at
        # init() time, when the tree is first seen (the 0.73x
        # small-tree residue: below APEX_TPU_PIPELINE_PACK_MIN_BYTES
        # of packed model bytes, direct per-leaf staged updates
        # measured faster than the persistent pack).
        self._pipeline_explicit = pipeline is True
        # Model-parallel axes to reduce the found-inf flag over, so every
        # shard takes the same skip-vs-step branch (ref:
        # apex/transformer/amp/grad_scaler.py:25-36).  Only meaningful
        # when apply_gradients runs inside shard_map over these axes.
        self.axis_names = axis_names
        # None (default) = reference parity: inspect gradients only under
        # dynamic scaling (apex's static LossScaler never skips a step —
        # ref: apex/amp/scaler.py update_scale, should_skip only when
        # dynamic).  True forces the finite-check + skip even for static
        # scales (costs a full pass over the gradients: measured
        # 14 ms/step on GPT-345M @ v5e).  False is rejected for dynamic
        # scalers, whose scale schedule needs the flag.
        self.check_finite = check_finite

    # -- lifecycle ----------------------------------------------------------

    def init(self, params: Any) -> AmpState:
        """Build amp state.  Pass the *original* (highest-precision) params
        here, not the already-cast copy — masters are snapshotted exactly
        from them (the reference likewise clones masters from the fp32
        model before it is cast, ref: apex/amp/_process_optimizer.py:28-44).
        """
        if self._route_pipeline(params):
            # Persistent packed mode: the master "tree" is a
            # PackedMasters (flat fp32 buffers + static layout), the
            # inner state packs into the same layout.  The layout is
            # computed from the CAST model template so per-step
            # gradient packing groups identically.
            masters = _pipeline.pack_masters(
                params, _cast.cast_params(params, self.policy))
            inner = self.tx.pipeline_init(masters.metas)
        elif self.use_masters:
            masters = _cast.master_copy(params)
            inner = self.tx.init(masters)
        else:
            masters = None
            # Inner state dtypes must match what will actually be stepped
            # (the cast model params, e.g. fp16 under O3).
            inner = self.tx.init(_cast.cast_params(params, self.policy))
        return AmpState(
            inner_state=inner,
            master_params=masters,
            scalers=tuple(
                _scaler.init(self.policy.effective_loss_scale)
                for _ in range(self.num_losses)
            ),
        )

    def _route_pipeline(self, params: Any) -> bool:
        """The init-time pipeline routing decision for this tree.
        Explicit ``pipeline=True`` always packs; the auto decision
        routes trees below ``APEX_TPU_PIPELINE_PACK_MIN_BYTES`` of
        packed model bytes to the direct per-leaf staged path — the
        regime where the persistent pack measured 0.73x vs direct
        (ROADMAP item 4; the flag table in docs/api/ops.md has the
        cutoff's provenance)."""
        if not self.use_pipeline:
            return False
        if self._pipeline_explicit:
            return True
        from ..analysis.flags import flag_int

        cutoff = flag_int("APEX_TPU_PIPELINE_PACK_MIN_BYTES")
        if cutoff <= 0:
            return True
        # shapes/dtypes only: eval_shape keeps the probe off-device (a
        # real cast here would allocate a full low-precision model
        # copy just to read its byte total)
        model_template = jax.eval_shape(
            lambda p: _cast.cast_params(p, self.policy), params)
        return _pipeline.packed_nbytes(model_template) >= cutoff

    # -- per-iteration hooks ------------------------------------------------

    def scale_loss(self, loss: jnp.ndarray, state: AmpState,
                   loss_id: int = 0) -> jnp.ndarray:
        """``with amp.scale_loss(..., loss_id=i)`` entry
        (ref: apex/amp/handle.py:16)."""
        return _scaler.scale_loss(loss, state.scalers[loss_id])

    def apply_gradients(
        self, scaled_grads: Any, state: AmpState, params: Any,
        loss_id: int = 0, axis_names=None,
    ) -> Tuple[Any, AmpState, StepInfo]:
        """Unscale, check, conditionally step, writeback, update scale.

        Returns ``(new_params, new_state, info)``.  The skipped branch
        returns params/state unchanged (the reference's patched-no-op
        ``optimizer.step``, ref: apex/amp/handle.py:128-154).  With
        multiple losses, call once per loss with the matching ``loss_id``;
        masters/inner state advance each call, scalers independently.
        ``axis_names`` (default ``None`` = use the constructor's)
        reduces the finite flag over those mesh axes before branching,
        so model-parallel shards skip or step in lockstep.  Pass ``()``
        to explicitly disable the reduction for this call (e.g. when
        stepping the same optimizer outside shard_map).
        """
        # Dispatch on the STATE's layout, not the constructor flag:
        # the auto pipeline decision is per-tree (init() applies the
        # packed-size cutoff), and a checkpoint-restored state must
        # step the way it was built.
        if isinstance(state.master_params, _pipeline.PackedMasters):
            return self._apply_gradients_pipeline(
                scaled_grads, state, params, loss_id, axis_names)
        scaler = state.scalers[loss_id]
        fused_capable = getattr(self.tx, "fused_step", None) is not None
        # Single-pass optimizers upcast per-leaf inside their update
        # loop, so unscale in the gradient dtype (exact: power-of-two
        # scales) instead of materializing an fp32 grad tree.
        grads32 = _scaler.unscale(scaled_grads, scaler,
                                  out_dtype=None if fused_capable
                                  else jnp.float32)
        if axis_names is None:
            axis_names = self.axis_names

        stepped = state.master_params if self.use_masters else params
        # Single-pass optimizers (FusedTransformation.fused_step) apply
        # the update AND emit the low-precision model copy inside the
        # update kernel — XLA does not multi-output-fuse the separate
        # restore_dtypes pass (measured 2.1 ms/step of pure master->
        # bf16 convert at GPT-345M).
        fused = getattr(self.tx, "fused_step", None)

        def do_step(operand):
            grads32_, inner_, stepped_, model_ = operand
            if fused is not None:
                # fused_step upcasts per leaf inside its own fused
                # loop — no _grads_like tree materialization
                new_stepped, new_inner, new_model = fused(
                    grads32_, inner_, stepped_,
                    model_params=model_ if self.use_masters else None)
            else:
                g = _grads_like(grads32_, stepped_)
                updates, new_inner = self.tx.update(g, inner_, stepped_)
                new_stepped = optax.apply_updates(stepped_, updates)
                new_model = None
            if self.use_masters and new_model is None:
                # Master -> model writeback: emit params in the model
                # dtype (ref: apex/amp/_process_optimizer.py:14-25).
                new_model = _cast.restore_dtypes(new_stepped, model_)
            return new_stepped, new_inner, new_model

        check = self._resolve_check(scaler)
        if not check:
            # Static scaling never inspects gradients: the reference's
            # static LossScaler steps regardless of overflow
            # (ref: apex/amp/scaler.py update_scale — should_skip only
            # when dynamic; O4/O5 pin loss_scale=1).  Skipping the
            # grad-wide isfinite reduction saves a full pass over the
            # gradients (measured 14 ms/step on GPT-345M @ v5e).
            # StepInfo.grads_finite then reports constant True
            # ("unchecked") — see StepInfo.
            finite = jnp.bool_(True)
            new_stepped, new_inner, new_model = do_step(
                (grads32, state.inner_state, stepped, params))
        else:
            finite = _scaler.all_finite(grads32, axis_names=axis_names)

            def skip_step(operand):
                _, inner_, stepped_, model_ = operand
                # mirror do_step's writeback so both branches emit the
                # same structure/shapes (a skipped step re-casts the
                # unchanged masters — bitwise the old model params)
                model_out = _cast.restore_dtypes(stepped_, model_) \
                    if self.use_masters else None
                return stepped_, inner_, model_out

            new_stepped, new_inner, new_model = jax.lax.cond(
                finite, do_step, skip_step,
                (grads32, state.inner_state, stepped, params))

        if self.use_masters:
            new_params = new_model
            new_masters = new_stepped
        else:
            new_params = new_stepped
            new_masters = None

        new_scaler = _scaler.update(state.scalers[loss_id], finite)
        new_scalers = tuple(
            new_scaler if i == loss_id else s
            for i, s in enumerate(state.scalers)
        )
        new_state = AmpState(new_inner, new_masters, new_scalers)
        return new_params, new_state, StepInfo(
            grads_finite=finite,
            loss_scale=new_scaler.loss_scale,
            steps_skipped=new_scaler.steps_skipped,
            grads_checked=check,
        )

    def _resolve_check(self, scaler) -> bool:
        """Static decision: inspect gradients this step?  None
        (default) = reference parity — only under dynamic scaling
        (apex's static LossScaler never skips); True forces the check;
        False is rejected for dynamic scalers."""
        check = self.check_finite
        if check is None:
            return scaler.dynamic
        if not check and scaler.dynamic:
            raise ValueError("check_finite=False is invalid with a dynamic "
                             "loss scaler: the scale schedule needs the "
                             "finite flag")
        return check

    def _apply_gradients_pipeline(self, scaled_grads, state, params,
                                  loss_id, axis_names):
        """The persistent-packed post-backward step: TWO fused sweeps
        instead of the per-stage unscale / finite-check / update /
        master->model chain (see ops/fused_pipeline.py).

        Sweep 1 reads the packed grads once, producing the unscaled
        global norm and the finite flag (the multi_tensor_l2norm +
        overflow-buffer roles); sweep 2 reads grads+masters+state and
        writes masters+state+model-copy, with the unscale (and any
        optimizer clip) folded into its combined scale and the
        overflow skip as an in-sweep select.  Skip semantics match the
        per-stage ``lax.cond`` bitwise: state unchanged, model re-cast
        from the unchanged masters.

        Static scaling steps unconditionally (``_resolve_check``) AND
        elides the norm/finite sweep entirely — the per-stage path
        deliberately skips that grad-wide pass (measured 14 ms/step at
        GPT-345M) and the pipeline must not re-add it; StepInfo.
        grad_norm is then None (telemetry falls back) and any
        optimizer-level clip derives its own norm inside the update
        path.
        """
        scaler = state.scalers[loss_id]
        if axis_names is None:
            axis_names = self.axis_names
        masters = state.master_params
        metas = masters.metas
        gbufs = _pipeline.pack_grads(scaled_grads, metas)
        inv = (1.0 / scaler.loss_scale).astype(jnp.float32)
        check = self._resolve_check(scaler)
        if check:
            gnorm, finite_measured = _pipeline.grad_norm_finite(gbufs,
                                                                inv)
            finite = _scaler.reduce_finite(finite_measured, axis_names)
        else:
            gnorm, finite = None, jnp.bool_(True)
        new_mbufs, new_inner, lowp = self.tx.pipeline_step(
            gbufs, state.inner_state, masters.bufs, metas,
            grad_scale=inv, grad_norm=gnorm, finite=finite)
        model_leaves = jax.tree_util.tree_leaves(params)
        new_params = _mt.assemble(
            lowp, list(metas),
            out_dtypes=[jnp.asarray(l).dtype for l in model_leaves])
        new_masters = _pipeline.PackedMasters(tuple(new_mbufs), metas)
        new_scaler = _scaler.update(scaler, finite)
        new_scalers = tuple(
            new_scaler if i == loss_id else s
            for i, s in enumerate(state.scalers))
        new_state = AmpState(new_inner, new_masters, new_scalers)
        return new_params, new_state, StepInfo(
            grads_finite=finite,
            loss_scale=new_scaler.loss_scale,
            steps_skipped=new_scaler.steps_skipped,
            grads_checked=check,
            grad_norm=gnorm,
        )

    # -- checkpointing (ref: apex/amp/frontend.py:428-454) ------------------

    def state_dict(self, state: AmpState) -> dict:
        """Serialize every loss scaler (ref: apex/amp/frontend.py:428-437
        loops over ``_amp_state.loss_scalers``)."""
        d = {"scalers": [_scaler.state_dict(s) for s in state.scalers]}
        d["scaler"] = d["scalers"][0]  # convenience alias
        return d

    def load_state_dict(self, state: AmpState, d: dict) -> AmpState:
        if "scalers" in d:
            return state._replace(scalers=tuple(
                _scaler.load_state_dict(sd) for sd in d["scalers"]))
        return state._replace(
            scalers=(_scaler.load_state_dict(d["scaler"]),))


def _grads_like(grads32: Any, ref_tree: Any) -> Any:
    """Cast fp32 grads to match the stepped tree's leaf dtypes (inner
    optimizers expect updates in param dtype)."""
    return jax.tree_util.tree_map(
        lambda g, p: g.astype(jnp.asarray(p).dtype), grads32, ref_tree)


def initialize(
    params: Any,
    optimizer: optax.GradientTransformation,
    opt_level: str = "O5",
    num_losses: int = 1,
    axis_names=None,
    check_finite: Optional[bool] = None,
    pipeline: Optional[bool] = None,
    **overrides,
) -> Tuple[Any, AmpOptimizer, Any]:
    """The two-line setup entry, mirroring
    ``model, opt = amp.initialize(model, opt, opt_level=...)``
    (ref: apex/amp/frontend.py:258).

    Returns ``(cast_params, amp_optimizer, amp_state)``.  The state holds
    ``num_losses`` independent scalers (ref: apex/amp/_initialize.py:227-231)
    over one shared master copy + inner optimizer state; masters are
    snapshotted from the original ``params`` *before* the low-precision
    cast, so no precision is lost at initialization.
    """
    policy = get_policy(opt_level, **overrides)
    cast = _cast.cast_params(params, policy)
    amp_opt = AmpOptimizer(optimizer, policy, num_losses=num_losses,
                           axis_names=axis_names,
                           check_finite=check_finite,
                           pipeline=pipeline)
    return cast, amp_opt, amp_opt.init(params)
