"""Precision policies: the O0–O5 opt levels as data.

TPU-native redesign of the reference's opt-level frontend
(ref: apex/amp/frontend.py:7-246).  The reference encodes a policy as a
mutable ``Properties`` object plus global monkey-patching; here a policy is
an immutable dataclass threaded explicitly through the training step.  The
fork's bf16 levels O4/O5 (ref: apex/amp/frontend.py:207-246) are the
TPU-preferred defaults: bf16 compute, fp32 master weights (O5), loss scale
pinned to 1.0.

Level table (ref: apex/amp/frontend.py:118-246):

=====  ===========  ============  ==========  =======  ===========
level  cast_model   autocast ops  keep_bn32   masters  loss_scale
=====  ===========  ============  ==========  =======  ===========
O0     —            —             (fp32)      no       1.0
O1     —            fp16 lists    yes         no       dynamic
O2     fp16         —             yes         yes      dynamic
O3     fp16         —             no          no       1.0
O4     —            bf16 lists    yes         no       1.0
O5     bf16         —             yes         yes      1.0
Q8     bf16         —             yes         yes      1.0
=====  ===========  ============  ==========  =======  ===========

Q8 extends the ladder below O5 for **serving**: same bf16 activation
casting, loss scale pinned 1.0, plus ``quantize_weights="int8"`` —
matmul weights stored as per-output-channel symmetric int8 and run
through :func:`apex_tpu.ops.quant_matmul.quant_matmul`.  Training
under Q8 is O5 (quantization is a deployment transform applied to the
extracted serving weights, never differentiated through).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Union

import jax.numpy as jnp

DTypeLike = Any


@dataclasses.dataclass(frozen=True)
class Policy:
    """Immutable precision policy (the reference's ``Properties``,
    ref: apex/amp/frontend.py:7-113, as a frozen dataclass)."""

    opt_level: str = "O5"
    # Dtype model params are stored/computed in (None = leave fp32).
    # Reference: ``cast_model_type`` (frontend.py:36-46).
    cast_model_type: Optional[DTypeLike] = None
    # Op-level autocasting per whitelist/blacklist (the functional
    # replacement for ``patch_torch_functions``, frontend.py:48-57).
    cast_ops: bool = False
    # Dtype used by the op-level autocaster for whitelisted ops
    # (``patch_type`` fp16-vs-bf16, ref: apex/amp/amp.py:76-107).
    cast_ops_type: Optional[DTypeLike] = None
    # Keep batch-norm layers in fp32 while casting the rest
    # (frontend.py:59-76; applied via convert_network,
    # apex/amp/_initialize.py:176-182).
    keep_batchnorm_fp32: Optional[bool] = None
    # fp32 master copies of low-precision params, held in optimizer state
    # (ref: apex/amp/_process_optimizer.py:28-91).
    master_weights: Optional[bool] = None
    # "dynamic", a float, or None (=1.0).
    loss_scale: Union[str, float, None] = None
    # Cast model outputs to this dtype (``cast_model_outputs``,
    # frontend.py initialize kwarg).
    cast_model_outputs: Optional[DTypeLike] = None
    # Weight-only quantization for serving matmuls: None, or "int8"
    # (per-output-channel symmetric, apex_tpu.ops.quant_matmul).
    # Fork-added below the reference's ladder — a storage/compute
    # format for extracted serving weights, not a training cast.
    quantize_weights: Optional[str] = None

    def __post_init__(self):
        # Consistency validation in the spirit of Properties' setters
        # (ref: apex/amp/frontend.py:59-113).
        if self.cast_ops and self.cast_model_type is not None:
            raise ValueError(
                "cast_ops (O1/O4-style) and cast_model_type (O2/O5-style) "
                "are mutually exclusive, as in the reference "
                "(apex/amp/frontend.py:59-67)."
            )
        if self.cast_ops and self.cast_ops_type is None:
            object.__setattr__(self, "cast_ops_type", jnp.bfloat16)
        if self.master_weights and self.cast_model_type is None:
            raise ValueError(
                "master_weights=True requires a low-precision "
                "cast_model_type."
            )
        if self.quantize_weights not in (None, "int8"):
            raise ValueError(
                f"quantize_weights {self.quantize_weights!r} not in "
                f"(None, 'int8')"
            )

    # -- derived views ------------------------------------------------------

    @property
    def param_dtype(self):
        return self.cast_model_type or jnp.float32

    @property
    def compute_dtype(self):
        if self.cast_model_type is not None:
            return self.cast_model_type
        if self.cast_ops:
            return self.cast_ops_type
        return jnp.float32

    @property
    def effective_loss_scale(self) -> Union[str, float]:
        return self.loss_scale if self.loss_scale is not None else 1.0

    def replace(self, **kw) -> "Policy":
        return dataclasses.replace(self, **kw)


# --- opt-level presets (ref: apex/amp/frontend.py:118-246) ------------------

O0 = Policy(opt_level="O0", keep_batchnorm_fp32=None, master_weights=False,
            loss_scale=1.0)
O1 = Policy(opt_level="O1", cast_ops=True, cast_ops_type=jnp.float16,
            keep_batchnorm_fp32=None, master_weights=False,
            loss_scale="dynamic")
O2 = Policy(opt_level="O2", cast_model_type=jnp.float16,
            keep_batchnorm_fp32=True, master_weights=True,
            loss_scale="dynamic")
O3 = Policy(opt_level="O3", cast_model_type=jnp.float16,
            keep_batchnorm_fp32=False, master_weights=False, loss_scale=1.0)
# Fork-added bf16 levels (ref: apex/amp/frontend.py:207-246): loss scale
# pinned to 1.0 — bf16 has fp32's exponent range, no scaling needed.
O4 = Policy(opt_level="O4", cast_ops=True, cast_ops_type=jnp.bfloat16,
            keep_batchnorm_fp32=None, master_weights=False, loss_scale=1.0)
O5 = Policy(opt_level="O5", cast_model_type=jnp.bfloat16,
            keep_batchnorm_fp32=True, master_weights=True, loss_scale=1.0)
# Q8: O5's casting discipline plus int8 weight-only serving matmuls —
# the tier BELOW O5 on the ladder (less weight precision, same
# activation precision, loss scale still pinned: bf16 range rules).
Q8 = Policy(opt_level="Q8", cast_model_type=jnp.bfloat16,
            keep_batchnorm_fp32=True, master_weights=True,
            loss_scale=1.0, quantize_weights="int8")

opt_levels = {"O0": O0, "O1": O1, "O2": O2, "O3": O3, "O4": O4, "O5": O5,
              "Q8": Q8}


def get_policy(opt_level: Union[str, Policy] = "O5", **overrides) -> Policy:
    """Look up a preset and apply user overrides, the
    ``amp.initialize(opt_level=..., **kwargs)`` entry semantics
    (ref: apex/amp/frontend.py:258-420)."""
    if isinstance(opt_level, Policy):
        policy = opt_level
    else:
        try:
            policy = opt_levels[opt_level]
        except KeyError:
            raise ValueError(
                f"Unexpected opt_level {opt_level!r}; expected one of "
                f"{sorted(opt_levels)} (ref: apex/amp/frontend.py:346-351)"
            ) from None
    if overrides:
        policy = policy.replace(**overrides)
    return policy
