"""apex_tpu.amp — mixed-precision training (TPU-native apex.amp).

Capability surface of the reference's precision stack
(ref: apex/amp — frontend, _initialize, _process_optimizer, scaler,
handle), redesigned functionally: policies are data, the scaler is a
pytree, overflow-skip is a ``lax.cond``, and master weights live in
optimizer state.  See SURVEY.md §2.1/§7.
"""
from . import lists, scaler
from .autocast import (
    autocast,
    bfloat16_function,
    float_function,
    half_function,
    promote_function,
)
from .cast import (
    cast_inputs,
    cast_outputs,
    cast_params,
    convert_network,
    master_copy,
    restore_dtypes,
    tree_cast,
)
from .mixed_precision import AmpOptimizer, AmpState, StepInfo, initialize
from .policy import (O0, O1, O2, O3, O4, O5, Q8, Policy, get_policy,
                     opt_levels)
from .scaler import ScalerState, all_finite, scale_loss, unscale

__all__ = [
    "autocast", "half_function", "bfloat16_function", "float_function",
    "promote_function", "lists",
    "AmpOptimizer", "AmpState", "StepInfo", "initialize",
    "Policy", "get_policy", "opt_levels",
    "O0", "O1", "O2", "O3", "O4", "O5", "Q8",
    "ScalerState", "scaler", "scale_loss", "unscale", "all_finite",
    "cast_params", "cast_inputs", "cast_outputs", "convert_network",
    "master_copy", "restore_dtypes", "tree_cast",
]
