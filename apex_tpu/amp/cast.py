"""Parameter/input casting utilities.

Functional replacement for the reference's network conversion
(ref: apex/fp16_utils/fp16util.py:7-187 ``convert_network`` /
``BN_convert_float``, used live by amp O2/O5 at
apex/amp/_initialize.py:176-182) and the patched ``model.forward``
input/output casting (ref: apex/amp/_initialize.py:190-201).
"""
from __future__ import annotations

import re
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

# Heuristic for "is this leaf part of a batch-norm layer": matches flax's
# default module naming ("BatchNorm_0") and common hand-rolled names.  The
# reference identifies BN structurally via isinstance checks
# (ref: apex/fp16_utils/fp16util.py:30-42); a functional pytree only has
# key paths, so the predicate is name-based and user-overridable.
_BN_PAT = re.compile(r"(batch_?norm|(^|[^a-z])bn([^a-z]|$))", re.IGNORECASE)


def default_bn_predicate(path) -> bool:
    keys = [getattr(k, "key", getattr(k, "name", str(k))) for k in path]
    return any(_BN_PAT.search(str(k)) for k in keys)


def tree_cast(tree: Any, dtype) -> Any:
    """Cast every floating leaf to ``dtype`` (non-float leaves untouched)."""
    def _cast(x):
        x = jnp.asarray(x)
        return x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x
    return jax.tree_util.tree_map(_cast, tree)


def convert_network(params: Any, dtype,
                    keep_batchnorm_fp32: bool = True,
                    bn_predicate: Optional[Callable] = None) -> Any:
    """Cast a parameter pytree to ``dtype``, optionally keeping batch-norm
    leaves fp32 (ref: apex/fp16_utils/fp16util.py ``convert_network``;
    BN exemption per apex/amp/_initialize.py:176-182)."""
    pred = bn_predicate or default_bn_predicate

    def _cast(path, x):
        x = jnp.asarray(x)
        if not jnp.issubdtype(x.dtype, jnp.floating):
            return x
        if keep_batchnorm_fp32 and pred(path):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(_cast, params)


def cast_params(params: Any, policy) -> Any:
    """Apply a :class:`~apex_tpu.amp.Policy`'s model cast to params."""
    if policy.cast_model_type is None:
        return params
    keep_bn = policy.keep_batchnorm_fp32
    if keep_bn is None:
        keep_bn = True
    return convert_network(params, policy.cast_model_type, keep_bn)


def cast_inputs(args: Any, policy) -> Any:
    """Cast model inputs to the model dtype, the patched-``forward``
    entry cast (ref: apex/amp/_initialize.py:190-199)."""
    if policy.cast_model_type is None:
        return args
    return tree_cast(args, policy.cast_model_type)


def cast_outputs(outputs: Any, policy) -> Any:
    """Cast model outputs (default fp32 for O2/O5-style policies,
    ref: apex/amp/_initialize.py:199-201)."""
    out_dtype = policy.cast_model_outputs
    if out_dtype is None and policy.cast_model_type is not None:
        out_dtype = jnp.float32
    if out_dtype is None:
        return outputs
    return tree_cast(outputs, out_dtype)


def master_copy(params: Any) -> Any:
    """fp32 master copy of a (possibly low-precision) param tree
    (ref: apex/amp/_process_optimizer.py:28-91
    ``lazy_init_with_master_weights``)."""
    return tree_cast(params, jnp.float32)


def restore_dtypes(src: Any, like: Any) -> Any:
    """Cast ``src`` leaf-wise to the dtypes of ``like`` (master -> model
    writeback, ref: apex/fp16_utils/fp16util.py
    ``master_params_to_model_params``).  ``like`` may hold abstract
    leaves (``jax.ShapeDtypeStruct`` templates) — only dtypes are
    read."""
    def _dtype(l):
        d = getattr(l, "dtype", None)
        return d if d is not None else jnp.asarray(l).dtype

    return jax.tree_util.tree_map(
        lambda s, l: s.astype(_dtype(l)) if jnp.issubdtype(
            _dtype(l), jnp.floating) else s,
        src, like)
