"""Functional loss scaling.

TPU-native redesign of the reference's ``LossScaler``
(ref: apex/amp/scaler.py:42-226).  The reference keeps mutable Python state
and performs one device->host sync per iteration to learn whether gradients
overflowed (ref: apex/amp/scaler.py:206-224, ``update_scale``'s
``.item()``).  Here the scaler is a pytree (``ScalerState``) updated inside
the jitted train step; overflow handling is a ``lax.cond`` over the whole
optimizer update, so a step never leaves the device — zero host syncs.

Dynamic-scaling schedule matches the reference: on overflow multiply the
scale by ``backoff_factor`` (0.5) and reset the growth counter; after
``growth_interval`` (2000) consecutive finite steps multiply by
``growth_factor`` (2.0) (ref: apex/amp/scaler.py:206-224, "DYNAMIC_SCALE_*"
constants at apex/amp/_amp_state.py).  Static scaling is the same state with
growth disabled.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp

DYNAMIC_INIT_SCALE = 2.0 ** 16  # ref: apex/amp/scaler.py:49
GROWTH_FACTOR = 2.0
BACKOFF_FACTOR = 0.5
GROWTH_INTERVAL = 2000  # ref: apex/amp/scaler.py:219


class ScalerState(NamedTuple):
    """Loss-scaler state carried through the jitted step (a pytree)."""

    loss_scale: jnp.ndarray          # f32 scalar
    growth_tracker: jnp.ndarray      # i32 scalar: consecutive finite steps
    steps_skipped: jnp.ndarray       # i32 scalar: total overflow skips
    # Static (non-traced) configuration:
    dynamic: bool = True
    min_loss_scale: float = 1.0
    max_loss_scale: float = 2.0 ** 24  # ref: apex/amp/frontend.py Properties
    growth_interval: int = GROWTH_INTERVAL


# Static config fields must not be treated as pytree leaves.
jax.tree_util.register_pytree_node(
    ScalerState,
    lambda s: (
        (s.loss_scale, s.growth_tracker, s.steps_skipped),
        (s.dynamic, s.min_loss_scale, s.max_loss_scale, s.growth_interval),
    ),
    lambda aux, leaves: ScalerState(*leaves, *aux),
)


def init(loss_scale: Union[str, float, int, None] = "dynamic",
         min_loss_scale: float = 1.0,
         max_loss_scale: float = 2.0 ** 24) -> ScalerState:
    """Create scaler state.

    ``loss_scale`` follows the reference's convention
    (ref: apex/amp/frontend.py:118-246): ``"dynamic"`` for dynamic scaling,
    a number for static scaling, ``None`` for 1.0 (the bf16 O4/O5 regime,
    ref: apex/amp/frontend.py:213,223,245 pins loss_scale=1).
    """
    dynamic = loss_scale == "dynamic"
    scale = DYNAMIC_INIT_SCALE if dynamic else float(loss_scale or 1.0)
    return ScalerState(
        loss_scale=jnp.float32(scale),
        growth_tracker=jnp.int32(0),
        steps_skipped=jnp.int32(0),
        dynamic=dynamic,
        min_loss_scale=float(min_loss_scale),
        max_loss_scale=float(max_loss_scale),
    )


def scale_loss(loss: jnp.ndarray, state: ScalerState) -> jnp.ndarray:
    """``loss.float() * loss_scale`` (ref: apex/amp/handle.py:113)."""
    return loss.astype(jnp.float32) * state.loss_scale


def all_finite(tree: Any, axis_names=None) -> jnp.ndarray:
    """Single fused finite-check over a gradient pytree.

    Replaces the overflow flag threaded through
    ``amp_C.multi_tensor_scale`` (ref: apex/amp/scaler.py:103-159); XLA
    fuses the per-leaf reductions.

    ``axis_names`` (a mesh axis name or sequence of names) reduces the
    flag over model-parallel shards so every rank agrees on skip-vs-step
    — the reference's model-parallel ``GradScaler._maybe_opt_step``
    MAX-allreduce of found-inf over the model-parallel group
    (ref: apex/transformer/amp/grad_scaler.py:25-36).  Must only be
    passed inside a ``shard_map``/``pmap`` over those axes; under
    plain-GSPMD ``pjit`` the flag is computed on global values and is
    already consistent.
    """
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        finite = jnp.bool_(True)
    else:
        finite = jnp.stack(
            [jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
        ).all()
    return reduce_finite(finite, axis_names)


def reduce_finite(finite: jnp.ndarray, axis_names=None) -> jnp.ndarray:
    """AND a local finite flag over model-parallel mesh axes so every
    shard takes the same skip-vs-step branch (the MAX-allreduce of
    found-inf, ref: apex/transformer/amp/grad_scaler.py:25-36).  Shared
    by :func:`all_finite` and the fused pipeline's norm sweep."""
    if not axis_names:
        return finite
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    # inf anywhere on the model-parallel axes => everyone skips.
    bad = jax.lax.psum((~finite).astype(jnp.int32), tuple(axis_names))
    return bad == 0


def unscale(tree: Any, state: ScalerState, out_dtype=jnp.float32) -> Any:
    """Multiply grads by 1/scale, casting to ``out_dtype`` (fp32 by default,
    matching master-grad materialization, ref: apex/amp/scaler.py:161-193).

    ``out_dtype=None`` keeps each gradient's own dtype: the scale
    schedule only ever holds powers of two (init 2^16, x2 growth, x0.5
    backoff — ref schedule), so the low-precision multiply is EXACT and
    the fp32 upcast can instead fuse into the optimizer's per-leaf
    update loop (a separate fp32 grad tree costs a full read+write pass
    — measured 2.1 ms/step at GPT-345M).  Exactness needs the value to
    stay representable: bf16 shares fp32's exponent range, but an fp16
    grad divided by 2^16 lands in/below fp16's subnormals and is
    silently destroyed — fp16 leaves therefore still unscale in fp32
    (the reference's master-grad materialization, which fp16 genuinely
    needs)."""
    inv = (1.0 / state.loss_scale).astype(jnp.float32)
    if out_dtype is None:
        return jax.tree_util.tree_map(
            lambda g: g.astype(jnp.float32) * inv
            if g.dtype == jnp.float16 else g * inv.astype(g.dtype),
            tree)
    return jax.tree_util.tree_map(
        lambda g: g.astype(jnp.float32) * inv if out_dtype == jnp.float32
        else (g.astype(jnp.float32) * inv).astype(out_dtype),
        tree,
    )


def update(state: ScalerState, grads_finite: jnp.ndarray) -> ScalerState:
    """Advance scaler state given this step's finite flag.

    Pure function of (state, flag); the caller pairs it with a ``lax.cond``
    (or ``jnp.where`` on the update) that skips the optimizer step when
    ``grads_finite`` is False — the monkey-patched-``optimizer.step`` skip
    of the reference (ref: apex/amp/handle.py:128-154) expressed
    functionally.
    """
    if not state.dynamic:
        return state._replace(
            steps_skipped=state.steps_skipped + jnp.where(grads_finite, 0, 1)
        )
    tracker = jnp.where(grads_finite, state.growth_tracker + 1, 0)
    grow = tracker >= state.growth_interval
    new_scale = jnp.where(
        grads_finite,
        jnp.where(grow, state.loss_scale * GROWTH_FACTOR, state.loss_scale),
        state.loss_scale * BACKOFF_FACTOR,
    )
    new_scale = jnp.clip(new_scale, state.min_loss_scale,
                         state.max_loss_scale)
    return state._replace(
        loss_scale=new_scale,
        growth_tracker=jnp.where(grow, 0, tracker),
        steps_skipped=state.steps_skipped + jnp.where(grads_finite, 0, 1),
    )


def snapshot(state: ScalerState) -> dict:
    """Host-side scalar view of scaler state for telemetry (forces a
    device sync — call once per step at most, outside jit)."""
    return {"loss_scale": float(state.loss_scale),
            "growth_tracker": int(state.growth_tracker),
            "steps_skipped": int(state.steps_skipped)}


def update_telemetry(prev: Optional[dict], cur) -> dict:
    """Describe the latest :func:`update` transition for run telemetry.

    The reference surfaces overflow skips only as a printed
    "Gradient overflow.  Skipping step" line (ref: apex/amp/scaler.py
    update_scale); here the transition is structured so
    :class:`apex_tpu.monitor.StepMonitor` can log the scale and feed the
    overflow-streak watchdog.  ``cur`` is either a :class:`ScalerState`
    or an :class:`~apex_tpu.amp.mixed_precision.StepInfo`; ``prev`` is
    the previous step's :func:`snapshot` (``None`` on the first step,
    when a skip cannot be distinguished without the measured flag).
    """
    if hasattr(cur, "grads_checked"):  # amp StepInfo: the measured flag
        checked = bool(cur.grads_checked)
        scale = float(cur.loss_scale)
        skipped = int(cur.steps_skipped)
        overflow = checked and not bool(cur.grads_finite)
        if not checked and prev is not None:
            overflow = skipped > prev["steps_skipped"]
    else:  # bare ScalerState: infer the skip from the counter delta
        checked = False
        scale = float(cur.loss_scale)
        skipped = int(cur.steps_skipped)
        overflow = prev is not None and skipped > prev["steps_skipped"]
    return {"loss_scale": scale,
            "steps_skipped": skipped,
            "overflow": bool(overflow),
            "scale_changed": (prev is not None
                              and scale != prev["loss_scale"]),
            "checked": checked}


def state_dict(state: ScalerState) -> dict:
    """Serializable view (ref: amp.state_dict, apex/amp/frontend.py:428-437)."""
    return {
        "loss_scale": float(state.loss_scale),
        "growth_tracker": int(state.growth_tracker),
        "steps_skipped": int(state.steps_skipped),
        "dynamic": state.dynamic,
        "min_loss_scale": state.min_loss_scale,
        "max_loss_scale": state.max_loss_scale,
        "growth_interval": state.growth_interval,
    }


def load_state_dict(d: dict) -> ScalerState:
    """Inverse of :func:`state_dict` (ref: apex/amp/frontend.py:440+)."""
    return ScalerState(
        loss_scale=jnp.float32(d["loss_scale"]),
        growth_tracker=jnp.int32(d["growth_tracker"]),
        steps_skipped=jnp.int32(d.get("steps_skipped", 0)),
        dynamic=bool(d["dynamic"]),
        min_loss_scale=float(d.get("min_loss_scale", 1.0)),
        max_loss_scale=float(d.get("max_loss_scale", 2.0 ** 24)),
        growth_interval=int(d.get("growth_interval", GROWTH_INTERVAL)),
    )
