"""Op-level autocasting: the O1/O4 opt levels as a jaxpr interpreter.

The reference implements O1/O4 by monkey-patching the torch/Tensor/F
namespaces with casting closures chosen from whitelist/blacklist tables
(ref: apex/amp/amp.py:76-150, apex/amp/wrap.py:10-116).  JAX has no
mutable op namespace worth patching — instead, :func:`autocast` is a
*function transform*: it traces the wrapped function to a jaxpr, then
re-evaluates it primitive-by-primitive, casting inputs per the lists in
:mod:`apex_tpu.amp.lists`:

- matmul/conv primitives run in the compute dtype (fp16 for O1, bf16 for
  O4) — the MXU path;
- numerically-sensitive primitives (exp/log/rsqrt/large reductions) run
  in fp32;
- everything else runs in its input dtypes, with widest-type promotion
  for mixed binary operands (ref: apex/amp/wrap.py:66-116 ``promote``).

Because evaluation re-binds primitives on the caller's tracers, the
transform composes with ``jax.grad``/``jax.jit``/``vmap``: casts become
part of the traced graph and XLA CSE's repeated casts of the same weight
(subsuming the reference's weight cast cache, apex/amp/wrap.py:31-64).

Control flow is recursed into: ``scan``/``while``/``cond`` bodies are
re-traced through ``lax.scan``/``while_loop``/``switch`` with the
interpreter inside, so a transformer stacked with ``lax.scan`` gets
O1/O4 casting in its layers (the reference's patches likewise apply
inside any Python loop).  Carry/branch outputs are cast back to their
incoming dtypes so the structured-control-flow contracts (carry fixed
point, branch aval agreement) hold.  ``custom_jvp``/``custom_vjp``
calls get BOUNDARY casting: their float inputs are cast to the compute
dtype while the bodies (and gradient rules) run unmodified — the
reference's O1 patching likewise wraps the *call sites* of its fused
extensions without editing the kernels (see
``lists.CUSTOM_BOUNDARY_PRIMS``).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from . import lists
from .policy import Policy
from .. import _autocast_ctx as _actx


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast(x, dtype):
    if _is_float(x) and x.dtype != dtype:
        return jax.lax.convert_element_type(x, dtype)
    return x


def _widest(vals):
    dtypes = [v.dtype for v in vals if _is_float(v)]
    if not dtypes:
        return None
    return functools.reduce(jnp.promote_types, dtypes)


def _safe_map(f, *xs):
    for t in zip(*xs, strict=True):
        f(*t)


def _run_closed(closed, invals, compute_dtype, restore_out_dtypes=None):
    """Interpret a (Closed)Jaxpr under autocast.  With
    ``restore_out_dtypes`` each output is cast back to the given dtypes —
    required when the result feeds a structured contract (scan carry,
    while carry, cond branch agreement)."""
    inner_jaxpr = getattr(closed, "jaxpr", closed)
    inner_consts = getattr(closed, "consts", [])
    outs = _eval_autocast(inner_jaxpr, inner_consts, list(invals),
                          compute_dtype)
    if restore_out_dtypes is not None:
        outs = [_cast(o, d) if (_is_float(o) and d is not None) else o
                for o, d in zip(outs, restore_out_dtypes)]
    return outs


def _float_dtypes(vals):
    return [v.dtype if _is_float(v) else None for v in vals]


def _eval_scan(eqn, invals, compute_dtype):
    """Autocast inside a scan body by re-tracing through ``lax.scan``
    with the interpreter in the body (VERDICT weak #7: scanned
    transformer layers must receive O1/O4 casting).  Carries are cast
    back to their incoming dtypes each step so the carry fixed point
    holds; stacked outputs restore the body's declared dtypes."""
    p = eqn.params
    nc, nk = p["num_consts"], p["num_carry"]
    consts_in = invals[:nc]
    carry0 = tuple(invals[nc:nc + nk])
    xs = tuple(invals[nc + nk:])
    closed = p["jaxpr"]
    out_dtypes = _float_dtypes([v.aval for v in
                                getattr(closed, "jaxpr", closed).outvars])
    carry_dtypes = _float_dtypes(carry0)
    restore = carry_dtypes + out_dtypes[nk:]

    def body(carry, x):
        outs = _run_closed(closed, [*consts_in, *carry, *x],
                           compute_dtype, restore_out_dtypes=restore)
        return tuple(outs[:nk]), tuple(outs[nk:])

    carry_f, ys = jax.lax.scan(body, carry0, xs, length=p["length"],
                               reverse=p["reverse"],
                               unroll=p.get("unroll", 1))
    return [*carry_f, *ys]


def _eval_while(eqn, invals, compute_dtype):
    p = eqn.params
    cn, bn = p["cond_nconsts"], p["body_nconsts"]
    cond_consts = invals[:cn]
    body_consts = invals[cn:cn + bn]
    init = tuple(invals[cn + bn:])
    carry_dtypes = _float_dtypes(init)

    def cond_fn(carry):
        return _run_closed(p["cond_jaxpr"], [*cond_consts, *carry],
                           compute_dtype)[0]

    def body_fn(carry):
        return tuple(_run_closed(p["body_jaxpr"],
                                 [*body_consts, *carry], compute_dtype,
                                 restore_out_dtypes=carry_dtypes))

    return list(jax.lax.while_loop(cond_fn, body_fn, init))


def _eval_cond(eqn, invals, compute_dtype):
    branches = eqn.params["branches"]
    index, ops = invals[0], invals[1:]
    out_dtypes = _float_dtypes(
        [v.aval for v in
         getattr(branches[0], "jaxpr", branches[0]).outvars])

    def mk(b):
        return lambda *xs: tuple(_run_closed(
            b, xs, compute_dtype, restore_out_dtypes=out_dtypes))

    return list(jax.lax.switch(index, [mk(b) for b in branches], *ops))


def _eval_autocast(jaxpr: jcore.Jaxpr, consts, args, compute_dtype):
    env = {}

    def read(v):
        return v.val if isinstance(v, jcore.Literal) else env[v]

    def write(v, val):
        env[v] = val

    _safe_map(write, jaxpr.constvars, consts)
    _safe_map(write, jaxpr.invars, args)

    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        prim = eqn.primitive
        name = prim.name

        if name in lists.RECURSE_PRIMS and "jaxpr" in eqn.params:
            inner = eqn.params["jaxpr"]
            inner_jaxpr = getattr(inner, "jaxpr", inner)
            inner_consts = getattr(inner, "consts", [])
            outvals = _eval_autocast(
                inner_jaxpr, inner_consts, invals, compute_dtype)
        elif name == "scan":
            outvals = _eval_scan(eqn, invals, compute_dtype)
        elif name == "while":
            outvals = _eval_while(eqn, invals, compute_dtype)
        elif name == "cond":
            outvals = _eval_cond(eqn, invals, compute_dtype)
        else:
            if name in lists.LOW_PRECISION_PRIMS:
                invals = [_cast(x, compute_dtype) for x in invals]
                # A dot/conv traced from fp32 inputs carries
                # preferred_element_type=fp32; keep it — fp32 accumulation
                # over low-precision operands is exactly the MXU regime.
                pref = eqn.params.get("preferred_element_type")
                if (pref is not None
                        and jnp.dtype(pref) != jnp.dtype(compute_dtype)
                        and jax.default_backend() != "tpu"):
                    # CPU XLA cannot emit mixed low->fp32 dots inside
                    # scan/while bodies; upcasting the already-rounded
                    # operands realizes numerically identical math
                    # (operand rounding + fp32 accumulate).
                    invals = [_cast(x, pref) for x in invals]
            elif name in lists.FP32_PRIMS:
                invals = [_cast(x, jnp.float32) for x in invals]
            else:
                wide = _widest(invals)
                if wide is not None and any(
                        _is_float(x) and x.dtype != wide for x in invals):
                    invals = [_cast(x, wide) for x in invals]
            subfuns, bind_params = prim.get_bind_params(eqn.params)
            outvals = prim.bind(*subfuns, *invals, **bind_params)

        if not prim.multiple_results:
            outvals = [outvals]
        _safe_map(write, eqn.outvars, outvals)

    return [read(v) for v in jaxpr.outvars]


def autocast(fn: Optional[Callable] = None, *,
             compute_dtype: Any = jnp.bfloat16,
             policy: Optional[Policy] = None) -> Callable:
    """Wrap ``fn`` so its primitives execute under the O1/O4 cast lists.

    Usage (O4 is the default; pass ``compute_dtype=jnp.float16`` or an O1
    policy for the fp16 variant)::

        @amp.autocast
        def forward(params, x): ...

        grads = jax.grad(amp.autocast(loss_fn, policy=amp.O1))(params, x)
    """
    if fn is None:
        return functools.partial(
            autocast, compute_dtype=compute_dtype, policy=policy)
    if policy is not None:
        if not policy.cast_ops:
            # O0/O2-style policy: op-level casting disabled — identity.
            return fn
        compute_dtype = policy.cast_ops_type

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        flat_args, in_tree = jax.tree_util.tree_flatten((args, kwargs))
        out_tree_box = []

        def flat_fn(*fargs):
            a, k = jax.tree_util.tree_unflatten(in_tree, fargs)
            out = fn(*a, **k)
            flat_out, out_tree = jax.tree_util.tree_flatten(out)
            out_tree_box.append(out_tree)
            return flat_out

        # Trace with the autocast context set: the framework's fused
        # custom-VJP ops (flash attention, fused layer norm) read it
        # and cast their own inputs, embedding the boundary casts in
        # the traced graph (see apex_tpu/_autocast_ctx.py for why the
        # interpreter cannot cast custom_vjp call sites itself).
        token = _actx.set_autocast_dtype(compute_dtype)
        try:
            closed = jax.make_jaxpr(flat_fn)(*flat_args)
        finally:
            _actx.reset_autocast_dtype(token)
        out_flat = _eval_autocast(
            closed.jaxpr, closed.consts, flat_args, compute_dtype)
        return jax.tree_util.tree_unflatten(out_tree_box[0], out_flat)

    return wrapped


# --- explicit function registration (ref: apex/amp/amp.py:29-71) -----------

def half_function(fn: Callable) -> Callable:
    """Force-cast a function's float args to fp16
    (ref: apex/amp/amp.py ``half_function`` :29)."""
    return _casting_wrapper(fn, jnp.float16)


def bfloat16_function(fn: Callable) -> Callable:
    """Force-cast a function's float args to bf16 (fork's
    ``bfloat16_function``, ref: apex/amp/amp.py:33-38)."""
    return _casting_wrapper(fn, jnp.bfloat16)


def float_function(fn: Callable) -> Callable:
    """Force-cast a function's float args to fp32
    (ref: apex/amp/amp.py ``float_function`` :41)."""
    return _casting_wrapper(fn, jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Promote mixed float args to the widest input dtype
    (ref: apex/amp/wrap.py ``promote`` :66)."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        leaves = [x for x in jax.tree_util.tree_leaves((args, kwargs))
                  if _is_float(x)]
        wide = _widest(leaves)
        if wide is not None:
            args, kwargs = jax.tree_util.tree_map(
                lambda x: _cast(x, wide) if _is_float(x) else x,
                (args, kwargs))
        return fn(*args, **kwargs)
    return wrapped


def _casting_wrapper(fn, dtype):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        args, kwargs = jax.tree_util.tree_map(
            lambda x: _cast(x, dtype) if _is_float(x) else x, (args, kwargs))
        return fn(*args, **kwargs)
    return wrapped
