"""Op-level autocasting: the O1/O4 opt levels as a jaxpr interpreter.

The reference implements O1/O4 by monkey-patching the torch/Tensor/F
namespaces with casting closures chosen from whitelist/blacklist tables
(ref: apex/amp/amp.py:76-150, apex/amp/wrap.py:10-116).  JAX has no
mutable op namespace worth patching — instead, :func:`autocast` is a
*function transform*: it traces the wrapped function to a jaxpr, then
re-evaluates it primitive-by-primitive, casting inputs per the lists in
:mod:`apex_tpu.amp.lists`:

- matmul/conv primitives run in the compute dtype (fp16 for O1, bf16 for
  O4) — the MXU path;
- numerically-sensitive primitives (exp/log/rsqrt/large reductions) run
  in fp32;
- everything else runs in its input dtypes, with widest-type promotion
  for mixed binary operands (ref: apex/amp/wrap.py:66-116 ``promote``).

Because evaluation re-binds primitives on the caller's tracers, the
transform composes with ``jax.grad``/``jax.jit``/``vmap``: casts become
part of the traced graph and XLA CSE's repeated casts of the same weight
(subsuming the reference's weight cast cache, apex/amp/wrap.py:31-64).

Deliberate deviation: bodies of ``custom_jvp``/``custom_vjp`` functions
and ``scan``/``while``/``cond`` control flow are executed unmodified
(casting inside them could break user gradient rules or carry dtype
contracts); ``jit``-nested regions are recursed into.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.extend import core as jcore

from . import lists
from .policy import Policy


def _is_float(x) -> bool:
    return hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)


def _cast(x, dtype):
    if _is_float(x) and x.dtype != dtype:
        return jax.lax.convert_element_type(x, dtype)
    return x


def _widest(vals):
    dtypes = [v.dtype for v in vals if _is_float(v)]
    if not dtypes:
        return None
    return functools.reduce(jnp.promote_types, dtypes)


def _safe_map(f, *xs):
    for t in zip(*xs, strict=True):
        f(*t)


def _eval_autocast(jaxpr: jcore.Jaxpr, consts, args, compute_dtype):
    env = {}

    def read(v):
        return v.val if isinstance(v, jcore.Literal) else env[v]

    def write(v, val):
        env[v] = val

    _safe_map(write, jaxpr.constvars, consts)
    _safe_map(write, jaxpr.invars, args)

    for eqn in jaxpr.eqns:
        invals = [read(v) for v in eqn.invars]
        prim = eqn.primitive
        name = prim.name

        if name in lists.RECURSE_PRIMS and "jaxpr" in eqn.params:
            inner = eqn.params["jaxpr"]
            inner_jaxpr = getattr(inner, "jaxpr", inner)
            inner_consts = getattr(inner, "consts", [])
            outvals = _eval_autocast(
                inner_jaxpr, inner_consts, invals, compute_dtype)
        else:
            if name in lists.LOW_PRECISION_PRIMS:
                invals = [_cast(x, compute_dtype) for x in invals]
                params = dict(eqn.params)
                # A dot/conv traced from fp32 inputs carries
                # preferred_element_type=fp32; keep it — fp32 accumulation
                # over low-precision operands is exactly the MXU regime.
            elif name in lists.FP32_PRIMS:
                invals = [_cast(x, jnp.float32) for x in invals]
            else:
                wide = _widest(invals)
                if wide is not None and any(
                        _is_float(x) and x.dtype != wide for x in invals):
                    invals = [_cast(x, wide) for x in invals]
            subfuns, bind_params = prim.get_bind_params(eqn.params)
            outvals = prim.bind(*subfuns, *invals, **bind_params)

        if not prim.multiple_results:
            outvals = [outvals]
        _safe_map(write, eqn.outvars, outvals)

    return [read(v) for v in jaxpr.outvars]


def autocast(fn: Optional[Callable] = None, *,
             compute_dtype: Any = jnp.bfloat16,
             policy: Optional[Policy] = None) -> Callable:
    """Wrap ``fn`` so its primitives execute under the O1/O4 cast lists.

    Usage (O4 is the default; pass ``compute_dtype=jnp.float16`` or an O1
    policy for the fp16 variant)::

        @amp.autocast
        def forward(params, x): ...

        grads = jax.grad(amp.autocast(loss_fn, policy=amp.O1))(params, x)
    """
    if fn is None:
        return functools.partial(
            autocast, compute_dtype=compute_dtype, policy=policy)
    if policy is not None:
        if not policy.cast_ops:
            # O0/O2-style policy: op-level casting disabled — identity.
            return fn
        compute_dtype = policy.cast_ops_type

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        flat_args, in_tree = jax.tree_util.tree_flatten((args, kwargs))
        out_tree_box = []

        def flat_fn(*fargs):
            a, k = jax.tree_util.tree_unflatten(in_tree, fargs)
            out = fn(*a, **k)
            flat_out, out_tree = jax.tree_util.tree_flatten(out)
            out_tree_box.append(out_tree)
            return flat_out

        closed = jax.make_jaxpr(flat_fn)(*flat_args)
        out_flat = _eval_autocast(
            closed.jaxpr, closed.consts, flat_args, compute_dtype)
        return jax.tree_util.tree_unflatten(out_tree_box[0], out_flat)

    return wrapped


# --- explicit function registration (ref: apex/amp/amp.py:29-71) -----------

def half_function(fn: Callable) -> Callable:
    """Force-cast a function's float args to fp16
    (ref: apex/amp/amp.py ``half_function`` :29)."""
    return _casting_wrapper(fn, jnp.float16)


def bfloat16_function(fn: Callable) -> Callable:
    """Force-cast a function's float args to bf16 (fork's
    ``bfloat16_function``, ref: apex/amp/amp.py:33-38)."""
    return _casting_wrapper(fn, jnp.bfloat16)


def float_function(fn: Callable) -> Callable:
    """Force-cast a function's float args to fp32
    (ref: apex/amp/amp.py ``float_function`` :41)."""
    return _casting_wrapper(fn, jnp.float32)


def promote_function(fn: Callable) -> Callable:
    """Promote mixed float args to the widest input dtype
    (ref: apex/amp/wrap.py ``promote`` :66)."""
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        leaves = [x for x in jax.tree_util.tree_leaves((args, kwargs))
                  if _is_float(x)]
        wide = _widest(leaves)
        if wide is not None:
            args, kwargs = jax.tree_util.tree_map(
                lambda x: _cast(x, wide) if _is_float(x) else x,
                (args, kwargs))
        return fn(*args, **kwargs)
    return wrapped


def _casting_wrapper(fn, dtype):
    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        args, kwargs = jax.tree_util.tree_map(
            lambda x: _cast(x, dtype) if _is_float(x) else x, (args, kwargs))
        return fn(*args, **kwargs)
    return wrapped
