"""Cast lists at JAX-primitive granularity.

Functional analogue of the reference's whitelist/blacklist tables
(ref: apex/amp/lists/torch_overrides.py:7-133,
functional_overrides.py:18-81, tensor_overrides.py:14-56).  The reference
classifies *torch functions*; the autocast interpreter classifies *XLA
primitives*, which is both finer-grained and exhaustive (an op reaches the
accelerator only through a primitive, so nothing escapes the lists the way
an unpatched namespace alias could escape the reference's monkey-patching).

- LOW_PRECISION ("whitelist", ref FP16_FUNCS/BFLOAT16_FUNCS): MXU ops —
  matmuls and convolutions run in the compute dtype.
- FP32 ("blacklist", ref FP32_FUNCS): numerically-sensitive transcendental
  and reduction ops run in fp32.
- Everything else: run in input dtypes, promoting mixed binary operands to
  the widest type (ref CASTS promote semantics, apex/amp/wrap.py:66-116).
"""

# MXU ops -> compute dtype (ref: torch_overrides.py FP16_FUNCS :7-27 /
# BFLOAT16_FUNCS :29-48 list mm/matmul/conv*/addmm/...; all of those lower
# to these two primitives).
LOW_PRECISION_PRIMS = frozenset({
    "dot_general",
    "conv_general_dilated",
    "ragged_dot_general",
})

# Numerically-sensitive ops -> fp32 (ref: torch_overrides.py FP32_FUNCS
# :50-105 — acos, asin, cosh, erfinv, exp, expm1, log, log10, log1p, log2,
# reciprocal, rsqrt, sinh, tan, pow, softmax/log_softmax decompose into
# exp/log/div below; norms/sums decompose into reduce_sum).
FP32_PRIMS = frozenset({
    "exp", "exp2", "expm1",
    "log", "log1p",
    "pow", "integer_pow",
    "rsqrt", "sqrt",
    "sinh", "cosh", "tanh", "tan",
    "asin", "acos", "atan", "atan2",
    "asinh", "acosh", "atanh",
    "erf", "erfc", "erf_inv",
    "lgamma", "digamma",
    "logistic",
    "cumsum", "cumlogsumexp", "cumprod",
    "reduce_sum", "reduce_prod",
    "div",
})

# Ops whose mixed-dtype operands promote to the widest floating type
# (ref: CASTS table, torch_overrides.py:107-131).  The interpreter applies
# widest-type promotion to *any* primitive with mixed float inputs; this
# set is documentation of the reference's explicit list.
PROMOTE_PRIMS = frozenset({
    "add", "sub", "mul", "max", "min", "rem",
    "atan2", "nextafter", "select_n", "concatenate",
})

# Call-like primitives the interpreter recurses into; scan/while/cond
# are handled structurally (re-traced with the interpreter in their
# bodies, see autocast._eval_scan et al.).  OPAQUE bodies keep their
# custom autodiff rules untouched.
RECURSE_PRIMS = frozenset({"jit", "pjit", "closed_call", "core_call",
                           "remat", "remat2", "checkpoint"})
OPAQUE_PRIMS = frozenset({
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "custom_root", "custom_linear_solve",
})

# custom_vjp call sites cannot be boundary-cast at the jaxpr level —
# the saved body jaxpr is dtype-frozen (fp32 literals/pallas blocks
# break when re-bound at bf16).  Instead the framework's OWN custom-VJP
# ops read the autocast TRACE-TIME context (autocast_compute_dtype())
# and cast their inputs themselves: flash attention to the compute
# dtype (matmul whitelist), fused layer norm to fp32 (the reference's
# O1 puts layer_norm in FP32_FUNCS, ref:
# apex/amp/lists/torch_overrides.py).  User custom-VJP functions are
# untouched, exactly like unregistered functions under the reference's
# patching; register with half/bfloat16/float_function as needed.
