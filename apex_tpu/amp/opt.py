"""Legacy per-optimizer handle API (``OptimWrapper``).

Parity surface for ``apex/amp/opt.py:9-103`` — the pre-``amp.initialize``
workflow where a handle wraps an optimizer and ``scale_loss`` is a
per-loss context manager with per-loss dynamic scalers and
skip-on-overflow.  The modern path is :class:`apex_tpu.amp.AmpOptimizer`
(which this wrapper delegates to); this class exists so reference users
migrating ``amp_handle.wrap_optimizer(opt, num_loss=N)`` scripts find
the same shape.

Tape-free translation of the reference's grad plumbing: the context
manager yields a *scale factor carrier* — compute your grads of
``scaled_loss`` and hand them to :meth:`accumulate`; ``step`` applies
the summed unscaled grads unless any loss overflowed (the reference's
cached-grads dance at opt.py:27-52 exists only because torch grads
accumulate in-place; functional grads just add).
"""
from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax

from ..fp16_utils.loss_scaler import DynamicLossScaler


class OptimWrapper:
    """ref: apex/amp/opt.py:9."""

    def __init__(self, optimizer: optax.GradientTransformation,
                 params: Any, num_loss: int = 1):
        self._optimizer = optimizer
        self.params = params
        self.opt_state = optimizer.init(params)
        self._num_loss = num_loss
        self._loss_idx = 0
        self._skip_next = [False] * num_loss
        self._loss_scaler = [DynamicLossScaler() for _ in range(num_loss)]
        self._acc_grads: Optional[Any] = None

    @contextlib.contextmanager
    def scale_loss(self, loss=None):
        """Per-loss scaling window (ref: opt.py:18-52).

        Yields the current loss scale (multiply your loss by it before
        differentiating); on exit the window advances to the next loss
        id.  Pass the scaled grads to :meth:`accumulate` inside the
        window.
        """
        scaler = self._cur_loss_scaler()
        yield scaler.loss_scale
        self._loss_idx += 1

    def accumulate(self, scaled_grads: Any) -> None:
        """Unscale grads of the current loss and add into the
        accumulator (the functional form of the reference's in-place
        ``p.grad`` accumulation + ``unscale``, ref: opt.py:39-45)."""
        scaler = self._cur_loss_scaler()
        inv = 1.0 / scaler.loss_scale
        grads = jax.tree_util.tree_map(
            lambda g: jnp.asarray(g).astype(jnp.float32) * inv,
            scaled_grads)
        overflow = scaler.has_overflow(grads)
        scaler.update_scale(overflow)
        self._skip_next[self._loss_idx] = overflow
        if not overflow:
            if self._acc_grads is None:
                self._acc_grads = grads
            else:
                self._acc_grads = jax.tree_util.tree_map(
                    jnp.add, self._acc_grads, grads)

    def _cur_loss_scaler(self) -> DynamicLossScaler:
        assert 0 <= self._loss_idx < self._num_loss
        return self._loss_scaler[self._loss_idx]

    def step(self, closure=None):
        """ref: opt.py:58-77 — skip if ANY loss overflowed this round."""
        if closure is not None:
            raise NotImplementedError(
                "The `closure` argument is unsupported by the amp "
                "optimizer wrapper.")
        self._loss_idx = 0
        if any(self._skip_next):
            self._skip_next = [False] * self._num_loss
            self._acc_grads = None
            return self.params
        if self._acc_grads is not None:
            updates, self.opt_state = self._optimizer.update(
                jax.tree_util.tree_map(
                    lambda g, p: g.astype(jnp.asarray(p).dtype),
                    self._acc_grads, self.params),
                self.opt_state, self.params)
            self.params = optax.apply_updates(self.params, updates)
            self._acc_grads = None
        return self.params

    def zero_grad(self) -> None:
        self._acc_grads = None

    def state_dict(self) -> dict:
        return {"opt_state": self.opt_state, "params": self.params,
                "loss_scales": [s.cur_scale for s in self._loss_scaler]}

    def load_state_dict(self, d: dict) -> None:
        self.opt_state = d["opt_state"]
        self.params = d["params"]
        for s, v in zip(self._loss_scaler, d["loss_scales"]):
            s.cur_scale = v
