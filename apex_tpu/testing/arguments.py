"""Megatron-style argparse for the transformer test stack.

Parity surface for ``apex/transformer/testing/arguments.py:23-806``:
grouped flags (network size, logging, regularization, training,
initialization, learning rate, checkpointing, mixed precision,
distributed, validation, data, autoresume), post-parse derivation
(world size factorization, consistency validation, fp16/bf16
params_dtype), and ``extra_args_provider``/``defaults`` hooks.  The
reference's ~200 flags include many GPU-runtime knobs with no TPU
meaning; those are kept as accepted-and-ignored entries so reference
launch scripts parse unchanged, while everything the TPU stack consumes
is wired through.
"""
from __future__ import annotations

import argparse
import os


def parse_args(extra_args_provider=None, defaults=None,
               ignore_unknown_args=False, args=None):
    """ref: arguments.py:23-260."""
    parser = argparse.ArgumentParser(
        description="apex_tpu Megatron-style arguments",
        allow_abbrev=False)

    parser = _add_network_size_args(parser)
    parser = _add_logging_args(parser)
    parser = _add_regularization_args(parser)
    parser = _add_training_args(parser)
    parser = _add_initialization_args(parser)
    parser = _add_learning_rate_args(parser)
    parser = _add_checkpointing_args(parser)
    parser = _add_mixed_precision_args(parser)
    parser = _add_distributed_args(parser)
    parser = _add_validation_args(parser)
    parser = _add_data_args(parser)
    parser = _add_autoresume_args(parser)

    if extra_args_provider is not None:
        parser = extra_args_provider(parser)

    if ignore_unknown_args:
        parsed, _ = parser.parse_known_args(args)
    else:
        parsed = parser.parse_args(args)

    # Defaults injection (ref :52-66): only fills unset values.
    for key, value in (defaults or {}).items():
        if getattr(parsed, key, None) is None:
            setattr(parsed, key, value)

    # Distributed sizes (ref :68-92): world size from the device count
    # (env override for dry-runs), dp = world / (tp * pp).
    if parsed.world_size is None:
        try:
            import jax
            parsed.world_size = jax.device_count()
        except (ImportError, RuntimeError):  # no backend in dry-runs
            parsed.world_size = int(os.environ.get("WORLD_SIZE", "1"))  # apex-lint: disable=APX301 -- torchrun launcher contract var, not an apex flag
    parsed.tensor_model_parallel_size = min(
        parsed.tensor_model_parallel_size, parsed.world_size)
    model_parallel = (parsed.tensor_model_parallel_size
                      * parsed.pipeline_model_parallel_size)
    if parsed.world_size % model_parallel:
        raise ValueError(
            f"world size {parsed.world_size} not divisible by "
            f"tp*pp {model_parallel}")
    parsed.data_parallel_size = parsed.world_size // model_parallel

    # Batch size derivation (ref :100-130).
    if parsed.micro_batch_size is None:
        parsed.micro_batch_size = parsed.batch_size  # legacy alias
    if parsed.global_batch_size is None and parsed.micro_batch_size:
        parsed.global_batch_size = (parsed.micro_batch_size
                                    * parsed.data_parallel_size)

    # Precision (ref :180-200): params_dtype from fp16/bf16 flags.
    import jax.numpy as jnp
    parsed.params_dtype = jnp.float32
    if parsed.fp16:
        assert not parsed.bf16
        parsed.params_dtype = jnp.float16
    elif parsed.bf16:
        parsed.params_dtype = jnp.bfloat16

    # Consistency checks (ref :202-240).
    if parsed.ffn_hidden_size is None and parsed.hidden_size:
        parsed.ffn_hidden_size = 4 * parsed.hidden_size
    if parsed.kv_channels is None and parsed.hidden_size \
            and parsed.num_attention_heads:
        assert parsed.hidden_size % parsed.num_attention_heads == 0
        parsed.kv_channels = (parsed.hidden_size
                              // parsed.num_attention_heads)
    if parsed.seq_length is not None \
            and parsed.max_position_embeddings is not None:
        assert parsed.max_position_embeddings >= parsed.seq_length

    return parsed


def _add_network_size_args(parser):
    group = parser.add_argument_group(title="network size")
    group.add_argument("--num-layers", type=int, default=None)
    group.add_argument("--hidden-size", type=int, default=None)
    group.add_argument("--ffn-hidden-size", type=int, default=None)
    group.add_argument("--num-attention-heads", type=int, default=None)
    group.add_argument("--kv-channels", type=int, default=None)
    group.add_argument("--max-position-embeddings", type=int, default=None)
    group.add_argument("--layernorm-epsilon", type=float, default=1e-5)
    group.add_argument("--make-vocab-size-divisible-by", type=int,
                       default=128)
    return parser


def _add_logging_args(parser):
    group = parser.add_argument_group(title="logging")
    group.add_argument("--log-interval", type=int, default=100)
    group.add_argument("--tensorboard-dir", type=str, default=None)
    group.add_argument("--log-timers-to-tensorboard", action="store_true")
    return parser


def _add_regularization_args(parser):
    group = parser.add_argument_group(title="regularization")
    group.add_argument("--attention-dropout", type=float, default=0.1)
    group.add_argument("--hidden-dropout", type=float, default=0.1)
    group.add_argument("--weight-decay", type=float, default=0.01)
    group.add_argument("--clip-grad", type=float, default=1.0)
    group.add_argument("--adam-beta1", type=float, default=0.9)
    group.add_argument("--adam-beta2", type=float, default=0.999)
    group.add_argument("--adam-eps", type=float, default=1e-8)
    return parser


def _add_training_args(parser):
    group = parser.add_argument_group(title="training")
    group.add_argument("--batch-size", type=int, default=None,
                       help="legacy alias of --micro-batch-size")
    group.add_argument("--micro-batch-size", type=int, default=None)
    group.add_argument("--global-batch-size", type=int, default=None)
    group.add_argument("--rampup-batch-size", nargs="*", default=None)
    group.add_argument("--train-iters", type=int, default=None)
    group.add_argument("--optimizer", type=str, default="adam",
                       choices=["adam", "sgd", "lamb"])
    group.add_argument("--use-checkpoint-activations", "--checkpoint-activations",
                       dest="checkpoint_activations", action="store_true")
    return parser


def _add_initialization_args(parser):
    group = parser.add_argument_group(title="initialization")
    group.add_argument("--seed", type=int, default=1234)
    group.add_argument("--init-method-std", type=float, default=0.02)
    return parser


def _add_learning_rate_args(parser):
    group = parser.add_argument_group(title="learning rate")
    group.add_argument("--lr", type=float, default=None)
    group.add_argument("--lr-decay-style", type=str, default="linear",
                       choices=["constant", "linear", "cosine"])
    group.add_argument("--lr-decay-iters", type=int, default=None)
    group.add_argument("--lr-warmup-fraction", type=float, default=None)
    group.add_argument("--min-lr", type=float, default=0.0)
    return parser


def _add_checkpointing_args(parser):
    group = parser.add_argument_group(title="checkpointing")
    group.add_argument("--save", type=str, default=None)
    group.add_argument("--save-interval", type=int, default=None)
    group.add_argument("--load", type=str, default=None)
    group.add_argument("--no-save-optim", action="store_true")
    group.add_argument("--no-load-optim", action="store_true")
    return parser


def _add_mixed_precision_args(parser):
    group = parser.add_argument_group(title="mixed precision")
    group.add_argument("--fp16", action="store_true")
    group.add_argument("--bf16", action="store_true")
    group.add_argument("--loss-scale", type=float, default=None)
    group.add_argument("--initial-loss-scale", type=float, default=2 ** 32)
    group.add_argument("--min-loss-scale", type=float, default=1.0)
    group.add_argument("--loss-scale-window", type=float, default=1000)
    group.add_argument("--hysteresis", type=int, default=2)
    return parser


def _add_distributed_args(parser):
    group = parser.add_argument_group(title="distributed")
    group.add_argument("--tensor-model-parallel-size", type=int, default=1)
    group.add_argument("--pipeline-model-parallel-size", type=int,
                       default=1)
    group.add_argument("--virtual-pipeline-model-parallel-size", type=int,
                       default=None)
    group.add_argument("--world-size", type=int, default=None,
                       help="override device count (dry runs)")
    group.add_argument("--local_rank", type=int, default=None,
                       help="accepted for launcher parity; unused "
                            "(single-controller)")
    group.add_argument("--distributed-backend", default="xla",
                       help="accepted for parity (reference: nccl/gloo)")
    return parser


def _add_validation_args(parser):
    group = parser.add_argument_group(title="validation")
    group.add_argument("--eval-iters", type=int, default=100)
    group.add_argument("--eval-interval", type=int, default=1000)
    return parser


def _add_data_args(parser):
    group = parser.add_argument_group(title="data and dataloader")
    group.add_argument("--data-path", nargs="*", default=None)
    group.add_argument("--vocab-size", type=int, default=None)
    group.add_argument("--seq-length", type=int, default=None)
    group.add_argument("--num-workers", type=int, default=2)
    return parser


def _add_autoresume_args(parser):
    group = parser.add_argument_group(title="autoresume")
    group.add_argument("--adlr-autoresume", action="store_true")
    group.add_argument("--adlr-autoresume-interval", type=int, default=1000)
    return parser
