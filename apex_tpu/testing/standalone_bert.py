"""Standalone BERT test model.

Parity surface for ``apex/transformer/testing/standalone_bert.py:10-223``:
bidirectional (padding-mask) transformer, token-type embeddings, pooler,
``BertLMHead`` (dense+gelu+LN then tied-embedding logits with its own
bias), optional binary (NSP) head, vocab-parallel masked-LM loss.  Built
from the same library blocks as the GPT model
(:mod:`apex_tpu.testing.standalone_gpt`).
"""
from __future__ import annotations

import functools
from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..normalization import FusedLayerNorm
from ..transformer.enums import AttnMaskType
from ..transformer.layers import ParallelTransformer
from ..transformer.tensor_parallel import vocab_parallel_cross_entropy
from .standalone_gpt import Dtype, GPTEmbedding

Array = jnp.ndarray


def bert_extended_attention_mask(attention_mask: Array) -> Array:
    """(b, s) 1=real/0=pad -> (b, 1, s, s) boolean, True = masked out
    (ref: standalone_bert.py:10-24 — outer product then ``< 0.5``)."""
    b1s = attention_mask[:, None, :]
    bs1 = attention_mask[:, :, None]
    bss = b1s * bs1
    return (bss[:, None, :, :] < 0.5)


def bert_position_ids(token_ids: Array) -> Array:
    """ref: standalone_bert.py:26-33."""
    s = token_ids.shape[1]
    return jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32),
                            token_ids.shape)


class BertEmbedding(GPTEmbedding):
    """GPT embedding + token-type embeddings
    (ref: BertModel num_tokentypes=2)."""

    num_tokentypes: int = 2

    def setup(self):
        super().setup()
        if self.num_tokentypes > 0:
            self.tokentype_embeddings = nn.Embed(
                self.num_tokentypes, self.hidden_size,
                embedding_init=nn.initializers.normal(stddev=0.02),
                dtype=self.dtype, name="tokentype_embeddings")

    def __call__(self, tokens, tokentype_ids=None,
                 deterministic: bool = True):
        h = super().__call__(tokens, deterministic)
        if tokentype_ids is not None and self.num_tokentypes > 0:
            h = h + self.tokentype_embeddings(tokentype_ids)
        return h


class Pooler(nn.Module):
    """[CLS] pooler: dense+tanh over position 0 (Megatron pooler)."""

    hidden_size: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden):  # (b, s, h)
        x = hidden[:, 0]
        x = nn.Dense(self.hidden_size, dtype=self.dtype,
                     name="dense")(x)
        return jnp.tanh(x)


class BertLMHead(nn.Module):
    """Masked-LM head (ref: standalone_bert.py:35-74): dense + gelu +
    LayerNorm, then logits against the (tied) word-embedding matrix with
    a learned per-vocab bias."""

    hidden_size: int
    vocab_size: int
    layernorm_epsilon: float = 1e-5
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, attend_fn):
        x = nn.Dense(self.hidden_size, dtype=self.dtype, name="dense")(
            hidden)
        x = jax.nn.gelu(x)
        x = FusedLayerNorm(self.hidden_size, eps=self.layernorm_epsilon,
                           name="layernorm")(x).astype(self.dtype)
        bias = self.param("bias", nn.initializers.zeros,
                          (self.vocab_size,), jnp.float32)
        return attend_fn(x) + bias


class BertModel(nn.Module):
    """ref: standalone_bert.py:101-213."""

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_attention_heads: int
    max_sequence_length: int
    num_tokentypes: int = 2
    add_binary_head: bool = True
    attention_dropout: float = 0.1
    hidden_dropout: float = 0.1
    checkpoint_activations: bool = False
    # use_flash routes the (b, s) padding mask through the flash
    # kernel's kv_mask path (no [b, h, s, s] score materialization) —
    # a capability the reference's FMHA lacks; False keeps the
    # reference-shaped FusedScaleMaskSoftmax path.
    use_flash: bool = False
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    def setup(self):
        self.embedding = BertEmbedding(
            self.vocab_size, self.hidden_size, self.max_sequence_length,
            embedding_dropout=self.hidden_dropout,
            num_tokentypes=self.num_tokentypes, dtype=self.dtype,
            axis_name=self.axis_name, name="embedding")
        self.transformer = ParallelTransformer(
            num_layers=self.num_layers, hidden_size=self.hidden_size,
            num_attention_heads=self.num_attention_heads,
            attn_mask_type=AttnMaskType.padding,
            attention_dropout=self.attention_dropout,
            hidden_dropout=self.hidden_dropout, use_flash=self.use_flash,
            checkpoint_activations=self.checkpoint_activations,
            dtype=self.dtype, axis_name=self.axis_name,
            name="transformer")
        self.lm_head = BertLMHead(
            self.hidden_size, self.vocab_size, dtype=self.dtype,
            name="lm_head")
        if self.add_binary_head:
            self.pooler = Pooler(self.hidden_size, dtype=self.dtype,
                                 name="pooler")
            self.binary_head = nn.Dense(2, dtype=jnp.float32,
                                        name="binary_head")

    def __call__(self, tokens, attention_mask, tokentype_ids=None,
                 lm_labels=None, deterministic: bool = True):
        """Returns ``(lm_logits_or_loss, binary_logits)``
        (ref: forward :148-175 + post_language_model_processing
        :76-99)."""
        h = self.embedding(tokens, tokentype_ids, deterministic)
        if self.use_flash:
            # the (b, s) mask rides the flash kernel's kv_mask lane
            h = self.transformer(h, None, deterministic,
                                 key_padding_mask=attention_mask)
        else:
            ext_mask = bert_extended_attention_mask(
                attention_mask.astype(jnp.float32))
            h = self.transformer(h, ext_mask, deterministic)

        binary_logits = None
        if self.add_binary_head:
            binary_logits = self.binary_head(
                self.pooler(h).astype(jnp.float32))

        lm_logits = self.lm_head(h, self.embedding.attend)
        if lm_labels is None:
            return lm_logits, binary_logits
        if self.axis_name is not None:
            lm_loss = vocab_parallel_cross_entropy(
                lm_logits.astype(jnp.float32), lm_labels,
                axis_name=self.axis_name)
        else:
            # fused CE: the plain logsumexp/take pair feeds the same
            # fp32 view to two consumers, materializing an fp32 copy of
            # the (tokens, vocab) logits (measured 9.2 ms/step of
            # convert+reduce at BERT-large's 30k vocab); the custom-VJP
            # loss keeps single-consumer fp32 views in fwd AND bwd.
            from ..contrib.xentropy import softmax_cross_entropy_loss

            # 3-D logits go straight in (the loss broadcasts over
            # leading dims) — a flatten/reshape round-trip materialized
            # a copy of the 0.5 GB logits
            lm_loss = softmax_cross_entropy_loss(
                lm_logits, lm_labels, half_to_float=True)
        return lm_loss, binary_logits


class BertSmokeSetup(NamedTuple):
    """Everything the BERT smoke train step needs, built once — the
    BERT sibling of :class:`.standalone_gpt.SmokeSetup`; shared by
    :func:`train_smoke` and the hlo-auditor entry registry."""

    model: Any
    tokens: jnp.ndarray
    mask: jnp.ndarray
    labels: jnp.ndarray
    nsp: jnp.ndarray
    params: Any
    amp_opt: Any
    amp_state: Any
    n_params: int


def make_smoke_setup(*, vocab: int = 64, hidden: int = 32,
                     num_heads: int = 4, num_layers: int = 2,
                     batch: int = 4, seq: int = 16,
                     opt_level: str = "O2", lr: float = 1e-3,
                     seed: int = 0, dtype=jnp.float32,
                     pipeline: Optional[bool] = None) -> BertSmokeSetup:
    from .. import amp
    from ..optimizers import fused_adam

    model = BertModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=num_layers,
        num_attention_heads=num_heads, max_sequence_length=seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=dtype)
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(jax.random.fold_in(key, 1),
                                (batch, seq), 0, vocab)
    mask = jnp.ones((batch, seq), jnp.int32)
    labels = jnp.roll(tokens, -1, -1)
    nsp = jax.random.randint(jax.random.fold_in(key, 2), (batch,), 0, 2)
    variables = jax.jit(model.init)(key, tokens, mask)
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(variables["params"]))
    params, amp_opt, amp_state = amp.initialize(
        variables["params"], fused_adam(lr), opt_level=opt_level,
        pipeline=pipeline)
    return BertSmokeSetup(model, tokens, mask, labels, nsp, params,
                          amp_opt, amp_state, int(n_params))


def make_step_fn(setup: BertSmokeSetup):
    """The raw (unjitted) BERT smoke train step — the single build
    site the jitted wrappers close over (see
    :func:`.standalone_gpt.make_step_fn`)."""
    from ..transformer.pipeline_parallel.utils import param_l2_norm

    model, tokens, mask = setup.model, setup.tokens, setup.mask
    labels, nsp, amp_opt = setup.labels, setup.nsp, setup.amp_opt

    def _step(params, amp_state):
        def loss_fn(p):
            from ..contrib.xentropy import softmax_cross_entropy_loss

            lm_loss, bin_logits = model.apply(
                {"params": p}, tokens, mask, lm_labels=labels)
            nsp_loss = jnp.mean(softmax_cross_entropy_loss(
                bin_logits, nsp, half_to_float=True))
            loss = jnp.mean(lm_loss) + nsp_loss
            return amp_opt.scale_loss(loss, amp_state), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        new_params, new_state, info = amp_opt.apply_gradients(
            grads, amp_state, params)
        # pipeline mode: reuse the norm sweep's measurement (see
        # standalone_gpt.train_smoke)
        gnorm = info.grad_norm if info.grad_norm is not None else \
            param_l2_norm(grads) / amp_state.scaler.loss_scale
        return new_params, new_state, loss, gnorm, info

    return _step


def build_train_step(setup: BertSmokeSetup, *, telemetry=None):
    """The jitted BERT smoke train step (LM + NSP loss through amp).
    ``params``/``amp_state`` are donated, exactly as in
    :func:`.standalone_gpt.build_train_step` — the loop rebinds both,
    and undonated masters/optimizer state double their HBM (APX601).
    ``telemetry`` (a ``DeviceMetricsBuffer``) switches to the deferred
    three-argument form, same as the GPT driver."""
    _step = make_step_fn(setup)
    if telemetry is None:
        return functools.partial(jax.jit, donate_argnums=(0, 1))(_step)
    from .standalone_gpt import wrap_deferred_step

    return wrap_deferred_step(_step, telemetry)


def build_train_step_scan(setup: BertSmokeSetup, k: int, *,
                          telemetry=None):
    """K BERT train steps per jit call — the batched-step scan driver,
    through the SAME :func:`.standalone_gpt.wrap_scan_step` the GPT
    driver uses (carry/donation/telemetry contract documented there)."""
    from .standalone_gpt import wrap_scan_step

    return wrap_scan_step(make_step_fn(setup), k, telemetry=telemetry)


def train_smoke(steps: int = 8, *, jsonl: Optional[str] = None,
                sink=None, vocab: int = 64, hidden: int = 32,
                num_heads: int = 4, num_layers: int = 2, batch: int = 4,
                seq: int = 16, opt_level: str = "O2", lr: float = 1e-3,
                stall_timeout: float = 300.0, seed: int = 0,
                ckpt_dir: Optional[str] = None, ckpt_every: int = 1,
                ckpt_keep: int = 3, resume: bool = True,
                fault=None, autoresume="auto", escalation=None,
                return_state: bool = False,
                trace_dir: Optional[str] = None,
                drain_every: Optional[int] = None,
                scan_steps: Optional[int] = None):
    """Tiny single-device BERT train loop wired through
    :mod:`apex_tpu.monitor` — the BERT sibling of
    :func:`apex_tpu.testing.standalone_gpt.train_smoke` (same event
    stream: step metrics, amp scale, phase timers, watchdog — and the
    same resilience wiring: periodic checkpoints + auto-resume under
    ``ckpt_dir``, deterministic ``fault`` injection, SIGTERM-safe
    exit; same observability wiring: ``trace_dir`` wall-time
    waterfall + Chrome export, ``drain_every`` deferred telemetry),
    proving both paths are driver-agnostic (``scan_steps`` >= 1: the
    batched-step scan driver, K steps per jit call — see the GPT
    docstring).  Returns the final loss, or
    ``(loss, params, amp_state, steps_done)`` with
    ``return_state=True``."""
    from ..transformer.pipeline_parallel.utils import Timers
    from ..utils.compile_cache import configure_compile_cache
    from .standalone_gpt import (_run_smoke_loop, make_smoke_monitor,
                                 resolve_driver_mode)

    configure_compile_cache()
    setup = make_smoke_setup(
        vocab=vocab, hidden=hidden, num_heads=num_heads,
        num_layers=num_layers, batch=batch, seq=seq,
        opt_level=opt_level, lr=lr, seed=seed)
    scan_steps, telemetry, step, scan_factory = resolve_driver_mode(
        setup, scan_steps, drain_every,
        build_step=build_train_step,
        build_step_scan=build_train_step_scan)
    params, amp_opt, amp_state = (setup.params, setup.amp_opt,
                                  setup.amp_state)
    n_params = setup.n_params
    monitor = make_smoke_monitor(
        jsonl, sink, tokens_per_step=batch * seq,
        flops_per_step=6.0 * n_params * batch * seq,
        stall_timeout=stall_timeout, escalation=escalation,
        run_attrs={"driver": "standalone_bert.train_smoke",
                   "params": int(n_params), "opt_level": opt_level,
                   "batch": batch, "seq": seq,
                   "scan_steps": scan_steps or 0,
                   "telemetry": "deferred" if telemetry else "sync"})
    timers = Timers()
    trace = None
    if trace_dir is not None:
        from ..monitor.tracing import TraceSession

        trace = TraceSession.from_flags(trace_dir, sink=monitor,
                                        timers=timers)
    return _run_smoke_loop(
        step, params, amp_opt, amp_state, steps, monitor, timers, lr=lr,
        ckpt_dir=ckpt_dir, ckpt_every=ckpt_every, ckpt_keep=ckpt_keep,
        resume=resume, fault=fault, autoresume=autoresume,
        escalation=escalation, return_state=return_state,
        trace=trace, telemetry=telemetry,
        scan_steps=scan_steps or 0, scan_factory=scan_factory)


def _main(argv=None):
    import argparse

    from .standalone_gpt import add_resilience_cli

    p = argparse.ArgumentParser(
        description="Monitored BERT smoke train loop (CPU-friendly); "
                    "writes an apex_tpu.monitor JSONL event log; "
                    "preemption-safe with --ckpt-dir.")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--jsonl", default=None,
                   help="event-log path (default: in-memory only)")
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--stall-timeout", type=float, default=300.0)
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="wall-time attribution (see standalone_gpt)")
    p.add_argument("--telemetry-drain-every", type=int, default=None,
                   metavar="K", help="deferred telemetry cadence "
                                     "(see standalone_gpt)")
    p.add_argument("--scan-steps", type=int, default=None, metavar="K",
                   help="batched-step scan driver: K steps per jit "
                        "call (see standalone_gpt)")
    add_resilience_cli(p)
    args = p.parse_args(argv)
    loss, _, _, done = train_smoke(
        steps=args.steps, jsonl=args.jsonl, opt_level=args.opt_level,
        stall_timeout=args.stall_timeout, ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every, resume=not args.no_resume,
        fault=args.fault, return_state=True, trace_dir=args.trace,
        drain_every=args.telemetry_drain_every,
        scan_steps=args.scan_steps)
    print(f"SMOKE_DONE steps_done={done}"
          + (f" loss={loss:.4f}" if loss is not None else "")
          + (f" jsonl={args.jsonl}" if args.jsonl else ""))


if __name__ == "__main__":
    _main()


def bert_model_provider(args, pre_process=True, post_process=True,
                        **overrides):
    """ref: standalone_bert.py:215-223 — build from Megatron args."""
    del pre_process, post_process  # single-program model
    kw = dict(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_size,
        num_layers=args.num_layers,
        num_attention_heads=args.num_attention_heads,
        max_sequence_length=args.max_position_embeddings,
        attention_dropout=args.attention_dropout,
        hidden_dropout=args.hidden_dropout,
        checkpoint_activations=getattr(args, "checkpoint_activations",
                                       False),
        dtype=args.params_dtype,
    )
    kw.update(overrides)
    return BertModel(**kw)
