"""Honest TPU timing helpers.

Through remote-execution tunnels, ``jax.block_until_ready`` may return
before device execution completes, so wall-clock loops under-report
wildly.  These helpers force completion by fetching a scalar value from
the result, and amortize the fetch round-trip over chained dependent
iterations (each call consumes the previous call's output, preventing
dedup/caching of identical executions).
"""
from __future__ import annotations

import time
from typing import Callable

import jax
import jax.numpy as jnp


def _fetch(x):
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(jnp.ravel(leaf)[0].astype(jnp.float32))


def bench_chained(step: Callable, init_carry, n: int = 20,
                  warmup: int = 2) -> float:
    """Return seconds/iteration of ``carry = step(carry)`` with a forced
    value fetch at the end.  ``step`` must map carry -> carry."""
    carry = init_carry
    for _ in range(warmup):
        carry = step(carry)
    _fetch(carry)
    carry = init_carry
    t0 = time.time()
    for _ in range(n):
        carry = step(carry)
    _fetch(carry)
    return (time.time() - t0) / n
