"""Standalone GPT: the flagship transformer exercising TP x PP x DP x amp.

Parity with the reference's test model
(ref: apex/transformer/testing/standalone_gpt.py — embedding, parallel
transformer layers with fused softmax / checkpointing, tied LM head,
vocab-parallel loss, pipeline stage wiring via pre_process/post_process),
re-designed for one-program SPMD:

* ``GPTModel`` — full model for TP-only / single-chip runs.
* ``GPTEmbedding`` / ``GPTStage`` / ``GPTHead`` — the pipeline split:
  embedding and head live *outside* the pipelined region (the
  reference's pre/post_process flags, ref: schedules/common.py:18-107);
  each pipeline stage is a uniform block of layers.
* ``gpt_forward_pipelined`` — the assembled TP+PP forward: embed ->
  microbatch -> pipeline_forward over the pipe axis -> head ->
  vocab-parallel CE.  Called inside ``shard_map`` over the full
  (pipe, data, tensor) mesh; gradient sync across data/tensor emerges
  from boundary transposition (replicated params sum their cotangents).
"""
from __future__ import annotations

import contextlib
import functools
import os
import signal
import tempfile
import time
from typing import Any, NamedTuple, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from .. import parallel_state
from ..transformer.enums import AttnMaskType
from ..transformer.layers import ParallelTransformer, ParallelTransformerLayer
from ..normalization import FusedLayerNorm
from ..transformer.tensor_parallel.cross_entropy import \
    vocab_parallel_cross_entropy
from ..transformer.tensor_parallel.layers import VocabParallelEmbedding

Dtype = Any


def unbox(tree):
    """Strip flax ``nn.Partitioned`` boxes, returning raw arrays."""
    return jax.tree.map(
        lambda l: l.unbox() if isinstance(l, nn.Partitioned) else l,
        tree, is_leaf=lambda l: isinstance(l, nn.Partitioned))


def boxed_specs(tree, extra_leading: int = 0,
                pipe_axis: str = parallel_state.PIPE_AXIS):
    """PartitionSpec tree from flax metadata, optionally prefixing leading
    (e.g. stacked-stage) axes with the pipe axis."""
    from jax.sharding import PartitionSpec as P

    def one(l):
        spec = (l.get_partition_spec()
                if isinstance(l, nn.Partitioned) else P())
        if extra_leading:
            spec = P(*((pipe_axis,) + tuple(spec)))
        return spec

    return jax.tree.map(one, tree,
                        is_leaf=lambda l: isinstance(l, nn.Partitioned))


class GPTEmbedding(nn.Module):
    """Token + learned position embeddings
    (ref: standalone_gpt.py Embedding)."""

    vocab_size: int
    hidden_size: int
    max_sequence_length: int
    embedding_dropout: float = 0.1
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    def setup(self):
        self.word_embeddings = VocabParallelEmbedding(
            self.vocab_size, self.hidden_size, dtype=self.dtype,
            axis_name=self.axis_name, name="word_embeddings")
        self.position_embeddings = nn.Embed(
            self.max_sequence_length, self.hidden_size,
            embedding_init=nn.initializers.normal(stddev=0.02),
            dtype=self.dtype, name="position_embeddings")

    def __call__(self, tokens, deterministic: bool = True):
        s = tokens.shape[-1]
        h = self.word_embeddings(tokens)
        h = h + self.position_embeddings(jnp.arange(s, dtype=jnp.int32))
        if not deterministic and self.embedding_dropout > 0.0:
            key = self.make_rng("dropout")
            keep = jax.random.bernoulli(
                key, 1.0 - self.embedding_dropout, h.shape)
            h = jnp.where(keep, h / (1.0 - self.embedding_dropout),
                          jnp.zeros((), h.dtype))
        return h

    def attend(self, x):
        return self.word_embeddings.attend(x)


class GPTModel(nn.Module):
    """Full (non-pipelined) GPT: embedding -> transformer -> tied head.
    Returns vocab(-sharded in explicit mode) logits
    (ref: standalone_gpt.py GPTModel / post_language_model_processing)."""

    vocab_size: int
    hidden_size: int
    num_layers: int
    num_attention_heads: int
    max_sequence_length: int
    ffn_hidden_size: Optional[int] = None
    attention_dropout: float = 0.1
    hidden_dropout: float = 0.1
    use_flash: bool = True
    checkpoint_activations: bool = False
    checkpoint_policy: str = "full"
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    def setup(self):
        self.embedding = GPTEmbedding(
            self.vocab_size, self.hidden_size, self.max_sequence_length,
            embedding_dropout=self.hidden_dropout, dtype=self.dtype,
            axis_name=self.axis_name, name="embedding")
        self.transformer = ParallelTransformer(
            num_layers=self.num_layers, hidden_size=self.hidden_size,
            num_attention_heads=self.num_attention_heads,
            ffn_hidden_size=self.ffn_hidden_size,
            attn_mask_type=AttnMaskType.causal,
            attention_dropout=self.attention_dropout,
            hidden_dropout=self.hidden_dropout, use_flash=self.use_flash,
            checkpoint_activations=self.checkpoint_activations,
            checkpoint_policy=self.checkpoint_policy,
            dtype=self.dtype, axis_name=self.axis_name, name="transformer")

    def __call__(self, tokens, deterministic: bool = True):
        return self.embedding.attend(
            self.hidden_states(tokens, deterministic))

    def hidden_states(self, tokens, deterministic: bool = True):
        """Final hidden states WITHOUT the tied-head projection — for
        memory-efficient losses that never materialize full logits
        (``contrib.xentropy.linear_cross_entropy_loss``)."""
        h = self.embedding(tokens, deterministic)
        return self.transformer(h, None, deterministic)


class GPTStage(nn.Module):
    """One pipeline stage: ``layers_per_stage`` uniform transformer layers
    (activation-shape preserving, as pipeline_forward requires)."""

    layers_per_stage: int
    hidden_size: int
    num_attention_heads: int
    ffn_hidden_size: Optional[int] = None
    attention_dropout: float = 0.1
    hidden_dropout: float = 0.1
    use_flash: bool = True
    dtype: Dtype = jnp.float32
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        for i in range(self.layers_per_stage):
            x = ParallelTransformerLayer(
                self.hidden_size, self.num_attention_heads,
                ffn_hidden_size=self.ffn_hidden_size,
                attn_mask_type=AttnMaskType.causal,
                attention_dropout=self.attention_dropout,
                hidden_dropout=self.hidden_dropout,
                use_flash=self.use_flash, dtype=self.dtype,
                axis_name=self.axis_name, name=f"layer_{i}")(
                    x, None, deterministic)
        return x


class GPTHead(nn.Module):
    """Final layernorm before the tied head
    (ref: standalone_gpt.py final_layernorm + logits)."""

    hidden_size: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        return FusedLayerNorm(self.hidden_size,
                              name="final_layernorm")(x).astype(self.dtype)


def gpt_loss(logits, labels, axis_name: Optional[str] = None,
             label_smoothing: float = 0.0):
    """Per-token mean LM loss over (possibly vocab-sharded) logits."""
    if axis_name is not None:
        losses = vocab_parallel_cross_entropy(
            logits, labels, label_smoothing=label_smoothing,
            axis_name=axis_name)
    else:
        # fused CE: single-consumer fp32 views in fwd AND bwd (the
        # logsumexp/take pair materializes an fp32 copy of the
        # (tokens, vocab) logits — see standalone_bert)
        from ..contrib.xentropy import softmax_cross_entropy_loss

        losses = softmax_cross_entropy_loss(
            logits, labels, label_smoothing, True)
    return jnp.mean(losses)


def gpt_forward_pipelined(embed_mod, stage_mod, head_mod,
                          embed_params, stage_params, head_params,
                          tokens, labels, *, num_microbatches: int,
                          tensor_axis: Optional[str],
                          pipe_axis: str = parallel_state.PIPE_AXIS,
                          data_axis: Optional[str] =
                          parallel_state.DATA_AXIS,
                          checkpoint_policy: Optional[str] = "full",
                          deterministic: bool = True):
    """TP+PP+DP GPT loss — call inside shard_map over the full mesh.

    ``tokens``/``labels`` arrive data-sharded [local_batch, seq];
    ``stage_params`` arrive pipe-sharded (leading stage dim of 1, as
    shard_map slices).  Returns the pmean (over data) scalar loss;
    differentiate *outside* the shard_map so boundary transposition
    performs the DP/TP gradient reductions.
    """
    from ..transformer.pipeline_parallel.schedules import pipeline_forward

    b, s = tokens.shape
    if b % num_microbatches != 0:
        raise ValueError(f"local batch {b} not divisible by "
                         f"num_microbatches {num_microbatches}")
    h = embed_mod.apply(embed_params, tokens, deterministic)
    mb = b // num_microbatches
    h_mb = h.reshape(num_microbatches, mb, s, h.shape[-1])

    def stage_fn(params, x):
        local = jax.tree.map(lambda p: p[0], params)
        return stage_mod.apply(local, x, deterministic)

    h_out = pipeline_forward(stage_fn, stage_params, h_mb,
                             axis_name=pipe_axis,
                             checkpoint_policy=checkpoint_policy)
    h_full = h_out.reshape(b, s, h.shape[-1])
    h_full = head_mod.apply(head_params, h_full)
    logits = embed_mod.apply(embed_params, h_full, method="attend")
    loss = gpt_loss(logits, labels, axis_name=tensor_axis)
    if data_axis is not None:
        loss = jax.lax.pmean(loss, data_axis)
    return loss


# ---------------------------------------------------------------------------
# Shared smoke-step construction — ONE build path for the train-smoke
# loop, the sanitizer smoke, and the compiled-graph auditor's entry
# registry (apex_tpu.testing.entry_points), so what CI lowers and
# audits is byte-for-byte what the drivers run.
# ---------------------------------------------------------------------------


class SmokeSetup(NamedTuple):
    """Everything a smoke train step needs, built once."""

    model: Any
    tokens: jnp.ndarray
    labels: jnp.ndarray
    params: Any
    amp_opt: Any
    amp_state: Any
    n_params: int


def make_smoke_setup(*, vocab: int = 64, hidden: int = 32,
                     num_heads: int = 4, num_layers: int = 2,
                     batch: int = 4, seq: int = 16,
                     opt_level: str = "O2", lr: float = 1e-3,
                     seed: int = 0, dtype=jnp.float32,
                     pipeline: Optional[bool] = None) -> SmokeSetup:
    """Build the tiny single-device GPT workload shared by
    :func:`train_smoke`, the sanitizer smoke, and the hlo-auditor entry
    registry.  ``dtype`` is the model COMPUTE dtype (the historical
    smoke default is fp32 even under O2 — params still cast per the
    policy); the O5 audit entry passes ``jnp.bfloat16`` so the lowered
    graph is a real low-precision policy region."""
    from .. import amp
    from ..optimizers import fused_adam

    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=num_layers,
        num_attention_heads=num_heads, max_sequence_length=seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=dtype)
    key = jax.random.PRNGKey(seed)
    tokens = jax.random.randint(jax.random.fold_in(key, 1),
                                (batch, seq), 0, vocab)
    labels = jnp.roll(tokens, -1, -1)
    variables = jax.jit(model.init)(key, tokens)
    n_params = sum(x.size for x in
                   jax.tree_util.tree_leaves(variables["params"]))
    params, amp_opt, amp_state = amp.initialize(
        variables["params"], fused_adam(lr), opt_level=opt_level,
        pipeline=pipeline)
    return SmokeSetup(model, tokens, labels, params, amp_opt,
                      amp_state, int(n_params))


def make_step_fn(setup: SmokeSetup):
    """The raw (unjitted) smoke train step: forward, scaled loss,
    backward, amp apply — ``step(params, amp_state) -> (params,
    amp_state, loss, gnorm, info)``.  The single build site the jitted
    wrappers (:func:`build_train_step`, :func:`build_train_step_scan`)
    all close over, so the per-step, deferred, and K-batched drivers
    cannot diverge in step semantics."""
    from ..transformer.pipeline_parallel.utils import param_l2_norm

    model, tokens, labels = setup.model, setup.tokens, setup.labels
    amp_opt = setup.amp_opt

    def _step(params, amp_state):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens)
            loss = gpt_loss(logits, labels)
            return amp_opt.scale_loss(loss, amp_state), loss

        grads, loss = jax.grad(loss_fn, has_aux=True)(params)
        new_params, new_state, info = amp_opt.apply_gradients(
            grads, amp_state, params)
        # the fused pipeline already measured the unscaled global norm
        # in its norm sweep; only the per-stage path re-sweeps the tree
        gnorm = info.grad_norm if info.grad_norm is not None else \
            param_l2_norm(grads) / amp_state.scaler.loss_scale
        return new_params, new_state, loss, gnorm, info

    return _step


def build_train_step(setup: SmokeSetup, *, telemetry=None):
    """The jitted smoke train step.  ``params`` and ``amp_state`` are
    DONATED — the loop rebinds both every step, and without donation
    XLA double-buffers the masters and optimizer state (the APX601
    finding this fixed: fp32 masters + m/v are the largest buffers in
    the step).  Returns ``step(params, amp_state) -> (params,
    amp_state, loss, gnorm, info)``.

    With ``telemetry`` (an :class:`apex_tpu.monitor.tracing.
    DeviceMetricsBuffer`) the step takes and returns the buffer's ring
    state as a third donated argument and appends this step's scalars
    (loss, grad-norm, loss-scale, overflow, skip count) **inside the
    jit** — the deferred-telemetry mode where the loop performs zero
    per-step host transfers: ``step(params, amp_state, tstate) ->
    (params, amp_state, tstate, loss, gnorm, info)``."""
    _step = make_step_fn(setup)
    if telemetry is None:
        return functools.partial(jax.jit, donate_argnums=(0, 1))(_step)
    return wrap_deferred_step(_step, telemetry)


def build_train_step_scan(setup: SmokeSetup, k: int, *, telemetry=None):
    """K train steps per jit call (the ISSUE-8 batched-step driver):
    the same smoke step as :func:`build_train_step`, iterated ``k``
    times inside one ``lax.scan`` — one dispatch, one compile, one
    donation round-trip per K steps, so the per-call host constant
    (dispatch + Python + tunnel latency) is amortized K-fold.  See
    :func:`wrap_scan_step` for the carry/signature contract."""
    return wrap_scan_step(make_step_fn(setup), k, telemetry=telemetry)


def _append_step_metrics(telemetry, tstate, *, loss, gnorm, finite,
                         scale, skipped):
    """The ONE build site for the per-step metric set recorded into
    the device ring — shared by the deferred (per-step) wrapper and
    the scan body, so the drained series cannot diverge between K=0
    and K>=1 runs (add/rename a metric here and both modes get it)."""
    return telemetry.append(
        tstate, loss=loss, grad_norm=gnorm, loss_scale=scale,
        overflow=1.0 - finite.astype(jnp.float32),
        steps_skipped=skipped)


def wrap_deferred_step(step_fn, telemetry):
    """Wrap an unjitted ``step_fn(params, amp_state) -> (params,
    amp_state, loss, gnorm, info)`` smoke step with the in-jit
    deferred-telemetry append — ONE wrapper shared by the GPT and
    BERT drivers so the recorded metric set cannot diverge between
    them.  Returns the jitted three-argument deferred form (all
    arguments donated)."""

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def step_deferred(params, amp_state, tstate):
        new_params, new_state, loss, gnorm, info = step_fn(params,
                                                           amp_state)
        tstate = _append_step_metrics(
            telemetry, tstate, loss=loss, gnorm=gnorm,
            finite=info.grads_finite, scale=info.loss_scale,
            skipped=info.steps_skipped)
        return new_params, new_state, tstate, loss, gnorm, info

    return step_deferred


def wrap_scan_step(step_fn, k: int, *, telemetry=None):
    """Wrap an unjitted ``step_fn(params, amp_state) -> (params,
    amp_state, loss, gnorm, info)`` smoke step into a jitted K-step
    ``lax.scan`` window — ONE wrapper shared by the GPT and BERT
    drivers (the scan sibling of :func:`wrap_deferred_step`).

    Everything the K steps mutate rides the scan carry: params (under
    the fused pipeline that includes the PackedMasters flat buffers
    reassembled into the model tree), the full amp state (masters +
    packed m/v + scaler), and — when ``telemetry`` (a
    :class:`~apex_tpu.monitor.tracing.DeviceMetricsBuffer` with
    ``capacity >= k``) is given — the telemetry ring, appended
    *inside* the scan body exactly as in the deferred step, so the
    whole window performs zero host transfers and every argument
    donates end-to-end (APX601: the scan entry is in the audited
    registry as ``gpt_train_step_scan``).

    The per-step amp semantics are unchanged — an overflow step inside
    the window skips its update and backs the scaler off exactly as it
    would standalone (tests prove K=1 vs K=4 bitwise-equal after N
    steps).  Returns, without telemetry, ``scan_step(params,
    amp_state) -> (params, amp_state, loss_last, gnorm_last,
    info_last)``; with telemetry the ring state joins as a third
    donated argument/result, matching the deferred signature."""
    if k < 1:
        raise ValueError(f"scan window must be >= 1 step, got {k}")
    meta = {}

    def _body(params, amp_state):
        new_params, new_state, loss, gnorm, info = step_fn(params,
                                                           amp_state)
        # static StepInfo structure, captured at trace time (the scan
        # body traces once): ys can only carry arrays
        meta["grads_checked"] = info.grads_checked
        meta["has_grad_norm"] = info.grad_norm is not None
        ys = (loss, gnorm, info.grads_finite, info.loss_scale,
              info.steps_skipped)
        return new_params, new_state, ys

    def _last(ys):
        from ..amp.mixed_precision import StepInfo

        loss, gnorm, finite, scale, skipped = ys
        info = StepInfo(
            grads_finite=finite[-1], loss_scale=scale[-1],
            steps_skipped=skipped[-1],
            grads_checked=meta["grads_checked"],
            grad_norm=gnorm[-1] if meta["has_grad_norm"] else None)
        return loss[-1], gnorm[-1], info

    if telemetry is None:
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def scan_step(params, amp_state):
            def body(carry, _):
                p, s = carry
                p, s, ys = _body(p, s)
                return (p, s), ys

            (params, amp_state), ys = jax.lax.scan(
                body, (params, amp_state), None, length=k)
            loss, gnorm, info = _last(ys)
            return params, amp_state, loss, gnorm, info

        return scan_step

    if telemetry.capacity < k:
        raise ValueError(
            f"telemetry ring capacity {telemetry.capacity} < scan "
            f"window {k}: a window's rows would overwrite each other "
            f"before the drain")

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def scan_step(params, amp_state, tstate):
        def body(carry, _):
            p, s, t = carry
            p, s, ys = _body(p, s)
            loss, gnorm, finite, scale, skipped = ys
            t = _append_step_metrics(
                telemetry, t, loss=loss, gnorm=gnorm, finite=finite,
                scale=scale, skipped=skipped)
            return (p, s, t), ys

        (params, amp_state, tstate), ys = jax.lax.scan(
            body, (params, amp_state, tstate), None, length=k)
        loss, gnorm, info = _last(ys)
        return params, amp_state, tstate, loss, gnorm, info

    return scan_step


def resolve_driver_mode(setup, scan_steps, drain_every, *, build_step,
                        build_step_scan):
    """Resolve a smoke driver's execution mode from ``(scan_steps,
    drain_every)`` — ONE copy of the scan/deferred policy shared by
    the GPT and BERT drivers: env-flag fallback
    (``APEX_TPU_SCAN_STEPS`` / ``APEX_TPU_TELEMETRY_DRAIN_EVERY``),
    the drain-cadence conflict check (the scan driver fixes the drain
    to the window size), DeferredTelemetry construction, and the
    ``(step, scan_factory)`` pair ``_run_smoke_loop`` consumes.
    ``build_step(setup, telemetry=)`` / ``build_step_scan(setup, n,
    telemetry=)`` are the driver's own builders.  Returns
    ``(scan_steps, telemetry, step, scan_factory)`` with exactly one
    of ``step`` / ``scan_factory`` non-None."""
    from ..analysis.flags import flag_int

    if scan_steps is None:
        scan_steps = flag_int("APEX_TPU_SCAN_STEPS")
    if drain_every is None:
        drain_every = flag_int("APEX_TPU_TELEMETRY_DRAIN_EVERY")
    if scan_steps and scan_steps > 0:
        from ..monitor.tracing import DeferredTelemetry

        if drain_every and drain_every > 0 \
                and drain_every != scan_steps:
            raise ValueError(
                f"scan_steps={scan_steps} fixes the telemetry drain "
                f"cadence to the window size; drain_every="
                f"{drain_every} conflicts (drop it, or match K)")
        telemetry = DeferredTelemetry(scan_steps)

        def scan_factory(n, _setup=setup, _buf=telemetry.buffer):
            return build_step_scan(_setup, n, telemetry=_buf)

        return scan_steps, telemetry, None, scan_factory
    telemetry = None
    if drain_every and drain_every > 0:
        from ..monitor.tracing import DeferredTelemetry

        telemetry = DeferredTelemetry(drain_every)
    step = build_step(
        setup, telemetry=telemetry.buffer if telemetry else None)
    return scan_steps, telemetry, step, None


# ---------------------------------------------------------------------------
# Monitored smoke train loop — the run-telemetry acceptance path
# ---------------------------------------------------------------------------

def make_smoke_monitor(jsonl, sink, *, tokens_per_step, flops_per_step,
                       stall_timeout, run_attrs, escalation=None,
                       watchdog_trace_dir=None):
    """Monitor bootstrap shared by the GPT/BERT smoke drivers: default
    sink selection (JSONL file if a path was given, else in-memory),
    watchdog wiring (optionally escalated through an
    ``apex_tpu.resilience.EscalationPolicy``; ``watchdog_trace_dir``
    arms the stall-alarm ``jax.profiler`` capture of a wedged step),
    and close-ownership — the monitor closes the sink only when it
    created it, so a caller-provided sink stays usable after the
    run."""
    from ..monitor import JsonlSink, MemorySink, StepMonitor, Watchdog

    own_sink = sink is None
    if sink is None:
        sink = JsonlSink(jsonl) if jsonl else MemorySink()
    return StepMonitor(
        sink, tokens_per_step=tokens_per_step,
        flops_per_step=flops_per_step,
        watchdog=Watchdog(sink, stall_timeout=stall_timeout,
                          trace_dir=watchdog_trace_dir,
                          on_alarm=None if escalation is None
                          else escalation.notify),
        run_attrs=run_attrs, close_sink=own_sink)


def _boundary_tail(done, prev_done, step_label, *, monitor, ckpt,
                   ckpt_every, save, part, wf, capture, escalation,
                   autoresume, wf_extras=None):
    """The per-boundary resilience/observability tail shared by
    :func:`run_monitored_steps` (boundary = every step) and
    :func:`run_scan_windows` (boundary = every K-step window edge):
    escalation poll -> checkpoint cadence -> waterfall close ->
    capture poll -> termination poll.  ``done`` is the steps-done
    count the checkpoint is cut at, ``prev_done`` the count at the
    previous boundary; ``step_label`` the step number events are
    stamped with (the window's last step under scan).  The checkpoint
    cadence is a *crossing* check — save when ``(prev_done, done]``
    contains a multiple of ``ckpt_every`` — so a cadence that is not a
    multiple of the scan window K still checkpoints at the first edge
    past each cadence point instead of aliasing to lcm(K, ckpt_every)
    (or never).  At K=1 this is exactly ``done % ckpt_every == 0``.
    Returns True when a termination request ended the run (the caller
    breaks), False to continue."""
    esc = escalation.pending() if escalation is not None else None
    if esc is not None:
        from ..resilience import (CHECKPOINT_THEN_ABORT,
                                  EscalationAbort)

        if esc.action == CHECKPOINT_THEN_ABORT and ckpt is not None:
            save(done, sync=True)
        monitor.event("resilience", "escalation_abort", step=step_label,
                      alarm=esc.alarm, action=esc.action,
                      checkpointed=esc.action == CHECKPOINT_THEN_ABORT
                      and ckpt is not None)
        raise EscalationAbort(esc.alarm, esc.action, step=step_label)
    saved = False
    with part("ckpt_io"):
        # always closes (zero-length when no manager/cadence hit) so
        # the canonical waterfall shape is uniform per boundary
        ce = max(1, ckpt_every)
        if ckpt is not None and done // ce > prev_done // ce:
            save(done)
            saved = True
    if wf is not None:
        wf.end_step(monitor, step=step_label, **(wf_extras or {}))
    if capture is not None:
        capture.poll(step_label)
    if autoresume is not None and autoresume.termination_requested():
        if ckpt is not None:
            if not saved:
                save(done)
            ckpt.wait()  # final checkpoint must be durable
        if autoresume.marker_dir is not None:
            autoresume.mark_clean_exit(done)
        monitor.event("resilience", "preempt_exit", step=step_label,
                      value=done, source=autoresume.source)
        return True
    return False


def run_monitored_steps(step_fn, params, amp_state, steps, monitor,
                        timers, lr=None, *, start_step: int = 0,
                        ckpt=None, ckpt_every: int = 1, amp_opt=None,
                        autoresume=None, escalation=None, fault=None,
                        sanitizer=None, trace=None, telemetry=None):
    """Drive ``step_fn(params, amp_state) -> (params, amp_state, loss,
    grad_norm, step_info)`` for steps ``[start_step, steps)``,
    recording each through an :class:`apex_tpu.monitor.StepMonitor` and
    exporting the per-step phase ``timers`` into the same event log.
    Shared by the GPT and BERT smoke drivers.

    The observability wiring (both optional):

    * ``trace`` — an :class:`apex_tpu.monitor.tracing.TraceSession`:
      every step is attributed over the canonical waterfall parts
      (``data_load`` / ``dispatch`` / ``device_compute`` from the
      block_until_ready boundary / ``telemetry_drain`` / ``ckpt_io`` /
      ``other`` residual), emitted per step as an ``attr`` event plus
      host spans, and the capture trigger is polled at each boundary.
    * ``telemetry`` — an :class:`apex_tpu.monitor.tracing.
      DeferredTelemetry`; ``step_fn`` must then be the deferred variant
      from ``build_train_step(setup, telemetry=buf)``.  Per-step
      scalars stay device-resident and drain every K steps through one
      explicit ``jax.device_get`` — the loop performs **zero** per-step
      host transfers (provable with ``sanitize(transfer_guard=
      "disallow", transfer_scope="device_to_host")``).  Deferred mode
      skips ``fault.observed_loss`` (losses are not host-visible at
      step time).

    The resilience wiring is all optional (None = PR-2 behavior):

    * ``ckpt`` — an ``apex_tpu.utils.CheckpointManager``; after step
      ``i`` completes, step ``i+1`` ("steps done") is saved every
      ``ckpt_every`` steps (async — the loop keeps running).
    * ``autoresume`` — polled at each step boundary; on a termination
      request the loop cuts a final *synchronous* checkpoint, writes
      the clean-exit marker, emits ``preempt_exit``, and returns early.
    * ``escalation`` — polled at each step boundary; a latched alarm
      raises :class:`~apex_tpu.resilience.EscalationAbort` (after a
      synchronous checkpoint iff the action says so) for
      ``run_resumable`` to catch and restart.
    * ``fault`` — an ``apex_tpu.resilience.FaultInjector`` driving
      deterministic failures (``before_step`` / ``observed_loss``).
    * ``sanitizer`` — an :class:`apex_tpu.analysis.Sanitizer`; its
      ``step()`` runs at each step boundary, so a post-warmup
      recompile fails the run (docs/api/analysis.md).

    Returns ``(params, amp_state, last_loss, steps_done)``.
    """
    import contextlib as _ctx

    loss_f = None
    done = start_step
    wf = trace.waterfall if trace is not None else None
    capture = trace.capture if trace is not None else None

    def part(name):
        return wf.part(name) if wf is not None else _ctx.nullcontext()

    def save(step, sync=False):
        ckpt.save(step, params, amp_opt, amp_state)
        if sync:
            ckpt.wait()

    for i in range(start_step, steps):
        if wf is not None:
            wf.begin_step(i)
        with part("data_load"):
            # the smoke workload is synthetic (tokens fixed at build);
            # a real driver wraps its loader fetch here.  The canonical
            # span still closes every step so the waterfall shape is
            # uniform across drivers.
            if fault is not None:
                fault.before_step(i)
        monitor.start_step(i)
        timers("step").start()
        with part("dispatch"):
            # async dispatch: this returns at enqueue; the device runs on
            if telemetry is not None:
                params, amp_state, loss, gnorm, info = telemetry.step(
                    step_fn, params, amp_state, step=i)
            else:
                params, amp_state, loss, gnorm, info = step_fn(
                    params, amp_state)
        with part("device_compute"):
            # the block_until_ready boundary: host time spent waiting
            # on the device (timers("step") syncs on the step outputs)
            timers("step").stop(wait_on=loss)
        with part("telemetry_drain"):
            if telemetry is None:
                loss_f = float(loss)
                if fault is not None:
                    loss_f = fault.observed_loss(i, loss_f)
                monitor.end_step(i, loss=loss_f, grad_norm=gnorm,
                                 lr=lr, scaler=info)
            else:
                # host-clock metrics only (step_ms, tokens/s, MFU) —
                # no device value is touched at step time
                monitor.end_step(i, lr=lr)
                if telemetry.maybe_drain(monitor):
                    loss_f = telemetry.last_metrics.get("loss")
            timers.events(monitor, i, reset=True)
            if trace is not None:
                trace.flush(monitor, step=i)
        if sanitizer is not None:
            sanitizer.step()  # post-warmup recompile -> raise here
        done = i + 1
        if _boundary_tail(done, i, i, monitor=monitor, ckpt=ckpt,
                          ckpt_every=ckpt_every, save=save, part=part,
                          wf=wf, capture=capture, escalation=escalation,
                          autoresume=autoresume):
            break
    if telemetry is not None and telemetry.maybe_drain(monitor,
                                                       force=True):
        loss_f = telemetry.last_metrics.get("loss")
    return params, amp_state, loss_f, done


def run_scan_windows(scan_factory, k, params, amp_state, steps, monitor,
                     timers, telemetry, *, lr=None, start_step: int = 0,
                     ckpt=None, ckpt_every: int = 1, amp_opt=None,
                     autoresume=None, escalation=None, fault=None,
                     sanitizer=None, trace=None):
    """The K-batched twin of :func:`run_monitored_steps`: drive
    ``ceil((steps - start_step) / k)`` scan windows, each one jit call
    running ``k`` train steps (``scan_factory(k)`` builds the window
    function — :func:`build_train_step_scan`; a trailing remainder
    window builds its own shorter scan, one extra compile the sanitize
    contract documents).  Every host-side boundary lands on K-step
    edges:

    * **dispatch-free hot path** — each window is AOT-compiled
      (``jit(...).lower().compile()``, timed and emitted as one
      ``compile``/``aot_compile`` event) and the loop calls the
      compiled executable, so the steady-state loop can never retrace;
      with the persistent cache configured
      (``APEX_TPU_COMPILE_CACHE_DIR``) a warmed host loads it from
      disk.
    * **telemetry** — per-step scalars accumulate in the device ring
      *inside* the scan body; :meth:`DeferredTelemetry.maybe_drain`
      performs one explicit ``device_get`` per window (ceil(N/K)
      drains for the run), re-emitting the full per-step metric
      series with reconstructed step numbers.
    * **waterfall** — one attribution row per window, stamped
      ``scan_k``: ``dispatch`` is the single enqueue for K steps,
      ``device_compute`` the block on its outputs — the amortization
      shows up directly as ``wall_device_ratio`` rising with K.
    * **resilience** — fault injection, the escalation poll,
      checkpoint cadence (a crossing check: the first window edge at
      or past each ``ckpt_every`` multiple saves, so a cadence that
      is not a multiple of K never aliases to silence) and
      ``autoresume.termination_requested()`` all run between windows;
      a kill mid-window resumes from the last K-boundary checkpoint.
    * **sanitizer** — ``sanitizer.step()`` per window: for N a
      multiple of K, exactly one compile (the first window's, during
      warmup) for the whole run.

    Returns ``(params, amp_state, last_loss, steps_done)`` with
    ``steps_done`` always on a window edge.
    """
    import contextlib as _ctx
    import time as _time

    if k < 1:
        raise ValueError(f"scan_steps must be >= 1, got {k}")
    loss_f = None
    done = start_step
    wf = trace.waterfall if trace is not None else None
    capture = trace.capture if trace is not None else None

    def part(name):
        return wf.part(name) if wf is not None else _ctx.nullcontext()

    def save(step, sync=False):
        ckpt.save(step, params, amp_opt, amp_state)
        if sync:
            ckpt.wait()

    compiled = {}

    def window_fn(n, *args):
        ex = compiled.get(n)
        if ex is None:
            t0 = _time.perf_counter()
            ex = scan_factory(n).lower(*args).compile()
            compiled[n] = ex
            monitor.event("compile", "aot_compile",
                          value=round((_time.perf_counter() - t0) * 1e3,
                                      2), scan_k=n)
        return ex

    # AOT-precompile every window length this run will use BEFORE the
    # first step: compile cost lands in its own `compile` events (and,
    # under --sanitize, in the warmup bucket), never in a window's
    # waterfall — the steady-state `dispatch` part measures dispatch,
    # not a hidden cold start.  Lengths: the full K window plus (for
    # runs where steps - start_step is not a multiple of K) the
    # trailing remainder.
    remaining = steps - start_step
    if remaining > 0:
        lengths = {min(k, remaining)}
        if remaining > k and remaining % k:
            lengths.add(remaining % k)
        for n in sorted(lengths, reverse=True):
            window_fn(n, params, amp_state, telemetry.state)

    per_step_tokens = monitor.tokens_per_step
    per_step_flops = monitor.flops_per_step
    w_start = start_step
    try:
        while w_start < steps:
            k_eff = min(k, steps - w_start)
            w_last = w_start + k_eff - 1
            if wf is not None:
                wf.begin_step(w_start)
            with part("data_load"):
                # synthetic smoke workload (see run_monitored_steps);
                # a fault aimed anywhere in this window fires at its
                # start edge (the only host boundary that exists)
                if fault is not None:
                    fault.before_window(w_start, k_eff)
            monitor.start_step(w_start)
            timers("step").start()
            with part("dispatch"):
                # ONE enqueue for k_eff steps — the amortization
                fn = window_fn(k_eff, params, amp_state,
                               telemetry.state)
                params, amp_state, loss, gnorm, info = \
                    telemetry.scan_window(fn, params, amp_state,
                                          start=w_start, k=k_eff)
            with part("device_compute"):
                timers("step").stop(wait_on=loss)
            with part("telemetry_drain"):
                # host-clock metrics for the whole window (step_ms is
                # the window wall; tokens/MFU scale by k_eff)
                if per_step_flops:
                    monitor.flops_per_step = per_step_flops * k_eff
                monitor.end_step(w_last, lr=lr,
                                 tokens=(per_step_tokens or 0) * k_eff
                                 or None)
                if telemetry.maybe_drain(monitor):
                    loss_f = telemetry.last_metrics.get("loss")
                timers.events(monitor, w_last, reset=True)
                if trace is not None:
                    trace.flush(monitor, step=w_last)
            if sanitizer is not None:
                sanitizer.step()
            done = w_start + k_eff
            if _boundary_tail(done, w_start, w_last, monitor=monitor,
                              ckpt=ckpt, ckpt_every=ckpt_every,
                              save=save, part=part, wf=wf,
                              capture=capture, escalation=escalation,
                              autoresume=autoresume,
                              wf_extras={"scan_k": k_eff}):
                break
            w_start = done
    finally:
        monitor.flops_per_step = per_step_flops
    if telemetry.maybe_drain(monitor, force=True):
        loss_f = telemetry.last_metrics.get("loss")
    return params, amp_state, loss_f, done


def train_smoke(steps: int = 8, *, jsonl: Optional[str] = None,
                sink=None, vocab: int = 64, hidden: int = 32,
                num_heads: int = 4, num_layers: int = 2, batch: int = 4,
                seq: int = 16, opt_level: str = "O2", lr: float = 1e-3,
                stall_timeout: float = 300.0, seed: int = 0,
                ckpt_dir: Optional[str] = None, ckpt_every: int = 1,
                ckpt_keep: int = 3, resume: bool = True,
                fault=None, autoresume="auto", escalation=None,
                return_state: bool = False, sanitize: bool = False,
                trace_dir: Optional[str] = None,
                drain_every: Optional[int] = None,
                scan_steps: Optional[int] = None):
    """Tiny single-device GPT train loop wired end-to-end through
    :mod:`apex_tpu.monitor` — the CPU telemetry smoke (exercised by
    tools/ci.sh on every run): step metrics (loss, grad-norm, lr,
    tokens/s, step ms, MFU), amp loss-scale/overflow events (the O2
    dynamic scaler genuinely backs off in fp16 at init scale 2^16),
    phase-timer events, and a live stall watchdog — all into one JSONL
    that ``tools/monitor_summary.py`` renders.

    Pass ``jsonl`` for a file log, or ``sink`` (e.g. a ``MemorySink``)
    to capture events in-process; with neither, events go to a
    throwaway ``MemorySink``.  Returns the final loss (host float), or
    ``(loss, params, amp_state, steps_done)`` with ``return_state=True``
    (how the kill-and-resume tests compare runs bitwise).  The monitor
    is closed on exit; it closes the sink too unless the caller
    provided one.

    With ``ckpt_dir`` the loop is **preemption-safe** (the tier-1
    resilience acceptance path, see docs/api/resilience.md): every
    ``ckpt_every`` steps an async checkpoint is cut; at start the run
    auto-resumes from the latest *valid* step (corrupt ones skipped +
    GC'd); ``autoresume="auto"`` installs a SIGTERM/SIGINT
    :class:`~apex_tpu.resilience.AutoResume` whose termination request
    produces a final synchronous checkpoint plus the ``CLEAN_EXIT.json``
    marker (pass an instance to share one, or None to disable).
    ``fault`` is a fault spec string or
    :class:`~apex_tpu.resilience.FaultInjector` (``"sigterm@4"``,
    ``"nan@3,crash@5"``, ...); ``escalation`` an
    :class:`~apex_tpu.resilience.EscalationPolicy` latched into the
    watchdog.  A crashing step emits a terminal ``run_error`` event
    before the exception propagates.

    ``trace_dir`` enables the wall-time attribution tracer
    (:mod:`apex_tpu.monitor.tracing`): per-step waterfall rows + host
    spans in the event log, a ``trace.chrome.json`` Perfetto artifact
    in the directory, and the on-demand capture trigger per the
    ``APEX_TPU_TRACE_*`` flags.  ``drain_every`` >= 1 switches to
    sync-free deferred telemetry (device metrics ring drained every K
    steps — zero per-step host transfers; with ``sanitize=True`` the
    transfer guard proves it); None reads
    ``APEX_TPU_TELEMETRY_DRAIN_EVERY``, 0 is the classic synchronous
    path.

    ``scan_steps`` >= 1 switches to the **batched-step scan driver**
    (:func:`build_train_step_scan` + :func:`run_scan_windows`): K
    train steps per jit call with amp state and the telemetry ring in
    the scan carry, AOT-compiled windows, ceil(N/K) telemetry drains,
    and checkpoint/watchdog/waterfall boundaries on K-step edges; None
    reads ``APEX_TPU_SCAN_STEPS``, 0 is the classic per-step loop.
    Scan mode implies deferred telemetry at cadence K (a conflicting
    explicit ``drain_every`` is rejected — the window IS the drain
    cadence).
    """
    from ..transformer.pipeline_parallel.utils import Timers
    from ..utils.compile_cache import configure_compile_cache

    configure_compile_cache()
    setup = make_smoke_setup(
        vocab=vocab, hidden=hidden, num_heads=num_heads,
        num_layers=num_layers, batch=batch, seq=seq,
        opt_level=opt_level, lr=lr, seed=seed)
    scan_steps, telemetry, step, scan_factory = resolve_driver_mode(
        setup, scan_steps, drain_every,
        build_step=build_train_step,
        build_step_scan=build_train_step_scan)
    params, amp_opt, amp_state = (setup.params, setup.amp_opt,
                                  setup.amp_state)
    n_params = setup.n_params
    flops = 6.0 * n_params * batch * seq \
        + 12.0 * num_layers * hidden * batch * seq * seq
    monitor = make_smoke_monitor(
        jsonl, sink, tokens_per_step=batch * seq, flops_per_step=flops,
        stall_timeout=stall_timeout, escalation=escalation,
        run_attrs={"driver": "standalone_gpt.train_smoke",
                   "params": int(n_params), "opt_level": opt_level,
                   "batch": batch, "seq": seq,
                   "scan_steps": scan_steps or 0,
                   "telemetry": "deferred" if telemetry else "sync"})
    timers = Timers()
    trace = None
    if trace_dir is not None:
        from ..monitor.tracing import TraceSession

        trace = TraceSession.from_flags(trace_dir, sink=monitor,
                                        timers=timers)
    return _run_smoke_loop(
        step, params, amp_opt, amp_state, steps, monitor, timers, lr=lr,
        ckpt_dir=ckpt_dir, ckpt_every=ckpt_every, ckpt_keep=ckpt_keep,
        resume=resume, fault=fault, autoresume=autoresume,
        escalation=escalation, return_state=return_state,
        sanitize=sanitize, trace=trace, telemetry=telemetry,
        scan_steps=scan_steps or 0, scan_factory=scan_factory)


def _run_smoke_loop(step_fn, params, amp_opt, amp_state, steps, monitor,
                    timers, *, lr, ckpt_dir, ckpt_every, ckpt_keep,
                    resume, fault, autoresume, escalation, return_state,
                    sanitize: bool = False, trace=None, telemetry=None,
                    scan_steps: int = 0, scan_factory=None):
    """Resilience-wired driver shell shared by the GPT and BERT smokes:
    checkpoint manager + auto-resume bootstrap around
    :func:`run_monitored_steps` (or, with ``scan_steps`` >= 1,
    :func:`run_scan_windows` — K steps per jit call via
    ``scan_factory``), ``run_error`` emission on a crashing step, and
    guaranteed teardown (watchdog heartbeat, JSONL sink, pending async
    saves, trace session -> Chrome artifact) via ``try/finally``.
    With ``telemetry`` (deferred mode — always on under the scan
    driver) the ``sanitize`` contract tightens: the device→host
    transfer guard is armed too, so ANY per-step implicit host
    readback fails the run — the zero-transfer proof, not just the
    recompile budget.  Under the scan driver the recompile budget
    additionally proves ONE compile per run when ``steps`` is a
    multiple of K (a trailing remainder window compiles its own
    shorter scan, but :func:`run_scan_windows` AOT-precompiles every
    window length before the first step, so both compiles land in the
    warmup bucket and the budget stays clean for any N)."""
    from ..monitor.events import ThreadExceptionCapture
    from ..resilience import AutoResume, parse_fault
    from ..utils import CheckpointManager

    if isinstance(fault, str):
        fault = parse_fault(fault)
    mgr = None
    own_autoresume = False
    loss_f = None
    done = 0
    # threading.excepthook capture: a watchdog-heartbeat (or any
    # other background) thread dying mid-run becomes a run_error
    # event at crash time and a raised failure after teardown,
    # instead of a stderr traceback and a silently dead thread
    thread_cap = ThreadExceptionCapture(monitor).install()
    try:
        if escalation is not None:
            escalation.reset()  # a fresh attempt re-arms the policy —
            # a stale latch from the previous attempt would otherwise
            # abort every retry at its first step boundary
        start_step = 0
        if ckpt_dir is not None:
            mgr = CheckpointManager(ckpt_dir, keep=ckpt_keep,
                                    sink=monitor)
            if autoresume == "auto":
                autoresume = AutoResume(marker_dir=mgr.directory,
                                        sink=monitor).install()
                own_autoresume = True
            if resume and mgr.latest_valid_step() is not None:
                params, amp_state, _, start_step = mgr.restore(
                    params, amp_opt, amp_state)
                monitor.event("resilience", "run_resumed",
                              value=start_step, directory=mgr.directory)
        if autoresume == "auto":  # no ckpt_dir to anchor a marker
            autoresume = None
        if autoresume is not None and autoresume.marker_dir:
            autoresume.clear_clean_exit()  # marker = THIS run's exit
        done = start_step
        with contextlib.ExitStack() as stack:
            san = None
            if sanitize:
                # smoke contract: the jitted step compiles once during
                # the first (warmup) step and never again — a
                # post-warmup recompile raises RecompileBudgetExceeded
                # out of the loop.  Deferred telemetry additionally
                # arms the d->h transfer guard: the ring's explicit
                # device_get drain is the ONLY permitted readback
                # (sync mode keeps transfers unguarded — its per-step
                # float(loss) is an expected, explicit design choice).
                from ..analysis import sanitize as sanitize_ctx

                san = stack.enter_context(sanitize_ctx(
                    transfer_guard=("disallow" if telemetry is not None
                                    else None),
                    transfer_scope="device_to_host",
                    recompile_budget=0, warmup_steps=1))
            if scan_steps and scan_steps > 0:
                params, amp_state, loss_f, done = run_scan_windows(
                    scan_factory, scan_steps, params, amp_state, steps,
                    monitor, timers, telemetry, lr=lr,
                    start_step=start_step, ckpt=mgr,
                    ckpt_every=ckpt_every, amp_opt=amp_opt,
                    autoresume=autoresume, escalation=escalation,
                    fault=fault, sanitizer=san, trace=trace)
            else:
                params, amp_state, loss_f, done = run_monitored_steps(
                    step_fn, params, amp_state, steps, monitor, timers,
                    lr=lr, start_step=start_step, ckpt=mgr,
                    ckpt_every=ckpt_every, amp_opt=amp_opt,
                    autoresume=autoresume, escalation=escalation,
                    fault=fault, sanitizer=san, trace=trace,
                    telemetry=telemetry)
    except BaseException as e:
        # terminal record first — the re-raise may end the process
        monitor.event("run", "run_error", step=done,
                      error=type(e).__name__, message=str(e)[:200])
        raise
    finally:
        if telemetry is not None:
            # a crash between drains must not lose the ring's pending
            # steps — they are exactly the losses needed to diagnose
            # it.  The guard context is closed by now, so the explicit
            # fetch is unconditionally legal.
            try:
                telemetry.maybe_drain(monitor, force=True)
            except Exception as e:
                from ..utils.log_util import get_logger

                get_logger(__name__).warning(
                    "final telemetry drain failed: %s", str(e)[:160])
        # Nested so one teardown failure cannot skip the next: the sink
        # close must not strand a pending async save, and a stranded
        # signal handler would swallow the process's next SIGTERM.
        try:
            if trace is not None:
                # flush remaining spans into the (still-open) sink and
                # commit the Chrome artifact before the sink closes
                trace.close(monitor)
        finally:
            try:
                monitor.close()
            finally:
                try:
                    if mgr is not None:
                        mgr.close()  # pending async saves become durable
                finally:
                    try:
                        if own_autoresume:
                            autoresume.uninstall()
                    finally:
                        thread_cap.uninstall()
    thread_cap.raise_first()
    if return_state:
        return loss_f, params, amp_state, done
    return loss_f


# ---------------------------------------------------------------------------
# Serving smoke — the continuous-batching acceptance path (ISSUE-9)
# ---------------------------------------------------------------------------

def serve_smoke(num_requests: int = 6, *, jsonl: Optional[str] = None,
                sink=None, vocab: int = 64, hidden: int = 32,
                num_heads: int = 4, num_layers: int = 2,
                max_seq: int = 64, max_new_tokens: int = 6,
                seed: int = 0, dtype=jnp.float32,
                policy: Optional[str] = None,
                decode_attention: str = "kernel",
                prefill_flash: bool = True,
                num_blocks: Optional[int] = None,
                block_size: Optional[int] = None,
                kv_dtype: Optional[str] = None, ladder=None,
                sanitize: bool = False, fault=None,
                autoresume="auto", stall_timeout: float = 300.0,
                trace_dir: Optional[str] = None,
                tick_every: Optional[int] = None,
                snapshot="auto",
                speculate_k: Optional[int] = None,
                prefill_chunk: Optional[int] = None,
                prefix_share: Optional[bool] = None,
                draft: str = "self",
                deadline_ms: Optional[float] = None,
                shed=None,
                journal_path: Optional[str] = None,
                supervise: bool = False,
                max_restarts: int = 3,
                escalation="auto",
                backoff_base: float = 0.05,
                metrics_port: Optional[int] = None,
                metrics_linger: float = 0.0,
                ep: Optional[int] = None,
                moe_experts: Optional[int] = None,
                return_engine: bool = False):
    """Continuous-batched serving smoke: a tiny GPT serves
    ``num_requests`` mixed-length prompts through the
    :mod:`apex_tpu.serving` engine — prefill via the flash forward
    kernel, decode via the paged flash-decode kernel, admissions and
    evictions interleaving with jitted decode steps — and reports
    decode tokens/s plus p50/p99 per-token latency through the
    monitor stack (the ``--serve`` acceptance path, tools/ci.sh step
    11).

    ``sanitize=True`` proves the bucket-ladder compile discipline:
    every (batch, pages) bucket is AOT-compiled by ``engine.warmup()``
    before traffic, so the whole serve holds a post-warmup recompile
    budget of ZERO — a shape leaking past the ladder fails the run.
    ``fault`` accepts the resilience spec syntax (``"sigterm@3"``
    fires at decode tick 3) and ``autoresume="auto"`` installs the
    flag-only SIGTERM handler: a mid-serve termination stops
    admissions, frees every block, marks in-flight requests
    preempted, and still returns a full summary — the clean-drain
    contract.  ``decode_attention="reference"`` swaps the kernel for
    the dense gather twin (the naive decode baseline bench.py's
    serving section measures against).

    The ISSUE-12 decode fast path rides the same smoke:
    ``speculate_k=K`` builds a draft GPT (``draft="self"`` reuses the
    target's weights — the acceptance-rate ceiling and the CI
    machinery proof; ``draft="narrow"`` initializes a 1-layer,
    half-width model — the low-acceptance rollback stress) and the
    engine emits 1..K+1 tokens per tick, token-for-token identical to
    plain greedy decode; ``prefix_share=True`` turns on copy-on-write
    prompt-prefix sharing; ``prefill_chunk=N`` splits admissions into
    N-token chunks interleaved with decode.  All three default to
    their ``APEX_TPU_SERVE_*`` flags.

    Per-request telemetry (ISSUE-11) is always on: every request's
    lifecycle chain (``request_submitted → request_admitted →
    request_first_token → request_done``) and the per-tick
    ``serve_tick`` engine gauges (cadence ``tick_every`` /
    ``APEX_TPU_SERVE_TICK_EVERY``) land in the event log, and the
    summary carries queue-wait/TTFT/ITL percentiles.  ``trace_dir``
    additionally writes ``<dir>/serve.chrome.json`` — one Perfetto
    lane per request with queued/prefill/decode phases — and arms the
    watchdog's stall-capture under ``<dir>/stall``.  ``snapshot=
    "auto"`` installs the on-demand engine snapshot trigger
    (SIGUSR1 + ``APEX_TPU_SERVE_SNAPSHOT_FILE``); pass an explicit
    :class:`~apex_tpu.serving.SnapshotTrigger` or None.

    Serving resilience (ISSUE-13) rides the same smoke:
    ``deadline_ms`` stamps a default request deadline (flag:
    ``APEX_TPU_SERVE_DEADLINE_MS``), ``shed`` a
    :class:`~apex_tpu.serving.ShedPolicy` (flags:
    ``APEX_TPU_SERVE_SHED_*``), ``journal_path`` a crash-safe
    :class:`~apex_tpu.serving.RequestJournal` (default:
    ``APEX_TPU_SERVE_JOURNAL_DIR``/serve.journal.jsonl when that flag
    is set), and ``supervise=True`` runs the engine under
    :func:`~apex_tpu.serving.run_serving` — bounded-backoff restarts
    with journal replay, so ``--fault crash@K`` recovers instead of
    dying (requires a journal).  ``escalation="auto"`` installs the
    serve watchdog policy (stall → snapshot-then-drain); pass an
    :class:`~apex_tpu.resilience.EscalationPolicy` or None.

    ``policy`` selects an amp serving tier (ISSUE-16): ``"O5"`` casts
    the model to bf16; ``"Q8"`` additionally quantizes every matmul
    weight to per-channel int8 (:func:`apex_tpu.ops.quant_matmul.
    quantize_weights`), so the serve exercises the quantized decode
    path end to end — the ``--policy Q8`` CI smoke.

    ``ep=N`` (flag: ``APEX_TPU_SERVE_EP``) serves expert-parallel
    (ISSUE-19): the model's MLPs expand to a ``moe_experts``-way
    Switch MoE (:func:`~apex_tpu.serving.expand_moe_weights`;
    default ``2*ep`` experts) and the engine runs under an
    :class:`~apex_tpu.serving.EPContext` — expert stacks sharded over
    N devices, attention and cache replicated, the fused routing +
    capacity-chunked overlapped all_to_all exchange per MoE layer.
    The same ladder/warmup/sanitize discipline applies: the EP serve
    holds a post-warmup recompile budget of ZERO.  Does not compose
    with ``--policy Q8`` or speculative decoding.

    The live metrics plane (ISSUE-17) arms with ``metrics_port``
    (flag: ``APEX_TPU_METRICS_PORT``; an explicit ``0`` picks an
    ephemeral port): a :class:`~apex_tpu.monitor.MetricsServer`
    daemon thread serves ``/metrics`` (Prometheus text exposition),
    ``/healthz`` (503 while draining; SLO-burn / shed / escalation
    aware) and ``/varz`` (the SIGUSR1 snapshot payload) from
    lock-free per-tick publishes — scrapes never touch the engine.
    SLO objectives come from the ``APEX_TPU_SLO_*`` flags
    (``ServingEngine(slo="auto")``).  ``metrics_linger`` keeps the
    server up that many seconds after the drain so an external probe
    (tools/metrics_probe.py, ci.sh step 16) can observe the
    ``/healthz`` flip before teardown.

    Returns the :class:`~apex_tpu.serving.ServeSummary` (with
    ``return_engine=True``, ``(summary, engine)`` — how tests read
    per-request token streams)."""
    import numpy as np

    from ..resilience import AutoResume, parse_fault, serve_policy
    from ..serving import (BucketLadder, Request, RequestJournal,
                           ServingEngine, ServingModelConfig,
                           SnapshotTrigger, default_cache_config,
                           extract_serving_weights, run_serving)

    pol = None
    if policy is not None:
        from ..amp import get_policy
        pol = get_policy(policy)
        if pol.cast_model_type is not None:
            dtype = pol.cast_model_type
    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=num_layers,
        num_attention_heads=num_heads, max_sequence_length=max_seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=dtype)
    key = jax.random.PRNGKey(seed)
    params = jax.jit(model.init)(
        key, jnp.zeros((1, min(8, max_seq)), jnp.int32))["params"]
    cfg = ServingModelConfig.from_model(
        model, prefill_flash=prefill_flash,
        decode_attention=decode_attention)
    weights = extract_serving_weights(params, num_layers)
    if pol is not None and pol.quantize_weights == "int8":
        from ..ops.quant_matmul import quantize_weights as _quantize_w
        weights = _quantize_w(weights)
    cache_cfg = default_cache_config(cfg, num_blocks=num_blocks,
                                     block_size=block_size,
                                     kv_dtype=kv_dtype)
    if ladder is None:
        ladder = BucketLadder.from_flags()
    from ..analysis.flags import flag_int as _flag_int
    from ..analysis.flags import flag_str as _flag_str

    spec_k = speculate_k if speculate_k is not None \
        else _flag_int("APEX_TPU_SERVE_SPECULATE_K")
    draft_weights = draft_cfg = None
    if spec_k > 0:
        if draft == "self":
            # the target proposes for itself: acceptance is exactly
            # 1.0, proving the verify/rollback machinery end to end
            # with the output-identity bar still armed
            draft_weights, draft_cfg = weights, cfg
        elif draft == "narrow":
            draft_model = GPTModel(
                vocab_size=vocab, hidden_size=max(hidden // 2,
                                                  2 * num_heads),
                num_layers=1, num_attention_heads=num_heads,
                max_sequence_length=max_seq, attention_dropout=0.0,
                hidden_dropout=0.0, use_flash=False, dtype=dtype)
            draft_params = jax.jit(draft_model.init)(
                jax.random.PRNGKey(seed + 1),
                jnp.zeros((1, min(8, max_seq)), jnp.int32))["params"]
            draft_cfg = ServingModelConfig.from_model(
                draft_model, prefill_flash=prefill_flash,
                decode_attention=decode_attention)
            draft_weights = extract_serving_weights(draft_params, 1)
        else:
            raise ValueError(f"draft {draft!r} not in "
                             f"('self', 'narrow')")
    ep_width = ep if ep is not None else _flag_int("APEX_TPU_SERVE_EP")
    ep_ctx = None
    if ep_width and ep_width > 0:
        import dataclasses as _dc

        from ..serving import EPContext, expand_moe_weights

        if pol is not None and pol.quantize_weights == "int8":
            raise ValueError(
                "--ep does not compose with the Q8 tier: the int8 "
                "kernel has no expert-stack layout")
        n_exp = moe_experts if moe_experts else 2 * ep_width
        # capacity_factor 8.0 keeps per-rank capacity >= the chunk
        # count at decode's 1-token-per-sequence buckets, so the
        # overlapped exchange engages even on the tiny smoke shapes
        cfg = _dc.replace(
            cfg, num_experts=n_exp, moe_capacity_factor=8.0,
            moe_a2a_chunks=max(1, _flag_int("APEX_TPU_MOE_A2A_CHUNKS")))
        weights = expand_moe_weights(weights, n_exp,
                                     jax.random.PRNGKey(seed + 2))
        ep_ctx = EPContext(cfg, cache_cfg, ep_width)
    if escalation == "auto":
        # serve watchdog policy: a stalled decode snapshots the live
        # engine state then drains cleanly, instead of the training
        # default's ignore (docs/api/resilience.md#serving-resilience)
        escalation = serve_policy()
    monitor = make_smoke_monitor(
        jsonl, sink, tokens_per_step=None, flops_per_step=None,
        stall_timeout=stall_timeout, escalation=escalation,
        watchdog_trace_dir=(os.path.join(trace_dir, "stall")
                            if trace_dir else None),
        run_attrs={"driver": "standalone_gpt.serve_smoke",
                   "requests": num_requests, "max_seq": max_seq,
                   "kv_dtype": cache_cfg.kv_dtype,
                   "block_size": cache_cfg.block_size,
                   "decode_attention": decode_attention,
                   "policy": policy or "none",
                   "ep": ep_width or 0})
    if metrics_port is None:
        _fp = _flag_int("APEX_TPU_METRICS_PORT")
        metrics_port = _fp if _fp > 0 else None
    exporter = metrics_server = None
    if metrics_port is not None:
        from ..monitor.export import MetricsExporter, MetricsServer

        exporter = MetricsExporter()
        metrics_server = MetricsServer(exporter, port=metrics_port,
                                       monitor=monitor)
        metrics_server.start()
        print(f"METRICS http://127.0.0.1:{metrics_server.port}"
              f"/metrics", flush=True)
    if isinstance(fault, str):
        fault = parse_fault(fault)
    journal = None
    if journal_path is None:
        jdir = _flag_str("APEX_TPU_SERVE_JOURNAL_DIR")
        if jdir:
            os.makedirs(jdir, exist_ok=True)
            journal_path = os.path.join(jdir, "serve.journal.jsonl")
    if journal_path is not None:
        journal = RequestJournal(journal_path)
    if supervise and journal is None:
        raise ValueError(
            "supervise=True needs a journal (journal_path or "
            "APEX_TPU_SERVE_JOURNAL_DIR): recovery replays it")
    own_autoresume = False
    if autoresume == "auto":
        autoresume = AutoResume(sink=monitor).install()
        own_autoresume = True
    own_snapshot = False
    if snapshot == "auto":
        # SIGUSR1 (flag-only handler) + the registered file trigger:
        # a wedged serve dumps its live state as one engine_snapshot
        # event at the next tick boundary
        snapshot = SnapshotTrigger.from_flags(
            signum=getattr(signal, "SIGUSR1", None))
        own_snapshot = True
    engine = ServingEngine(weights, cfg, cache_cfg, ladder=ladder,
                           monitor=monitor, autoresume=autoresume,
                           tick_every=tick_every, snapshot=snapshot,
                           ep=ep_ctx, speculate_k=spec_k,
                           draft_weights=draft_weights,
                           draft_cfg=draft_cfg,
                           prefill_chunk=prefill_chunk,
                           prefix_share=prefix_share,
                           deadline_ms=deadline_ms, shed=shed,
                           journal=journal, escalation=escalation,
                           fault=fault, exporter=exporter)
    # mixed-length prompts, deterministic per seed; every request
    # fits the ladder span and the model's position table
    rng = np.random.RandomState(seed)
    span = ladder.max_pages * cache_cfg.block_size
    max_prompt = max(1, min(max_seq, span) - max_new_tokens)
    lengths = [1 + (int(x) % max_prompt)
               for x in rng.randint(1, 10 ** 6, num_requests)]
    prompts = [[int(t) for t in rng.randint(0, vocab, n)]
               for n in lengths]
    before = None
    if fault is not None:
        # the serve-aware hook: crash/stall/signals like the training
        # loop, plus corrupt_journal against the live journal (the
        # reject_alloc kind fires inside the engine's admission path)
        def before(tick, _f=fault):
            _f.before_tick(tick, journal_path=journal_path)
    from ..monitor.events import ThreadExceptionCapture

    thread_cap = ThreadExceptionCapture(monitor).install()
    try:
        with contextlib.ExitStack() as stack:
            san = None
            if sanitize:
                from ..analysis import sanitize as sanitize_ctx

                # every ladder bucket AOT-compiles in warmup(), so the
                # serve holds recompile_budget=0 after the first tick
                san = stack.enter_context(sanitize_ctx(
                    transfer_guard=None, recompile_budget=0,
                    warmup_steps=1))
            engine.warmup()
            if escalation is not None:
                # warmup is not serving: a single AOT compile can
                # outlast a short stall timeout and latch the policy
                # before the first tick ever runs — re-arm it at the
                # traffic boundary (the same per-attempt reset
                # discipline as _run_smoke_loop)
                escalation.reset()
            # submit AFTER warmup so the reported queue-wait/TTFT
            # distributions measure serving, not AOT compile time
            requests = [Request(rid=f"req{i:03d}", prompt=p,
                                max_new_tokens=max_new_tokens)
                        for i, p in enumerate(prompts)]
            after = (lambda i: san.step()) if san else None
            if supervise:
                res = run_serving(
                    engine, requests, journal=journal,
                    max_restarts=max_restarts,
                    backoff_base=backoff_base,
                    monitor=monitor, before_tick=before,
                    after_tick=after)
                summary = res.summary   # restarts set by run_serving
            else:
                for r in requests:
                    engine.submit(r)
                summary = engine.run(before_tick=before,
                                     after_tick=after)
        if trace_dir is not None:
            # one Perfetto lane per request (queued/prefill/decode),
            # written through the PR-7 atomic Chrome writer so the
            # serve loads next to a device trace
            from ..monitor.tracing import write_chrome_trace

            os.makedirs(trace_dir, exist_ok=True)
            write_chrome_trace(
                os.path.join(trace_dir, "serve.chrome.json"),
                engine.metrics.chrome_trace())
    except BaseException as e:
        monitor.event("run", "run_error", step=engine.steps,
                      error=type(e).__name__, message=str(e)[:200])
        raise
    finally:
        try:
            if metrics_server is not None:
                # linger so an external probe can see the drained
                # /healthz (the run() tail published it with
                # draining=True) before the server goes away
                if metrics_linger > 0:
                    time.sleep(metrics_linger)
                metrics_server.stop()
        finally:
            try:
                monitor.close()
            finally:
                try:
                    if journal is not None:
                        journal.close()
                finally:
                    try:
                        if own_snapshot and snapshot is not None:
                            snapshot.close()
                    finally:
                        try:
                            if own_autoresume:
                                autoresume.uninstall()
                        finally:
                            thread_cap.uninstall()
    # a background thread (watchdog heartbeat) that died mid-serve
    # fails the run after teardown instead of vanishing
    thread_cap.raise_first()
    if return_engine:
        return summary, engine
    return summary


# ---------------------------------------------------------------------------
# Fleet serving smoke — multi-replica acceptance path (ISSUE-14)
# ---------------------------------------------------------------------------

def fleet_smoke(num_requests: int = 8, *, replicas: Optional[int] = None,
                tp: Optional[int] = None,
                ep: Optional[int] = None,
                moe_experts: Optional[int] = None,
                disaggregate: Optional[bool] = None,
                policy: Optional[str] = None,
                jsonl_dir: Optional[str] = None,
                vocab: int = 64, hidden: int = 32, num_heads: int = 4,
                num_layers: int = 2, max_seq: int = 64,
                max_new_tokens: int = 4, seed: int = 0,
                dtype=jnp.float32, decode_attention: str = "kernel",
                num_blocks: Optional[int] = None,
                block_size: Optional[int] = None,
                kv_dtype: Optional[str] = None, ladder=None,
                sanitize: bool = False, threads: bool = False,
                swap: bool = False, swap_after: int = 2,
                prefix_share: Optional[bool] = None,
                journal_dir: Optional[str] = None, fault=None,
                fault_replica: str = "r0", max_restarts: int = 3,
                stall_timeout: float = 300.0,
                metrics_port: Optional[int] = None,
                metrics_linger: float = 0.0,
                return_router: bool = False, scheduler=None):
    """Multi-replica serving smoke: N :class:`~apex_tpu.serving.
    ServingEngine` replicas behind the gauge-fed
    :class:`~apex_tpu.serving.FleetRouter` (the ``--serve-fleet``
    acceptance path, tools/ci.sh step 13).

    ``replicas``/``tp``/``disaggregate``/``policy`` default to the
    ``APEX_TPU_SERVE_REPLICAS``/``_TP``/``_DISAGGREGATE``/``_ROUTER``
    flags.  Each replica gets its own engine, KV pool, device (the
    i-th host device, or with ``tp`` its own ``tp``-device slice and
    a :class:`~apex_tpu.serving.TPContext` — head-sharded attention,
    2 psums/layer, greedy output token-identical to single-chip), its
    own JSONL event log (``jsonl_dir/serve-<rid>.jsonl``,
    replica-stamped events) and, with ``journal_dir``, its own crash
    journal — ``fault="crash@K"`` on ``fault_replica`` then recovers
    by crash_reset + replay while the other replicas keep serving.
    ``disaggregate=True`` adds a prefill-role replica streaming
    finished prompt KV into the decode replicas' pools (warm
    admissions, ``prefix_hit_tokens > 0``).  ``swap=True`` performs
    one rolling weight swap (to a freshly initialized model) after
    ``swap_after`` fleet rounds — zero requests lost, zero new
    compiles (the sanitized leg proves both).  ``threads=True`` runs
    one thread per replica (the aggregate-tokens/s scaling mode);
    the default stepped loop is deterministic and supports
    disaggregation and the mid-serve swap.

    ``metrics_port`` (flag: ``APEX_TPU_METRICS_PORT``; explicit
    ``0`` = ephemeral) starts ONE :class:`~apex_tpu.monitor.
    MetricsServer` for the whole fleet: ``/metrics`` carries every
    replica's series under ``replica`` labels plus the
    ``apex_tpu_fleet_*`` aggregates and trend gauges (ISSUE-17),
    ``/healthz`` is ok only when every replica is, ``/varz`` maps
    replica id → snapshot.  ``metrics_linger`` holds the server up
    after the serve for external probes.

    ``scheduler`` (an :class:`apex_tpu.analysis.schedule.
    DeterministicScheduler`) gates the threaded replicas' tick
    boundaries in a seeded permuted order — the race-stress mode.
    A background thread dying mid-serve (``threading.excepthook``)
    is captured, emitted as a ``run_error`` event, and re-raised
    after teardown instead of vanishing.

    Returns the :class:`~apex_tpu.serving.FleetSummary` (with
    ``return_router=True``, ``(summary, router)``)."""
    import numpy as np

    from ..analysis.flags import (flag_bool, flag_int,
                                  flag_str)
    from ..resilience import parse_fault
    from ..serving import (BucketLadder, FleetRouter, Replica, Request,
                           RequestJournal, ServingEngine,
                           ServingModelConfig, TPContext,
                           default_cache_config,
                           extract_serving_weights)

    replicas = replicas if replicas is not None \
        else flag_int("APEX_TPU_SERVE_REPLICAS")
    tp = tp if tp is not None else flag_int("APEX_TPU_SERVE_TP")
    ep = ep if ep is not None else flag_int("APEX_TPU_SERVE_EP")
    if ep and ep > 1 and tp and tp > 1:
        raise ValueError("a replica is tensor-parallel OR expert-"
                         "parallel, not both — pass --tp or --ep")
    disaggregate = disaggregate if disaggregate is not None \
        else flag_bool("APEX_TPU_SERVE_DISAGGREGATE")
    policy = policy if policy is not None \
        else flag_str("APEX_TPU_SERVE_ROUTER")
    if disaggregate:
        prefix_share = True         # the handoff lands through the
        # shared index; colocated replicas may still opt in
    if disaggregate and threads:
        raise ValueError("disaggregation needs the stepped fleet "
                         "loop (threads=False)")

    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=num_layers,
        num_attention_heads=num_heads, max_sequence_length=max_seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=dtype)
    key = jax.random.PRNGKey(seed)
    probe = jnp.zeros((1, min(8, max_seq)), jnp.int32)
    params = jax.jit(model.init)(key, probe)["params"]
    cfg = ServingModelConfig.from_model(
        model, decode_attention=decode_attention)
    weights = extract_serving_weights(params, num_layers)
    if ep and ep > 1:
        import dataclasses as _dc

        from ..serving import expand_moe_weights

        n_exp = moe_experts if moe_experts else 2 * ep
        cfg = _dc.replace(
            cfg, num_experts=n_exp, moe_capacity_factor=8.0,
            moe_a2a_chunks=max(1, flag_int("APEX_TPU_MOE_A2A_CHUNKS")))
        weights = expand_moe_weights(weights, n_exp,
                                     jax.random.PRNGKey(seed + 2))
    swap_weights = None
    if swap:
        # a REAL weight change (fresh init): the swap leg proves the
        # fleet swaps models, not just that the plumbing runs
        swap_params = jax.jit(model.init)(
            jax.random.PRNGKey(seed + 101), probe)["params"]
        swap_weights = extract_serving_weights(swap_params, num_layers)
        if ep and ep > 1:
            from ..serving import expand_moe_weights

            swap_weights = expand_moe_weights(
                swap_weights, cfg.num_experts,
                jax.random.PRNGKey(seed + 2))
    if ladder is None:
        ladder = BucketLadder.from_flags()
    devices = jax.devices()
    if isinstance(fault, str):
        fault = parse_fault(fault)

    def make_cache_cfg():
        return default_cache_config(cfg, num_blocks=num_blocks,
                                    block_size=block_size,
                                    kv_dtype=kv_dtype)

    monitors = []
    members = []
    total = replicas + (1 if disaggregate else 0)
    if tp and tp > 1 and total * tp > len(devices):
        raise ValueError(
            f"{total} replica(s) x tp={tp} needs {total * tp} "
            f"devices, host has {len(devices)}")
    if ep and ep > 1 and total * ep > len(devices):
        raise ValueError(
            f"{total} replica(s) x ep={ep} needs {total * ep} "
            f"devices, host has {len(devices)}")

    if jsonl_dir:
        os.makedirs(jsonl_dir, exist_ok=True)

    def make_member(idx: int, rid: str, role: str) -> Replica:
        monitor = make_smoke_monitor(
            (os.path.join(jsonl_dir, f"serve-{rid}.jsonl")
             if jsonl_dir else None), None,
            tokens_per_step=None, flops_per_step=None,
            stall_timeout=stall_timeout,
            run_attrs={"driver": "standalone_gpt.fleet_smoke",
                       "replica": rid, "role": role,
                       "replicas": replicas, "tp": tp or 0,
                       "ep": ep or 0,
                       "disaggregate": bool(disaggregate)})
        monitors.append(monitor)
        cache_cfg = make_cache_cfg()
        tp_ctx = None
        ep_ctx = None
        device = None
        if tp and tp > 1:
            tp_ctx = TPContext(cfg, cache_cfg, tp,
                               devices=devices[idx * tp:
                                               (idx + 1) * tp])
        elif ep and ep > 1:
            from ..serving import EPContext

            ep_ctx = EPContext(cfg, cache_cfg, ep,
                               devices=devices[idx * ep:
                                               (idx + 1) * ep])
        else:
            device = devices[idx % len(devices)]
        engine = ServingEngine(
            weights, cfg, cache_cfg, ladder=ladder, monitor=monitor,
            prefix_share=prefix_share, tp=tp_ctx, ep=ep_ctx,
            device=device, replica_id=rid)
        journal = None
        if journal_dir:
            os.makedirs(journal_dir, exist_ok=True)
            journal = RequestJournal(
                os.path.join(journal_dir, f"{rid}.journal.jsonl"))
        return Replica(rid, engine, role=role, journal=journal,
                       max_restarts=max_restarts,
                       fault=(fault if rid == fault_replica
                              else None))

    for i in range(replicas):
        members.append(make_member(i, f"r{i}", "serve"))
    if disaggregate:
        members.append(make_member(replicas, "pf0", "prefill"))
    if metrics_port is None:
        _fp = flag_int("APEX_TPU_METRICS_PORT")
        metrics_port = _fp if _fp > 0 else None
    exporter = metrics_server = None
    if metrics_port is not None:
        from ..monitor.export import MetricsExporter, MetricsServer

        exporter = MetricsExporter()
        metrics_server = MetricsServer(exporter, port=metrics_port,
                                       monitor=monitors[0])
        metrics_server.start()
        print(f"METRICS http://127.0.0.1:{metrics_server.port}"
              f"/metrics", flush=True)
    # the router gets replica 0's RAW monitor (pre-stamping): fleet-
    # scope events (request_routed, kv_handoff, fleet_done) carry
    # their own explicit replica attrs and must not inherit a bogus
    # replica="r0" default
    router = FleetRouter(members, policy=policy, monitor=monitors[0],
                         exporter=exporter)

    # deterministic mixed-length prompts with shared-prefix pairs (so
    # sticky routing and the prefix machinery have something to bite)
    rng = np.random.RandomState(seed)
    span = ladder.max_pages * make_cache_cfg().block_size
    max_prompt = max(1, min(max_seq, span) - max_new_tokens)
    prompts = []
    for i in range(num_requests):
        n = 1 + (int(rng.randint(1, 10 ** 6)) % max_prompt)
        prompts.append([int(t) for t in rng.randint(0, vocab, n)])
    requests = [Request(rid=f"req{i:03d}", prompt=p,
                        max_new_tokens=max_new_tokens)
                for i, p in enumerate(prompts)]

    from ..monitor.events import ThreadExceptionCapture

    # the crash event lands in replica 0's JSONL (the fleet-scope
    # log); the explicit replica="fleet" attr keeps it from reading
    # as an r0 failure — the record's `thread` names the real owner
    thread_cap = ThreadExceptionCapture(
        monitors[0] if monitors else None,
        attrs={"replica": "fleet"})
    try:
        with contextlib.ExitStack() as stack:
            stack.enter_context(thread_cap)
            san = None
            if sanitize:
                from ..analysis import sanitize as sanitize_ctx

                san = stack.enter_context(sanitize_ctx(
                    transfer_guard=None, recompile_budget=0,
                    warmup_steps=1))
            for m in members:
                with m.device_scope():
                    m.engine.warmup()
            if threads:
                summary = router.serve_threaded(requests,
                                                scheduler=scheduler)
            else:
                after = (lambda i: san.step()) if san else None
                summary = router.serve(
                    requests,
                    swap_after=(swap_after if swap else None),
                    swap_weights=swap_weights,
                    before_round=after)
    finally:
        try:
            if metrics_server is not None:
                if metrics_linger > 0:
                    time.sleep(metrics_linger)
                metrics_server.stop()
        finally:
            for m in monitors:
                m.close()
    # a background thread that died mid-serve (captured by the
    # excepthook above, run_error already in the log) fails the run
    # AFTER teardown — it must not vanish into stderr
    thread_cap.raise_first()
    if return_router:
        return summary, router
    return summary


# ---------------------------------------------------------------------------
# Process-isolated fleet (ISSUE-18) — subprocess builder + driver
# ---------------------------------------------------------------------------

def build_fleet_engine(spec_dict: dict) -> dict:
    """Child-side :class:`~apex_tpu.serving.EngineSpec` builder — the
    default entry point a replica subprocess resolves and calls with
    its spec as a plain dict.  Runs entirely IN THE CHILD: model init,
    weight extraction, cache allocation, warmup, the JSONL monitor and
    the crash journal all live here; the supervising parent only ever
    sees the socket.  The model kwargs mirror :func:`fleet_smoke`'s
    member construction, so a process fleet and an in-process fleet
    built from the same seed serve token-identical greedy output.

    Returns ``{"engine", "monitor", "journal", "close"}`` per the
    builder contract.  ``close`` pops the ``jax.default_device`` scope
    that pins this replica's staging to its own device for the life of
    the process (the fleet-scaling discipline from ISSUE-14)."""
    import contextlib as _ctx

    from ..serving import (BucketLadder, RequestJournal,
                           ServingEngine, ServingModelConfig,
                           default_cache_config,
                           extract_serving_weights)

    m = dict(spec_dict.get("model") or {})
    vocab = int(m.get("vocab", 64))
    hidden = int(m.get("hidden", 32))
    num_heads = int(m.get("num_heads", 4))
    num_layers = int(m.get("num_layers", 2))
    max_seq = int(m.get("max_seq", 64))
    seed = int(m.get("seed", 0))
    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=num_layers,
        num_attention_heads=num_heads, max_sequence_length=max_seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    key = jax.random.PRNGKey(seed)
    probe = jnp.zeros((1, min(8, max_seq)), jnp.int32)
    params = jax.jit(model.init)(key, probe)["params"]
    cfg = ServingModelConfig.from_model(
        model, decode_attention=m.get("decode_attention", "kernel"))
    weights = extract_serving_weights(params, num_layers)
    cache_cfg = default_cache_config(
        cfg, num_blocks=m.get("num_blocks"),
        block_size=m.get("block_size"),
        kv_dtype=m.get("kv_dtype"))
    devices = jax.devices()
    di = spec_dict.get("device_index")
    device = (devices[int(di) % len(devices)]
              if di is not None else None)
    rid = str(spec_dict["replica_id"])
    monitor = make_smoke_monitor(
        spec_dict.get("jsonl_path"), None, tokens_per_step=None,
        flops_per_step=None,
        stall_timeout=float(m.get("stall_timeout", 300.0)),
        run_attrs={"driver": "standalone_gpt.build_fleet_engine",
                   "replica": rid, "role": spec_dict.get("role"),
                   "pid": os.getpid()})
    journal = (RequestJournal(spec_dict["journal_path"])
               if spec_dict.get("journal_path") else None)
    scope = _ctx.ExitStack()
    if device is not None:
        scope.enter_context(jax.default_device(device))
    engine = ServingEngine(
        weights, cfg, cache_cfg,
        ladder=BucketLadder.from_flags(), monitor=monitor,
        prefix_share=m.get("prefix_share"), device=device,
        replica_id=rid, journal=journal)
    engine.warmup()
    return {"engine": engine, "monitor": monitor,
            "journal": journal, "close": scope.close}


def fleet_procs_smoke(num_requests: int = 8, *, replicas: int = 2,
                      disaggregate: bool = False,
                      jsonl_dir: Optional[str] = None,
                      journal_dir: Optional[str] = None,
                      vocab: int = 64, hidden: int = 32,
                      num_heads: int = 4, num_layers: int = 2,
                      max_seq: int = 64, max_new_tokens: int = 4,
                      seed: int = 0,
                      decode_attention: str = "kernel",
                      num_blocks: Optional[int] = None,
                      block_size: Optional[int] = None,
                      kv_dtype: Optional[str] = None,
                      prefix_share: Optional[bool] = None,
                      fault=None, fault_replica: str = "r0",
                      max_restarts: int = 3,
                      autoscale: Optional[str] = None,
                      qos=None,
                      metrics_port: Optional[int] = None,
                      freerun: bool = False,
                      stall_timeout: float = 300.0,
                      tick_seed: int = 0,
                      rpc_timeout_s: Optional[float] = None,
                      poll_timeout_s: Optional[float] = None,
                      heartbeat_misses: Optional[int] = None,
                      return_fleet: bool = False):
    """Process-isolated fleet smoke (``--serve-fleet --procs``,
    tools/ci.sh step 17): ``replicas`` supervised subprocesses, each
    a full :func:`build_fleet_engine` replica on its own device,
    driven over local sockets by :class:`~apex_tpu.serving.
    ProcessFleet` — heartbeat liveness, ``fault="kill9@K"`` SIGKILL
    drills recovered by journal replay (fleet digest token-identical
    to an uninterrupted run), ``fault="rpc_timeout@K"`` degraded
    gauge polls, disaggregated prefill KV handoff over the socket,
    and ``autoscale="MIN:MAX"`` queue-depth-trend scaling with
    drain-then-reap scale-down.  ``freerun=True`` posts one ``run``
    RPC per replica instead of the stepped round loop (the scaling
    bench mode).  Returns the :class:`~apex_tpu.serving.
    ProcessFleetSummary` (with ``return_fleet=True``, ``(summary,
    fleet)`` — the fleet is already closed)."""
    import numpy as np

    from ..serving import (AutoscalePolicy, BucketLadder, EngineSpec,
                           ProcessFleet, ServingModelConfig,
                           default_cache_config)

    if jsonl_dir:
        os.makedirs(jsonl_dir, exist_ok=True)
    if journal_dir is None:
        # the kill-9 drill is only recoverable through the on-disk
        # journal, so a journal is not optional in process mode
        journal_dir = tempfile.mkdtemp(prefix="apexcp-journal-")
    os.makedirs(journal_dir, exist_ok=True)

    model_kwargs = {
        "vocab": vocab, "hidden": hidden, "num_heads": num_heads,
        "num_layers": num_layers, "max_seq": max_seq, "seed": seed,
        "decode_attention": decode_attention,
        "num_blocks": num_blocks, "block_size": block_size,
        "kv_dtype": kv_dtype, "stall_timeout": stall_timeout,
        "prefix_share": (True if disaggregate else prefix_share),
    }

    def make_spec(rid: str, idx: int, role: str = "serve"
                  ) -> EngineSpec:
        return EngineSpec(
            replica_id=rid, role=role, model=model_kwargs,
            device_index=idx,
            jsonl_path=(os.path.join(jsonl_dir,
                                     f"serve-{rid}.jsonl")
                        if jsonl_dir else None),
            journal_path=os.path.join(journal_dir,
                                      f"{rid}.journal.jsonl"))

    specs = [make_spec(f"r{i}", i) for i in range(replicas)]
    if disaggregate:
        specs.append(make_spec("pf0", replicas, "prefill"))

    policy = None
    if autoscale:
        lo, _, hi = str(autoscale).partition(":")
        policy = AutoscalePolicy(min_replicas=int(lo),
                                 max_replicas=int(hi or lo))

    # the same deterministic prompt mix as fleet_smoke — cfg/ladder
    # construction here is host-side math only (no device arrays in
    # the parent)
    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=num_layers,
        num_attention_heads=num_heads, max_sequence_length=max_seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    cfg = ServingModelConfig.from_model(
        model, decode_attention=decode_attention)
    cache_cfg = default_cache_config(cfg, num_blocks=num_blocks,
                                     block_size=block_size,
                                     kv_dtype=kv_dtype)
    ladder = BucketLadder.from_flags()
    rng = np.random.RandomState(seed)
    span = ladder.max_pages * cache_cfg.block_size
    max_prompt = max(1, min(max_seq, span) - max_new_tokens)
    requests = []
    for i in range(num_requests):
        n = 1 + (int(rng.randint(1, 10 ** 6)) % max_prompt)
        requests.append({
            "rid": f"req{i:03d}",
            "prompt": [int(t) for t in rng.randint(0, vocab, n)],
            "max_new_tokens": max_new_tokens})

    fleet = ProcessFleet(
        specs,
        jsonl_path=(os.path.join(jsonl_dir, "supervisor.jsonl")
                    if jsonl_dir else None),
        qos=qos, autoscale=policy,
        spec_factory=make_spec,
        metrics_port=metrics_port, fault=fault,
        fault_replica=fault_replica, max_restarts=max_restarts,
        rpc_timeout_s=rpc_timeout_s, poll_timeout_s=poll_timeout_s,
        heartbeat_misses=heartbeat_misses, tick_seed=tick_seed)
    with fleet:
        summary = fleet.serve(requests, freerun=freerun)
    if return_fleet:
        return summary, fleet
    return summary


def add_resilience_cli(p) -> None:
    """The shared GPT/BERT smoke-driver resilience flags."""
    p.add_argument("--ckpt-dir", default=None,
                   help="checkpoint directory; enables periodic saves, "
                        "auto-resume from the latest valid step, and "
                        "SIGTERM-safe exit with a CLEAN_EXIT.json "
                        "marker")
    p.add_argument("--ckpt-every", type=int, default=1,
                   help="save every N steps (default 1)")
    p.add_argument("--no-resume", action="store_true",
                   help="start from step 0 even if checkpoints exist")
    p.add_argument("--fault", default=None,
                   help="deterministic fault spec, e.g. 'sigterm@4', "
                        "'crash@3', 'nan@2,crash@5', 'stall@1:0.5' "
                        "(see apex_tpu.resilience.faults)")


def _main(argv=None):
    import argparse

    p = argparse.ArgumentParser(
        description="Monitored GPT smoke train loop (CPU-friendly); "
                    "writes an apex_tpu.monitor JSONL event log. "
                    "With --ckpt-dir the loop is preemption-safe: "
                    "kill it (--fault sigterm@K or a real SIGTERM) and "
                    "re-run the same command to resume.")
    p.add_argument("--steps", type=int, default=8)
    p.add_argument("--jsonl", default=None,
                   help="event-log path (default: in-memory only)")
    p.add_argument("--opt-level", default="O2")
    p.add_argument("--stall-timeout", type=float, default=300.0)
    p.add_argument("--sanitize", action="store_true",
                   help="run under apex_tpu.analysis.sanitize(): fail "
                        "if the train step recompiles after warmup "
                        "(with --telemetry-drain-every also fail on "
                        "ANY per-step implicit device->host transfer)")
    p.add_argument("--trace", default=None, metavar="DIR",
                   help="wall-time attribution: per-step waterfall + "
                        "host spans into the event log, "
                        "DIR/trace.chrome.json for Perfetto, and the "
                        "APEX_TPU_TRACE_* capture triggers")
    p.add_argument("--telemetry-drain-every", type=int, default=None,
                   metavar="K",
                   help="deferred telemetry: accumulate per-step "
                        "scalars in a device ring, drain every K "
                        "steps (zero per-step host transfers); "
                        "default: APEX_TPU_TELEMETRY_DRAIN_EVERY "
                        "(0 = classic synchronous readback)")
    p.add_argument("--scan-steps", type=int, default=None, metavar="K",
                   help="batched-step scan driver: K train steps per "
                        "jit call (lax.scan; amp state + telemetry "
                        "ring in the donated carry, AOT-compiled "
                        "windows, drains/checkpoints on K-step "
                        "edges); default: APEX_TPU_SCAN_STEPS "
                        "(0 = classic per-step loop)")
    p.add_argument("--serve", action="store_true",
                   help="run the continuous-batching serving smoke "
                        "instead of the train loop: mixed-length "
                        "requests through the apex_tpu.serving "
                        "engine (prefill = flash fwd kernel, decode "
                        "= paged flash-decode kernel), tokens/s and "
                        "p50/p99 per-token latency plus TTFT/queue-"
                        "wait percentiles reported; with --sanitize "
                        "proves one compile per ladder bucket; "
                        "--fault sigterm@K proves the clean drain; "
                        "with --trace DIR also writes per-request "
                        "Perfetto lanes to DIR/serve.chrome.json")
    p.add_argument("--requests", type=int, default=6,
                   help="(--serve) number of requests to serve")
    p.add_argument("--new-tokens", type=int, default=6,
                   help="(--serve) tokens generated per request")
    p.add_argument("--serve-max-seq", type=int, default=64,
                   help="(--serve) model position-table length")
    p.add_argument("--decode-reference", action="store_true",
                   help="(--serve) dense full-gather decode instead "
                        "of the paged kernel (the naive baseline)")
    p.add_argument("--policy", default=None, choices=("O5", "Q8"),
                   help="(--serve) amp serving tier: O5 casts the "
                        "model to bf16; Q8 additionally quantizes "
                        "every matmul weight to per-channel int8 "
                        "(weight-only, fp32 accumulation) — the "
                        "quantized decode smoke")
    p.add_argument("--speculate-k", type=int, default=None,
                   metavar="K",
                   help="(--serve) speculative decoding: a draft "
                        "model proposes K tokens per tick, the "
                        "target scores all of them in one paged "
                        "multi-token call; greedy-match acceptance "
                        "keeps output token-identical to plain "
                        "greedy decode (default: "
                        "APEX_TPU_SERVE_SPECULATE_K)")
    p.add_argument("--draft", choices=("self", "narrow"),
                   default="self",
                   help="(--serve --speculate-k) draft model: "
                        "'self' reuses the target (acceptance 1.0 "
                        "ceiling), 'narrow' a 1-layer half-width "
                        "GPT (rollback stress)")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   metavar="N",
                   help="(--serve) chunked prefill: split prompt "
                        "admission into N-token chunks interleaved "
                        "one per tick with decode (default: "
                        "APEX_TPU_SERVE_PREFILL_CHUNK)")
    p.add_argument("--prefix-share", action="store_true",
                   default=None,
                   help="(--serve) copy-on-write prompt-prefix "
                        "sharing: warm prefixes map shared KV pages "
                        "instead of re-prefilling (default: "
                        "APEX_TPU_SERVE_PREFIX_SHARE)")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="(--serve) default request deadline in ms "
                        "(submit -> last token); queued requests past "
                        "it expire terminal deadline_exceeded, "
                        "running ones are evicted terminal deadline "
                        "(default: APEX_TPU_SERVE_DEADLINE_MS)")
    p.add_argument("--shed-pool-hw", type=float, default=None,
                   help="(--serve) load-shedding high-water mark on "
                        "pool pressure, fraction (default: "
                        "APEX_TPU_SERVE_SHED_POOL_HW; 0 disables)")
    p.add_argument("--shed-queue-hw", type=int, default=None,
                   help="(--serve) load-shedding high-water mark on "
                        "the admission backlog (default: "
                        "APEX_TPU_SERVE_SHED_QUEUE_HW; 0 disables)")
    p.add_argument("--journal", default=None, metavar="PATH",
                   help="(--serve) crash-safe request journal JSONL "
                        "(submit/progress/terminal transitions; "
                        "default: APEX_TPU_SERVE_JOURNAL_DIR/"
                        "serve.journal.jsonl when that flag is set)")
    p.add_argument("--supervise", action="store_true",
                   help="(--serve) run the engine under the "
                        "serving supervisor: bounded-backoff "
                        "restarts, journal replay of every "
                        "non-terminal request after a crash "
                        "(requires --journal); --fault crash@K "
                        "recovers instead of dying")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="(--serve --supervise) restart budget "
                        "(default 3)")
    p.add_argument("--serve-fleet", action="store_true",
                   help="multi-replica serving smoke: N engines "
                        "behind the gauge-fed FleetRouter "
                        "(apex_tpu.serving.fleet) — per-replica KV "
                        "pools/devices/JSONL logs, sticky warm "
                        "routing, optional TP decode, disaggregated "
                        "prefill/decode, and a rolling weight swap; "
                        "prints a FLEET_DONE row")
    p.add_argument("--replicas", type=int, default=None,
                   help="(--serve-fleet) serve-role replica count "
                        "(default: APEX_TPU_SERVE_REPLICAS)")
    p.add_argument("--tp", type=int, default=None,
                   help="(--serve-fleet) tensor-parallel width per "
                        "replica; each replica takes its own "
                        "TP-device slice (default: "
                        "APEX_TPU_SERVE_TP; 0 = single-chip)")
    p.add_argument("--ep", type=int, default=None,
                   help="(--serve / --serve-fleet) expert-parallel "
                        "width: expand the "
                        "MLPs to a Switch MoE and shard the expert "
                        "stacks over this many devices (default: "
                        "APEX_TPU_SERVE_EP; 0 = single-chip)")
    p.add_argument("--moe-experts", type=int, default=None,
                   help="(--serve --ep) expert count for the MoE "
                        "expansion (default: 2*ep; must divide by "
                        "ep)")
    p.add_argument("--disaggregate", action="store_true",
                   default=None,
                   help="(--serve-fleet) add a prefill-role replica "
                        "that streams finished prompt KV into the "
                        "decode replicas' pools (warm admissions; "
                        "default: APEX_TPU_SERVE_DISAGGREGATE)")
    p.add_argument("--router-policy", default=None,
                   choices=("gauges", "round_robin"),
                   help="(--serve-fleet) submission policy "
                        "(default: APEX_TPU_SERVE_ROUTER)")
    p.add_argument("--swap", action="store_true",
                   help="(--serve-fleet) perform one rolling weight "
                        "swap (to a freshly initialized model) "
                        "mid-serve — zero lost requests, zero new "
                        "compiles")
    p.add_argument("--fleet-threads", action="store_true",
                   help="(--serve-fleet) one thread per replica "
                        "(the aggregate tokens/s scaling mode); "
                        "default is the deterministic stepped loop")
    p.add_argument("--procs", action="store_true",
                   help="(--serve-fleet) process-isolated fleet "
                        "(ISSUE-18): each replica is a supervised "
                        "SUBPROCESS on its own device, driven over "
                        "local sockets by the control plane — "
                        "heartbeat liveness, kill-9 restart with "
                        "journal replay, socket KV handoff; "
                        "--fleet-threads selects the freerun drive "
                        "mode (one run RPC per replica) instead of "
                        "the stepped round loop; prints a "
                        "FLEETP_DONE row")
    p.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                   help="(--procs) autoscale the serve-replica count "
                        "between MIN and MAX from the fleet "
                        "aggregator's queue-depth trend (scale-up on "
                        "backlog, drain-then-reap scale-down); the "
                        "autoscale event trace lands in the "
                        "supervisor JSONL")
    p.add_argument("--jsonl-dir", default=None, metavar="DIR",
                   help="(--serve-fleet) per-replica event logs "
                        "DIR/serve-<rid>.jsonl (replica-stamped; "
                        "aggregate with trace_check --serve "
                        "DIR/serve-*.jsonl)")
    p.add_argument("--journal-dir", default=None, metavar="DIR",
                   help="(--serve-fleet) per-replica crash journals "
                        "DIR/<rid>.journal.jsonl; with --fault "
                        "crash@K the faulted replica recovers by "
                        "journal replay while the rest keep serving")
    p.add_argument("--fleet-hidden", type=int, default=32,
                   help="(--serve-fleet) model hidden size — the "
                        "bench scaling legs use a compute-heavier "
                        "shape than the CI smoke default")
    p.add_argument("--fleet-layers", type=int, default=2,
                   help="(--serve-fleet) model layer count")
    p.add_argument("--fleet-vocab", type=int, default=64,
                   help="(--serve-fleet) model vocab size")
    p.add_argument("--metrics-port", type=int, default=None,
                   metavar="PORT",
                   help="(--serve / --serve-fleet) start the live "
                        "metrics plane on this port: /metrics "
                        "(Prometheus text exposition), /healthz "
                        "(drain/shed/SLO aware), /varz (engine "
                        "snapshot JSON).  0 = ephemeral port "
                        "(printed as a METRICS line); default: "
                        "APEX_TPU_METRICS_PORT (0 there = off)")
    p.add_argument("--metrics-linger", type=float, default=0.0,
                   metavar="SEC",
                   help="(--metrics-port) keep the metrics server "
                        "up SEC seconds after the drain so an "
                        "external probe can observe the drained "
                        "/healthz before teardown")
    add_resilience_cli(p)
    args = p.parse_args(argv)
    if args.serve_fleet and args.procs:
        s = fleet_procs_smoke(
            args.requests,
            replicas=(args.replicas if args.replicas is not None
                      else 2),
            disaggregate=bool(args.disaggregate),
            jsonl_dir=args.jsonl_dir, journal_dir=args.journal_dir,
            max_new_tokens=args.new_tokens,
            max_seq=args.serve_max_seq, hidden=args.fleet_hidden,
            num_layers=args.fleet_layers, vocab=args.fleet_vocab,
            decode_attention=("reference" if args.decode_reference
                              else "kernel"),
            fault=args.fault, max_restarts=args.max_restarts,
            autoscale=args.autoscale,
            metrics_port=args.metrics_port,
            freerun=args.fleet_threads,
            stall_timeout=args.stall_timeout)
        print(f"FLEETP_DONE replicas={s.replicas} "
              f"prefill_replicas={s.prefill_replicas} "
              f"offered={s.offered} "
              f"submitted={s.submitted} "
              f"shed_admission={s.shed_admission} "
              f"rejected={s.rejected} "
              f"done={s.requests_done} "
              f"lost={s.lost_requests} "
              f"tokens={s.tokens_generated} "
              f"tokens_s={s.tokens_per_sec} "
              f"rounds={s.rounds} "
              f"restarts={s.restarts} "
              f"rpc_timeouts={s.rpc_timeouts} "
              f"handoffs={s.handoffs} "
              f"handoff_retries={s.handoff_retries} "
              f"autoscale_ups={s.autoscale_ups} "
              f"autoscale_downs={s.autoscale_downs} "
              f"replayed={s.replayed_requests} "
              f"digest={s.digest} "
              f"freerun={int(s.freerun)}"
              + (f" jsonl_dir={args.jsonl_dir}"
                 if args.jsonl_dir else ""))
        return
    if args.serve_fleet:
        s = fleet_smoke(
            args.requests, replicas=args.replicas, tp=args.tp,
            ep=args.ep, moe_experts=args.moe_experts,
            disaggregate=args.disaggregate,
            policy=args.router_policy, jsonl_dir=args.jsonl_dir,
            max_new_tokens=args.new_tokens,
            max_seq=args.serve_max_seq,
            hidden=args.fleet_hidden, num_layers=args.fleet_layers,
            vocab=args.fleet_vocab,
            decode_attention=("reference" if args.decode_reference
                              else "kernel"),
            sanitize=args.sanitize, threads=args.fleet_threads,
            swap=args.swap, journal_dir=args.journal_dir,
            fault=args.fault, max_restarts=args.max_restarts,
            stall_timeout=args.stall_timeout,
            metrics_port=args.metrics_port,
            metrics_linger=args.metrics_linger)
        print(f"FLEET_DONE replicas={s.replicas} "
              f"prefill_replicas={s.prefill_replicas} "
              f"policy={s.router_policy} "
              f"submitted={s.requests_submitted} "
              f"done={s.requests_done} "
              f"preempted={s.requests_preempted} "
              f"lost={s.lost_requests} "
              f"tokens={s.tokens_generated} "
              f"tokens_s={s.tokens_per_sec} "
              f"sum_decode_tokens_s={s.sum_decode_tokens_per_sec} "
              f"swaps={s.swaps} handoffs={s.handoffs} "
              f"warm_admissions={s.warm_prefix_admissions} "
              f"prefix_hit_tokens={s.prefix_hit_tokens} "
              f"sticky_routes={s.sticky_routes} "
              f"replayed={s.replayed_requests} "
              f"restarts={s.restarts} "
              f"ttft_p50_ms={s.ttft_p50_ms} "
              f"ttft_p99_ms={s.ttft_p99_ms} "
              f"threaded={int(s.threaded)}"
              + (f" jsonl_dir={args.jsonl_dir}"
                 if args.jsonl_dir else ""))
        return
    if args.serve:
        shed = None
        if args.shed_pool_hw is not None \
                or args.shed_queue_hw is not None:
            from ..analysis.flags import flag_float, flag_int
            from ..serving import ShedPolicy

            # each CLI mark overrides only ITSELF; the other keeps its
            # APEX_TPU_SERVE_SHED_* default as the help text promises
            shed = ShedPolicy(
                pool_hw=(args.shed_pool_hw
                         if args.shed_pool_hw is not None else
                         flag_float("APEX_TPU_SERVE_SHED_POOL_HW")),
                queue_hw=(args.shed_queue_hw
                          if args.shed_queue_hw is not None else
                          flag_int("APEX_TPU_SERVE_SHED_QUEUE_HW")))
        s, eng = serve_smoke(
            args.requests, jsonl=args.jsonl, sanitize=args.sanitize,
            max_new_tokens=args.new_tokens,
            max_seq=args.serve_max_seq, policy=args.policy,
            decode_attention=("reference" if args.decode_reference
                              else "kernel"),
            stall_timeout=args.stall_timeout, fault=args.fault,
            trace_dir=args.trace, speculate_k=args.speculate_k,
            prefill_chunk=args.prefill_chunk,
            prefix_share=args.prefix_share, draft=args.draft,
            deadline_ms=args.deadline_ms, shed=shed,
            journal_path=args.journal, supervise=args.supervise,
            max_restarts=args.max_restarts,
            metrics_port=args.metrics_port,
            metrics_linger=args.metrics_linger,
            ep=args.ep, moe_experts=args.moe_experts,
            return_engine=True)
        spec = "" if s.spec_accept_rate is None else (
            f" spec_accept_rate={s.spec_accept_rate}"
            f" spec_proposed={s.spec_tokens_proposed}")
        share = "" if not (s.warm_prefix_admissions
                           or s.shared_blocks_hw) else (
            f" warm_admissions={s.warm_prefix_admissions}"
            f" prefix_hit_tokens={s.prefix_hit_tokens}"
            f" shared_blocks_hw={s.shared_blocks_hw}"
            f" cow_copies={s.cow_copies}")
        chunks = f" prefill_chunks={s.prefill_chunks}" \
            if s.prefill_chunks else ""
        resil = ""
        if args.supervise or s.replayed_requests:
            resil += (f" restarts={s.restarts}"
                      f" replayed={s.replayed_requests}")
        if s.requests_deadline:
            resil += f" deadline={s.requests_deadline}"
        if s.requests_shed:
            resil += (f" shed={s.requests_shed}"
                      f" shed_engagements={s.shed_engagements}")
        if s.spec_disabled:
            resil += " spec_disabled=1"
        if s.slo_burn_episodes or s.slo_burning:
            resil += (f" slo_burns={s.slo_burn_episodes}"
                      f" slo_recoveries={s.slo_recoveries}"
                      f" slo_burning={','.join(s.slo_burning) or '-'}")
        print(f"SERVE_DONE requests={s.requests_done} "
              f"preempted={s.requests_preempted} "
              f"tokens={s.tokens_generated} "
              f"tokens_s={s.tokens_per_sec} "
              f"p50_ms={s.latency_p50_ms} p99_ms={s.latency_p99_ms} "
              f"ttft_p50_ms={s.ttft_p50_ms} "
              f"ttft_p99_ms={s.ttft_p99_ms} "
              f"queue_wait_p99_ms={s.queue_wait_p99_ms} "
              f"steps={s.decode_steps} "
              f"compiles={len(s.compiles)} "
              f"drained={int(s.drained)}"
              f"{spec}{share}{chunks}{resil} "
              f"digest={eng.tokens_digest()}"
              + (f" jsonl={args.jsonl}" if args.jsonl else ""))
        return
    loss, _, _, done = train_smoke(
        steps=args.steps, jsonl=args.jsonl, opt_level=args.opt_level,
        stall_timeout=args.stall_timeout, ckpt_dir=args.ckpt_dir,
        ckpt_every=args.ckpt_every, resume=not args.no_resume,
        fault=args.fault, return_state=True, sanitize=args.sanitize,
        trace_dir=args.trace,
        drain_every=args.telemetry_drain_every,
        scan_steps=args.scan_steps)
    print(f"SMOKE_DONE steps_done={done}"
          + (f" loss={loss:.4f}" if loss is not None else "")
          + (f" jsonl={args.jsonl}" if args.jsonl else ""))


if __name__ == "__main__":
    _main()
