"""Registry of the framework's lowerable entry points.

ONE list of real jitted steps, shared by everything that needs "the
programs this framework actually compiles":

* the compiled-graph auditor (:mod:`apex_tpu.analysis.hlo`) lowers each
  entry and checks donation, dtype promotion, the collective census,
  host transfers, and peak live memory against the committed baseline
  (``python -m apex_tpu.analysis --check-hlo``, tools/ci.sh step 8);
* the sanitizer smoke drives the GPT entry's exact step function;
* the train-smoke drivers build their steps through the same
  ``make_smoke_setup``/``build_train_step`` pair the entries here use.

Before this registry the smoke drivers, the sanitizer, and CI each
reconstructed their own copy of "the GPT step" — an audit of one said
nothing about the others.  Now an entry point is data: name, builder,
precision-policy tag, which arguments die at the call boundary
(donation candidates, APX601), which provenance paths are sanctioned
fp32 regions under the policy (APX602), and how many devices the build
needs (multichip entries lower on an 8-device host-platform mesh —
``XLA_FLAGS=--xla_force_host_platform_device_count=8``, the tests'
standing configuration).

Builders are lazy (nothing lowers at import) and cheap: tiny shapes,
CPU-lowerable, no compile — the auditor only needs ``.lower()``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Tuple

__all__ = ["EntryPoint", "ENTRY_POINTS", "register_entry_point",
           "available_entry_points"]

# Provenance path substrings (repo-relative) where an fp32 upcast is
# the precision policy's own doing, shared by every low-precision
# entry: fp32 layer-norm statistics, fp32 softmax, fp32 loss, and the
# amp/optimizer machinery (masters, unscale, norm sweeps) are what
# O4/O5 *mean* — APX602 exists for upcasts outside this list.
POLICY_FP32_REGIONS = (
    "apex_tpu/normalization/",
    "apex_tpu/ops/layer_norm.py",
    "apex_tpu/ops/scaled_softmax.py",
    "apex_tpu/ops/flash_attention.py",
    "apex_tpu/contrib/xentropy/",
    "apex_tpu/transformer/tensor_parallel/cross_entropy.py",
    "apex_tpu/transformer/functional/fused_softmax.py",
    "apex_tpu/amp/",
    "apex_tpu/optimizers/",
    "apex_tpu/ops/fused_pipeline.py",
    "apex_tpu/ops/fused_optim.py",
    "apex_tpu/ops/multi_tensor.py",
    # the smoke drivers' own loss-side fp32 entry (gpt_loss /
    # bert lm+nsp mean): loss math is fp32 under every policy
    "apex_tpu/testing/standalone_gpt.py",
    "apex_tpu/testing/standalone_bert.py",
    # param_l2_norm / loss averaging: fp32 norm accumulation is the
    # same sanctioned class as multi_tensor.sumsq
    "apex_tpu/transformer/pipeline_parallel/utils.py",
    # serving: fp32 softmax/layer-norm statistics and int8 KV dequant
    # scales are the decode path's sanctioned fp32 regions
    "apex_tpu/serving/",
    "apex_tpu/ops/flash_decode.py",
    # Q8: fp32 accumulation is the quantized matmul's contract (the
    # activation upcast feeding the int8 contraction) — APX606, not
    # APX602, polices what may leave this module
    "apex_tpu/ops/quant_matmul.py",
)


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One lowerable entry point: the registry row the auditor walks.

    ``build()`` returns ``(fn, args)`` with ``fn`` a ``jax.jit``-wrapped
    callable and ``args`` example arguments — the auditor calls
    ``fn.lower(*args)`` and never executes the step.
    """

    name: str
    build: Callable[[], Tuple[Any, tuple]]
    # O-level tag; 'O4'/'O5' arms APX602 (silent bf16/f16->f32
    # promotion) for this entry.
    policy: Optional[str] = None
    # Positional argnums whose buffers are dead after the call (the
    # caller rebinds them) — donation candidates for APX601.
    dead_args: Tuple[int, ...] = ()
    # Extra sanctioned-fp32 provenance substrings on top of
    # POLICY_FP32_REGIONS.
    allow_upcast: Tuple[str, ...] = ()
    min_devices: int = 1
    doc: str = ""
    # Lazy MeshPlan constructor: the entry's declared topology contract
    # (axes + kinds, per-tensor partition specs, collective budget).
    # Entries that carry one are compiled under their mesh by the SPMD
    # auditor (apex_tpu.analysis.sharding, APX701-705) and their plan
    # is committed to tools/sharding_baseline.json — a topology change
    # is a reviewed JSON diff.  The builder itself must derive its
    # runtime in/out specs from the SAME plan, or the auditor will
    # report the drift.
    plan: Optional[Callable[[], Any]] = None


ENTRY_POINTS: Dict[str, EntryPoint] = {}


def register_entry_point(name: str, build, **kw) -> EntryPoint:
    if name in ENTRY_POINTS:
        raise ValueError(f"duplicate entry point registration: {name}")
    ep = EntryPoint(name=name, build=build, **kw)
    ENTRY_POINTS[name] = ep
    return ep


def available_entry_points() -> Dict[str, EntryPoint]:
    """Entries buildable on this host (device-count gate)."""
    import jax

    n = jax.device_count()
    return {k: v for k, v in ENTRY_POINTS.items() if v.min_devices <= n}


# ---------------------------------------------------------------------------
# Single-chip entries: the smoke train steps and the fused pipeline
# ---------------------------------------------------------------------------

def _build_gpt_train_step():
    from .standalone_gpt import build_train_step, make_smoke_setup

    setup = make_smoke_setup(opt_level="O2")
    return build_train_step(setup), (setup.params, setup.amp_state)


def _build_gpt_train_step_o5():
    import jax.numpy as jnp

    from .standalone_gpt import build_train_step, make_smoke_setup

    setup = make_smoke_setup(opt_level="O5", dtype=jnp.bfloat16)
    return build_train_step(setup), (setup.params, setup.amp_state)


def _build_bert_train_step():
    from .standalone_bert import build_train_step, make_smoke_setup

    setup = make_smoke_setup(opt_level="O2")
    return build_train_step(setup), (setup.params, setup.amp_state)


def _build_gpt_train_step_deferred():
    """The deferred-telemetry smoke step: the GPT train step with the
    per-step scalars (loss / grad-norm / scale state) appended into a
    device-resident :class:`apex_tpu.monitor.tracing.
    DeviceMetricsBuffer` ring INSIDE the jit.  Auditing it proves
    statically what the runtime sanitizer proves dynamically: the
    deferred mode compiles in zero host transfers (APX604) and the
    ring state donates cleanly alongside params/amp state (APX601) —
    observability is no longer part of the host time it measures."""
    from ..monitor.tracing import DeviceMetricsBuffer
    from .standalone_gpt import build_train_step, make_smoke_setup

    setup = make_smoke_setup(opt_level="O2")
    buf = DeviceMetricsBuffer(capacity=4)
    return (build_train_step(setup, telemetry=buf),
            (setup.params, setup.amp_state, buf.init()))


def _build_gpt_train_step_scan():
    """The ISSUE-8 batched-step scan driver: K=4 GPT train steps per
    jit call (``build_train_step_scan``) with the deferred-telemetry
    ring appended inside the scan body.  Auditing it proves the whole
    hot path stays clean when K steps fuse into one dispatch: params,
    amp state (masters + packed m/v + scaler), and the ring all donate
    through the scan carry (APX601 — a missed donation here costs
    K-fold nothing extra, but it doubles the largest buffers exactly
    like the per-step entry), and zero host transfers compile in
    (APX604).  The census walker multiplies scan-body ops by the trip
    count, so any per-step collective would be priced K times."""
    from ..monitor.tracing import DeviceMetricsBuffer
    from .standalone_gpt import build_train_step_scan, make_smoke_setup

    setup = make_smoke_setup(opt_level="O2")
    buf = DeviceMetricsBuffer(capacity=4)
    return (build_train_step_scan(setup, 4, telemetry=buf),
            (setup.params, setup.amp_state, buf.init()))


def _build_gpt_decode_step():
    """The serving stack's hot path (ISSUE-9): one bucketed
    continuous-batching decode step — embed one token per sequence,
    per layer write its k/v into the block-paged cache then attend
    over the pages through the Pallas flash-decode kernel, greedy-
    sample in-graph.  Auditing it proves the per-token serving cost
    statically: the paged cache (the largest serving buffer — double-
    buffering it halves capacity) donates through every step (APX601),
    and zero host transfers compile in (APX604) — the engine's only
    per-tick fetch is the explicit (b,) next-token readout.  Built at
    the bf16 O5 surface so APX602 guards the decode path's precision
    regime exactly as it guards training."""
    import jax.numpy as jnp

    from ..serving import (BucketLadder, ServingEngine,
                           ServingModelConfig, default_cache_config,
                           extract_serving_weights)
    from .standalone_gpt import make_smoke_setup

    setup = make_smoke_setup(opt_level="O5", dtype=jnp.bfloat16)
    cfg = ServingModelConfig.from_model(setup.model)
    weights = extract_serving_weights(setup.params, cfg.num_layers)
    cache_cfg = default_cache_config(cfg, num_blocks=8, block_size=4)
    engine = ServingEngine(weights, cfg, cache_cfg,
                           ladder=BucketLadder(batch=(2,), pages=(2,)))
    return engine._jit_decode(), engine._decode_args(2, 2)


def _build_gpt_decode_step_q8():
    """The ISSUE-16 Q8 serving tier: the SAME continuous-batching
    decode step as ``gpt_decode_step`` with the weight pytree
    quantized to per-output-channel int8
    (:func:`apex_tpu.ops.quant_matmul.quantize_weights`).  Built at
    the Q8 policy surface so the compiled-graph audit holds the
    quantized hot path to BOTH precision contracts: APX602 (no
    unsanctioned bf16→f32 activation upcasts, same as O5) and APX606
    (no weight-sized int8→float convert outside the quant kernel
    family — the dequant must stay tile-local, never an HLO-visible
    fp32 weight resident).  Donation and host-transfer guarantees are
    unchanged from the bf16 entry."""
    import jax.numpy as jnp

    from ..ops.quant_matmul import quantize_weights
    from ..serving import (BucketLadder, ServingEngine,
                           ServingModelConfig, default_cache_config,
                           extract_serving_weights)
    from .standalone_gpt import make_smoke_setup

    setup = make_smoke_setup(opt_level="O5", dtype=jnp.bfloat16)
    cfg = ServingModelConfig.from_model(setup.model)
    weights = quantize_weights(
        extract_serving_weights(setup.params, cfg.num_layers))
    cache_cfg = default_cache_config(cfg, num_blocks=8, block_size=4)
    engine = ServingEngine(weights, cfg, cache_cfg,
                           ladder=BucketLadder(batch=(2,), pages=(2,)))
    return engine._jit_decode(), engine._decode_args(2, 2)


def _build_gpt_decode_step_tp():
    """The ISSUE-14 tensor-parallel serving decode step: the SAME
    continuous-batching decode program as ``gpt_decode_step``, shard-
    mapped over a 2-way MeshPlan ``tensor`` axis — heads and ffn
    columns local, the paged KV cache sharded on its head axis, 2
    psums per layer (attention dense + MLP fc2, the Megatron
    forward).  The plan is the runtime's own
    :func:`apex_tpu.serving.tp.serving_tp_plan`, so the SPMD auditor
    (APX701/703/705) guards the serving topology exactly as it
    guards training: a replicated cache shard or an extra all-reduce
    is a CI failure here before it is a TPU bill.  APX601 proves the
    sharded cache still donates end to end; APX604 that zero host
    transfers compile in — the engine's one fetch per tick stays the
    explicit (b,) next-token readout."""
    import jax.numpy as jnp

    from ..serving import (BucketLadder, ServingEngine,
                           ServingModelConfig, TPContext,
                           default_cache_config,
                           extract_serving_weights)
    from .standalone_gpt import make_smoke_setup

    setup = make_smoke_setup(opt_level="O5", dtype=jnp.bfloat16)
    cfg = ServingModelConfig.from_model(setup.model)
    weights = extract_serving_weights(setup.params, cfg.num_layers)
    cache_cfg = default_cache_config(cfg, num_blocks=8, block_size=4)
    tp = TPContext(cfg, cache_cfg, 2)
    engine = ServingEngine(weights, cfg, cache_cfg,
                           ladder=BucketLadder(batch=(2,), pages=(2,)),
                           tp=tp)
    return engine._jit_decode(), engine._decode_args(2, 2)


def _serving_tp_plan():
    """gpt_decode_step_tp's contract = the serving stack's own
    :func:`~apex_tpu.serving.tp.serving_tp_plan` (tp=2 over the
    2-layer smoke GPT, bf16 cache): qkv/fc1 column-split, dense/fc2
    row-split, cache head-axis sharded in AND out, 2 psums per
    layer."""
    from ..serving.tp import serving_tp_plan

    return serving_tp_plan(2, num_layers=2, quantized=False)


def _build_gpt_decode_step_ep():
    """The ISSUE-19 expert-parallel serving decode step: the smoke
    GPT's MLPs expanded to a 4-expert Switch MoE
    (:func:`~apex_tpu.serving.ep.expand_moe_weights`) and the
    continuous-batching decode program shard-mapped over a 2-way
    MeshPlan ``expert`` axis — expert stacks split, attention and the
    paged cache replicated.  Per MoE layer the trace carries the
    fused routing front (:func:`~apex_tpu.ops.moe_routing.
    moe_route_dispatch`), the capacity-chunked OVERLAPPED all_to_all
    exchange (``moe_a2a_chunks=2`` — the schedule APX704 certifies
    quiet on the training entry), and one masked psum replicating the
    combined token slice.  ``moe_capacity_factor=8.0`` keeps the
    per-rank capacity ≥ chunks at the 2-token decode bucket so the
    chunked exchange actually engages.  The plan is the runtime's own
    :func:`~apex_tpu.serving.ep.serving_ep_plan`, so APX701/703/705
    guard the MoE serving topology like training; APX601 proves the
    replicated cache still donates end to end, APX604 that the
    engine's one fetch per tick stays the only host transfer."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from ..serving import (BucketLadder, EPContext, ServingEngine,
                           ServingModelConfig, default_cache_config,
                           expand_moe_weights, extract_serving_weights)
    from .standalone_gpt import make_smoke_setup

    setup = make_smoke_setup(opt_level="O5", dtype=jnp.bfloat16)
    cfg = ServingModelConfig.from_model(setup.model)
    cfg = dataclasses.replace(cfg, num_experts=4,
                              moe_capacity_factor=8.0, moe_a2a_chunks=2)
    weights = expand_moe_weights(
        extract_serving_weights(setup.params, cfg.num_layers), 4,
        jax.random.PRNGKey(0))
    cache_cfg = default_cache_config(cfg, num_blocks=8, block_size=4)
    ep = EPContext(cfg, cache_cfg, 2)
    engine = ServingEngine(weights, cfg, cache_cfg,
                           ladder=BucketLadder(batch=(2,), pages=(2,)),
                           ep=ep)
    return engine._jit_decode(), engine._decode_args(2, 2)


def _serving_ep_plan():
    """gpt_decode_step_ep's contract = the serving stack's own
    :func:`~apex_tpu.serving.ep.serving_ep_plan` (ep=2 over the
    2-layer 4-expert smoke MoE GPT): wi/wo expert-sharded, everything
    else replicated, 2·chunks all_to_all + 1 psum per layer."""
    from ..serving.ep import serving_ep_plan

    return serving_ep_plan(2, num_layers=2, a2a_chunks=2)


def _build_fused_pipeline_step():
    """The PR-4 persistent packed optimizer pipeline as its own entry:
    one full amp post-backward step (pack -> norm/finite sweep ->
    clip/update/cast sweep) with ``pipeline=True`` forced, grads/state/
    model donated — masters and optimizer state live in the packed
    buffers, so a missed donation here doubles the largest allocations
    in the whole step (the APX601 end-to-end requirement)."""
    import functools

    import jax
    import jax.numpy as jnp

    from .. import amp
    from ..optimizers import fused_adam

    params = {
        "w": jnp.linspace(-1.0, 1.0, 4096,
                          dtype=jnp.float32).reshape(32, 128),
        "b": jnp.linspace(0.1, 0.5, 128, dtype=jnp.float32),
        "deep": {"k": jnp.full((16, 128), 0.25, jnp.float32)},
    }
    amp_opt = amp.AmpOptimizer(
        fused_adam(1e-3, weight_decay=0.01, max_grad_norm=1.0),
        amp.get_policy("O5", loss_scale=1024.0), check_finite=True,
        pipeline=True)
    amp_state = amp_opt.init(params)
    model = jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16), params)
    grads = jax.tree_util.tree_map(
        lambda x: (x * 0.001 * 1024.0).astype(jnp.bfloat16), params)

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def post_backward_step(grads, amp_state, model):
        new_model, new_state, info = amp_opt.apply_gradients(
            grads, amp_state, model)
        return new_model, new_state, info.grad_norm

    return post_backward_step, (grads, amp_state, model)


def _build_flash_attention_grad():
    """The flash-attention call site, fwd+bwd: whatever branch is
    legal on this backend (Pallas kernels on TPU, the dispatching
    fallback elsewhere) is exactly what the auditor should see —
    auditing a forced branch would certify a graph production never
    runs."""
    import jax
    import jax.numpy as jnp

    from ..ops.flash_attention import flash_attention

    b, h, s, d = 2, 4, 128, 64
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i),
                                 (b, h, s, d), jnp.bfloat16)
               for i in range(3))

    def loss(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True)
                       .astype(jnp.float32))

    return jax.jit(jax.grad(loss, argnums=(0, 1, 2))), (q, k, v)


register_entry_point(
    "gpt_train_step", _build_gpt_train_step, policy="O2",
    dead_args=(0, 1),
    doc="standalone-GPT smoke train step (O2 fp16, dynamic scaling) — "
        "the step the sanitizer smoke and CI telemetry smoke drive")
register_entry_point(
    "gpt_train_step_o5", _build_gpt_train_step_o5, policy="O5",
    dead_args=(0, 1),
    doc="standalone-GPT train step under the O5 bf16 policy — the "
        "APX602 promotion-audit surface")
register_entry_point(
    "bert_train_step", _build_bert_train_step, policy="O2",
    dead_args=(0, 1),
    doc="standalone-BERT smoke train step (LM + NSP loss)")
register_entry_point(
    "gpt_train_step_deferred", _build_gpt_train_step_deferred,
    policy="O2", dead_args=(0, 1, 2),
    doc="GPT smoke train step with the deferred-telemetry device ring "
        "appended in-jit (monitor.tracing.DeviceMetricsBuffer) — the "
        "static zero-host-transfer proof; params/state/ring donated")
register_entry_point(
    "gpt_train_step_scan", _build_gpt_train_step_scan,
    policy="O2", dead_args=(0, 1, 2),
    doc="K=4 batched-step scan driver (lax.scan over the GPT smoke "
        "train step, telemetry ring appended in-body) — params/amp "
        "state/ring donated through the scan carry; the "
        "dispatch-amortized hot path the smoke drivers run under "
        "--scan-steps / APEX_TPU_SCAN_STEPS")
register_entry_point(
    "gpt_decode_step", _build_gpt_decode_step, policy="O5",
    dead_args=(1,),
    doc="serving-stack continuous-batching decode step (paged KV "
        "write + flash-decode attention + in-graph greedy sampling, "
        "one (batch=2, pages=2) bucket) — the cache carry donated, "
        "zero compiled-in host transfers; what standalone_gpt "
        "--serve runs per tick")
register_entry_point(
    "gpt_decode_step_q8", _build_gpt_decode_step_q8, policy="Q8",
    dead_args=(1,),
    doc="Q8 serving decode step: int8 weight-only matmuls "
        "(ops/quant_matmul) on the same bucketed decode program — "
        "the APX606 dequant-residency audit surface (what "
        "standalone_gpt --serve --policy Q8 runs per tick)")
register_entry_point(
    "fused_pipeline_step", _build_fused_pipeline_step, policy="O5",
    dead_args=(0, 1, 2),
    doc="persistent packed optimizer pipeline post-backward step "
        "(pipeline=True forced), grads/state/model donated")
register_entry_point(
    "flash_attention_grad", _build_flash_attention_grad, policy="O5",
    dead_args=(),
    # the builder's own loss sums in fp32 on purpose (loss math is
    # fp32 under every policy)
    allow_upcast=("apex_tpu/testing/entry_points.py",),
    doc="flash-attention fwd+bwd call site (q/k/v retained by the "
        "caller — no donation expected)")


# ---------------------------------------------------------------------------
# Multichip entries (8-device host-platform mesh): the collective
# census must cover the parallel stack, not just single-chip steps.
# Each carries a MeshPlan — the SPMD auditor compiles it under its mesh
# and checks the partitioner's output against the plan (APX701-705).
# ---------------------------------------------------------------------------

def plan_shardings(plan, mesh, args: tuple):
    """Per-leaf ``NamedSharding`` tree for ``args`` from the plan's
    declared specs, named exactly as the auditor names them (``in0``,
    ``in1['w']``, ``in2.m[0]``): the builder's ``in_shardings`` and the
    audit read the SAME contract, so a builder that stops consulting
    the plan becomes an APX701/703 finding, not a silent regression."""
    import jax
    from jax.sharding import NamedSharding

    def leaf(prefix):
        def f(path, _):
            name = prefix + jax.tree_util.keystr(path)
            return NamedSharding(mesh, plan.partition_spec(name))

        return f

    return tuple(
        jax.tree_util.tree_map_with_path(leaf(f"in{i}"), a)
        for i, a in enumerate(args))


def _build_dp8_train_step():
    """Pure data-parallel GPT loss step over an 8-way mesh: pmean of
    the loss inside shard_map, gradient psum from boundary
    transposition (replicated params sum their cotangents) — the
    collectives every DP run emits."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..optimizers import fused_adam
    from .standalone_gpt import GPTModel, gpt_loss

    vocab, hidden, heads, layers, seq = 64, 32, 4, 2, 16
    batch = 16  # 2 per device
    model = GPTModel(
        vocab_size=vocab, hidden_size=hidden, num_layers=layers,
        num_attention_heads=heads, max_sequence_length=seq,
        attention_dropout=0.0, hidden_dropout=0.0, use_flash=False,
        dtype=jnp.float32)
    key = jax.random.PRNGKey(0)
    tokens = jax.random.randint(jax.random.fold_in(key, 1),
                                (batch, seq), 0, vocab)
    labels = jnp.roll(tokens, -1, -1)
    params = jax.jit(model.init)(key, tokens[:2])["params"]
    tx = fused_adam(1e-3)
    opt_state = jax.jit(tx.init)(params)
    plan = _dp8_plan()
    mesh = plan.make_mesh()

    def loss_fn(p, t, l):
        def shard(p, t, l):
            loss = gpt_loss(model.apply({"params": p}, t), l)
            return jax.lax.pmean(loss, "data")

        return shard_map(shard, mesh=mesh,
                         in_specs=(P(), P("data"), P("data")),
                         out_specs=P(), check_vma=False)(p, t, l)

    args = (params, opt_state, tokens, labels)

    @functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=plan_shardings(plan, mesh, args))
    def train_step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens,
                                                  labels)
        updates, new_opt = tx.update(grads, opt_state, params)
        import optax

        return optax.apply_updates(params, updates), new_opt, loss

    return train_step, args


def _build_zero_dp8_update_step():
    """ZeRO-style sharded update over 8 devices: grads psum_scatter'd
    (each device reduces+keeps 1/8th), the shard updated locally, the
    updated shard all_gather'd back into replicated params — the
    reduce_scatter + all_gather pair the census must price."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map

    n = 8
    dim = 1024  # divisible by 8
    key = jax.random.PRNGKey(0)
    params = jax.random.normal(key, (dim, 64), jnp.float32)
    grads = params * 1e-3
    plan = _zero_update_plan()
    mesh = plan.make_mesh()

    def update(p, g):
        def shard(p, g):
            # g arrives FULL (replicated, as from a DP backward);
            # reduce+shard it, step the local shard, regather.
            g_shard = jax.lax.psum_scatter(g, "zero",
                                           scatter_dimension=0,
                                           tiled=True)
            i = jax.lax.axis_index("zero")
            rows = p.shape[0] // n
            p_shard = jax.lax.dynamic_slice_in_dim(p, i * rows, rows, 0)
            p_new = p_shard - 0.1 * g_shard
            return jax.lax.all_gather(p_new, "zero", axis=0,
                                      tiled=True)

        return shard_map(shard, mesh=mesh, in_specs=(P(), P()),
                         out_specs=P(), check_vma=False)(p, g)

    args = (params, grads)
    return (functools.partial(
        jax.jit, donate_argnums=(0,),
        in_shardings=plan_shardings(plan, mesh, args))(update), args)


def _build_zero_dp8_adam_step():
    """The REAL ZeRO optimizer over 8 devices with its persistent
    state crossing the jit boundary: DistributedFusedAdam's m/v flat
    buffers live sharded 1/8 over the ``zero`` axis (the memory saving
    that IS ZeRO), enter and leave the step as ``P('zero')`` globals,
    and the in/out specs derive from :func:`zero_adam_plan` — the same
    object the SPMD auditor checks.  A builder change that stops
    consulting the plan (the bench-driver bug this PR fixed carried
    the state as ``P()``) makes the state replicated and fires
    APX701 here instead of surfacing as a TPU bill."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..contrib.optimizers import distributed_fused_adam

    plan = _zero_adam_entry_plan()
    mesh = plan.make_mesh()
    key = jax.random.PRNGKey(3)
    params = {"w": jax.random.normal(key, (512, 16), jnp.float32),
              "b": jnp.zeros((16,), jnp.float32)}
    grads = jax.tree_util.tree_map(lambda x: x * 1e-3 + 1e-4, params)
    tx = distributed_fused_adam(1e-2, axis_name="zero",
                                use_pallas=False)

    def state_specs(state):
        return jax.tree_util.tree_map_with_path(
            lambda path, _: plan.partition_spec(
                "state" + jax.tree_util.keystr(path)), state)

    # init must run inside shard_map (shard sizes read the axis size);
    # learn the state's tree structure first (out_specs P() never
    # executes under eval_shape), then stitch the per-device shards
    # into P('zero') globals with the plan's real per-leaf specs
    shapes = jax.eval_shape(
        lambda p: shard_map(tx.init, mesh=mesh, in_specs=P(),
                            out_specs=P(), check_vma=False)(p),
        params)
    state = shard_map(tx.init, mesh=mesh, in_specs=P(),
                      out_specs=state_specs(shapes),
                      check_vma=False)(params)

    def step(params, state, grads):
        def shard(p, s, g):
            updates, s2 = tx.update(g, s, p)
            return optax.apply_updates(p, updates), s2

        return shard_map(
            shard, mesh=mesh,
            in_specs=(P(), state_specs(state), P()),
            out_specs=(P(), state_specs(state)),
            check_vma=False)(params, state, grads)

    args = (params, state, grads)
    return (functools.partial(
        jax.jit, donate_argnums=(0, 1),
        in_shardings=plan_shardings(plan, mesh, args))(step), args)


def _build_moe_ep8_train_step():
    """Top-2 (GShard) expert-parallel MoE train step over an 8-way
    ``expert`` mesh: the layer's OWN :meth:`ExpertParallelMLP.
    mesh_plan` supplies the axes, the wi/wo-sharded + router-replicated
    specs, and the all_to_all budget (2 hops per capacity chunk of the
    overlapped exchange forward, their transposes backward) the census
    is held to."""
    import functools

    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .._compat import shard_map
    from ..transformer.expert_parallel import ExpertParallelMLP

    n = 8
    layer = ExpertParallelMLP(hidden_size=16, ffn_hidden_size=32,
                              num_experts=n, capacity_factor=4.0,
                              router="top2")
    plan = _moe_ep8_plan()
    mesh = plan.make_mesh()
    params = layer.init(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (16 * n, 16),
                          jnp.float32) * 0.5

    def loss_fn(p, x):
        def f(p, x):
            y, aux = layer.apply(p, x)
            return jax.lax.psum(jnp.sum(y ** 2) + 0.01 * aux,
                                "expert")

        return shard_map(
            f, mesh=mesh,
            in_specs=({"router": P(), "wi": P("expert"),
                       "wo": P("expert")}, P("expert")),
            out_specs=P(), check_vma=False)(p, x)

    args = (params, x)
    return (functools.partial(
        jax.jit,
        in_shardings=plan_shardings(plan, mesh, args))(
            jax.value_and_grad(loss_fn)), args)


def _dp8_plan():
    """gpt_dp8_train_step's contract: one data axis, batch sharded,
    params/opt-state replicated (plain DP — ZeRO is the other entry),
    and the DP collective pair: ONE loss pmean + ONE fused gradient
    psum from the boundary transposition."""
    from ..mesh_plan import MeshPlan

    return MeshPlan.build(
        axes=(("data", 8, "data"),),
        tensor_specs={
            r"^in[23]$": ("data",),     # tokens / labels, batch dim
            r"^in[01]": (),             # params + adam state: replicated
        },
        # 1 loss pmean + one psum per replicated param leaf from the
        # boundary transposition (the UNFUSED per-leaf grad sync —
        # fusing it into one tree-psum is the budget cut ROADMAP item
        # 3 can bank, and this number is where it would show)
        collective_budget={"psum": 30})


def _zero_update_plan():
    """zero_dp8_update_step's contract: one zero-kind axis; params and
    grads replicated at the boundary (the entry models the update
    glue, not persistent state — zero_dp8_adam_step audits that); one
    reduce_scatter + one all_gather per step."""
    from ..mesh_plan import MeshPlan

    return MeshPlan.build(
        axes=(("zero", 8, "zero"),),
        tensor_specs={r"^in[01]$": (), r"^out0$": ()},
        collective_budget={"reduce_scatter": 1, "all_gather": 1})


def _zero_adam_entry_plan():
    """zero_dp8_adam_step's contract = the OPTIMIZER's own plan
    (:func:`~apex_tpu.contrib.optimizers.zero_adam_plan`: m/v sharded
    1/8 over the zero axis, count replicated, one reduce_scatter + one
    all_gather per dtype group) specialized with the entry's
    replicated params/grads boundary."""
    from ..contrib.optimizers import zero_adam_plan

    return zero_adam_plan(8, axis_name="zero").with_specs(
        {r"^in[02]": (), r"^out0$": ()})


def _moe_ep8_plan():
    """moe_ep8_train_step's contract = the LAYER's own
    :meth:`ExpertParallelMLP.mesh_plan` (wi/wo expert-sharded, router
    replicated, 2 all_to_all per capacity chunk with the backward —
    8 at the default ``APEX_TPU_MOE_A2A_CHUNKS=2``) specialized with
    the entry's token sharding and its loss/grad psum pair."""
    from ..transformer.expert_parallel import ExpertParallelMLP

    layer = ExpertParallelMLP(hidden_size=16, ffn_hidden_size=32,
                              num_experts=8, capacity_factor=4.0,
                              router="top2")
    # psum: the forward loss psum + its per-operand backward partials
    # as this jax transposes them (measured 7 on the pre-vma stack
    # with the fused routing front)
    return layer.mesh_plan(8).with_specs(
        {r"^in1$": ("expert",)}, budget={"psum": 7})


register_entry_point(
    "gpt_dp8_train_step", _build_dp8_train_step, policy="O0",
    dead_args=(0, 1), min_devices=8, plan=_dp8_plan,
    doc="8-way data-parallel GPT train step (pmean loss, psum grad "
        "sync from boundary transposition)")
register_entry_point(
    "zero_dp8_update_step", _build_zero_dp8_update_step, policy="O0",
    dead_args=(0,), min_devices=8, plan=_zero_update_plan,
    doc="ZeRO-sharded update: psum_scatter grads -> local shard "
        "update -> all_gather params")
register_entry_point(
    "zero_dp8_adam_step", _build_zero_dp8_adam_step, policy="O0",
    dead_args=(0, 1), min_devices=8, plan=_zero_adam_entry_plan,
    doc="DistributedFusedAdam ZeRO step with the sharded m/v state "
        "crossing the jit boundary as P('zero') globals — specs "
        "derived from zero_adam_plan, the APX701 guard surface")
register_entry_point(
    "moe_ep8_train_step", _build_moe_ep8_train_step, policy="O0",
    dead_args=(), min_devices=8, plan=_moe_ep8_plan,
    doc="top-2 GShard MoE train step over expert=8 — the layer's own "
        "mesh_plan supplies specs and the all_to_all budget")
register_entry_point(
    "gpt_decode_step_tp", _build_gpt_decode_step_tp, policy="O5",
    dead_args=(1,), min_devices=2, plan=_serving_tp_plan,
    doc="tensor-parallel serving decode step (tp=2): head-sharded "
        "paged attention + column/row-split MLP under shard_map, "
        "2 psums per layer, cache donated through the sharded carry "
        "— the serving topology audited like training "
        "(what --serve-fleet --tp runs per tick)")
register_entry_point(
    "gpt_decode_step_ep", _build_gpt_decode_step_ep, policy="O5",
    dead_args=(1,), min_devices=2, plan=_serving_ep_plan,
    # the MoE combine accumulates gate-weighted expert outputs in
    # fp32 on purpose (router probabilities are fp32, and a bf16 sum
    # across chunks/experts would break the bit-exact single-buffer
    # equivalence the routing tests pin) — same sanctioned class as
    # the softmax/layer-norm statistics
    allow_upcast=("apex_tpu/transformer/expert_parallel.py",
                  "apex_tpu/ops/moe_routing.py"),
    doc="expert-parallel MoE serving decode step (ep=2, 4 experts): "
        "fused top-1 routing + capacity-chunked overlapped "
        "all_to_all exchange + one masked psum per layer under "
        "shard_map, expert stacks sharded and attention/cache "
        "replicated, cache donated through the carry — the ISSUE-19 "
        "MoE decode fast path audited like training "
        "(what --serve --ep runs per tick)")


# ---------------------------------------------------------------------------
# AOT warmup: pre-compile the registry (ISSUE-8 tentpole c)
# ---------------------------------------------------------------------------

def aot_warmup(names=None, *, configure_cache: bool = True):
    """``jit(...).lower().compile()`` every (buildable) registry entry
    point ahead of time — no execution, just the compile.  With the
    persistent compilation cache configured
    (``APEX_TPU_COMPILE_CACHE_DIR``; wired here unless
    ``configure_cache=False``), one warmup run per host populates the
    on-disk cache and every later process — smoke drivers, bench
    sections, tests — warm-starts its compiles from it, so cold-start
    and retrace cost stop polluting wall measurements.

    ``names`` restricts to specific entries (unknown names raise,
    naming the registry — a typo must not produce a do-nothing warmup
    that claims success); entries this host cannot build (device-count
    gate) are skipped and reported as None.  Returns
    ``{name: compile_ms | None}``.
    """
    import time

    from ..utils.compile_cache import configure_compile_cache

    if configure_cache:
        configure_compile_cache()
    if names is not None:
        unknown = sorted(set(names) - set(ENTRY_POINTS))
        if unknown:
            raise KeyError(
                f"unknown entry point(s) {unknown}; registered: "
                f"{sorted(ENTRY_POINTS)}")
    avail = available_entry_points()
    out = {}
    for name in sorted(names if names is not None else avail):
        ep = avail.get(name)
        if ep is None:
            out[name] = None  # device-count gated on this host
            continue
        fn, args = ep.build()
        t0 = time.perf_counter()
        fn.lower(*args).compile()
        out[name] = round((time.perf_counter() - t0) * 1e3, 1)
    return out


def _main(argv=None):
    """CLI: ``python -m apex_tpu.testing.entry_points --aot`` —
    pre-compile the registry into the persistent cache (tools/ci.sh
    step 10 proves the second process warm-starts from it)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="python -m apex_tpu.testing.entry_points",
        description="Registry of lowerable entry points; --aot "
                    "pre-compiles them (persistent cache per "
                    "APEX_TPU_COMPILE_CACHE_DIR).")
    ap.add_argument("--aot", action="store_true",
                    help="lower+compile every buildable entry point")
    ap.add_argument("--entry", action="append", default=None,
                    help="restrict to this entry (repeatable)")
    ap.add_argument("--expect-cache-hits", action="store_true",
                    help="fail (exit 1) unless at least one compile "
                         "was served from the persistent cache — the "
                         "second-process warm-start proof")
    args = ap.parse_args(argv)
    if not args.aot:
        for name, ep in sorted(ENTRY_POINTS.items()):
            print(f"{name}: {ep.doc}")
        return 0
    hits = []
    if args.expect_cache_hits:
        # jax logs "Persistent compilation cache hit for '<name>'"
        # through jax._src.compiler when jax_log_compiles is on;
        # capturing it is the ground truth that the compile was read
        # from disk rather than redone.  The flag also makes the
        # dispatch/pxla loggers chatty — keep the capture out of the
        # console (the sanitizer's discipline): capture-only handler,
        # propagation off, NullHandlers so logging.lastResort stays
        # quiet.
        import logging
        import re

        import jax

        class _Hits(logging.Handler):
            def emit(self, record):
                m = re.search(r"Persistent compilation cache hit",
                              record.getMessage())
                if m:
                    hits.append(record.getMessage())

        lg = logging.getLogger("jax._src.compiler")
        lg.addHandler(_Hits())
        if lg.level > logging.DEBUG:
            lg.setLevel(logging.DEBUG)
        for name in ("jax._src.compiler", "jax._src.dispatch",
                     "jax._src.interpreters.pxla"):
            noisy = logging.getLogger(name)
            noisy.addHandler(logging.NullHandler())
            noisy.propagate = False
        jax.config.update("jax_log_compiles", True)
    res = aot_warmup(args.entry)
    for name, ms in res.items():
        state = "SKIPPED (device count)" if ms is None else f"{ms} ms"
        print(f"[aot] {name}: {state}")
    compiled = [ms for ms in res.values() if ms is not None]
    print(f"[aot] {len(compiled)} entry point(s) compiled, "
          f"{sum(compiled):.0f} ms total"
          + (f", {len(hits)} persistent-cache hit(s)"
             if args.expect_cache_hits else ""))
    if args.expect_cache_hits and not hits:
        print("[aot] FAIL: no persistent-cache hits — the warmup did "
              "not warm-start (is APEX_TPU_COMPILE_CACHE_DIR set and "
              "pre-populated?)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(_main())
