"""apex_tpu.testing — test/bench harness (ref: apex/transformer/testing)."""
from .timing import bench_chained

__all__ = ["bench_chained"]
