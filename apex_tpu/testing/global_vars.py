"""Megatron-style global singletons for the test stack.

Parity surface for ``apex/transformer/testing/global_vars.py:26-190``:
``set_global_variables`` parses args and builds the microbatch
calculator / tensorboard writer / timers singletons; ``get_args`` /
``get_num_microbatches`` / ``get_timers`` etc. read them.  The timers and
microbatch calculator are the ones the pipeline stack already owns
(:mod:`apex_tpu.transformer.pipeline_parallel.utils`), so state is never
duplicated.
"""
from __future__ import annotations

from typing import Optional

from ..transformer.pipeline_parallel import utils as _pp_utils
from .arguments import parse_args as _parse_args_impl

_GLOBAL_ARGS = None
_GLOBAL_TENSORBOARD_WRITER = None
_GLOBAL_ADLR_AUTORESUME = None


def _ensure_var_is_initialized(var, name):
    assert var is not None, f"{name} is not initialized."


def _ensure_var_is_not_initialized(var, name):
    assert var is None, f"{name} is already initialized."


def get_args():
    """ref: global_vars.py:34-37."""
    _ensure_var_is_initialized(_GLOBAL_ARGS, "args")
    return _GLOBAL_ARGS


def get_num_microbatches() -> int:
    return _pp_utils.get_num_microbatches()


def get_current_global_batch_size() -> int:
    return _pp_utils.get_current_global_batch_size()


def update_num_microbatches(consumed_samples: int, *,
                            consistency_check: bool = True) -> None:
    _pp_utils.update_num_microbatches(consumed_samples,
                                      consistency_check)


def get_tensorboard_writer():
    """ref: global_vars.py:69-72 (may be None)."""
    return _GLOBAL_TENSORBOARD_WRITER


def get_adlr_autoresume():
    return _GLOBAL_ADLR_AUTORESUME


def get_timers():
    return _pp_utils.get_timers()


def set_global_variables(extra_args_provider=None, args_defaults=None,
                         ignore_unknown_args=False, args=None):
    """Parse args + build singletons (ref: global_vars.py:87-99)."""
    global _GLOBAL_ARGS
    _ensure_var_is_not_initialized(_GLOBAL_ARGS, "args")
    _GLOBAL_ARGS = _parse_args_impl(
        extra_args_provider=extra_args_provider,
        defaults=args_defaults or {},
        ignore_unknown_args=ignore_unknown_args, args=args)
    _build_num_microbatches_calculator(_GLOBAL_ARGS)
    if _GLOBAL_ARGS.tensorboard_dir is not None:
        _set_tensorboard_writer(_GLOBAL_ARGS)
    return _GLOBAL_ARGS


def _build_num_microbatches_calculator(args):
    """ref: global_vars.py:112-120."""
    if args.global_batch_size is None or args.micro_batch_size is None:
        return
    _pp_utils.setup_microbatch_calculator(
        rank=0,
        rampup_batch_size=args.rampup_batch_size,
        global_batch_size=args.global_batch_size,
        micro_batch_size=args.micro_batch_size,
        data_parallel_size=args.data_parallel_size)


def _set_tensorboard_writer(args):
    """ref: global_vars.py:136-154 — best-effort import."""
    global _GLOBAL_TENSORBOARD_WRITER
    _ensure_var_is_not_initialized(_GLOBAL_TENSORBOARD_WRITER,
                                   "tensorboard writer")
    try:
        from torch.utils.tensorboard import SummaryWriter

        _GLOBAL_TENSORBOARD_WRITER = SummaryWriter(
            log_dir=args.tensorboard_dir)
    except Exception as e:
        from ..utils.log_util import get_logger

        get_logger(__name__).warning(
            "TensorBoard writing requested but unavailable (%s); no "
            "TensorBoard logs will be written.", str(e)[:120])


def destroy_global_vars():
    """Testing hook: reset the singletons (the reference relies on
    process exit; tests here share a process)."""
    global _GLOBAL_ARGS, _GLOBAL_TENSORBOARD_WRITER
    _GLOBAL_ARGS = None
    _GLOBAL_TENSORBOARD_WRITER = None
