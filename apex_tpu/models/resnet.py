"""ResNet family (v1.5) — the reference's canonical amp workload.

The reference drives torchvision's ResNet-50 through amp + DDP + SyncBN
(ref: examples/imagenet/main_amp.py); this is the equivalent flax model,
channels-last (native TPU layout), with an injectable ``norm_factory``
so ``apex_tpu.parallel.convert_syncbn_model`` can swap synchronized
batch norm in at construction (the reference converts the module tree,
ref: apex/parallel/__init__.py:42-95).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp


def _default_norm(num_features: int, **kw):
    # Local (non-synchronized) batch norm in fp32.
    from ..parallel.sync_batchnorm import SyncBatchNorm
    kw.setdefault("axis_name", None)
    return SyncBatchNorm(num_features=num_features, **kw)


class Bottleneck(nn.Module):
    """ResNet v1.5 bottleneck: stride lives in the 3x3 conv."""

    features: int
    stride: int = 1
    downsample: bool = False
    norm_factory: Callable = _default_norm
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.features, (1, 1), name="conv1")(x)
        y = self.norm_factory(self.features)(
            y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.features, (3, 3), strides=(self.stride, self.stride),
                 name="conv2")(y)
        y = self.norm_factory(self.features)(
            y, use_running_average=not train)
        y = nn.relu(y)
        y = conv(self.features * 4, (1, 1), name="conv3")(y)
        y = self.norm_factory(self.features * 4)(
            y, use_running_average=not train)
        if self.downsample:
            residual = conv(self.features * 4, (1, 1),
                            strides=(self.stride, self.stride),
                            name="downsample_conv")(x)
            residual = self.norm_factory(self.features * 4)(
                residual, use_running_average=not train)
        return nn.relu(y + residual.astype(y.dtype))


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    width: int = 64
    norm_factory: Callable = _default_norm
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        y = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=self.dtype, name="conv1")(x)
        y = self.norm_factory(self.width)(y, use_running_average=not train)
        y = nn.relu(y)
        y = nn.max_pool(y, (3, 3), strides=(2, 2), padding="SAME")
        for stage, n_blocks in enumerate(self.stage_sizes):
            features = self.width * (2 ** stage)
            for block in range(n_blocks):
                stride = 2 if stage > 0 and block == 0 else 1
                y = Bottleneck(features=features, stride=stride,
                               downsample=(block == 0),
                               norm_factory=self.norm_factory,
                               dtype=self.dtype,
                               name=f"stage{stage + 1}_block{block}")(
                    y, train=train)
        y = jnp.mean(y, axis=(1, 2))
        # Classifier head in fp32 for a stable loss.
        y = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="fc")(y.astype(jnp.float32))
        return y


def ResNet50(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), **kw)


def ResNet101(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 23, 3), **kw)


def ResNet152(**kw) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), **kw)
