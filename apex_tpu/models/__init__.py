"""Model zoo: the example/benchmark architectures.

ResNet variants live here (the imagenet driver + headline bench);
transformer families (GPT, BERT) live in :mod:`apex_tpu.testing`
mirroring the reference's placement of its standalone models under
``apex/transformer/testing``.
"""
from .resnet import ResNet, ResNet50, ResNet101, ResNet152

__all__ = ["ResNet", "ResNet50", "ResNet101", "ResNet152"]
