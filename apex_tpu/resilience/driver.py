"""Retrying run driver: bounded restarts with backoff around a train fn.

``run_resumable`` is the outermost loop of a fault-tolerant run: each
attempt is expected to *resume itself* from the latest valid checkpoint
(``CheckpointManager.restore`` falls back past corrupt steps on its
own), so the driver's only jobs are bounded retry, exponential backoff
with deterministic jitter, and a structured ``resilience`` event trail
(``attempt_start`` / ``attempt_error`` / ``attempt_backoff`` /
``attempt_done`` / ``run_giveup``) so a post-mortem can reconstruct the
restart history from the same JSONL as everything else.

Deliberate non-goals: no in-driver checkpointing (the loop owns state),
no retry of ``KeyboardInterrupt``/``SystemExit`` (BaseException never
matches the default ``retry_on=(Exception,)``), and no retry once an
:class:`~apex_tpu.resilience.autoresume.AutoResume` says the scheduler
wants the slot back — preemption is not a failure.
"""
from __future__ import annotations

import random
import time
import traceback
from typing import Callable, Optional, Tuple, Type

DEFAULT_MAX_RESTARTS = 3
DEFAULT_BACKOFF_BASE_S = 1.0
DEFAULT_BACKOFF_MAX_S = 60.0
DEFAULT_JITTER = 0.25


class GiveUp(RuntimeError):
    """All restart budget spent; ``__cause__`` is the last failure."""

    def __init__(self, attempts: int, last_error: BaseException):
        super().__init__(
            f"giving up after {attempts} attempt(s); last error: "
            f"{type(last_error).__name__}: {str(last_error)[:200]}")
        self.attempts = attempts
        self.last_error = last_error


def backoff_delay(attempt: int, *, base: float = DEFAULT_BACKOFF_BASE_S,
                  maximum: float = DEFAULT_BACKOFF_MAX_S,
                  jitter: float = DEFAULT_JITTER,
                  rng: Optional[random.Random] = None) -> float:
    """Capped exponential backoff with multiplicative jitter in
    ``[0, jitter]`` — jitter decorrelates a fleet of preempted workers
    restarting in lockstep.  Deterministic given ``rng``."""
    delay = min(float(maximum), float(base) * (2.0 ** attempt))
    if jitter and rng is not None:
        delay *= 1.0 + float(jitter) * rng.random()
    return min(delay, float(maximum))


def run_resumable(train_fn: Callable[[int], object], *,
                  max_restarts: int = DEFAULT_MAX_RESTARTS,
                  backoff_base: float = DEFAULT_BACKOFF_BASE_S,
                  backoff_max: float = DEFAULT_BACKOFF_MAX_S,
                  jitter: float = DEFAULT_JITTER,
                  retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                  no_retry_on: Tuple[Type[BaseException], ...] = (),
                  autoresume=None, sink=None,
                  sleep: Callable[[float], None] = time.sleep,
                  rng: Optional[random.Random] = None,
                  wall_clock=time.time):
    """Run ``train_fn(attempt)`` with bounded restarts; return its result.

    ``train_fn`` receives the 0-based attempt index and must itself
    resume from the latest valid checkpoint (pass the same checkpoint
    directory in via closure).  On a ``retry_on`` failure the driver
    backs off (``backoff_delay``) and retries, up to ``max_restarts``
    *re*-starts (i.e. at most ``max_restarts + 1`` attempts), then
    raises :class:`GiveUp` from the last error.  ``no_retry_on`` wins
    over ``retry_on``.  With ``autoresume``, a failure that races a
    termination request is not retried (the scheduler is taking the
    slot; exit now, resume on the next incarnation).

    ``sleep`` and ``rng`` are injectable for deterministic tests.  The
    default rng is seeded per process (urandom) — a shared fixed seed
    would give every worker in a preempted fleet the *same* jitter,
    defeating the decorrelation the jitter exists for.
    """
    rng = random.Random() if rng is None else rng

    def emit(name, value=None, **attrs):
        from ..monitor.events import emit_resilience

        emit_resilience(sink, name, value=value, clock=wall_clock,
                        **attrs)

    attempt = 0
    while True:
        emit("attempt_start", value=attempt,
             max_restarts=int(max_restarts))
        try:
            result = train_fn(attempt)
        except no_retry_on:
            emit("run_giveup", value=attempt, reason="no_retry")
            raise
        except retry_on as e:
            tb = traceback.format_exc(limit=8)
            emit("attempt_error", value=attempt,
                 error=type(e).__name__, message=str(e)[:300],
                 traceback=tb[-1200:])
            if autoresume is not None and \
                    autoresume.termination_requested():
                emit("run_giveup", value=attempt, reason="preempted")
                raise
            if attempt >= max_restarts:
                emit("run_giveup", value=attempt,
                     reason="budget_exhausted",
                     attempts=attempt + 1)
                raise GiveUp(attempt + 1, e) from e
            delay = backoff_delay(attempt, base=backoff_base,
                                  maximum=backoff_max, jitter=jitter,
                                  rng=rng)
            emit("attempt_backoff", value=delay, attempt=attempt)
            sleep(delay)
            attempt += 1
        else:
            emit("attempt_done", value=attempt, attempts=attempt + 1)
            return result
