"""Watchdog-alarm escalation: turn alarms into restartable aborts.

PR 2's :class:`apex_tpu.monitor.Watchdog` raises once-per-episode
``alarm`` events (``nonfinite_loss``, ``overflow_streak``, ``stall``)
— eyes only.  :class:`EscalationPolicy` is the hands: plugged into the
watchdog's ``on_alarm`` callback, it latches the first alarm whose
configured action is not ``ignore``; the training loop polls
:meth:`pending` at step boundaries and raises :class:`EscalationAbort`
(after an optional synchronous checkpoint) so
:func:`apex_tpu.resilience.run_resumable` can restart the attempt from
the last valid checkpoint.

Default policy (rationale in docs/api/resilience.md):

=================  =====================  =================================
alarm              action                 why
=================  =====================  =================================
nonfinite_loss     abort                  params may already be poisoned —
                                          restart from the last *good*
                                          checkpoint, don't save this one
overflow_streak    checkpoint_then_abort  a collapsing scaler skipped the
                                          updates, params are sound — keep
                                          recency, then restart
stall              ignore                 fires on the heartbeat thread
                                          while the main thread is wedged
                                          in a device call; an abort flag
                                          would never be polled
=================  =====================  =================================

Alarms not named in the policy (``*_recovered``, trace markers) are
ignored.  ``notify`` may run on the watchdog heartbeat thread, so it
only latches state — the loop emits the ``resilience`` events and does
the checkpointing from the main thread.
"""
from __future__ import annotations

import threading
from typing import Dict, NamedTuple, Optional

IGNORE = "ignore"
ABORT = "abort"
CHECKPOINT_THEN_ABORT = "checkpoint_then_abort"
#: Serving-side action (ISSUE-13): dump ONE structured engine
#: snapshot, then drain the serve cleanly — blocks freed, every
#: request terminal ``preempted``, summary returned.  The serve
#: answer to ``stall``: unlike a training step, a serve can end
#: usefully without a checkpoint, so a wedged decode should never be
#: ``ignore``\ d — it should leave a post-mortem and stop honestly.
SNAPSHOT_THEN_DRAIN = "snapshot_then_drain"

ACTIONS = (IGNORE, ABORT, CHECKPOINT_THEN_ABORT, SNAPSHOT_THEN_DRAIN)

DEFAULT_POLICY: Dict[str, str] = {
    "nonfinite_loss": ABORT,
    "overflow_streak": CHECKPOINT_THEN_ABORT,
    "stall": IGNORE,
}

#: The serving default (:func:`serve_policy`): the stall rationale
#: flips — the serve loop's heartbeat fires off-thread while decode is
#: wedged, and once the tick boundary is reached again the engine CAN
#: act: snapshot the live state, then drain.  Training alarms that
#: cannot occur in a serve (no loss, no scaler) are left ignored.
DEFAULT_SERVE_POLICY: Dict[str, str] = {
    "stall": SNAPSHOT_THEN_DRAIN,
}


def serve_policy(policy: Optional[Dict[str, str]] = None
                 ) -> "EscalationPolicy":
    """An :class:`EscalationPolicy` with the serving defaults
    (``stall`` → ``snapshot_then_drain``; the training alarms —
    nonfinite loss, overflow streaks — cannot occur on the serve path
    and stay ignored); ``policy`` overrides merge on top.  Plug into
    ``Watchdog(on_alarm=...)`` and hand the same object to
    :class:`~apex_tpu.serving.ServingEngine`, which polls it at tick
    boundaries."""
    return EscalationPolicy(policy, defaults=DEFAULT_SERVE_POLICY)


class EscalationAbort(RuntimeError):
    """Raised by the training loop when an escalated alarm demands a
    restart; :func:`~apex_tpu.resilience.run_resumable` treats it like
    any other retryable failure."""

    def __init__(self, alarm: str, action: str,
                 step: Optional[int] = None):
        super().__init__(f"watchdog alarm {alarm!r} escalated to "
                         f"{action} at step {step}")
        self.alarm = alarm
        self.action = action
        self.step = step


class Escalation(NamedTuple):
    alarm: str
    action: str
    step: Optional[int]


class EscalationPolicy:
    """Maps watchdog alarm names to actions; latches the first hit.

    Use as ``Watchdog(..., on_alarm=policy.notify)``.  Overrides merge
    over :data:`DEFAULT_POLICY`; an explicit ``ignore`` disables a
    default escalation.
    """

    def __init__(self, policy: Optional[Dict[str, str]] = None, *,
                 defaults: Optional[Dict[str, str]] = None):
        self.policy = dict(DEFAULT_POLICY if defaults is None
                           else defaults)
        if policy:
            for name, action in policy.items():
                if action not in ACTIONS:
                    raise ValueError(
                        f"unknown escalation action {action!r} for "
                        f"{name!r}; expected one of {ACTIONS}")
                self.policy[name] = action
        self._lock = threading.Lock()
        self._pending: Optional[Escalation] = None

    def notify(self, event) -> None:
        """Watchdog ``on_alarm`` callback (any thread, never raises):
        latch the first non-ignored alarm of the episode."""
        action = self.policy.get(event.name, IGNORE)
        if action == IGNORE:
            return
        with self._lock:
            if self._pending is None:
                self._pending = Escalation(event.name, action, event.step)

    def pending(self) -> Optional[Escalation]:
        """The latched escalation, if any — poll at step boundaries."""
        with self._lock:
            return self._pending

    def reset(self) -> None:
        """Re-arm (e.g. at the start of a fresh attempt)."""
        with self._lock:
            self._pending = None
