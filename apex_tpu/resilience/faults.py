"""Deterministic fault injection: the proof harness for resilience.

Every recovery claim in this package is tested by *making* the failure
happen, at an exact step, reproducibly:

* :func:`parse_fault` / :class:`FaultInjector` — step-triggered faults
  for a training loop (``--fault`` on the standalone GPT/BERT smoke
  drivers): ``crash@K`` (raise :class:`InjectedCrash`), ``kill@K``
  (SIGKILL — the hard-preemption case, nothing runs after), ``sigterm@K``
  / ``sigint@K`` (graceful preemption through
  :class:`~apex_tpu.resilience.autoresume.AutoResume`), ``nan@K`` /
  ``inf@K`` (non-finite observed loss — drives the watchdog
  ``nonfinite_loss`` alarm and its escalation), ``stall@K:SECS``
  (sleep, for stall-watchdog drills).  Specs compose with commas
  (``"nan@3,crash@5"``); each fires **once** — an injector shared
  across ``run_resumable`` attempts does not re-fail the recovered run.

* checkpoint corruption (:func:`corrupt_checkpoint`) — damage an
  on-disk Orbax step the ways a real preemption does: ``truncate``
  (partial TensorStore flush: every payload file cut in half, structure
  intact — caught only by the restore attempt), ``unfinalize`` (killed
  before the commit marker: ``_CHECKPOINT_METADATA`` removed — caught
  by the structural scan), ``delete`` (a required item payload gone).

All injectors are plain host-side Python: no device, no randomness, no
wall-clock dependence — a fault fires at step K or it does not.
"""
from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

#: Step-triggered fault kinds understood by :func:`parse_fault`.
KINDS = ("crash", "kill", "sigterm", "sigint", "nan", "inf", "stall")


class InjectedFault(RuntimeError):
    """Base class for failures raised by the harness itself."""


class InjectedCrash(InjectedFault):
    """The ``crash@K`` fault: an ordinary retryable exception."""


class _Spec:
    __slots__ = ("kind", "step", "arg", "fired")

    def __init__(self, kind: str, step: int, arg: Optional[float] = None):
        self.kind = kind
        self.step = int(step)
        self.arg = arg
        self.fired = False

    def __repr__(self):
        suffix = "" if self.arg is None else f":{self.arg}"
        return f"{self.kind}@{self.step}{suffix}"


class FaultInjector:
    """Holds parsed fault specs; the loop calls the two hooks below.

    ``before_step(k)`` fires process-level faults (crash/kill/signal/
    stall) at the start of step ``k``; ``observed_loss(k, loss)``
    rewrites the host-visible loss for value faults (nan/inf).  Fired
    specs disarm, so a resumed attempt sails past the step that killed
    its predecessor.
    """

    def __init__(self, specs: List[_Spec]):
        self.specs = list(specs)

    def __repr__(self):
        return f"FaultInjector({','.join(map(repr, self.specs))})"

    def fired(self) -> List[str]:
        return [repr(s) for s in self.specs if s.fired]

    def before_step(self, step: int) -> None:
        for s in self.specs:
            if s.fired or s.step != step:
                continue
            if s.kind == "crash":
                s.fired = True
                raise InjectedCrash(f"injected crash at step {step}")
            if s.kind == "kill":
                s.fired = True
                os.kill(os.getpid(), signal.SIGKILL)  # no return
            if s.kind in ("sigterm", "sigint"):
                s.fired = True
                os.kill(os.getpid(),
                        signal.SIGTERM if s.kind == "sigterm"
                        else signal.SIGINT)
            if s.kind == "stall":
                s.fired = True
                time.sleep(float(s.arg or 1.0))

    def before_window(self, start: int, k: int) -> None:
        """Scan-driver form of :meth:`before_step`: fire every armed
        process-level spec whose step lands anywhere in the K-step
        window ``[start, start + k)``.  Host code only runs at window
        edges under the scan driver, so a fault aimed mid-window fires
        at the nearest preceding boundary — the same edge checkpoints
        and termination polls land on (and the edge a real preemption
        would resume from)."""
        for s in list(self.specs):
            if not s.fired and start <= s.step < start + k:
                self.before_step(s.step)

    def observed_loss(self, step: int, loss: float) -> float:
        for s in self.specs:
            if s.fired or s.step != step:
                continue
            if s.kind == "nan":
                s.fired = True
                return float("nan")
            if s.kind == "inf":
                s.fired = True
                return float("inf")
        return loss


def parse_fault(spec: Optional[str]) -> Optional[FaultInjector]:
    """Parse ``"kind@step[:arg][,kind@step...]"`` into an injector
    (None for empty/None input — the no-fault fast path)."""
    if not spec:
        return None
    out: List[_Spec] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            kind, _, rest = part.partition("@")
            stepstr, _, argstr = rest.partition(":")
            kind = kind.strip().lower()
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            out.append(_Spec(kind, int(stepstr),
                             float(argstr) if argstr else None))
        except ValueError as e:
            raise ValueError(
                f"bad fault spec {part!r} (expected kind@step[:arg] "
                f"with kind in {KINDS}): {e}") from None
    return FaultInjector(out) if out else None


# ---------------------------------------------------------------------------
# Checkpoint corruption
# ---------------------------------------------------------------------------

CORRUPTION_MODES = ("truncate", "unfinalize", "delete")


def corrupt_checkpoint(directory: str, step: Optional[int] = None,
                       mode: str = "truncate") -> int:
    """Deterministically damage one Orbax step dir (default: the newest
    on disk).  Returns the corrupted step number.  See module docstring
    for what each mode simulates."""
    # Share the step-dir scan and commit-marker name with the integrity
    # layer — the corruption this injects must track exactly what that
    # layer checks (lazy import: checkpoint pulls the jax/amp stack).
    from ..utils.checkpoint import _FINALIZE_MARKER, _fs_steps

    if mode not in CORRUPTION_MODES:
        raise ValueError(f"mode {mode!r} not in {CORRUPTION_MODES}")
    steps = _fs_steps(directory)
    if step is None:
        if not steps:
            raise FileNotFoundError(f"no checkpoint steps in {directory}")
        step = steps[-1]
    elif step not in steps:
        raise FileNotFoundError(
            f"checkpoint step {step} not found in {directory}; "
            f"available: {steps}")
    step_dir = os.path.join(directory, str(step))

    if mode == "unfinalize":
        os.remove(os.path.join(step_dir, _FINALIZE_MARKER))
        return step

    payloads = []
    for root, _, files in os.walk(step_dir):
        for name in files:
            if name == _FINALIZE_MARKER:
                continue
            payloads.append(os.path.join(root, name))
    if not payloads:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    if mode == "delete":
        for p in payloads:
            os.remove(p)
    else:  # truncate: halve every payload, as a torn flush would
        for p in payloads:
            with open(p, "r+b") as f:
                f.truncate(os.path.getsize(p) // 2)
    return step
