"""Deterministic fault injection: the proof harness for resilience.

Every recovery claim in this package is tested by *making* the failure
happen, at an exact step, reproducibly:

* :func:`parse_fault` / :class:`FaultInjector` — step-triggered faults
  for a training loop (``--fault`` on the standalone GPT/BERT smoke
  drivers): ``crash@K`` (raise :class:`InjectedCrash`), ``kill@K``
  (SIGKILL — the hard-preemption case, nothing runs after), ``sigterm@K``
  / ``sigint@K`` (graceful preemption through
  :class:`~apex_tpu.resilience.autoresume.AutoResume`), ``nan@K`` /
  ``inf@K`` (non-finite observed loss — drives the watchdog
  ``nonfinite_loss`` alarm and its escalation), ``stall@K:SECS``
  (sleep, for stall-watchdog drills).  Specs compose with commas
  (``"nan@3,crash@5"``); each fires **once** — an injector shared
  across ``run_resumable`` attempts does not re-fail the recovered run.

* checkpoint corruption (:func:`corrupt_checkpoint`) — damage an
  on-disk Orbax step the ways a real preemption does: ``truncate``
  (partial TensorStore flush: every payload file cut in half, structure
  intact — caught only by the restore attempt), ``unfinalize`` (killed
  before the commit marker: ``_CHECKPOINT_METADATA`` removed — caught
  by the structural scan), ``delete`` (a required item payload gone).

All injectors are plain host-side Python: no device, no randomness, no
wall-clock dependence — a fault fires at step K or it does not.
"""
from __future__ import annotations

import os
import signal
import time
from typing import List, Optional

#: Step-triggered fault kinds understood by :func:`parse_fault`.
#: ``reject_alloc`` and ``corrupt_journal`` are SERVE-path injectors
#: (ISSUE-13): ``reject_alloc@K`` makes the engine treat tick K's
#: admissions as pool-exhausted (one tick, once); ``corrupt_journal@K
#: [:mode]`` damages the live request journal at tick K (``truncate``
#: = torn trailing line, ``unfinalize`` = the last terminal record
#: stripped — a request that finished looks in-flight, the
#: at-least-once replay drill).  Both fire through the serve driver's
#: :meth:`FaultInjector.before_tick` / the engine's admission poll,
#: with the same once-semantics as the training kinds.
#: ``kill9`` and ``rpc_timeout`` are PROCESS-fleet injectors
#: (ISSUE-18): ``kill9@K`` SIGKILLs a live replica *subprocess* at
#: its engine tick K (fired child-side through ``before_tick`` —
#: operationally identical to ``kill``, named separately so a fleet
#: spec reads as the drill it is); ``rpc_timeout@K`` drops ONE
#: gauge-poll response at supervisor round K (fired parent-side
#: through :meth:`FaultInjector.drop_rpc` — the supervisor treats the
#: poll as timed out and degrades that replica's router score).
KINDS = ("crash", "kill", "kill9", "sigterm", "sigint", "nan", "inf",
         "stall", "reject_alloc", "corrupt_journal", "rpc_timeout")

#: Kinds the control-plane SUPERVISOR fires (everything else ships to
#: the replica subprocess) — :func:`split_fault` partitions on this.
PARENT_KINDS = ("rpc_timeout",)

#: Kinds that take the hosting process down when they fire.  A replica
#: respawned for journal replay must NOT carry these: the fresh
#: process's tick counter restarts at 0, so the replay would re-reach
#: tick K and re-fire forever (in-memory once-semantics cannot survive
#: a SIGKILL).  The supervisor strips them from the respawn spec —
#: injected faults are once-per-serve by contract, same as a
#: ``run_resumable`` attempt sailing past the step that killed its
#: predecessor.
PROCESS_FATAL_KINDS = ("crash", "kill", "kill9", "sigterm", "sigint")


class InjectedFault(RuntimeError):
    """Base class for failures raised by the harness itself."""


class InjectedCrash(InjectedFault):
    """The ``crash@K`` fault: an ordinary retryable exception."""


class _Spec:
    __slots__ = ("kind", "step", "arg", "fired")

    def __init__(self, kind: str, step: int, arg: Optional[float] = None):
        self.kind = kind
        self.step = int(step)
        self.arg = arg
        self.fired = False

    def __repr__(self):
        suffix = "" if self.arg is None else f":{self.arg}"
        return f"{self.kind}@{self.step}{suffix}"


class FaultInjector:
    """Holds parsed fault specs; the loop calls the two hooks below.

    ``before_step(k)`` fires process-level faults (crash/kill/signal/
    stall) at the start of step ``k``; ``observed_loss(k, loss)``
    rewrites the host-visible loss for value faults (nan/inf).  Fired
    specs disarm, so a resumed attempt sails past the step that killed
    its predecessor.
    """

    def __init__(self, specs: List[_Spec]):
        self.specs = list(specs)

    def __repr__(self):
        return f"FaultInjector({','.join(map(repr, self.specs))})"

    def fired(self) -> List[str]:
        return [repr(s) for s in self.specs if s.fired]

    def before_step(self, step: int) -> None:
        for s in self.specs:
            if s.fired or s.step != step:
                continue
            if s.kind == "crash":
                s.fired = True
                raise InjectedCrash(f"injected crash at step {step}")
            if s.kind in ("kill", "kill9"):
                s.fired = True
                os.kill(os.getpid(), signal.SIGKILL)  # no return
            if s.kind in ("sigterm", "sigint"):
                s.fired = True
                os.kill(os.getpid(),
                        signal.SIGTERM if s.kind == "sigterm"
                        else signal.SIGINT)
            if s.kind == "stall":
                s.fired = True
                time.sleep(float(s.arg or 1.0))

    def before_tick(self, tick: int, *,
                    journal_path: Optional[str] = None) -> None:
        """Serve-loop form of :meth:`before_step`: fires the
        process-level kinds (crash/kill/signal/stall) exactly as the
        training hook does, plus ``corrupt_journal`` against the live
        journal at ``journal_path`` (a spec with no journal wired is a
        no-op that still disarms — once-semantics over silent
        re-arming)."""
        for s in self.specs:
            if s.fired or s.step != tick \
                    or s.kind != "corrupt_journal":
                continue
            s.fired = True
            if journal_path is not None:
                corrupt_journal(journal_path,
                                mode=str(s.arg or "truncate"))
        self.before_step(tick)

    def reject_alloc(self, tick: int) -> bool:
        """True exactly once, at the first admission poll AT OR AFTER
        an armed ``reject_alloc@K`` spec's tick — the serving engine
        polls this in its admission path and skips the tick's
        admissions (simulated pool exhaustion).  At-or-after, not
        exact-match: the engine only polls on ticks that would admit,
        so a drain/shed tick landing exactly on K must defer the
        fault to the next admitting tick instead of leaving the spec
        armed-but-dead forever."""
        for s in self.specs:
            if not s.fired and tick >= s.step \
                    and s.kind == "reject_alloc":
                s.fired = True
                return True
        return False

    def drop_rpc(self, tick: int) -> bool:
        """True exactly once, at the first gauge poll AT OR AFTER an
        armed ``rpc_timeout@K`` spec's tick — the process-fleet
        supervisor polls this before each replica's snapshot RPC and,
        when it fires, treats that one response as dropped (stale
        snapshot + router-score penalty, never a blocked tick).
        At-or-after for the same reason as :meth:`reject_alloc`: the
        supervisor only polls replicas that are up, so a spec landing
        on a round spent restarting must defer to the next poll
        instead of staying armed-but-dead forever."""
        for s in self.specs:
            if not s.fired and tick >= s.step \
                    and s.kind == "rpc_timeout":
                s.fired = True
                return True
        return False

    def before_window(self, start: int, k: int) -> None:
        """Scan-driver form of :meth:`before_step`: fire every armed
        process-level spec whose step lands anywhere in the K-step
        window ``[start, start + k)``.  Host code only runs at window
        edges under the scan driver, so a fault aimed mid-window fires
        at the nearest preceding boundary — the same edge checkpoints
        and termination polls land on (and the edge a real preemption
        would resume from)."""
        for s in list(self.specs):
            if not s.fired and start <= s.step < start + k:
                self.before_step(s.step)

    def observed_loss(self, step: int, loss: float) -> float:
        for s in self.specs:
            if s.fired or s.step != step:
                continue
            if s.kind == "nan":
                s.fired = True
                return float("nan")
            if s.kind == "inf":
                s.fired = True
                return float("inf")
        return loss


def parse_fault(spec: Optional[str]) -> Optional[FaultInjector]:
    """Parse ``"kind@step[:arg][,kind@step...]"`` into an injector
    (None for empty/None input — the no-fault fast path)."""
    if not spec:
        return None
    out: List[_Spec] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            kind, _, rest = part.partition("@")
            stepstr, _, argstr = rest.partition(":")
            kind = kind.strip().lower()
            if kind not in KINDS:
                raise ValueError(f"unknown fault kind {kind!r}")
            arg: Optional[object] = None
            if argstr:
                if kind == "corrupt_journal":
                    # the one string-arg kind; validated HERE so a
                    # typo'd mode fails at parse time, not mid-run
                    arg = argstr.strip()
                    if arg not in JOURNAL_CORRUPTION_MODES:
                        raise ValueError(
                            f"corrupt_journal mode {arg!r} not in "
                            f"{JOURNAL_CORRUPTION_MODES}")
                else:
                    # numeric-arg kinds stay strict: a malformed
                    # number must fail the CLI, not fire time
                    arg = float(argstr)
            out.append(_Spec(kind, int(stepstr), arg))
        except ValueError as e:
            raise ValueError(
                f"bad fault spec {part!r} (expected kind@step[:arg] "
                f"with kind in {KINDS}): {e}") from None
    return FaultInjector(out) if out else None


def split_fault(spec: Optional[str]
                ) -> "tuple[Optional[str], Optional[str]]":
    """Partition a composed fault spec into its ``(child, parent)``
    halves for the process fleet: :data:`PARENT_KINDS` fire in the
    supervisor (``rpc_timeout`` — the RPC layer is parent-side code),
    everything else ships to the replica subprocess and fires at its
    engine's tick boundaries.  Validates the WHOLE spec up front with
    :func:`parse_fault`'s strictness — a typo'd kind fails the CLI,
    not fire time.  Either half may be None."""
    if not spec:
        return None, None
    parse_fault(spec)
    child: List[str] = []
    parent: List[str] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind = part.partition("@")[0].strip().lower()
        (parent if kind in PARENT_KINDS else child).append(part)
    return (",".join(child) or None, ",".join(parent) or None)


# ---------------------------------------------------------------------------
# Checkpoint corruption
# ---------------------------------------------------------------------------

CORRUPTION_MODES = ("truncate", "unfinalize", "delete")


def corrupt_checkpoint(directory: str, step: Optional[int] = None,
                       mode: str = "truncate") -> int:
    """Deterministically damage one Orbax step dir (default: the newest
    on disk).  Returns the corrupted step number.  See module docstring
    for what each mode simulates."""
    # Share the step-dir scan and commit-marker name with the integrity
    # layer — the corruption this injects must track exactly what that
    # layer checks (lazy import: checkpoint pulls the jax/amp stack).
    from ..utils.checkpoint import _FINALIZE_MARKER, _fs_steps

    if mode not in CORRUPTION_MODES:
        raise ValueError(f"mode {mode!r} not in {CORRUPTION_MODES}")
    steps = _fs_steps(directory)
    if step is None:
        if not steps:
            raise FileNotFoundError(f"no checkpoint steps in {directory}")
        step = steps[-1]
    elif step not in steps:
        raise FileNotFoundError(
            f"checkpoint step {step} not found in {directory}; "
            f"available: {steps}")
    step_dir = os.path.join(directory, str(step))

    if mode == "unfinalize":
        os.remove(os.path.join(step_dir, _FINALIZE_MARKER))
        return step

    payloads = []
    for root, _, files in os.walk(step_dir):
        for name in files:
            if name == _FINALIZE_MARKER:
                continue
            payloads.append(os.path.join(root, name))
    if not payloads:
        raise FileNotFoundError(f"no payload files under {step_dir}")
    if mode == "delete":
        for p in payloads:
            os.remove(p)
    else:  # truncate: halve every payload, as a torn flush would
        for p in payloads:
            with open(p, "r+b") as f:
                f.truncate(os.path.getsize(p) // 2)
    return step


# ---------------------------------------------------------------------------
# Request-journal corruption (serving, ISSUE-13)
# ---------------------------------------------------------------------------

JOURNAL_CORRUPTION_MODES = ("truncate", "unfinalize")


def corrupt_journal(path: str, mode: str = "truncate") -> None:
    """Deterministically damage a serving
    :class:`~apex_tpu.serving.resilience.RequestJournal` the ways a
    real crash does:

    * ``truncate`` — cut the file mid-line (a torn trailing record:
      the flush raced the kill).  The loader must tolerate it — the
      malformed tail is counted, every complete line still parses.
    * ``unfinalize`` — strip the LAST ``terminal`` record: a request
      that finished now looks in-flight, so a replay re-runs it — the
      at-least-once delivery drill for journal-corruption recovery
      (greedy determinism makes the re-run token-identical; the
      duplicate terminal is the documented degraded mode).
    """
    if mode not in JOURNAL_CORRUPTION_MODES:
        raise ValueError(f"mode {mode!r} not in "
                         f"{JOURNAL_CORRUPTION_MODES}")
    if mode == "truncate":
        with open(path, "r+b") as f:
            data = f.read()
            body = data.rstrip(b"\n")
            if len(body) < 2:
                return
            # tear through exactly the FINAL record (cut mid-line):
            # every earlier line stays independently valid JSONL — the
            # torn-trailing-line shape a real kill leaves.  Terminate
            # the fragment with a newline so a LIVE journal's next
            # append starts its own line instead of gluing onto (and
            # corrupting) the fragment.
            start = body.rfind(b"\n") + 1
            cut = start + max(1, (len(body) - start) // 2)
            f.truncate(cut)
            f.seek(0, os.SEEK_END)
            f.write(b"\n")
        return
    # rewrite IN PLACE (same inode): the live journal's append-mode
    # sink keeps writing at the new end — an os.replace would strand
    # its fd on the unlinked file and silently drop every later record
    with open(path, "r+b") as f:
        lines = f.read().splitlines(keepends=True)
        for i in range(len(lines) - 1, -1, -1):
            if b'"name":"terminal"' in lines[i]:
                del lines[i]
                break
        f.seek(0)
        f.writelines(lines)
        f.truncate()
